"""Baseline config #3: federated char-LSTM next-token (LEAF-Shakespeare shaped).

1k-participant-scale config with a bounded M3 mask; this simulation drives a
scaled-down round (pass --participants to widen). Character sequences are
synthesized with per-participant distributions standing in for the LEAF
shards.

Run:  python examples/shakespeare_lstm.py [--rounds 1] [--participants 8]
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import threading
import time
from fractions import Fraction

import numpy as np

sys.path.insert(0, ".")

import os

import jax

# the TPU plugin's sitecustomize overrides jax_platforms; re-assert the
# user's env choice so examples run wherever they're pointed
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from xaynet_tpu.models import lstm
from xaynet_tpu.models.federated import FederatedTrainer, model_length
from xaynet_tpu.sdk.api import spawn_participant
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store

SEQ_LEN = 40
HIDDEN = 64


def synthetic_shards(seed: int, n: int = 64, seq_len: int = SEQ_LEN):
    """Per-participant character streams with distinct symbol biases."""
    rng = np.random.default_rng(seed)
    bias = rng.dirichlet(np.ones(lstm.VOCAB_SIZE) * 0.3)
    tokens = rng.choice(lstm.VOCAB_SIZE, size=(n, seq_len + 1), p=bias).astype(np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=1)
    ap.add_argument("--participants", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=HIDDEN)
    ap.add_argument("--seq-len", type=int, default=SEQ_LEN)
    ap.add_argument("--check-loss", action="store_true",
                    help="exit nonzero unless the final global model beats the init loss")
    ap.add_argument("--epochs", type=int, default=1, help="local epochs per round")
    ap.add_argument("--lr", type=float, default=1e-3, help="local Adam learning rate")
    args = ap.parse_args()

    hidden, seq_len = args.hidden, args.seq_len
    template = lstm.init_params(jax.random.PRNGKey(0), seq_len=seq_len, hidden=hidden)
    model_len = model_length(template)
    n_sum, n_update = 1, max(3, args.participants - 1)
    print(f"char-LSTM: {model_len} parameters (bounded M3 mask config)")

    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.2, count=CountSettings(n_sum, n_sum), time=TimeSettings(0, 300)),
            update=PhaseSettings(prob=0.5, count=CountSettings(n_update, n_update), time=TimeSettings(0, 300)),
            sum2=Sum2Settings(count=CountSettings(n_sum, n_sum), time=TimeSettings(0, 300)),
        )
    )
    settings.model.length = model_len
    info, started = {}, threading.Event()

    def run():
        async def amain():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0)
            info["url"] = f"http://{host}:{port}"
            started.set()
            await machine.run()

        asyncio.run(amain())

    threading.Thread(target=run, daemon=True).start()
    started.wait(10)
    url = info["url"]
    probe = HttpClient(url)

    def sync(coro):
        return asyncio.run(coro)

    shared_step = lstm.make_train_step(hidden=hidden, learning_rate=args.lr)
    threads = []
    last_seed = None
    for round_no in range(1, args.rounds + 1):
        t0 = time.time()
        params = sync(probe.get_round_params())
        while last_seed is not None and params.seed.as_bytes() == last_seed:
            time.sleep(0.2)
            params = sync(probe.get_round_params())
        seed = params.seed.as_bytes()

        def kwargs(i):
            return dict(
                init_params_fn=lambda: lstm.init_params(
                    jax.random.PRNGKey(1), seq_len=seq_len, hidden=hidden
                ),
                make_step=lambda: shared_step,
                data=synthetic_shards(i, seq_len=seq_len),
                epochs=args.epochs,
                batch_size=16,
            )

        for i in range(n_sum):
            threads.append(
                spawn_participant(
                    url, FederatedTrainer, kwargs=kwargs(900 + i),
                    keys=keys_for_task(seed, 0.2, 0.5, "sum", start=i * 1000),
                )
            )
        for i in range(n_update):
            threads.append(
                spawn_participant(
                    url, FederatedTrainer, kwargs=kwargs(i), scalar=Fraction(1, n_update),
                    keys=keys_for_task(seed, 0.2, 0.5, "update", start=(500 + i) * 1000),
                )
            )

        while True:
            model = sync(probe.get_model())
            fresh = sync(probe.get_round_params())
            if model is not None and fresh.seed.as_bytes() != seed:
                break
            time.sleep(0.2)
        last_seed = seed
        print(f"round {round_no}: completed in {time.time() - t0:.1f}s "
              f"(model norm {float(np.linalg.norm(model)):.2f})")

    for t in threads:
        t.stop()

    if args.check_loss:
        from eval_check import require_loss_improved

        model_obj, _, _ = shared_step
        # the federated average must at least fit the participating shards
        require_loss_improved(
            model_obj,
            template,
            lstm.init_params(jax.random.PRNGKey(1), seq_len=seq_len, hidden=hidden),
            model,
            [synthetic_shards(i, seq_len=seq_len) for i in range(n_update)],
        )


if __name__ == "__main__":
    main()
