"""Participant save/restore across process boundaries.

Analogue of the reference's restore example
(bindings/python/examples/restore.py): a participant is suspended
(serialized to bytes) mid-protocol and resumed later — the whole FSM state
(keys, task signatures, ephemeral keys, round parameters) survives.

Run:  python examples/restore.py
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")

from xaynet_tpu.sdk.client import InProcessClient
from xaynet_tpu.sdk.participant import Participant


class _OfflineClient(InProcessClient):
    """A client with no coordinator behind it (participant stays pending)."""

    def __init__(self):
        pass

    async def get_round_params(self):
        raise RuntimeError("coordinator unreachable")

    async def get_model(self):
        return None


def main():
    participant = Participant(_OfflineClient())
    participant.tick()  # coordinator unreachable -> pending, state intact
    print("task before suspend:", participant.task().value)

    state = participant.save()
    print(f"suspended: {len(state)} bytes of serialized state")

    resumed = Participant.restore(state, _OfflineClient())
    resumed.tick()
    print("task after resume:", resumed.task().value)
    print("save/restore round-trip OK")


if __name__ == "__main__":
    main()
