"""Test drive: N in-process participants against a coordinator.

Analogue of the reference's test-drive example
(rust/examples/test-drive/main.rs): spawns a coordinator and N participants
uploading a dummy model of length ``-l``, then runs rounds until
interrupted, printing round progress.

Run:  python examples/test_drive.py -n 20 -l 1000 -r 3
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import threading
import time
from fractions import Fraction

import numpy as np

sys.path.insert(0, ".")

from xaynet_tpu.sdk.api import ParticipantABC, spawn_participant
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store


class DummyTrainer(ParticipantABC):
    def __init__(self, length: int):
        self.length = length

    def train_round(self, training_input):
        return np.zeros(self.length, dtype=np.float32)


def start_coordinator(model_len, n_sum, n_update):
    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.3, count=CountSettings(n_sum, n_sum), time=TimeSettings(0, 60)),
            update=PhaseSettings(prob=0.6, count=CountSettings(n_update, n_update), time=TimeSettings(0, 60)),
            sum2=Sum2Settings(count=CountSettings(n_sum, n_sum), time=TimeSettings(0, 60)),
        )
    )
    settings.model.length = model_len
    info, started = {}, threading.Event()

    def run():
        async def main():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0)
            info["url"] = f"http://{host}:{port}"
            started.set()
            await machine.run()

        asyncio.run(main())

    threading.Thread(target=run, daemon=True).start()
    started.wait(10)
    return info["url"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=20, help="participants per round")
    ap.add_argument("-l", type=int, default=1000, help="model length")
    ap.add_argument("-r", type=int, default=3, help="rounds")
    ap.add_argument("--url", default=None,
                    help="drive an EXISTING coordinator (e.g. the docker-compose stack) "
                         "instead of starting one in-process; -l and -n must match its config")
    args = ap.parse_args()

    n_sum = max(1, args.n // 10)
    n_update = max(3, args.n - n_sum)
    url = args.url or start_coordinator(args.l, n_sum, n_update)
    probe = HttpClient(url)
    print(f"coordinator at {url}: {n_sum} sum + {n_update} update participants/round")

    def sync(coro):
        return asyncio.run(coro)

    last_seed = None
    threads = []  # participants stay alive across rounds (roles re-draw)
    for round_no in range(1, args.r + 1):
        t0 = time.time()
        params = sync(probe.get_round_params())
        while last_seed is not None and params.seed.as_bytes() == last_seed:
            time.sleep(0.1)
            params = sync(probe.get_round_params())
        seed = params.seed.as_bytes()

        for i in range(n_sum):
            keys = keys_for_task(seed, params.sum, params.update, "sum", start=i * 1000)
            threads.append(spawn_participant(url, DummyTrainer, args=(args.l,), keys=keys))
        for i in range(n_update):
            keys = keys_for_task(seed, params.sum, params.update, "update", start=(1000 + i) * 1000)
            threads.append(
                spawn_participant(
                    url, DummyTrainer, args=(args.l,), scalar=Fraction(1, n_update), keys=keys
                )
            )

        while True:
            model = sync(probe.get_model())
            fresh = sync(probe.get_round_params())
            if model is not None and fresh.seed.as_bytes() != seed:
                break
            time.sleep(0.1)
        last_seed = seed  # the completed round; the next loop uses the new seed
        print(f"round {round_no}: completed in {time.time() - t0:.1f}s "
              f"(model norm {float(np.linalg.norm(model)):.3f})")

    for t in threads:
        t.stop()


if __name__ == "__main__":
    main()
