"""Fetch the latest global model from a running coordinator.

Analogue of the reference's download_global_model.py example.

Run:  python examples/download_global_model.py http://localhost:8081
"""

from __future__ import annotations

import asyncio
import sys

sys.path.insert(0, ".")

from xaynet_tpu.sdk.client import HttpClient


async def main(url: str):
    client = HttpClient(url)
    model = await client.get_model()
    if model is None:
        print("no global model available yet (204)")
        return
    print(f"global model: {model.shape[0]} parameters, "
          f"norm {float((model ** 2).sum()) ** 0.5:.4f}")


if __name__ == "__main__":
    asyncio.run(main(sys.argv[1] if len(sys.argv) > 1 else "http://localhost:8081"))
