"""Baseline config #5 (stretch): federated LoRA adapters, integer masking.

Analogue of the Llama-LoRA federation scenario in BASELINE.md: each
participant fine-tunes low-rank adapters over a FROZEN base model, and only
the adapter deltas federate. The deltas are quantized to int fixed-point and
masked with an INTEGER mask config (i64/B6) — the masked payload covers the
adapters only (~0.1% of a full model) and integer masking avoids the
float fixed-point encode entirely.

The "base model" here is a small frozen linear probe so the example runs
anywhere; the federation mechanics (quantize -> i64 masking -> aggregate ->
dequantize -> apply) are exactly what a Llama-scale adapter run uses, with
`LoraSpec.targets` swapped for the attention projections.

Run:  JAX_PLATFORMS=cpu python examples/lora_federated.py
"""

from __future__ import annotations

import os
import sys

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

sys.path.insert(0, ".")

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from xaynet_tpu.core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType
from xaynet_tpu.models import lora
from xaynet_tpu.sdk.api import ParticipantABC
from xaynet_tpu.sdk.federation import LocalFederation

D_IN, D_OUT, RANK = 32, 16, 4
Q_SCALE = 10**4  # fixed-point quantization step for the adapter deltas
N_UPDATE, ROUNDS = 3, 2

SPEC = lora.LoraSpec(targets={"probe": (D_IN, D_OUT)}, rank=RANK)
BASE_W = np.asarray(
    np.random.default_rng(7).normal(size=(D_IN, D_OUT)) * 0.1, dtype=np.float32
)


def adapter_len() -> int:
    return D_IN * RANK + RANK * D_OUT


class LoraTrainer(ParticipantABC):
    """Trains adapters on a private shard; federates quantized int deltas."""

    def __init__(self, seed: int):
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(128, D_IN)).astype(np.float32)
        true_w = BASE_W + 0.05 * rng.standard_normal((D_IN, D_OUT)).astype(np.float32)
        self.y = self.x @ true_w
        self.adapters = lora.init_adapters(jax.random.PRNGKey(seed), SPEC)

        def loss_fn(adapters, batch):
            x, y = batch
            base = x @ BASE_W
            pred = lora.apply_adapter(base, x, adapters["probe"], SPEC.alpha, SPEC.rank)
            return jnp.mean((pred - y) ** 2)

        self._tx, self._step = lora.make_train_step(loss_fn, learning_rate=1e-2)
        self._opt_state = self._tx.init(self.adapters)
        self.last_loss: Optional[float] = None

    def train_round(self, training_input) -> np.ndarray:
        if training_input is not None:
            self.adapters = lora.dequantize_deltas(training_input, self.adapters, Q_SCALE)
            self._opt_state = self._tx.init(self.adapters)
        for _ in range(10):
            self.adapters, self._opt_state, loss = self._step(
                self.adapters, self._opt_state, (self.x, self.y)
            )
        self.last_loss = float(loss)
        return lora.quantize_deltas(self.adapters, Q_SCALE)

    def serialize_training_result(self, result) -> np.ndarray:
        return np.asarray(result, dtype=np.int64)  # integer masking path

    def deserialize_training_input(self, global_model):
        return None if global_model is None else np.asarray(global_model)


def _eval_mse(adapters, shards) -> float:
    """Mean squared error over the union of the updaters' shards; adapters
    ``None`` evaluates the frozen base model alone."""
    tot, n = 0.0, 0
    for x, y in shards:
        base = x @ BASE_W
        pred = (
            base
            if adapters is None
            else lora.apply_adapter(base, x, adapters["probe"], SPEC.alpha, SPEC.rank)
        )
        tot += float(np.mean((np.asarray(pred) - y) ** 2)) * len(x)
        n += len(x)
    return tot / n


def main() -> None:
    import argparse

    from xaynet_tpu.server.settings import (
        CountSettings,
        PetSettings,
        PhaseSettings,
        Settings,
        Sum2Settings,
        TimeSettings,
    )

    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=ROUNDS)
    ap.add_argument(
        "--check-loss",
        action="store_true",
        help="exit nonzero unless the federated adapters beat the frozen "
        "base model on the union of the updaters' shards (the same "
        "acceptance-gate contract as cifar_lenet / shakespeare_lstm)",
    )
    args = ap.parse_args()

    cfg = MaskConfig(GroupType.INTEGER, DataType.I64, BoundType.B6, ModelType.M3)
    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.3, count=CountSettings(1, 1), time=TimeSettings(0, 300)),
            update=PhaseSettings(
                prob=0.6, count=CountSettings(N_UPDATE, N_UPDATE), time=TimeSettings(0, 300)
            ),
            sum2=Sum2Settings(count=CountSettings(1, 1), time=TimeSettings(0, 300)),
        )
    )
    settings.mask.group_type = cfg.group_type
    settings.mask.data_type = cfg.data_type
    settings.mask.bound_type = cfg.bound_type
    settings.mask.model_type = cfg.model_type
    fed = LocalFederation(model_length=adapter_len(), n_sum=1, n_update=N_UPDATE, settings=settings)

    trainers = [LoraTrainer(seed=i) for i in range(1 + N_UPDATE)]
    print(f"federating {adapter_len()} int64 adapter deltas (rank {RANK}, scale {Q_SCALE})")
    final_delta = None
    try:
        for result in fed.rounds(trainers, n_rounds=args.rounds):
            losses = [t.last_loss for t in trainers[1:] if t.last_loss is not None]
            final_delta = result.global_model
            print(
                f"round {result.round_id}: global adapter delta ready in "
                f"{result.wall_seconds:.1f}s; local losses: "
                + ", ".join(f"{l:.4f}" for l in losses)
            )
    finally:
        fed.stop()

    if args.check_loss:
        # acceptance gate (VERDICT r04 item 8): the federated global adapters
        # must beat the frozen base model on the union of the updaters' data
        if final_delta is None:
            raise SystemExit("--check-loss needs at least one completed round")
        template = lora.init_adapters(jax.random.PRNGKey(0), SPEC)
        fed_adapters = lora.dequantize_deltas(np.asarray(final_delta), template, Q_SCALE)
        shards = [(t.x, t.y) for t in trainers[1:]]
        before, after = _eval_mse(None, shards), _eval_mse(fed_adapters, shards)
        print(f"eval loss: frozen base {before:.5f} -> base+federated adapters {after:.5f}")
        if not after < before:
            raise SystemExit("federated adapters did not improve on the frozen base model")
    print("done")


if __name__ == "__main__":
    main()
