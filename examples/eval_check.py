"""Shared acceptance gate for the federated examples' ``--check-loss``.

Evaluates the initial participant parameters and the final federated
global model on the union of the updaters' shards; exits nonzero unless
federation improved the loss. Both cifar_lenet and shakespeare_lstm use
this, so the contract CI keys off lives in one place.
"""

from __future__ import annotations

import numpy as np
import optax

from xaynet_tpu.models.mlp import unflatten_params


def require_loss_improved(model_obj, template, init_params, final_model, shards) -> None:
    """Exit nonzero unless the federated model beats ``init_params``.

    ``shards`` is a list of (x, y) arrays (the updaters' own data);
    ``final_model`` the flattened global model vector.
    """
    eval_x = np.concatenate([x for x, _ in shards])
    eval_y = np.concatenate([y for _, y in shards])

    def eval_loss(params) -> float:
        logits = model_obj.apply(params, eval_x)
        return float(optax.softmax_cross_entropy_with_integer_labels(logits, eval_y).mean())

    final_params = unflatten_params(template, np.asarray(final_model, dtype=np.float32))
    before, after = eval_loss(init_params), eval_loss(final_params)
    print(f"eval loss: init {before:.4f} -> federated {after:.4f}")
    if not after < before:
        raise SystemExit("federated model did not improve on the init loss")
