"""Baseline config #2: federated LeNet on CIFAR-10-shaped data.

100 simulated participants (8 sum + 12 update per round drawn from the
pool), f32 mask config, LeNet local training. Synthetic CIFAR-shaped data
stands in for the dataset (zero-egress environment).

Run:  python examples/cifar_lenet.py [--rounds 2] [--participants 20]
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import threading
import time
from fractions import Fraction

import numpy as np

sys.path.insert(0, ".")

import os

import jax

# the TPU plugin's sitecustomize overrides jax_platforms; re-assert the
# user's env choice so examples run wherever they're pointed
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from xaynet_tpu.models import lenet
from xaynet_tpu.models.federated import FederatedTrainer, model_length
from xaynet_tpu.sdk.api import spawn_participant
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store


def synthetic_cifar(seed: int, n: int = 128, image_size: int = 32):
    """CIFAR-shaped data with a shared linear teacher so the federated
    objective is actually learnable (labels = argmax of a fixed random
    projection of the image)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, image_size, image_size, 3)).astype(np.float32)
    teacher = np.random.default_rng(123).normal(size=(image_size * image_size * 3, 10))
    y = np.argmax(x.reshape(n, -1) @ teacher, axis=1).astype(np.int32)
    return x, y


def start_coordinator(model_len: int, n_sum: int, n_update: int, quant: int = 0):
    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.2, count=CountSettings(n_sum, n_sum), time=TimeSettings(0, 300)),
            update=PhaseSettings(prob=0.5, count=CountSettings(n_update, n_update), time=TimeSettings(0, 300)),
            sum2=Sum2Settings(count=CountSettings(n_sum, n_sum), time=TimeSettings(0, 300)),
        )
    )
    settings.model.length = model_len
    # pre-mask quantization (docs/DESIGN.md §17): a coarser fixed-point
    # config — smaller group order, fewer limbs, proportionally cheaper
    # masks/folds/transfers. Participants follow via the round params.
    settings.mask.quant = quant
    info, started = {}, threading.Event()

    def run():
        async def main():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0)
            info["url"] = f"http://{host}:{port}"
            started.set()
            await machine.run()

        asyncio.run(main())

    threading.Thread(target=run, daemon=True).start()
    started.wait(10)
    return info["url"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--participants", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=32, help="synthetic image side (CI smoke: 8)")
    ap.add_argument("--epochs", type=int, default=1, help="local epochs per round")
    ap.add_argument("--lr", type=float, default=1e-3, help="local SGD learning rate")
    ap.add_argument("--check-loss", action="store_true",
                    help="exit nonzero unless the final global model beats the init loss")
    ap.add_argument("--quant", type=int, default=0,
                    help="pre-mask quantization level (0 = exact catalogue "
                    "config; level q divides the fixed-point scale by 10^q "
                    "and shrinks the group order/limb count). The "
                    "--check-loss gate is the accuracy gate for quantized "
                    "rounds: federation must still beat the init loss.")
    args = ap.parse_args()

    image_shape = (args.image_size, args.image_size, 3)
    template = lenet.init_params(jax.random.PRNGKey(0), image_shape=image_shape)
    model_len = model_length(template)
    n_sum, n_update = 2, max(3, args.participants - 2)
    print(f"LeNet: {model_len} parameters; {n_sum} sum + {n_update} update per round")

    url = start_coordinator(model_len, n_sum, n_update, quant=args.quant)
    probe = HttpClient(url)

    def sync(coro):
        return asyncio.run(coro)

    shared_step = lenet.make_train_step(learning_rate=args.lr)
    last_seed = None
    threads = []
    for round_no in range(1, args.rounds + 1):
        t0 = time.time()
        params = sync(probe.get_round_params())
        while last_seed is not None and params.seed.as_bytes() == last_seed:
            time.sleep(0.2)
            params = sync(probe.get_round_params())
        seed = params.seed.as_bytes()

        def kwargs(i):
            return dict(
                init_params_fn=lambda: lenet.init_params(jax.random.PRNGKey(1), image_shape=image_shape),
                make_step=lambda: shared_step,
                data=synthetic_cifar(i, image_size=args.image_size),
                epochs=args.epochs,
                batch_size=32,
            )

        for i in range(n_sum):
            threads.append(
                spawn_participant(
                    url, FederatedTrainer, kwargs=kwargs(900 + i),
                    keys=keys_for_task(seed, 0.2, 0.5, "sum", start=i * 1000),
                )
            )
        for i in range(n_update):
            threads.append(
                spawn_participant(
                    url, FederatedTrainer, kwargs=kwargs(i), scalar=Fraction(1, n_update),
                    keys=keys_for_task(seed, 0.2, 0.5, "update", start=(500 + i) * 1000),
                )
            )

        while True:
            model = sync(probe.get_model())
            fresh = sync(probe.get_round_params())
            if model is not None and fresh.seed.as_bytes() != seed:
                break
            time.sleep(0.2)
        last_seed = seed
        print(f"round {round_no}: completed in {time.time() - t0:.1f}s "
              f"(model norm {float(np.linalg.norm(model)):.2f})")

    for t in threads:
        t.stop()

    if args.check_loss:
        from eval_check import require_loss_improved

        model_obj, _, _ = shared_step
        # the shared linear teacher makes every shard the same task
        require_loss_improved(
            model_obj,
            template,
            lenet.init_params(jax.random.PRNGKey(1), image_shape=image_shape),
            model,
            [synthetic_cifar(i, image_size=args.image_size) for i in range(n_update)],
        )


if __name__ == "__main__":
    main()
