"""Simulate a whole PET round in one jitted program — no coordinator.

The research-workload face of ``xaynet_tpu.sim`` (docs/DESIGN.md §13):
thousands of simulated participants per call, exact protocol arithmetic
(the global model is byte-identical to what the production server would
compute for the same seeds), single-device or mesh-sharded.

    JAX_PLATFORMS=cpu python examples/sim_quickstart.py -p 1024 -l 1000
    python examples/sim_quickstart.py -p 4096 -l 1000 --mesh --rounds 3
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from fractions import Fraction

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-p", "--participants", type=int, default=1024)
    ap.add_argument("-l", "--length", type=int, default=1000, help="model length")
    ap.add_argument("-b", "--block", type=int, default=128, help="participants per vmap block")
    ap.add_argument("--rounds", type=int, default=2, help="simulated rounds (1st compiles)")
    ap.add_argument("--mesh", action="store_true", help="shard participants over all devices")
    args = ap.parse_args()

    import numpy as np

    from xaynet_tpu.core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType
    from xaynet_tpu.sim import SimRound, SimSpec, seeds_for

    # M6 allows up to 1e6 aggregated models; B0 bounds weights to [-1, 1]
    config = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6).pair()
    mesh = None
    if args.mesh:
        from xaynet_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        print(f"mesh: {len(mesh.devices.flat)} devices (participant-axis sharding)")

    sim = SimRound(SimSpec(config, args.length, block_size=args.block), mesh=mesh)
    rng = np.random.default_rng(0)
    p = args.participants
    for rnd in range(args.rounds):
        # fresh population every round: new seeds, new local models
        seeds = seeds_for(p, root=rnd)
        weights = rng.uniform(-1, 1, (p, args.length)).astype(np.float32)
        t0 = time.perf_counter()
        result = sim.run(seeds, weights, scalar=Fraction(1, p))
        dt = time.perf_counter() - t0
        mean_err = float(np.max(np.abs(result.global_model - weights.mean(axis=0))))
        note = " (includes compile)" if rnd == 0 else ""
        print(
            f"round {rnd}: {p} participants x {args.length} params in {dt:.2f}s "
            f"= {p / dt:,.0f} participants/s{note}; "
            f"max |global - float mean| = {mean_err:.2e} (fixed-point quantization)"
        )
    print(f"program invocations: {sim.program_calls} (one per round — no per-participant loop)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
