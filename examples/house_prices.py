"""Baseline config #1: federated house-prices regression (MLP, 10 participants).

Analogue of the reference's keras_house_prices example
(bindings/python/examples/keras_house_prices/): one coordinator, ten
participants each holding a private shard of the dataset, training a
2-hidden-layer MLP with federated averaging over the PET protocol.

Synthetic data stands in for the Kaggle dataset (zero-egress environment);
swap ``make_data`` for a real loader.

Run:  python examples/house_prices.py
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time
from fractions import Fraction

import jax
import numpy as np

# the TPU plugin's sitecustomize overrides jax_platforms; re-assert the
# user's env choice so examples run wherever they're pointed
import os
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

sys.path.insert(0, ".")

from xaynet_tpu.models import mlp
from xaynet_tpu.models.federated import FederatedTrainer, model_length
from xaynet_tpu.sdk.api import spawn_participant
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store

N_PARTICIPANTS = 10
N_SUM = 2
N_UPDATE = 6
ROUNDS = 3
INPUT_DIM = 13


def make_data(rng, n=256):
    """Synthetic housing-style regression data."""
    x = rng.normal(size=(n, INPUT_DIM)).astype(np.float32)
    w = rng.normal(size=INPUT_DIM).astype(np.float32)
    y = (x @ w + 0.1 * rng.normal(size=n)).astype(np.float32)
    return x, y


def start_coordinator(model_len: int):
    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.3, count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 60)),
            update=PhaseSettings(prob=0.7, count=CountSettings(N_UPDATE, N_UPDATE), time=TimeSettings(0, 60)),
            sum2=Sum2Settings(count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 60)),
        )
    )
    settings.model.length = model_len
    info, started = {}, threading.Event()

    def run():
        async def main():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0)
            info["url"] = f"http://{host}:{port}"
            started.set()
            await machine.run()

        asyncio.run(main())

    threading.Thread(target=run, daemon=True).start()
    started.wait(10)
    return info["url"]


def main():
    rng = np.random.default_rng(0)
    template = mlp.init_params(jax.random.PRNGKey(0), INPUT_DIM)
    model_len = model_length(template)
    print(f"model length: {model_len} parameters")

    url = start_coordinator(model_len)
    probe = HttpClient(url)

    def sync(coro):
        return asyncio.run(coro)

    # Task eligibility re-draws every round (fresh seed), so the simulation
    # pins role-matched participants per round; threads from earlier rounds
    # stay alive (they idle or pick up whatever role the new seed gives them).
    shared_step = mlp.make_train_step()
    threads = []
    last_seed = None
    for round_no in range(1, ROUNDS + 1):
        params = sync(probe.get_round_params())
        while last_seed is not None and params.seed.as_bytes() == last_seed:
            time.sleep(0.2)
            params = sync(probe.get_round_params())
        seed = params.seed.as_bytes()

        trainers = []
        for i in range(N_SUM):
            keys = keys_for_task(seed, 0.3, 0.7, "sum", start=i * 1000)
            threads.append(
                spawn_participant(
                    url,
                    FederatedTrainer,
                    kwargs=dict(
                        init_params_fn=lambda: mlp.init_params(jax.random.PRNGKey(1), INPUT_DIM),
                        make_step=lambda: shared_step,
                        data=make_data(rng),
                    ),
                    keys=keys,
                )
            )
        for i in range(N_UPDATE):
            keys = keys_for_task(seed, 0.3, 0.7, "update", start=(50 + i) * 1000)
            t = spawn_participant(
                url,
                FederatedTrainer,
                kwargs=dict(
                    init_params_fn=lambda i=i: mlp.init_params(jax.random.PRNGKey(10 + i), INPUT_DIM),
                    make_step=lambda: shared_step,
                    data=make_data(rng),
                    epochs=2,
                ),
                scalar=Fraction(1, N_UPDATE),
                keys=keys,
            )
            threads.append(t)
            trainers.append(t)

        deadline = time.time() + 120
        while time.time() < deadline:
            model = sync(probe.get_model())
            fresh = sync(probe.get_round_params())
            if model is not None and fresh.seed.as_bytes() != seed:
                break
            time.sleep(0.2)
        last_seed = seed
        losses = [t._participant.last_loss for t in trainers if t._participant.last_loss]
        print(f"round {round_no}: global model ready; local losses: "
              + ", ".join(f"{l:.4f}" for l in losses))

    for t in threads:
        t.stop()
    print("done")


if __name__ == "__main__":
    main()
