/* End-to-end native participant over the built-in HTTP transport.
 *
 * Usage: http_demo <host> <port> <signing_seed_hex64> <model_len> [value]
 *
 * Completes a PET round against a live coordinator with NO embedder
 * transport code and NO Python anywhere on the client side — the parity
 * demo for the reference's reqwest-backed mobile client
 * (rust/xaynet-mobile/src/reqwest_client.rs + examples).
 *
 * Ticks the FSM; when selected as an update participant it submits a
 * constant model [value, value, ...]; prints one line per state change and
 * "global-model n=<len> first=<v>" once the new global model arrives
 * (consumed by tests/test_native_participant.py).
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include "xaynet_participant.h"

static int hex_nibble(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s <host> <port> <signing_seed_hex64> <model_len> [value]\n", argv[0]);
    return 2;
  }
  const char* host = argv[1];
  uint16_t port = (uint16_t)atoi(argv[2]);
  uint64_t model_len = (uint64_t)strtoull(argv[4], NULL, 10);
  float value = argc > 5 ? (float)atof(argv[5]) : 0.5f;

  uint8_t seed[32];
  if (strlen(argv[3]) != 64) {
    fprintf(stderr, "signing seed must be 64 hex chars\n");
    return 2;
  }
  for (int i = 0; i < 32; i++) {
    int hi = hex_nibble(argv[3][2 * i]), lo = hex_nibble(argv[3][2 * i + 1]);
    if (hi < 0 || lo < 0) {
      fprintf(stderr, "bad hex in signing seed\n");
      return 2;
    }
    seed[i] = (uint8_t)((hi << 4) | lo);
  }

  if (xaynet_ffi_crypto_init() != 0) {
    fprintf(stderr, "crypto init failed\n");
    return 1;
  }
  /* XN_TLS_CA pins the coordinator's root cert (in-process TLS);
   * XN_TLS_CERT + XN_TLS_KEY add a client identity (mutual TLS) */
  const char* tls_ca = getenv("XN_TLS_CA");
  XnHttpClient* http =
      tls_ca ? xn_http_client_new_tls(host, port, tls_ca, getenv("XN_TLS_CERT"),
                                      getenv("XN_TLS_KEY"))
             : xn_http_client_new(host, port);
  if (!http) {
    fprintf(stderr, "http client alloc failed%s\n", tls_ca ? " (tls)" : "");
    return 1;
  }
  /* scalar 1/3: the smoke round runs 3 update participants */
  void* p = xaynet_ffi_participant_new(seed, 1, 3, 4096, xn_http_transport, http);
  if (!p) {
    fprintf(stderr, "participant_new failed\n");
    return 1;
  }

  float* model = (float*)malloc(model_len * sizeof(float));
  for (uint64_t i = 0; i < model_len; i++) model[i] = value;

  int last_task = -1;
  int consecutive_transport_errors = 0, ever_reached = 0;
  for (int i = 0; i < 600; i++) {
    int rc = xaynet_ffi_participant_tick(p);
    if (rc == -2) {
      /* transient once the coordinator has been reached at least once;
       * 20 straight failures from the start means the endpoint/TLS config
       * is wrong (e.g. a root-pin mismatch) — abort instead of spinning */
      if (!ever_reached && ++consecutive_transport_errors >= 20) {
        fprintf(stderr, "transport unreachable from the first tick (endpoint/TLS config?)\n");
        free(model);
        xaynet_ffi_participant_destroy(p);
        xn_http_client_free(http);
        return 1;
      }
    } else {
      ever_reached = 1;
      consecutive_transport_errors = 0;
    }
    if (rc < 0 && rc != -2) {
      fprintf(stderr, "fatal tick error %d\n", rc);
      free(model);
      xaynet_ffi_participant_destroy(p);
      xn_http_client_free(http);
      return 1;
    }
    int task = xaynet_ffi_participant_task(p);
    if (task != last_task) {
      printf("task=%d\n", task);
      fflush(stdout);
      last_task = task;
    }
    if (xaynet_ffi_participant_should_set_model(p)) {
      if (xaynet_ffi_participant_set_model(p, model, model_len) != 0) {
        fprintf(stderr, "set_model failed\n");
        return 1;
      }
      printf("model-set n=%llu\n", (unsigned long long)model_len);
      fflush(stdout);
    }
    const double* global = NULL;
    int64_t n = xaynet_ffi_participant_global_model(p, &global);
    if (n > 0 && global) {
      printf("global-model n=%lld first=%.6f\n", (long long)n, global[0]);
      fflush(stdout);
      free(model);
      xaynet_ffi_participant_destroy(p);
      xn_http_client_free(http);
      return 0;
    }
    usleep(100000); /* 100ms poll cadence */
  }
  fprintf(stderr, "no global model within the tick budget\n");
  return 1;
}
