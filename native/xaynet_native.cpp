// Native host kernels for xaynet_tpu.
//
// The reference implements its entire hot path in native code (Rust); the
// TPU build keeps the *device* hot loops in XLA/Pallas and implements the
// host-side compute-heavy pieces here in C++:
//
//   - ChaCha20 keystream generation (the PET mask-expansion PRNG;
//     reference semantics: rust/xaynet-core/src/crypto/prng.rs:16-27),
//   - rejection sampling of uniform finite-group elements from that
//     keystream (byte-stream compatible with the Python/JAX samplers),
//   - fixed-width little-endian modular add/sub over element vectors (the
//     CPU fallback of the aggregation kernels).
//
// Built as a plain shared library; loaded via ctypes (no pybind11).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#ifdef __AVX2__
#include <immintrin.h>
#endif

#define XN_EXPORT extern "C" __attribute__((visibility("default")))

namespace {

inline uint32_t rotl(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

inline void quarter(uint32_t s[16], int a, int b, int c, int d) {
  s[a] += s[b];
  s[d] = rotl(s[d] ^ s[a], 16);
  s[c] += s[d];
  s[b] = rotl(s[b] ^ s[c], 12);
  s[a] += s[b];
  s[d] = rotl(s[d] ^ s[a], 8);
  s[c] += s[d];
  s[b] = rotl(s[b] ^ s[c], 7);
}

// One 64-byte ChaCha20 block (djb variant: 64-bit counter, 64-bit zero nonce).
void chacha20_block(const uint32_t key[8], uint64_t counter, uint8_t out[64]) {
  uint32_t s[16] = {0x61707865u, 0x3320646eu, 0x79622d32u, 0x6b206574u,
                    key[0],      key[1],      key[2],      key[3],
                    key[4],      key[5],      key[6],      key[7],
                    (uint32_t)(counter & 0xffffffffu),
                    (uint32_t)(counter >> 32),
                    0u,          0u};
  uint32_t w[16];
  std::memcpy(w, s, sizeof(w));
  for (int i = 0; i < 10; i++) {
    quarter(w, 0, 4, 8, 12);
    quarter(w, 1, 5, 9, 13);
    quarter(w, 2, 6, 10, 14);
    quarter(w, 3, 7, 11, 15);
    quarter(w, 0, 5, 10, 15);
    quarter(w, 1, 6, 11, 12);
    quarter(w, 2, 7, 8, 13);
    quarter(w, 3, 4, 9, 14);
  }
  for (int i = 0; i < 16; i++) {
    uint32_t v = w[i] + s[i];
    out[i * 4 + 0] = (uint8_t)(v);
    out[i * 4 + 1] = (uint8_t)(v >> 8);
    out[i * 4 + 2] = (uint8_t)(v >> 16);
    out[i * 4 + 3] = (uint8_t)(v >> 24);
  }
}

#ifdef __AVX2__
namespace {

inline __m256i rotl8v(__m256i x, int n) {
  return _mm256_or_si256(_mm256_slli_epi32(x, n), _mm256_srli_epi32(x, 32 - n));
}

#define XN_QUARTER8(a, b, c, d)            \
  a = _mm256_add_epi32(a, b);              \
  d = rotl8v(_mm256_xor_si256(d, a), 16);  \
  c = _mm256_add_epi32(c, d);              \
  b = rotl8v(_mm256_xor_si256(b, c), 12);  \
  a = _mm256_add_epi32(a, b);              \
  d = rotl8v(_mm256_xor_si256(d, a), 8);   \
  c = _mm256_add_epi32(c, d);              \
  b = rotl8v(_mm256_xor_si256(b, c), 7)

// Eight consecutive ChaCha20 blocks in parallel (one block per SIMD lane).
void chacha20_blocks8(const uint32_t key[8], uint64_t counter0, uint8_t out[512]) {
  const uint32_t consts[4] = {0x61707865u, 0x3320646eu, 0x79622d32u, 0x6b206574u};
  __m256i s[16];
  for (int i = 0; i < 4; i++) s[i] = _mm256_set1_epi32((int)consts[i]);
  for (int i = 0; i < 8; i++) s[4 + i] = _mm256_set1_epi32((int)key[i]);
  alignas(32) uint32_t ctr_lo[8], ctr_hi[8];
  for (int l = 0; l < 8; l++) {
    uint64_t c = counter0 + (uint64_t)l;
    ctr_lo[l] = (uint32_t)(c & 0xffffffffu);
    ctr_hi[l] = (uint32_t)(c >> 32);
  }
  s[12] = _mm256_load_si256((const __m256i*)ctr_lo);
  s[13] = _mm256_load_si256((const __m256i*)ctr_hi);
  s[14] = _mm256_setzero_si256();
  s[15] = _mm256_setzero_si256();

  __m256i w0 = s[0], w1 = s[1], w2 = s[2], w3 = s[3], w4 = s[4], w5 = s[5],
          w6 = s[6], w7 = s[7], w8 = s[8], w9 = s[9], w10 = s[10], w11 = s[11],
          w12 = s[12], w13 = s[13], w14 = s[14], w15 = s[15];
  for (int r = 0; r < 10; r++) {
    XN_QUARTER8(w0, w4, w8, w12);
    XN_QUARTER8(w1, w5, w9, w13);
    XN_QUARTER8(w2, w6, w10, w14);
    XN_QUARTER8(w3, w7, w11, w15);
    XN_QUARTER8(w0, w5, w10, w15);
    XN_QUARTER8(w1, w6, w11, w12);
    XN_QUARTER8(w2, w7, w8, w13);
    XN_QUARTER8(w3, w4, w9, w14);
  }
  __m256i v[16] = {w0, w1, w2, w3, w4, w5, w6, w7, w8, w9, w10, w11, w12, w13, w14, w15};
  for (int i = 0; i < 16; i++) v[i] = _mm256_add_epi32(v[i], s[i]);
  // transpose: block l = words 0..15, lane l. Two SIMD 8x8 32-bit
  // transposes (words 0-7 -> first 32B of each block, words 8-15 -> second
  // 32B) replace the 128 scalar stores the first version paid per 512B.
  for (int half = 0; half < 2; half++) {
    const __m256i* r = v + half * 8;
    __m256i t0 = _mm256_unpacklo_epi32(r[0], r[1]);
    __m256i t1 = _mm256_unpackhi_epi32(r[0], r[1]);
    __m256i t2 = _mm256_unpacklo_epi32(r[2], r[3]);
    __m256i t3 = _mm256_unpackhi_epi32(r[2], r[3]);
    __m256i t4 = _mm256_unpacklo_epi32(r[4], r[5]);
    __m256i t5 = _mm256_unpackhi_epi32(r[4], r[5]);
    __m256i t6 = _mm256_unpacklo_epi32(r[6], r[7]);
    __m256i t7 = _mm256_unpackhi_epi32(r[6], r[7]);
    __m256i u0 = _mm256_unpacklo_epi64(t0, t2);
    __m256i u1 = _mm256_unpackhi_epi64(t0, t2);
    __m256i u2 = _mm256_unpacklo_epi64(t1, t3);
    __m256i u3 = _mm256_unpackhi_epi64(t1, t3);
    __m256i u4 = _mm256_unpacklo_epi64(t4, t6);
    __m256i u5 = _mm256_unpackhi_epi64(t4, t6);
    __m256i u6 = _mm256_unpacklo_epi64(t5, t7);
    __m256i u7 = _mm256_unpackhi_epi64(t5, t7);
    uint8_t* o = out + half * 32;
    _mm256_storeu_si256((__m256i*)(o + 0 * 64), _mm256_permute2x128_si256(u0, u4, 0x20));
    _mm256_storeu_si256((__m256i*)(o + 1 * 64), _mm256_permute2x128_si256(u1, u5, 0x20));
    _mm256_storeu_si256((__m256i*)(o + 2 * 64), _mm256_permute2x128_si256(u2, u6, 0x20));
    _mm256_storeu_si256((__m256i*)(o + 3 * 64), _mm256_permute2x128_si256(u3, u7, 0x20));
    _mm256_storeu_si256((__m256i*)(o + 4 * 64), _mm256_permute2x128_si256(u0, u4, 0x31));
    _mm256_storeu_si256((__m256i*)(o + 5 * 64), _mm256_permute2x128_si256(u1, u5, 0x31));
    _mm256_storeu_si256((__m256i*)(o + 6 * 64), _mm256_permute2x128_si256(u2, u6, 0x31));
    _mm256_storeu_si256((__m256i*)(o + 7 * 64), _mm256_permute2x128_si256(u3, u7, 0x31));
  }
}

}  // namespace
#endif  // __AVX2__

namespace {

// Fill `nblocks` consecutive blocks starting at `counter0` into `out`,
// using the 8-way kernel where possible.
void chacha20_fill(const uint32_t key[8], uint64_t counter0, uint64_t nblocks,
                   uint8_t* out) {
  uint64_t b = 0;
#ifdef __AVX2__
  for (; b + 8 <= nblocks; b += 8) {
    chacha20_blocks8(key, counter0 + b, out + b * 64);
  }
#endif
  for (; b < nblocks; b++) chacha20_block(key, counter0 + b, out + b * 64);
}

}  // namespace

// value < order over fixed-width little-endian byte strings.
inline bool lt_le(const uint8_t* value, const uint8_t* order, uint32_t n) {
  for (int i = (int)n - 1; i >= 0; i--) {
    if (value[i] < order[i]) return true;
    if (value[i] > order[i]) return false;
  }
  return false;  // equal
}

inline unsigned __int128 load_le16(const uint8_t* p, uint32_t nbytes) {
  uint64_t lo, hi;
  std::memcpy(&lo, p, 8);
  if (nbytes <= 8) {
    if (nbytes == 8) return lo;
    return lo & ((1ull << (8 * nbytes)) - 1);
  }
  std::memcpy(&hi, p + 8, 8);
  unsigned __int128 v = ((unsigned __int128)hi << 64) | lo;
  if (nbytes == 16) return v;
  unsigned __int128 mask = ((unsigned __int128)1 << (8 * nbytes)) - 1;
  return v & mask;
}

}  // namespace

// Generate `nblocks` keystream blocks starting at `block_start` into `out`
// (64 bytes per block).
XN_EXPORT void xn_chacha20_blocks(const uint8_t key_bytes[32], uint64_t block_start,
                                  uint64_t nblocks, uint8_t* out) {
  uint32_t key[8];
  std::memcpy(key, key_bytes, 32);
  chacha20_fill(key, block_start, nblocks, out);
}

// Draw `count` uniform values below `order` (little-endian, `order_nbytes`
// wide — the byte length of the order itself) from the keystream of `key`,
// starting at absolute keystream byte `byte_offset`. Each rejection attempt
// consumes `order_nbytes` bytes, exactly like the sequential reference
// sampler. Accepted values are written fixed-width little-endian to `out`
// (count * order_nbytes bytes). Returns the new keystream byte offset.
XN_EXPORT uint64_t xn_sample_uniform(const uint8_t key_bytes[32], uint64_t byte_offset,
                                     uint64_t count, const uint8_t* order_le,
                                     uint32_t order_nbytes, uint8_t* out) {
  uint32_t key[8];
  std::memcpy(key, key_bytes, 32);
  unsigned __int128 order128 = 0;
  const bool small_order = order_nbytes <= 16;
  if (small_order) {
    for (int i = (int)order_nbytes - 1; i >= 0; i--)
      order128 = (order128 << 8) | order_le[i];
  }

  // Buffered keystream: generate CHUNK_BLOCKS blocks at a time and slice
  // candidates out of the flat buffer (carrying the partial tail between
  // refills), instead of reassembling byte-by-byte.
  constexpr uint64_t CHUNK_BLOCKS = 1024;  // 64 KiB of keystream per refill
  std::vector<uint8_t> buf(CHUNK_BLOCKS * 64 + 512);
  uint64_t avail = 0;  // valid bytes in buf

  uint64_t next_block = byte_offset / 64;
  uint64_t intra = byte_offset % 64;
  // prime the buffer with the partial first block
  if (intra) {
    uint8_t first[64];
    chacha20_block(key, next_block, first);
    next_block++;
    avail = 64 - intra;
    std::memcpy(buf.data(), first + intra, avail);
  }

  uint64_t offset = byte_offset;
  uint64_t pos = 0;  // read cursor within buf
  uint64_t got = 0;

  if (order_nbytes <= 8) {
    // u64 fast path (every <= 2-limb order): one unaligned 8-byte load +
    // mask + compare per candidate instead of the generic __int128
    // reassembly, and accepted values store as one masked u64 (the spill
    // byte is zero and the next accept overwrites it; only the LAST
    // element stores exactly its width). The candidate loop — not the
    // keystream — was ~80% of the sampler wall at bpn=7.
    const uint64_t order64 = (uint64_t)order128;
    const uint64_t vmask =
        order_nbytes == 8 ? ~0ull : ((1ull << (8 * order_nbytes)) - 1);
    const uint64_t out_bytes = count * order_nbytes;
    while (got < count) {
      if (avail - pos < order_nbytes + 8) {
        uint64_t tail = avail - pos;
        std::memmove(buf.data(), buf.data() + pos, tail);
        chacha20_fill(key, next_block, CHUNK_BLOCKS, buf.data() + tail);
        next_block += CHUNK_BLOCKS;
        avail = tail + CHUNK_BLOCKS * 64;
        pos = 0;
      }
      // candidates fully inside the buffer (8-byte loads stay in the +512
      // slack); stop at `count` accepts so the cursor lands exactly on the
      // byte after the count-th accepted attempt
      const uint64_t n_here = (avail - pos - 8) / order_nbytes;
      const uint8_t* p = buf.data() + pos;
      uint64_t consumed = 0;
      for (uint64_t i = 0; i < n_here; i++) {
        uint64_t v;
        std::memcpy(&v, p + i * order_nbytes, 8);
        v &= vmask;
        consumed += order_nbytes;
        if (v < order64) {
          if (got * order_nbytes + 8 <= out_bytes) {
            std::memcpy(out + got * order_nbytes, &v, 8);
          } else {
            std::memcpy(out + got * order_nbytes, &v, order_nbytes);
          }
          got++;
          if (got == count) break;
        }
      }
      pos += consumed;
      offset += consumed;
    }
    return offset;
  }

  for (; got < count;) {
    if (avail - pos < order_nbytes) {
      // move the tail to the front, refill through the 8-way AVX2 kernel
      uint64_t tail = avail - pos;
      std::memmove(buf.data(), buf.data() + pos, tail);
      chacha20_fill(key, next_block, CHUNK_BLOCKS, buf.data() + tail);
      next_block += CHUNK_BLOCKS;
      avail = tail + CHUNK_BLOCKS * 64;
      pos = 0;
    }
    const uint8_t* candidate = buf.data() + pos;
    pos += order_nbytes;
    offset += order_nbytes;
    const bool accept = small_order ? (load_le16(candidate, order_nbytes) < order128)
                                    : lt_le(candidate, order_le, order_nbytes);
    if (accept) {
      std::memcpy(out + got * order_nbytes, candidate, order_nbytes);
      got++;
    }
  }
  return offset;
}

// Fused sample+fold (the host twin of the Pallas mask kernel): draw `count`
// uniform values below `order` from the keystream exactly like
// xn_sample_uniform (same attempts, same acceptance, same end cursor) and
// ADD each accepted value into the u64 accumulator `acc[count]` instead of
// materializing the mask. Orders must fit 8 little-endian bytes; the CALLER
// owns the lazy-reduction headroom (sum of all folded values per slot must
// stay below 2^64 — reduce `acc` mod order between waves). Returns the end
// byte cursor, or 0 when the order is out of range for this entry.
XN_EXPORT uint64_t xn_sample_fold_u64(const uint8_t key_bytes[32], uint64_t byte_offset,
                                      uint64_t count, const uint8_t* order_le,
                                      uint32_t order_nbytes, uint64_t* acc) {
  if (order_nbytes == 0 || order_nbytes > 8) return 0;
  uint32_t key[8];
  std::memcpy(key, key_bytes, 32);
  uint64_t order64 = 0;
  for (int i = (int)order_nbytes - 1; i >= 0; i--)
    order64 = (order64 << 8) | order_le[i];
  const uint64_t vmask =
      order_nbytes == 8 ? ~0ull : ((1ull << (8 * order_nbytes)) - 1);

  constexpr uint64_t CHUNK_BLOCKS = 1024;
  std::vector<uint8_t> buf(CHUNK_BLOCKS * 64 + 512);
  uint64_t avail = 0;
  uint64_t next_block = byte_offset / 64;
  uint64_t intra = byte_offset % 64;
  if (intra) {
    uint8_t first[64];
    chacha20_block(key, next_block, first);
    next_block++;
    avail = 64 - intra;
    std::memcpy(buf.data(), first + intra, avail);
  }

  uint64_t offset = byte_offset;
  uint64_t pos = 0;
  uint64_t got = 0;
  while (got < count) {
    if (avail - pos < order_nbytes + 8) {
      uint64_t tail = avail - pos;
      std::memmove(buf.data(), buf.data() + pos, tail);
      chacha20_fill(key, next_block, CHUNK_BLOCKS, buf.data() + tail);
      next_block += CHUNK_BLOCKS;
      avail = tail + CHUNK_BLOCKS * 64;
      pos = 0;
    }
    const uint64_t n_here = (avail - pos - 8) / order_nbytes;
    const uint8_t* p = buf.data() + pos;
    uint64_t consumed = 0;
    for (uint64_t i = 0; i < n_here; i++) {
      uint64_t v;
      std::memcpy(&v, p + i * order_nbytes, 8);
      v &= vmask;
      consumed += order_nbytes;
      if (v < order64) {
        acc[got] += v;  // lazy: caller reduces mod order between waves
        got++;
        if (got == count) break;
      }
    }
    pos += consumed;
    offset += consumed;
  }
  return offset;
}



// (a + b) mod order, elementwise over `n` values of `n_limbs` uint32 limbs
// (little-endian limb order, wire layout [n, L]); a, b < order.
// `order_limbs` may be all zero when order == 2^(32*L) (natural wraparound).
XN_EXPORT void xn_mod_add(const uint32_t* a, const uint32_t* b, uint32_t* out,
                          uint64_t n, uint32_t n_limbs, const uint32_t* order_limbs) {
  bool order_is_pow2_boundary = true;
  for (uint32_t j = 0; j < n_limbs; j++)
    if (order_limbs[j] != 0) order_is_pow2_boundary = false;

  for (uint64_t i = 0; i < n; i++) {
    const uint32_t* av = a + i * n_limbs;
    const uint32_t* bv = b + i * n_limbs;
    uint32_t* ov = out + i * n_limbs;
    uint64_t carry = 0;
    for (uint32_t j = 0; j < n_limbs; j++) {
      uint64_t s = (uint64_t)av[j] + bv[j] + carry;
      ov[j] = (uint32_t)s;
      carry = s >> 32;
    }
    if (order_is_pow2_boundary) continue;
    bool ge = carry != 0;
    if (!ge) {
      ge = !lt_le((const uint8_t*)ov, (const uint8_t*)order_limbs, n_limbs * 4);
    }
    if (ge) {
      uint64_t borrow = 0;
      for (uint32_t j = 0; j < n_limbs; j++) {
        uint64_t d = (uint64_t)ov[j] - order_limbs[j] - borrow;
        ov[j] = (uint32_t)d;
        borrow = (d >> 63) & 1;
      }
    }
  }
}

namespace {

// Worker-thread count for the batch folds: XAYNET_NATIVE_THREADS overrides
// (values < 1 mean single-threaded), otherwise 2x hardware_concurrency
// capped at 16. The folds are bandwidth-bound; the 2x oversubscription is
// deliberate — on the small shared-container CPU quotas the coordinator
// runs under, extra runnable threads hide per-thread DRAM stalls and
// scheduler preemption (measured ~15% over 1x at the 25M bench shape on a
// 2-CPU cgroup), while the cap keeps big hosts from spawning threads well
// past the memory channels.
unsigned fold_threads() {
  static const unsigned cached = [] {
    const char* env = std::getenv("XAYNET_NATIVE_THREADS");
    if (env && *env) {
      const long v = std::strtol(env, nullptr, 10);
      if (v < 1) return 1u;
      return (unsigned)(v > 64 ? 64 : v);
    }
    unsigned hc = std::thread::hardware_concurrency();
    if (hc == 0) hc = 1;
    const unsigned t = 2 * hc;
    return t > 16 ? 16u : t;
  }();
  return cached;
}

// Run fn(s0, s1) over contiguous slices of [0, n): the fold's element axis
// is embarrassingly parallel, so each thread owns a disjoint slice and no
// merge step exists. Slices align to `align` (the fold's BLOCK size) and a
// minimum slice keeps tiny folds single-threaded — thread spawn (~10us)
// must never dominate a sub-millisecond fold. `nt_override` > 0 pins the
// worker count for this call (the per-shard thread budget of the sharded
// streaming fold, where several kernel calls run concurrently and must
// split the process-wide budget between them); 0 keeps fold_threads().
template <typename F>
void run_sliced(uint64_t n, uint64_t align, F&& fn, unsigned nt_override = 0) {
  unsigned nt = nt_override ? (nt_override > 64 ? 64u : nt_override) : fold_threads();
  constexpr uint64_t MIN_SLICE = 1ull << 19;  // 512k elements (~4 MB of u64 sums)
  if (nt > 1) {
    const uint64_t cap = n / MIN_SLICE;
    if (cap < nt) nt = (unsigned)(cap ? cap : 1);
  }
  if (nt <= 1) {
    fn((uint64_t)0, n);
    return;
  }
  uint64_t chunk = (n + nt - 1) / nt;
  chunk = (chunk + align - 1) / align * align;
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (unsigned t = 0; t < nt; t++) {
    const uint64_t s0 = (uint64_t)t * chunk;
    if (s0 >= n) break;
    const uint64_t s1 = s0 + chunk < n ? s0 + chunk : n;
    threads.emplace_back([&fn, s0, s1] { fn(s0, s1); });
  }
  for (auto& th : threads) th.join();
}

// Shared core of the single-pass u64 batch folds, one element slice
// [s0, s1). `Wire` selects the data layout: planar uint32[L, n]
// (limb-major) or wire uint32[n, L] (for L == 2 a wire row is one
// little-endian u64 — contiguous 8-byte loads). The arithmetic —
// double-reciprocal quotient with two rounding fixups, u64 wraparound on
// pow2-boundary orders — lives exactly once here.
// Strides (planar layout only; the wire layout is always natural):
// `acc_stride` separates the limb planes of acc AND out (full-width buffers
// pass their row length; a contiguous per-shard slice passes its width),
// `stack_row_stride` separates limb planes within one staged update and
// `stack_batch_stride` separates updates — so a fold can read one shard's
// column slice [*, s0:s1) straight out of a full staged batch with zero
// slice copies (the sharded streaming fold and the multi-device bench leg).
template <bool Wire>
void fold_u64_slice(const uint32_t* acc, const uint32_t* stack, uint32_t* out, uint64_t n,
                    uint64_t acc_stride, uint64_t stack_row_stride, uint64_t stack_batch_stride,
                    uint32_t n_limbs, uint64_t k, uint64_t order, uint64_t s0, uint64_t s1) {
  const bool pow2_boundary = order == 0;
  const bool two_limbs = n_limbs == 2;
  // quotient sum/order is tiny (< K+1): one double multiply approximates it
  // to +-1 and two fixups make it exact — far cheaper than a u64 divide
  const double inv_order = pow2_boundary ? 0.0 : 1.0 / (double)order;

  // i-blocked so every inner loop is a flat auto-vectorizable stream and
  // the u64 partial sums stay in L1/L2 while the K streams are read once
  constexpr uint64_t BLOCK = 4096;
  uint64_t sum[BLOCK];
  for (uint64_t s = s0; s < s1; s += BLOCK) {
    const uint64_t bn = (s1 - s) < BLOCK ? (s1 - s) : BLOCK;
    if (two_limbs) {
      if (Wire) {
        for (uint64_t i = 0; i < bn; i++) {
          const uint32_t* row = acc + 2 * (s + i);
          sum[i] = (uint64_t)row[0] | ((uint64_t)row[1] << 32);
        }
        for (uint64_t kk = 0; kk < k; kk++) {
          const uint32_t* up = stack + kk * 2 * n + 2 * s;
          for (uint64_t i = 0; i < bn; i++)
            sum[i] += (uint64_t)up[2 * i] | ((uint64_t)up[2 * i + 1] << 32);
        }
      } else {
        // planar: walk the lo and hi limb planes as two lockstep
        // CONTIGUOUS streams (lo[i] / hi[i]) rather than indexing both
        // through one base pointer — measured ~1.5x on the 25M bench
        // shape (the prefetcher tracks two unit-stride streams)
        const uint32_t* alo = acc + s;
        const uint32_t* ahi = acc + acc_stride + s;
        for (uint64_t i = 0; i < bn; i++)
          sum[i] = (uint64_t)alo[i] | ((uint64_t)ahi[i] << 32);
        for (uint64_t kk = 0; kk < k; kk++) {
          const uint32_t* lo = stack + kk * stack_batch_stride + s;
          const uint32_t* hi = lo + stack_row_stride;
          for (uint64_t i = 0; i < bn; i++)
            sum[i] += (uint64_t)lo[i] | ((uint64_t)hi[i] << 32);
        }
      }
    } else {
      for (uint64_t i = 0; i < bn; i++) sum[i] = acc[s + i];
      for (uint64_t kk = 0; kk < k; kk++) {
        const uint32_t* up = stack + kk * (Wire ? n : stack_batch_stride) + s;
        for (uint64_t i = 0; i < bn; i++) sum[i] += up[i];
      }
    }
    if (!pow2_boundary) {
      for (uint64_t i = 0; i < bn; i++) {
        const uint64_t q = (uint64_t)((double)sum[i] * inv_order);
        uint64_t r = sum[i] - q * order;
        // double rounding can land one order off in either direction
        r += (r >> 63) ? order : 0;     // q overshot (r went negative)
        r -= (r >= order) ? order : 0;  // q undershot
        sum[i] = r;
      }
    } else if (!two_limbs) {
      for (uint64_t i = 0; i < bn; i++) sum[i] &= 0xFFFFFFFFull;
    }  // order == 2^64: u64 arithmetic wraps naturally
    if (two_limbs) {
      if (Wire) {
        for (uint64_t i = 0; i < bn; i++) {
          out[2 * (s + i)] = (uint32_t)sum[i];
          out[2 * (s + i) + 1] = (uint32_t)(sum[i] >> 32);
        }
      } else {
        uint32_t* olo = out + s;
        uint32_t* ohi = out + acc_stride + s;
        for (uint64_t i = 0; i < bn; i++) {
          olo[i] = (uint32_t)sum[i];
          ohi[i] = (uint32_t)(sum[i] >> 32);
        }
      }
    } else {
      for (uint64_t i = 0; i < bn; i++) out[s + i] = (uint32_t)sum[i];
    }
  }
}

template <bool Wire>
void fold_u64_core(const uint32_t* acc, const uint32_t* stack, uint32_t* out, uint64_t n,
                   uint64_t acc_stride, uint64_t stack_row_stride, uint64_t stack_batch_stride,
                   uint32_t n_limbs, uint64_t k, const uint32_t* order_limbs,
                   unsigned n_threads) {
  uint64_t order = 0;
  for (uint32_t j = 0; j < n_limbs; j++) order |= (uint64_t)order_limbs[j] << (32 * j);
  run_sliced(
      n, 4096,
      [=](uint64_t s0, uint64_t s1) {
        fold_u64_slice<Wire>(acc, stack, out, n, acc_stride, stack_row_stride,
                             stack_batch_stride, n_limbs, k, order, s0, s1);
      },
      n_threads);
}

// Packed-byte-planar leg of the single-pass u64 fold: the staged batch is
// uint8[K, bpn, n] byte-planes (ops/limbs.py pack_planar — byte-plane b
// holds byte b of every element), so one element slice reads bpn
// unit-stride byte streams instead of n_limbs u32 streams: bpn/(4*L) of
// the batch traffic (6/8 for the standard 2-limb f32 configs). Arithmetic
// and headroom requirements match fold_u64_slice exactly; acc/out stay
// planar uint32[L, *].
void fold_packed_u64_slice(const uint32_t* acc, const uint8_t* packed, uint32_t* out,
                           uint64_t acc_stride, uint64_t packed_row_stride,
                           uint64_t packed_batch_stride, uint32_t n_limbs, uint32_t bpn,
                           uint64_t k, uint64_t order, uint64_t s0, uint64_t s1) {
  const bool pow2_boundary = order == 0;
  const bool two_limbs = n_limbs == 2;
  const double inv_order = pow2_boundary ? 0.0 : 1.0 / (double)order;
  constexpr uint64_t BLOCK = 4096;
  uint64_t sum[BLOCK];
  for (uint64_t s = s0; s < s1; s += BLOCK) {
    const uint64_t bn = (s1 - s) < BLOCK ? (s1 - s) : BLOCK;
    if (two_limbs) {
      const uint32_t* alo = acc + s;
      const uint32_t* ahi = acc + acc_stride + s;
      for (uint64_t i = 0; i < bn; i++)
        sum[i] = (uint64_t)alo[i] | ((uint64_t)ahi[i] << 32);
    } else {
      for (uint64_t i = 0; i < bn; i++) sum[i] = acc[s + i];
    }
    for (uint64_t kk = 0; kk < k; kk++) {
      const uint8_t* base = packed + kk * packed_batch_stride + s;
      // unit-stride byte planes, low to high: the shifted adds vectorize
      // per plane and the u64 partials stay in L1 across planes
      for (uint32_t b = 0; b < bpn; b++) {
        const uint8_t* plane = base + (uint64_t)b * packed_row_stride;
        const uint32_t shift = 8u * b;
        for (uint64_t i = 0; i < bn; i++) sum[i] += (uint64_t)plane[i] << shift;
      }
    }
    if (!pow2_boundary) {
      for (uint64_t i = 0; i < bn; i++) {
        const uint64_t q = (uint64_t)((double)sum[i] * inv_order);
        uint64_t r = sum[i] - q * order;
        r += (r >> 63) ? order : 0;
        r -= (r >= order) ? order : 0;
        sum[i] = r;
      }
    } else if (!two_limbs) {
      for (uint64_t i = 0; i < bn; i++) sum[i] &= 0xFFFFFFFFull;
    }
    if (two_limbs) {
      uint32_t* olo = out + s;
      uint32_t* ohi = out + acc_stride + s;
      for (uint64_t i = 0; i < bn; i++) {
        olo[i] = (uint32_t)sum[i];
        ohi[i] = (uint32_t)(sum[i] >> 32);
      }
    } else {
      for (uint64_t i = 0; i < bn; i++) out[s + i] = (uint32_t)sum[i];
    }
  }
}

}  // namespace

// Strided single-pass fold of a PACKED byte-planar uint8[K, bpn, n] batch
// into the planar uint32[L, *] accumulator slice (ABI 8; the packed twin of
// xn_fold_planar_u64_strided). Pointers are pre-offset to the slice start;
// `acc_stride` is in uint32 elements, `packed_row_stride` (between byte
// planes) and `packed_batch_stride` (between updates) in bytes.
// Requirements: bpn <= 8, n_limbs <= 2, every element < order, and
// (K+1) * order < 2^64 for non-pow2 orders (all-zero order_limbs = the
// 2^(32L) boundary, natural wraparound for any K).
XN_EXPORT void xn_fold_packed_u64_strided(const uint32_t* acc, const uint8_t* packed,
                                          uint32_t* out, uint64_t width, uint64_t acc_stride,
                                          uint64_t packed_row_stride,
                                          uint64_t packed_batch_stride, uint32_t n_limbs,
                                          uint32_t bpn, uint64_t k,
                                          const uint32_t* order_limbs, uint32_t n_threads) {
  uint64_t order = 0;
  for (uint32_t j = 0; j < n_limbs; j++) order |= (uint64_t)order_limbs[j] << (32 * j);
  run_sliced(
      width, 4096,
      [=](uint64_t s0, uint64_t s1) {
        fold_packed_u64_slice(acc, packed, out, acc_stride, packed_row_stride,
                              packed_batch_stride, n_limbs, bpn, k, order, s0, s1);
      },
      n_threads);
}

// Pack wire-layout uint32 elements into byte-planar planes (ABI 8; the
// staging-ring pack of ops/limbs.py). `wire` points at n elements of
// n_limbs little-endian u32 limbs each (stride n_limbs — callers pass a
// pre-offset pointer to address a column slice of a larger batch); byte
// plane b of the output receives byte b of every element at
// out + b * out_plane_stride. Plane-major loops keep every write
// unit-stride; numpy's byte-granularity gather for the same copy measures
// ~3x a planar transpose, this kernel ~memcpy speed. `n_threads` > 0 pins
// the worker count (the producer thread packs 8 shard slices per batch).
XN_EXPORT void xn_pack_wire_planes(const uint32_t* wire, uint64_t n, uint32_t n_limbs,
                                   uint32_t bpn, uint8_t* out, uint64_t out_plane_stride,
                                   uint32_t n_threads) {
  run_sliced(
      n, 4096,
      [=](uint64_t s0, uint64_t s1) {
        // i-blocked like the fold kernels: the first byte-plane's pass
        // warms the element block into L1, the remaining bpn-1 passes hit
        // cache instead of re-streaming DRAM
        constexpr uint64_t BLOCK = 4096;
        for (uint64_t s = s0; s < s1; s += BLOCK) {
          const uint64_t bn = (s1 - s) < BLOCK ? (s1 - s) : BLOCK;
          for (uint32_t b = 0; b < bpn; b++) {
            const uint32_t* src = wire + s * n_limbs + (b / 4);
            const uint32_t sh = 8u * (b % 4);
            uint8_t* dst = out + (uint64_t)b * out_plane_stride + s;
            for (uint64_t i = 0; i < bn; i++)
              dst[i] = (uint8_t)(src[i * n_limbs] >> sh);
          }
        }
      },
      n_threads);
}

// Planar twin: pack planar uint32[L, n] limb planes (plane stride
// `in_plane_stride` elements) into byte planes — unit-stride reads AND
// writes (the host planar-row staging path).
XN_EXPORT void xn_pack_planar_planes(const uint32_t* planar, uint64_t n,
                                     uint64_t in_plane_stride, uint32_t bpn, uint8_t* out,
                                     uint64_t out_plane_stride, uint32_t n_threads) {
  run_sliced(
      n, 4096,
      [=](uint64_t s0, uint64_t s1) {
        constexpr uint64_t BLOCK = 4096;
        for (uint64_t s = s0; s < s1; s += BLOCK) {
          const uint64_t bn = (s1 - s) < BLOCK ? (s1 - s) : BLOCK;
          for (uint32_t b = 0; b < bpn; b++) {
            const uint32_t* src = planar + (uint64_t)(b / 4) * in_plane_stride + s;
            const uint32_t sh = 8u * (b % 4);
            uint8_t* dst = out + (uint64_t)b * out_plane_stride + s;
            for (uint64_t i = 0; i < bn; i++) dst[i] = (uint8_t)(src[i] >> sh);
          }
        }
      },
      n_threads);
}

// Single-pass batch fold for orders that fit in 64 bits (n_limbs <= 2 —
// every f32/i32 B0-B6 config): fold K planar uint32[L, n] updates plus the
// accumulator in ONE read of the batch, sliced over the element axis across
// fold_threads() workers (the fold is elementwise — no merge step). The
// host analogue of ops/fold_jax.fold_planar_batch, used as a production
// aggregation kernel on CPU where XLA's strided u16 reduction leaves ~10x
// bandwidth unused (reference hot loop analogue:
// rust/xaynet-core/src/mask/masking.rs:292-316).
//
// Layouts: acc/out uint32[L, n] planar (limb-major), stack uint32[K, L, n].
// Requirements: every input element < order; (K+1) * order < 2^64 for
// non-pow2 orders (callers bound K exactly as MAX_LAZY_BATCH does for the
// device fold). order_limbs all zero means order == 2^(32*L): natural
// wraparound, valid for any K.
XN_EXPORT void xn_fold_planar_u64(const uint32_t* acc, const uint32_t* stack, uint32_t* out,
                                  uint64_t n, uint32_t n_limbs, uint64_t k,
                                  const uint32_t* order_limbs) {
  fold_u64_core<false>(acc, stack, out, n, n, n, (uint64_t)n_limbs * n, n_limbs, k,
                       order_limbs, 0);
}

// Strided planar fold over a column slice: acc/out address `width` elements
// per limb plane with `acc_stride` elements between planes (callers pass
// pointers already offset to the slice start), while the staged batch is
// read in place through `stack_row_stride`/`stack_batch_stride` — one
// shard's contiguous plane slice folds straight out of the full staged
// batch with zero slice copies. `n_threads` > 0 pins this call's worker
// count (the per-shard budget when several shard folds run concurrently);
// 0 keeps the process-wide fold_threads() default.
XN_EXPORT void xn_fold_planar_u64_strided(const uint32_t* acc, const uint32_t* stack,
                                          uint32_t* out, uint64_t width, uint64_t acc_stride,
                                          uint64_t stack_row_stride,
                                          uint64_t stack_batch_stride, uint32_t n_limbs,
                                          uint64_t k, const uint32_t* order_limbs,
                                          uint32_t n_threads) {
  fold_u64_core<false>(acc, stack, out, width, acc_stride, stack_row_stride,
                       stack_batch_stride, n_limbs, k, order_limbs, n_threads);
}

// The process-wide fold worker budget (XAYNET_NATIVE_THREADS or the 2x-cores
// default), exported so the Python shard planner can split it into per-shard
// budgets without duplicating the policy.
XN_EXPORT uint32_t xn_fold_threads(void) { return fold_threads(); }

// Wire-layout variant: acc/out uint32[n, L], stack uint32[K, n, L] — the
// layout the coordinator's host aggregation path
// (`Aggregation.aggregate_batch`) already holds, with no transposes.
XN_EXPORT void xn_fold_wire_u64(const uint32_t* acc, const uint32_t* stack, uint32_t* out,
                                uint64_t n, uint32_t n_limbs, uint64_t k,
                                const uint32_t* order_limbs) {
  fold_u64_core<true>(acc, stack, out, n, n, n, n, n_limbs, k, order_limbs, 0);
}

// (a - b) mod order, elementwise (same layout/conventions as xn_mod_add).
XN_EXPORT void xn_mod_sub(const uint32_t* a, const uint32_t* b, uint32_t* out,
                          uint64_t n, uint32_t n_limbs, const uint32_t* order_limbs) {
  for (uint64_t i = 0; i < n; i++) {
    const uint32_t* av = a + i * n_limbs;
    const uint32_t* bv = b + i * n_limbs;
    uint32_t* ov = out + i * n_limbs;
    uint64_t borrow = 0;
    for (uint32_t j = 0; j < n_limbs; j++) {
      uint64_t d = (uint64_t)av[j] - bv[j] - borrow;
      ov[j] = (uint32_t)d;
      borrow = (d >> 63) & 1;
    }
    if (borrow) {
      uint64_t carry = 0;
      for (uint32_t j = 0; j < n_limbs; j++) {
        uint64_t s = (uint64_t)ov[j] + order_limbs[j] + carry;
        ov[j] = (uint32_t)s;
        carry = s >> 32;
      }
    }
  }
}

// Generic n-limb single-pass fold (wire layout): covers every config the
// u64 fast path cannot — f64 families (3-6 limbs) through the 173-byte
// f64/Bmax worst case (44 limbs). One read of the batch: per-limb column
// sums accumulate in u64 (exact for K+1 <= 2^32 terms), then each element
// carry-propagates into an (L+1)-limb value and reduces modulo the order
// with ceil(log2(K+1)) conditional subtracts of order << b — the same
// reduction schedule as the device fold (ops/fold_jax.fold_planar_batch).
//
// Layouts: acc/out uint32[n, L] wire-order, stack uint32[K, n, L].
// Requirements: elements < order; K <= 65535; L <= 63. All-zero
// order_limbs means order == 2^(32L): natural wraparound. Returns 0 on
// success, 1 on a parameter violation.
// PRECONDITION (not checked here, cost would double the single pass):
// every acc/stack element must already be < order — the kbits reduction
// relies on the running value staying < (K+1)*order, so out-of-range
// input silently yields a result >= order. Python callers route inbound
// data through elements_lt_order/is_valid before folding.
XN_EXPORT int xn_fold_wire_nlimb(const uint32_t* acc, const uint32_t* stack, uint32_t* out,
                                 uint64_t n, uint32_t n_limbs, uint64_t k,
                                 const uint32_t* order_limbs) {
  if (n_limbs == 0 || n_limbs > 63 || k > 65535) return 1;
  const uint32_t L = n_limbs;
  int pow2_boundary = 1;
  for (uint32_t l = 0; l < L; l++) pow2_boundary &= (order_limbs[l] == 0);

  // how many conditional-subtract rounds the reduction needs: value < (K+1)*order
  uint32_t kbits = 0;
  while ((1ull << kbits) < k + 1) kbits++;

  // precompute order << b for every reduction round (kbits <= 16, so the
  // shift never crosses a limb boundary by more than one limb)
  std::vector<uint32_t> shifted((kbits + 1) * (L + 1));
  for (uint32_t b = 0; b <= kbits; b++) {
    uint32_t* so = shifted.data() + b * (L + 1);
    const uint32_t limb_off = b >> 5;
    const uint32_t bit_off = b & 31;
    for (uint32_t l = 0; l <= L; l++) {
      uint64_t ol = 0;
      const int src_hi = (int)l - (int)limb_off;
      if (src_hi >= 0 && src_hi < (int)L) ol = ((uint64_t)order_limbs[src_hi] << bit_off) & 0xFFFFFFFFull;
      if (bit_off && src_hi - 1 >= 0 && src_hi - 1 < (int)L)
        ol |= order_limbs[src_hi - 1] >> (32 - bit_off);
      so[l] = (uint32_t)ol;
    }
  }

  // block over elements so each batch row is read as one contiguous
  // stretch (element-at-a-time order would reload every cache line
  // ~elements-per-line times); block sized to keep the u64 column
  // accumulator ~16 KB regardless of L. Element slices are independent, so
  // the blocks fan out over fold_threads() workers (shifted is shared
  // read-only; colbuf/w are per-slice).
  uint64_t block = 2048 / L;
  if (block == 0) block = 1;
  const uint32_t* shifted_ro = shifted.data();
  run_sliced(n, block, [=](uint64_t e0, uint64_t e1) {
    std::vector<uint64_t> colbuf(block * L);
    uint32_t w[64];  // carry-propagated (L+1)-limb value, one element
    for (uint64_t i0 = e0; i0 < e1; i0 += block) {
      const uint64_t bn = (i0 + block <= e1) ? block : e1 - i0;
      uint64_t* col = colbuf.data();
      for (uint64_t j = 0; j < bn * L; j++) col[j] = acc[i0 * L + j];
      for (uint64_t kk = 0; kk < k; kk++) {
        const uint32_t* row = stack + (kk * n + i0) * L;
        for (uint64_t j = 0; j < bn * L; j++) col[j] += row[j];
      }
      for (uint64_t bi = 0; bi < bn; bi++) {
        const uint64_t i = i0 + bi;
        uint64_t carry = 0;
        for (uint32_t l = 0; l < L; l++) {
          const uint64_t t = col[bi * L + l] + carry;
          w[l] = (uint32_t)t;
          carry = t >> 32;
        }
        w[L] = (uint32_t)carry;  // < K+1 <= 2^16
        if (pow2_boundary) {
          for (uint32_t l = 0; l < L; l++) out[i * L + l] = w[l];
          continue;
        }
        // reduce: repeated conditional subtract of the precomputed order << b
        for (int b = (int)kbits; b >= 0; b--) {
          const uint32_t* so = shifted_ro + (uint32_t)b * (L + 1);
          int ge = 1;  // lexicographic w >= (order << b), from the top limb down
          for (int l = (int)L; l >= 0; l--) {
            if (w[l] > so[l]) { ge = 1; break; }
            if (w[l] < so[l]) { ge = 0; break; }
          }
          if (!ge) continue;
          uint64_t borrow = 0;
          for (uint32_t l = 0; l <= L; l++) {
            const uint64_t d = (uint64_t)w[l] - so[l] - borrow;
            w[l] = (uint32_t)d;
            borrow = (d >> 63) & 1;
          }
        }
        for (uint32_t l = 0; l < L; l++) out[i * L + l] = w[l];
      }
    }
  });
  return 0;
}

// --- wire <-> limb codecs --------------------------------------------------
//
// The coordinator ingests every masked update as `count` fixed-width
// little-endian group elements (`bytes_per_number` wide, reference wire
// shape: rust/xaynet-core/src/mask/object/serialization.rs) and the
// participant serializes the masked model back out the same way. The numpy
// strided pad/slice path measures ~370 MB/s parse / ~120 MB/s serialize on
// one core; these single-pass codecs run at memory bandwidth, which matters
// because at 25M params one update is a 150 MB wire payload and parse is on
// the coordinator's per-update critical path.

XN_EXPORT void xn_wire_to_limbs(const uint8_t* buf, uint64_t count, uint32_t bpn,
                                uint32_t n_limbs, uint32_t* out) {
  if (count == 0 || bpn == 0 || n_limbs == 0) return;
  // enough trailing elements decoded bytewise that the fast path's 8-byte
  // load at its last element, (n_fast-1)*bpn + 8, stays inside the
  // count*bpn buffer: n_fast = count + 1 - ceil(8/bpn)
  const uint64_t tail = (8 + bpn - 1) / bpn - 1;
  const uint64_t n_fast = (bpn <= 8 && n_limbs <= 2 && count > tail) ? count - tail : 0;
  if (n_fast) {
    const uint64_t mask = bpn == 8 ? ~0ull : ((1ull << (8 * bpn)) - 1);
    if (n_limbs == 2) {
      for (uint64_t i = 0; i < n_fast; i++) {
        uint64_t v;
        std::memcpy(&v, buf + i * bpn, 8);
        v &= mask;
        out[i * 2] = (uint32_t)v;
        out[i * 2 + 1] = (uint32_t)(v >> 32);
      }
    } else {
      for (uint64_t i = 0; i < n_fast; i++) {
        uint64_t v;
        std::memcpy(&v, buf + i * bpn, 8);
        out[i] = (uint32_t)(v & mask);
      }
    }
  }
  const uint64_t start = n_fast;
  for (uint64_t i = start; i < count; i++) {
    const uint8_t* p = buf + i * bpn;
    for (uint32_t l = 0; l < n_limbs; l++) {
      uint32_t v = 0;
      for (uint32_t b = 0; b < 4; b++) {
        const uint32_t idx = l * 4 + b;
        if (idx < bpn) v |= (uint32_t)p[idx] << (8 * b);
      }
      out[i * n_limbs + l] = v;
    }
  }
}

XN_EXPORT void xn_limbs_to_wire(const uint32_t* limbs, uint64_t count, uint32_t bpn,
                                uint32_t n_limbs, uint8_t* out) {
  if (count == 0 || bpn == 0 || n_limbs == 0) return;
  // write 8 bytes per element: the overhang clobbers the next element's
  // leading bytes, which the next iteration immediately rewrites; the last
  // ceil(8/bpn)-1 elements are written bytewise so the final 8-byte store,
  // (n_fast-1)*bpn + 8, never lands past the count*bpn buffer
  const uint64_t tail = (8 + bpn - 1) / bpn - 1;
  const uint64_t n_fast = (bpn <= 8 && n_limbs <= 2 && count > tail) ? count - tail : 0;
  for (uint64_t i = 0; i < n_fast; i++) {
    uint64_t v = limbs[i * n_limbs];
    if (n_limbs == 2) v |= (uint64_t)limbs[i * 2 + 1] << 32;
    std::memcpy(out + i * bpn, &v, 8);
  }
  const uint64_t start = n_fast;
  for (uint64_t i = start; i < count; i++) {
    uint8_t* p = out + i * bpn;
    for (uint32_t idx = 0; idx < bpn; idx++) {
      p[idx] = (uint8_t)(limbs[i * n_limbs + idx / 4] >> (8 * (idx % 4)));
    }
  }
}

// Count of elements >= order (0 == every element is a valid group member).
// Callers handle the 2^(32L) boundary (all-zero order_limbs) themselves —
// that order admits every representable element.
XN_EXPORT uint64_t xn_count_ge(const uint32_t* limbs, uint64_t count, uint32_t n_limbs,
                               const uint32_t* order_limbs) {
  uint64_t bad = 0;
  for (uint64_t i = 0; i < count; i++) {
    const uint32_t* v = limbs + i * n_limbs;
    int ge = 1;  // equal-so-far counts as >=
    for (int l = (int)n_limbs - 1; l >= 0; l--) {
      if (v[l] > order_limbs[l]) { ge = 1; break; }
      if (v[l] < order_limbs[l]) { ge = 0; break; }
    }
    bad += (uint64_t)ge;
  }
  return bad;
}

XN_EXPORT uint32_t xn_abi_version(void) { return 8; }

// Fixed-point decode: out[i] = ((value_i - C) ) * inv, computed in
// double-double, where value_i is the unmasked group element (wire-layout
// uint32 limbs, n_limbs <= 4 so values fit __int128), C = nb_models *
// add_shift * exp_shift (integer, little-endian bytes), and (inv_hi,
// inv_lo) is the double-double reciprocal of exp_shift * scalar_sum.
// This is the unmask decode hot loop (python fallback: double-double
// numpy in xaynet_tpu/core/mask/encode.py).
XN_EXPORT int xn_decode_f64(const uint32_t* limbs, uint64_t n, uint32_t n_limbs,
                            const uint8_t* c_le, uint32_t c_len, double inv_hi,
                            double inv_lo, double* out) {
  if (n_limbs == 0 || n_limbs > 4 || c_len > 15) return 1;
  __int128 c = 0;
  for (int i = (int)c_len - 1; i >= 0; i--) c = (c << 8) | c_le[i];

  for (uint64_t i = 0; i < n; i++) {
    const uint32_t* v = limbs + i * n_limbs;
    unsigned __int128 val = 0;
    for (int j = (int)n_limbs - 1; j >= 0; j--) val = (val << 32) | v[j];
    __int128 diff = (__int128)val - c;
    // exact double-double of diff (|diff| < 2^127)
    double d_hi = (double)diff;
    double d_lo = (double)(diff - (__int128)d_hi);
    // dd multiply (d_hi, d_lo) * (inv_hi, inv_lo), Dekker two_prod
    double p = d_hi * inv_hi;
    const double split = 134217729.0;  // 2^27 + 1
    double ah = split * d_hi, bh = split * inv_hi;
    ah = ah - (ah - d_hi);
    bh = bh - (bh - inv_hi);
    double al = d_hi - ah, bl = inv_hi - bh;
    double err = ((ah * bh - p) + ah * bl + al * bh) + al * bl;
    err += d_hi * inv_lo + d_lo * inv_hi;
    out[i] = p + err;
  }
  return 0;
}

namespace {

// Dekker double-double helpers (same sequences as xaynet_tpu/ops/dd.py,
// so results are bit-identical to the numpy fast path).
inline void two_sum(double x, double y, double& s, double& err) {
  s = x + y;
  double bb = s - x;
  err = (x - (s - bb)) + (y - bb);
}
inline void quick_two_sum(double x, double y, double& s, double& err) {
  s = x + y;
  err = y - (s - x);
}
inline void two_prod(double x, double y, double& p, double& err) {
  p = x * y;
  const double split = 134217729.0;
  double xh = split * x, yh = split * y;
  xh = xh - (xh - x);
  yh = yh - (yh - y);
  double xl = x - xh, yl = y - yh;
  err = ((xh * yh - p) + xh * yl + xl * yh) + xl * yl;
}

}  // namespace

// Exact-path unmask decode for ANY config family (arbitrary limb width,
// including i64/f64/Bmax where C = nb_models * add_shift * exp_shift can be
// hundreds of bits): out[i] = (value_i - C) * inv. The subtraction is exact
// multi-limb integer arithmetic; the difference (which has no cancellation
// left) is then truncated to its top three 32-bit limbs and multiplied by
// the double-double *normalized mantissa* (inv_hi, inv_lo) of the
// reciprocal of exp_shift * scalar_sum, whose binary exponent `inv_exp` is
// applied by one final ldexp — so reciprocals far outside float64 range
// (BMAX exp_shifts) stay exact. Worst-case relative error ~2^-64 (small
// leading limb), far below the 1/exp_shift protocol tolerance and the f64
// output rounding (reference: rust/xaynet-core/src/mask/masking.rs:190-231).
// Returns nonzero on unsupported widths.
XN_EXPORT int xn_decode_exact(const uint32_t* limbs, uint64_t n, uint32_t n_limbs,
                              const uint32_t* c_limbs, uint32_t c_nlimbs,
                              double inv_hi, double inv_lo, int32_t inv_exp,
                              double* out) {
  constexpr uint32_t MAX_LIMBS = 96;  // catalogue orders cap at 2143 bits = 67 limbs
  if (n_limbs == 0 || n_limbs > MAX_LIMBS || c_nlimbs > MAX_LIMBS) return 1;
  const uint32_t L = (n_limbs > c_nlimbs ? n_limbs : c_nlimbs);
  uint32_t c_ext[MAX_LIMBS];
  for (uint32_t j = 0; j < L; j++) c_ext[j] = (j < c_nlimbs) ? c_limbs[j] : 0;

  // embarrassingly parallel over elements: split across hardware threads for
  // large inputs (the 25M x 67-limb worst case is ~6.6 GB of limb reads)
  auto decode_range = [&](uint64_t i_lo, uint64_t i_hi) {
    for (uint64_t i = i_lo; i < i_hi; i++) {
    const uint32_t* v = limbs + i * n_limbs;
    uint32_t d[MAX_LIMBS];
    uint64_t borrow = 0;
    for (uint32_t j = 0; j < L; j++) {
      uint64_t vj = (j < n_limbs) ? v[j] : 0;
      uint64_t s = vj - c_ext[j] - borrow;
      d[j] = (uint32_t)s;
      borrow = (s >> 63) & 1;
    }
    double sign = 1.0;
    if (borrow) {  // negative: two's-complement negate to the magnitude
      sign = -1.0;
      uint64_t carry = 1;
      for (uint32_t j = 0; j < L; j++) {
        uint64_t s = (uint64_t)(uint32_t)~d[j] + carry;
        d[j] = (uint32_t)s;
        carry = s >> 32;
      }
    }
    // top three limbs -> <= 96-bit chunk, exactly scaled by 2^(32*low)
    int t = (int)L - 1;
    while (t > 0 && d[t] == 0) t--;
    unsigned __int128 chunk = d[t];
    int low = t;
    if (t >= 1) { chunk = (chunk << 32) | d[t - 1]; low = t - 1; }
    if (t >= 2) { chunk = (chunk << 32) | d[t - 2]; low = t - 2; }
    double d_hi = (double)chunk;  // <= 2^96: cast back below cannot overflow
    double d_lo = (double)(__int128)(chunk - (unsigned __int128)d_hi);
    // dd multiply (d_hi, d_lo) * (inv_hi, inv_lo); scale once at the end so
    // neither the limb value nor the reciprocal needs to fit float64 range
    double p, err;
    two_prod(d_hi, inv_hi, p, err);
    err += d_hi * inv_lo + d_lo * inv_hi;
    out[i] = __builtin_ldexp(sign * (p + err), 32 * low + inv_exp);
    }
  };

  const uint64_t work = n * (uint64_t)L;
  unsigned nthreads = std::thread::hardware_concurrency();
  if (nthreads > 16) nthreads = 16;
  if (nthreads < 2 || work < (1u << 22)) {
    decode_range(0, n);
    return 0;
  }
  std::vector<std::thread> pool;
  uint64_t per = (n + nthreads - 1) / nthreads;
  for (unsigned ti = 0; ti < nthreads; ti++) {
    uint64_t lo = ti * per, hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    pool.emplace_back(decode_range, lo, hi);
  }
  for (auto& th : pool) th.join();
  return 0;
}

// Fused participant masking for bounded-f32 configs with orders <= 128 bits:
// per element, draw the next uniform mask value from the seed's keystream
// (rejection sampling, byte-stream compatible with the other samplers),
// fixed-point-encode the weight in double-double (bit-identical to the
// numpy fast path), add modulo the order, and emit the wire-layout element.
// Returns the new keystream byte offset, or 0 on unsupported parameters.
XN_EXPORT uint64_t xn_mask_f32(const uint8_t key_bytes[32], uint64_t byte_offset,
                               const float* weights, uint64_t n,
                               const uint8_t* order_le, uint32_t draw_nbytes,
                               uint32_t elem_nbytes, double a, double e,
                               double s_hi, double s_lo, uint8_t* out) {
  if (draw_nbytes == 0 || draw_nbytes > 16 || elem_nbytes > 16 ||
      elem_nbytes > draw_nbytes)
    return 0;
  uint32_t key[8];
  std::memcpy(key, key_bytes, 32);
  unsigned __int128 order = 0;
  for (int i = (int)draw_nbytes - 1; i >= 0; i--) order = (order << 8) | order_le[i];

  constexpr uint64_t CHUNK_BLOCKS = 1024;
  std::vector<uint8_t> buf(CHUNK_BLOCKS * 64 + 64);
  uint64_t avail = 0, pos = 0;
  uint64_t next_block = byte_offset / 64;
  uint64_t intra = byte_offset % 64;
  if (intra) {
    uint8_t first[64];
    chacha20_block(key, next_block, first);
    next_block++;
    avail = 64 - intra;
    std::memcpy(buf.data(), first + intra, avail);
  }
  uint64_t offset = byte_offset;

  for (uint64_t i = 0; i < n; i++) {
    // 1. next accepted uniform draw below the order
    unsigned __int128 rnd;
    for (;;) {
      if (avail - pos < draw_nbytes) {
        uint64_t tail = avail - pos;
        std::memmove(buf.data(), buf.data() + pos, tail);
        chacha20_fill(key, next_block, CHUNK_BLOCKS, buf.data() + tail);
        next_block += CHUNK_BLOCKS;
        avail = tail + CHUNK_BLOCKS * 64;
        pos = 0;
      }
      const uint8_t* cand = buf.data() + pos;
      pos += draw_nbytes;
      offset += draw_nbytes;
      rnd = load_le16(cand, draw_nbytes);
      if (rnd < order) break;
    }

    // 2. double-double fixed-point encode of the weight
    double w = (double)weights[i];
    double hi, lo;
    two_prod(w, s_hi, hi, lo);
    lo += w * s_lo;
    quick_two_sum(hi, lo, hi, lo);
    if (hi > a || (hi == a && lo > 0)) {
      hi = a;
      lo = 0;
    } else if (hi < -a || (hi == -a && lo < 0)) {
      hi = -a;
      lo = 0;
    }
    double t, terr;
    two_sum(hi, a, t, terr);
    terr += lo;
    quick_two_sum(t, terr, hi, lo);
    double p, perr;
    two_prod(hi, e, p, perr);
    perr += lo * e;
    quick_two_sum(p, perr, hi, lo);
    double f = __builtin_floor(hi);
    f += __builtin_floor((hi - f) + lo);
    long long shifted = (long long)f;
    if (shifted < 0) shifted = 0;

    // 3. modular add + wire emit (little-endian fixed width)
    unsigned __int128 masked = rnd + (unsigned __int128)shifted;
    if (masked >= order) masked -= order;
    uint8_t* dst = out + i * elem_nbytes;
    for (uint32_t j = 0; j < elem_nbytes; j++) {
      dst[j] = (uint8_t)(masked & 0xff);
      masked >>= 8;
    }
  }
  return offset;
}
