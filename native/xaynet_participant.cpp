// Interpreter-free embeddable PET participant (C ABI, no Python).
//
// Native analogue of the reference's xaynet-mobile crate
// (reference: rust/xaynet-mobile/src/participant.rs:129-353 tick-driven
// Participant, src/ffi/ C API): a caller-driven state machine owning the
// full client protocol — task signatures + exact eligibility, ephemeral
// keys, fused masking, seed-dict sealing, sum2 mask derivation/aggregation,
// multipart chunking with chunk-level send retry, save/restore — linked
// against libsodium for Ed25519/X25519/ChaCha20-Poly1305 (the reference
// links the same library through sodiumoxide).
//
// Transport is a callback (bundled HTTP client: xaynet_http_transport.c;
// or caller-provided — one callback receiving "GET /params",
// "POST /message", ... and returning the response bytes), which keeps the
// library free of any network stack — the right shape for constrained
// edge targets; the embedding app brings its own HTTP/TLS.
//
// Wire format parity: 136-byte signed header, Sum/Update/Sum2/Chunk
// payload layouts, 4-byte mask configs, LV seed dicts — all matching
// xaynet_tpu/core/message/* byte for byte (tested cross-language).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <string>
#include <vector>

#include "xaynet_orders.h"

#define XN_EXPORT extern "C" __attribute__((visibility("default")))

// --------------------------------------------------------------------------
// libsodium prototypes (stable C ABI; linked against libsodium.so)
// --------------------------------------------------------------------------

extern "C" {
int sodium_init(void);
void randombytes_buf(void* buf, size_t size);
int crypto_sign_seed_keypair(unsigned char* pk, unsigned char* sk, const unsigned char* seed);
int crypto_sign_detached(unsigned char* sig, unsigned long long* siglen,
                         const unsigned char* m, unsigned long long mlen,
                         const unsigned char* sk);
int crypto_scalarmult_base(unsigned char* q, const unsigned char* n);
int crypto_scalarmult(unsigned char* q, const unsigned char* n, const unsigned char* p);
int crypto_hash_sha256(unsigned char* out, const unsigned char* in, unsigned long long inlen);
typedef struct {
  unsigned char opaque[208];
} xn_hmacsha256_state;
int crypto_auth_hmacsha256_init(xn_hmacsha256_state* state, const unsigned char* key,
                                size_t keylen);
int crypto_auth_hmacsha256_update(xn_hmacsha256_state* state, const unsigned char* in,
                                  unsigned long long inlen);
int crypto_auth_hmacsha256_final(xn_hmacsha256_state* state, unsigned char* out);
int crypto_aead_chacha20poly1305_ietf_encrypt(unsigned char* c, unsigned long long* clen,
                                              const unsigned char* m, unsigned long long mlen,
                                              const unsigned char* ad, unsigned long long adlen,
                                              const unsigned char* nsec, const unsigned char* npub,
                                              const unsigned char* k);
int crypto_aead_chacha20poly1305_ietf_decrypt(unsigned char* m, unsigned long long* mlen,
                                              unsigned char* nsec, const unsigned char* c,
                                              unsigned long long clen, const unsigned char* ad,
                                              unsigned long long adlen, const unsigned char* npub,
                                              const unsigned char* k);
// from xaynet_native.cpp (same shared library)
uint64_t xn_sample_uniform(const uint8_t key_bytes[32], uint64_t byte_offset, uint64_t count,
                           const uint8_t* order_le, uint32_t order_nbytes, uint8_t* out);
uint64_t xn_mask_f32(const uint8_t key_bytes[32], uint64_t byte_offset, const float* weights,
                     uint64_t n, const uint8_t* order_le, uint32_t draw_nbytes,
                     uint32_t elem_nbytes, double a, double e, double s_hi, double s_lo,
                     uint8_t* out);
}

namespace {

using bytes = std::vector<uint8_t>;

// --------------------------------------------------------------------------
// sealed box (format parity with xaynet_tpu/core/crypto/encrypt.py:
// eph_pk(32) || ChaCha20Poly1305(msg), key = HKDF-SHA256(X25519 shared,
// info = "xaynet-tpu-sealedbox" || eph_pk || recipient_pk), zero nonce)
// --------------------------------------------------------------------------

const char kSealInfo[] = "xaynet-tpu-sealedbox";
const unsigned char kZeroNonce[12] = {0};

void hkdf_sha256(const uint8_t* ikm, size_t ikm_len, const uint8_t* info, size_t info_len,
                 uint8_t out[32]) {
  // extract with a zero salt of hash length, then one expand block
  uint8_t zero_salt[32] = {0};
  xn_hmacsha256_state st;
  uint8_t prk[32];
  crypto_auth_hmacsha256_init(&st, zero_salt, 32);
  crypto_auth_hmacsha256_update(&st, ikm, ikm_len);
  crypto_auth_hmacsha256_final(&st, prk);
  uint8_t one = 1;
  crypto_auth_hmacsha256_init(&st, prk, 32);
  crypto_auth_hmacsha256_update(&st, info, info_len);
  crypto_auth_hmacsha256_update(&st, &one, 1);
  crypto_auth_hmacsha256_final(&st, out);
}

void seal_key(const uint8_t shared[32], const uint8_t eph_pk[32], const uint8_t recipient_pk[32],
              uint8_t key[32]) {
  bytes info(sizeof(kSealInfo) - 1 + 64);
  std::memcpy(info.data(), kSealInfo, sizeof(kSealInfo) - 1);
  std::memcpy(info.data() + sizeof(kSealInfo) - 1, eph_pk, 32);
  std::memcpy(info.data() + sizeof(kSealInfo) - 1 + 32, recipient_pk, 32);
  hkdf_sha256(shared, 32, info.data(), info.size(), key);
}

bool seal(const uint8_t* msg, size_t len, const uint8_t recipient_pk[32], bytes& out) {
  uint8_t eph_sk[32], eph_pk[32], shared[32], key[32];
  randombytes_buf(eph_sk, 32);
  crypto_scalarmult_base(eph_pk, eph_sk);
  if (crypto_scalarmult(shared, eph_sk, recipient_pk) != 0) return false;
  seal_key(shared, eph_pk, recipient_pk, key);
  out.resize(32 + len + 16);
  std::memcpy(out.data(), eph_pk, 32);
  unsigned long long clen = 0;
  crypto_aead_chacha20poly1305_ietf_encrypt(out.data() + 32, &clen, msg, len, nullptr, 0, nullptr,
                                            kZeroNonce, key);
  out.resize(32 + clen);
  return true;
}

bool seal_open(const uint8_t* sealed, size_t len, const uint8_t my_sk[32],
               const uint8_t my_pk[32], bytes& out) {
  if (len < 48) return false;
  uint8_t shared[32], key[32];
  if (crypto_scalarmult(shared, my_sk, sealed) != 0) return false;
  seal_key(shared, sealed, my_pk, key);
  out.resize(len - 48);
  unsigned long long mlen = 0;
  if (crypto_aead_chacha20poly1305_ietf_decrypt(out.data(), &mlen, nullptr, sealed + 32, len - 32,
                                                nullptr, 0, kZeroNonce, key) != 0)
    return false;
  out.resize(mlen);
  return true;
}

// --------------------------------------------------------------------------
// exact eligibility: int_le(sha256(sig)) / (2^256 - 1) <= threshold
// (reference semantics: sign.rs:186-202; exact rational comparison)
// --------------------------------------------------------------------------

// compare n * 2^(53-e) <= m53 * (2^256 - 1) over little-endian u32 bignums
bool is_eligible(const uint8_t sig[64], double threshold) {
  if (threshold <= 0.0) {
    if (threshold < 0.0) return false;
    // threshold == 0: only the all-zero hash qualifies
  }
  if (threshold >= 1.0) return true;
  uint8_t h[32];
  crypto_hash_sha256(h, sig, 64);

  int e;
  double m = std::frexp(threshold, &e);  // threshold = m * 2^e, m in [0.5, 1)
  uint64_t m53 = (uint64_t)std::ldexp(m, 53);  // exact 53-bit integer

  // lhs = h (256 bits) shifted left by (53 - e) bits
  int shift = 53 - e;  // e <= 0 for threshold < 1, so shift >= 53
  std::vector<uint64_t> lhs(4 + shift / 64 + 2, 0);
  for (int i = 0; i < 4; i++) {
    uint64_t w;
    std::memcpy(&w, h + i * 8, 8);  // little-endian words
    int word = shift / 64, bit = shift % 64;
    lhs[i + word] |= w << bit;
    if (bit) lhs[i + word + 1] |= w >> (64 - bit);
  }
  // rhs = m53 * (2^256 - 1) = (m53 << 256) - m53
  std::vector<uint64_t> rhs(lhs.size(), 0);
  if (rhs.size() < 6) rhs.resize(6, 0);
  rhs[4] = m53;  // m53 << 256
  // subtract m53 with borrow
  uint64_t borrow = m53;
  for (size_t i = 0; i < rhs.size() && borrow; i++) {
    uint64_t before = rhs[i];
    rhs[i] = before - borrow;
    borrow = before < borrow ? 1 : 0;
  }
  if (lhs.size() < rhs.size()) lhs.resize(rhs.size(), 0);
  if (rhs.size() < lhs.size()) rhs.resize(lhs.size(), 0);
  for (int i = (int)lhs.size() - 1; i >= 0; i--) {
    if (lhs[i] < rhs[i]) return true;
    if (lhs[i] > rhs[i]) return false;
  }
  return true;  // equal
}

// --------------------------------------------------------------------------
// mask config catalogue lookup
// --------------------------------------------------------------------------

// --------------------------------------------------------------------------
// minimal unsigned bignum (little-endian u64 limbs) — only what the Bmax
// float encode needs: x*u64, +, -, <<, >>, compare, divmod by u64
// --------------------------------------------------------------------------

using BigU = std::vector<uint64_t>;

void bu_trim(BigU& a) {
  while (a.size() > 1 && a.back() == 0) a.pop_back();
}

BigU bu_from_u128(unsigned __int128 v) {
  BigU out{(uint64_t)v, (uint64_t)(v >> 64)};
  bu_trim(out);
  return out;
}

BigU bu_mul_u64(const BigU& a, uint64_t f) {
  BigU out(a.size() + 1, 0);
  unsigned __int128 carry = 0;
  for (size_t i = 0; i < a.size(); i++) {
    unsigned __int128 p = (unsigned __int128)a[i] * f + carry;
    out[i] = (uint64_t)p;
    carry = p >> 64;
  }
  out[a.size()] = (uint64_t)carry;
  bu_trim(out);
  return out;
}

BigU bu_add(const BigU& a, const BigU& b) {
  BigU out(std::max(a.size(), b.size()) + 1, 0);
  unsigned __int128 carry = 0;
  for (size_t i = 0; i < out.size(); i++) {
    unsigned __int128 s = carry;
    if (i < a.size()) s += a[i];
    if (i < b.size()) s += b[i];
    out[i] = (uint64_t)s;
    carry = s >> 64;
  }
  bu_trim(out);
  return out;
}

// a - b, requires a >= b
BigU bu_sub(const BigU& a, const BigU& b) {
  BigU out(a.size(), 0);
  unsigned __int128 borrow = 0;
  for (size_t i = 0; i < a.size(); i++) {
    unsigned __int128 d = (unsigned __int128)a[i] - (i < b.size() ? b[i] : 0) - borrow;
    out[i] = (uint64_t)d;
    borrow = (d >> 127) & 1;
  }
  bu_trim(out);
  return out;
}

int bu_cmp(const BigU& a, const BigU& b) {
  size_t n = std::max(a.size(), b.size());
  for (size_t i = n; i-- > 0;) {
    uint64_t av = i < a.size() ? a[i] : 0;
    uint64_t bv = i < b.size() ? b[i] : 0;
    if (av != bv) return av < bv ? -1 : 1;
  }
  return 0;
}

bool bu_is_zero(const BigU& a) { return a.size() == 1 && a[0] == 0; }

BigU bu_shl(const BigU& a, unsigned bits) {
  unsigned limbs = bits / 64, rem = bits % 64;
  BigU out(a.size() + limbs + 1, 0);
  for (size_t i = 0; i < a.size(); i++) {
    out[i + limbs] |= rem ? (a[i] << rem) : a[i];
    if (rem) out[i + limbs + 1] |= a[i] >> (64 - rem);
  }
  bu_trim(out);
  return out;
}

BigU bu_shr(const BigU& a, unsigned bits) {  // floor shift
  unsigned limbs = bits / 64, rem = bits % 64;
  if (limbs >= a.size()) return BigU{0};
  BigU out(a.size() - limbs, 0);
  for (size_t i = 0; i < out.size(); i++) {
    out[i] = rem ? (a[i + limbs] >> rem) : a[i + limbs];
    if (rem && i + limbs + 1 < a.size()) out[i] |= a[i + limbs + 1] << (64 - rem);
  }
  bu_trim(out);
  return out;
}

// floor(a / d) for u64 d (d < 2^63 here)
BigU bu_div_u64(const BigU& a, uint64_t d) {
  BigU out(a.size(), 0);
  unsigned __int128 r = 0;
  for (size_t i = a.size(); i-- > 0;) {
    r = (r << 64) | a[i];
    out[i] = (uint64_t)(r / d);
    r %= d;
  }
  bu_trim(out);
  return out;
}

void bu_write_le(const BigU& a, uint8_t* out, uint32_t nbytes) {
  std::memset(out, 0, nbytes);
  for (uint32_t i = 0; i < nbytes; i++) {
    size_t limb = i / 8;
    if (limb >= a.size()) break;
    out[i] = (uint8_t)(a[limb] >> (8 * (i % 8)));
  }
}

BigU bu_pow10(unsigned k) {
  BigU out{1};
  for (unsigned i = 0; i < k; i++) out = bu_mul_u64(out, 10);
  return out;
}

// A/E/A*E for the Bmax float families, computed once per process
// (A = f32max = 2^104*(2^24-1) with E = 10^45, or A = f64max =
// 2^971*(2^53-1) with E = 10^324)
struct BmaxConsts {
  BigU a, e, ae;
};

const BmaxConsts& bmax_consts(bool is_f64) {
  static const BmaxConsts f32c{
      bu_shl(bu_from_u128((1u << 24) - 1), 104),
      bu_pow10(45),
      bu_shl(bu_mul_u64(bu_pow10(45), (1u << 24) - 1), 104),
  };
  static const BmaxConsts f64c{
      bu_shl(bu_from_u128((1ull << 53) - 1), 971),
      bu_pow10(324),
      bu_shl(bu_mul_u64(bu_pow10(324), (1ull << 53) - 1), 971),
  };
  return is_f64 ? f64c : f32c;
}

// Exact Bmax float encode: shifted = floor((clamp(num/den * w, -A, A) + A)*E)
// over arbitrary-width A/E (f32max*10^45 or f64max*10^324). All arithmetic
// exact. Non-finite weights clamp to the bound (the Python stack rejects
// them before masking; an embedded device gets the defensive clamp).
BigU encode_bmax_exact(double w, int64_t num, int64_t den, const BigU& A, const BigU& E,
                       const BigU& AE) {
  if (!(w == w) || num == 0 || w == 0.0) return AE;
  const bool negative = w < 0.0;
  if (std::isinf(w)) return negative ? BigU{0} : bu_add(AE, AE);
  double aw = negative ? -w : w;
  int e2;
  double frac = std::frexp(aw, &e2);
  uint64_t m = (uint64_t)std::ldexp(frac, 53);  // aw = m * 2^e, exact
  int e = e2 - 53;

  // clamp test: num*m*2^e >= A*den ?
  const unsigned __int128 nm128 = (unsigned __int128)m * (uint64_t)num;
  BigU lhs = bu_from_u128(nm128);
  if (e > 0) lhs = bu_shl(lhs, (unsigned)e);
  BigU rhs = bu_mul_u64(A, (uint64_t)den);
  if (e < 0) rhs = bu_shl(rhs, (unsigned)-e);
  if (bu_cmp(lhs, rhs) >= 0) {
    return negative ? BigU{0} : bu_add(AE, AE);  // clamped at -A / +A
  }

  // X = E * (num*m) [* 2^e when e > 0]
  BigU X = bu_mul_u64(E, (uint64_t)nm128);
  uint64_t nm_hi = (uint64_t)(nm128 >> 64);
  if (nm_hi) X = bu_add(X, bu_shl(bu_mul_u64(E, nm_hi), 64));
  if (e > 0) X = bu_shl(X, (unsigned)e);
  if (negative && !bu_is_zero(X)) X = bu_sub(X, BigU{1});  // ceil = floor(X-1)+1
  BigU q = bu_div_u64(X, (uint64_t)den);
  if (e < 0) q = bu_shr(q, (unsigned)-e);

  if (negative) {
    q = bu_add(q, BigU{1});               // ceil(|c|*E)
    if (bu_cmp(q, AE) >= 0) return BigU{0};
    return bu_sub(AE, q);
  }
  return bu_add(AE, q);
}


struct MaskCfg {
  uint8_t raw[4];  // group, data, bound, model (wire bytes)
  const uint8_t* order_le = nullptr;
  uint32_t order_nbytes = 0;   // byte length of the order itself
  uint32_t elem_nbytes = 0;    // bytes_per_number = byte length of order-1
  double add_shift = 0.0;      // valid for the f32 bounded fast path
  double exp_shift = 0.0;
  bool fast_f32 = false;       // f32 data, bounded, order <= 16 bytes
  // exact shifts — valid for f32/f64 bounded and i32/i64 (any bound):
  // E = 10^20 for f64, 10^10 otherwise; A <= 2^63
  bool exact_ae = false;
  unsigned __int128 a_int = 0;
  unsigned __int128 e_int = 0;
  // Bmax float configs: arbitrary-width A/E/A*E (f32max*10^45, f64max*10^324)
  bool bmax_float = false;
  BigU big_a, big_e, big_ae;
};

bool lookup_cfg(const uint8_t raw[4], MaskCfg& cfg) {
  for (int i = 0; i < XN_N_ORDERS; i++) {
    const XnOrderEntry& e = XN_ORDERS[i];
    if (e.group == raw[0] && e.data == raw[1] && e.bound == raw[2] && e.model == raw[3]) {
      std::memcpy(cfg.raw, raw, 4);
      cfg.order_le = e.bytes;
      cfg.order_nbytes = e.nbytes;
      // bytes_per_number = byte length of (order - 1); differs from the
      // order's own length only when the order is 2^(8k)
      uint32_t n = e.nbytes;
      bool pow2_at_boundary = e.bytes[n - 1] == 1;
      for (uint32_t j = 0; j + 1 < n && pow2_at_boundary; j++)
        if (e.bytes[j] != 0) pow2_at_boundary = false;
      cfg.elem_nbytes = pow2_at_boundary ? n - 1 : n;
      // bound wire values: B0=0, B2=2, B4=4, B6=6, BMAX=255
      const bool bmax = raw[2] == 255;
      cfg.fast_f32 = raw[1] == 0 && !bmax && e.nbytes <= 16;
      if (!bmax) {  // bounded: A = 10^bound
        unsigned long long a = 1;
        for (uint8_t d = 0; d < raw[2]; d++) a *= 10;
        cfg.a_int = a;
        cfg.exact_ae = true;
      } else if (raw[1] == 2) {  // i32 Bmax: A = 2^31
        cfg.a_int = 1ull << 31;
        cfg.exact_ae = true;
      } else if (raw[1] == 3) {  // i64 Bmax: A = 2^63
        cfg.a_int = (unsigned __int128)1 << 63;
        cfg.exact_ae = true;
      }
      // E = 10^20 for f64, 10^10 otherwise; Bmax FLOAT configs use the
      // arbitrary-width bignum path (A = f32max/f64max, E = 10^45/10^324)
      if (bmax && (raw[1] == 0 || raw[1] == 1)) {  // float Bmax families
        cfg.bmax_float = true;
        const BmaxConsts& c = bmax_consts(raw[1] == 1);
        cfg.big_a = c.a;
        cfg.big_e = c.e;
        cfg.big_ae = c.ae;
        cfg.exact_ae = false;
      }
      cfg.e_int = raw[1] == 1
                      ? (unsigned __int128)10000000000ull * 10000000000ull
                      : (unsigned __int128)10000000000ull;
      if (cfg.fast_f32) {
        cfg.add_shift = (double)(unsigned long long)cfg.a_int;
        cfg.exp_shift = 1e10;
      }
      return true;
    }
  }
  return false;
}

// (a + b) mod order over fixed-width little-endian byte strings
void add_mod_le(uint8_t* a, const uint8_t* b, const uint8_t* order_le, uint32_t order_nbytes,
                uint32_t width) {
  unsigned carry = 0;
  for (uint32_t i = 0; i < width; i++) {
    unsigned s = a[i] + b[i] + carry;
    a[i] = (uint8_t)s;
    carry = s >> 8;
  }
  // compare against the order (order may be wider than width by 1 for
  // powers of two at a byte boundary — then the sum < order always)
  bool ge = carry != 0;
  if (!ge && order_nbytes <= width) {
    ge = true;
    for (int i = (int)width - 1; i >= 0; i--) {
      uint8_t o = i < (int)order_nbytes ? order_le[i] : 0;
      if (a[i] != o) {
        ge = a[i] > o;
        break;
      }
    }
  }
  if (ge && order_nbytes <= width) {
    unsigned borrow = 0;
    for (uint32_t i = 0; i < width; i++) {
      uint8_t o = i < order_nbytes ? order_le[i] : 0;
      int d = (int)a[i] - (int)o - (int)borrow;
      borrow = d < 0;
      a[i] = (uint8_t)(d & 0xff);
    }
  }
}

// Exact f64 fixed-point encode for bounded configs:
//   shifted = floor((clamp(num/den * w, -A, A) + A) * E)
// computed without rounding: w = m * 2^e exactly (53-bit mantissa), the
// numerator num*m*E spans up to ~2^185 and is handled as 3 base-2^64 limbs
// with long division by den and an exact right-shift. Preconditions:
// 0 <= num <= 2^31-1, 1 <= den <= 2^31-1, A <= 10^6, E <= 10^20, w finite.
unsigned __int128 encode_f64_exact(double w, int64_t num, int64_t den,
                                   unsigned long long A, unsigned __int128 E) {
  const unsigned __int128 AE = (unsigned __int128)A * E;
  if (!(w == w) || num == 0 || w == 0.0) return AE;  // NaN/zero scalar/zero
  const bool negative = w < 0.0;
  double aw = negative ? -w : w;
  int e2;
  double frac = std::frexp(aw, &e2);            // aw = frac * 2^e2, frac in [0.5, 1)
  uint64_t m = (uint64_t)std::ldexp(frac, 53);  // exact 53-bit integer
  int e = e2 - 53;                              // aw = m * 2^e
  if (e >= 0) {
    // m >= 2^52 while A*den < 2^52: |num*w| >= A, fully clamped
    return negative ? 0 : 2 * AE;
  }
  const int k = -e;  // k >= 1

  // early clamp test (also guards the shift math below from overflow):
  // |c| >= A  <=>  num*m >= A*den*2^k  <=>  (num*m) >> k >= A*den
  const unsigned __int128 nm = (unsigned __int128)m * (uint64_t)num;  // <= 2^84
  const unsigned __int128 ad = (unsigned __int128)A * (uint64_t)den;  // <= 2^51
  if ((k < 128 ? (nm >> k) : (unsigned __int128)0) >= ad) {
    return negative ? 0 : 2 * AE;
  }
  // from here |c| < A, so the result c*E < A*E <= 2^87 fits comfortably

  // X = num * (m * E) as limbs x2:x1:x0 (m*E <= 2^120 fits u128)
  const unsigned __int128 mE = (unsigned __int128)m * E;
  const unsigned __int128 p0 = (unsigned __int128)(uint64_t)mE * (uint64_t)num;
  const unsigned __int128 p1 = (unsigned __int128)(uint64_t)(mE >> 64) * (uint64_t)num;
  uint64_t x0 = (uint64_t)p0;
  const unsigned __int128 mid = (p0 >> 64) + p1;
  uint64_t x1 = (uint64_t)mid;
  uint64_t x2 = (uint64_t)(mid >> 64);
  if (negative) {
    // ceil(X/D) = floor((X-1)/D) + 1 for X >= 1 (X >= m*E*num >= 1 here)
    if (x0 == 0) {
      x0 = ~0ull;
      if (x1 == 0) {
        x1 = ~0ull;
        x2 -= 1;
      } else {
        x1 -= 1;
      }
    } else {
      x0 -= 1;
    }
  }

  // floor(X / den): 192/31-bit long division (each quotient digit < 2^64
  // because the running remainder stays < den)
  const uint64_t d = (uint64_t)den;
  unsigned __int128 r = x2;
  const uint64_t q2 = (uint64_t)(r / d);
  r %= d;
  r = (r << 64) | x1;
  const uint64_t q1 = (uint64_t)(r / d);
  r %= d;
  r = (r << 64) | x0;
  const uint64_t q0 = (uint64_t)(r / d);

  // Q >> k (Q = q2:q1:q0); the result fits u128 by the clamp guard above
  unsigned __int128 shifted;
  if (k >= 192) {
    shifted = 0;
  } else if (k >= 128) {
    shifted = (unsigned __int128)q2 >> (k - 128);
  } else if (k >= 64) {
    shifted = (((unsigned __int128)q2 << 64) | q1) >> (k - 64);
  } else {
    shifted = ((((unsigned __int128)q2 << 64) | q1) << (64 - k)) | (q0 >> k);
  }

  if (negative) {
    const unsigned __int128 ceil_val = shifted + 1;  // ceil(|c|*E)
    return ceil_val >= AE ? 0 : AE - ceil_val;
  }
  return shifted >= AE ? 2 * AE : AE + shifted;
}

// --------------------------------------------------------------------------
// minimal JSON field extraction (our own coordinator's fixed schemas)
// --------------------------------------------------------------------------

bool json_find(const std::string& body, const char* key, size_t& val_start) {
  std::string needle = std::string("\"") + key + "\"";
  size_t p = body.find(needle);
  if (p == std::string::npos) return false;
  p = body.find(':', p + needle.size());
  if (p == std::string::npos) return false;
  p++;
  while (p < body.size() && (body[p] == ' ' || body[p] == '\t')) p++;
  val_start = p;
  return true;
}

bool json_string(const std::string& body, const char* key, std::string& out) {
  size_t p;
  if (!json_find(body, key, p) || body[p] != '"') return false;
  size_t end = body.find('"', p + 1);
  if (end == std::string::npos) return false;
  out = body.substr(p + 1, end - p - 1);
  return true;
}

bool json_number(const std::string& body, const char* key, double& out) {
  size_t p;
  if (!json_find(body, key, p)) return false;
  out = std::strtod(body.c_str() + p, nullptr);
  return true;
}

// "key": [1, 2, 3, 4] -> 4 bytes
bool json_byte4(const std::string& body, const char* key, uint8_t out[4]) {
  size_t p;
  if (!json_find(body, key, p) || body[p] != '[') return false;
  const char* s = body.c_str() + p + 1;
  for (int i = 0; i < 4; i++) {
    char* end;
    long v = std::strtol(s, &end, 10);
    if (end == s || v < 0 || v > 255) return false;
    out[i] = (uint8_t)v;
    s = end;
    while (*s == ',' || *s == ' ') s++;
  }
  return true;
}

int hex_nibble(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

bool hex_decode(const std::string& hex, bytes& out) {
  if (hex.size() % 2) return false;
  out.resize(hex.size() / 2);
  for (size_t i = 0; i < out.size(); i++) {
    int hi = hex_nibble(hex[2 * i]), lo = hex_nibble(hex[2 * i + 1]);
    if (hi < 0 || lo < 0) return false;
    out[i] = (uint8_t)((hi << 4) | lo);
  }
  return true;
}

std::string hex_encode(const uint8_t* data, size_t len) {
  static const char* d = "0123456789abcdef";
  std::string out(len * 2, '0');
  for (size_t i = 0; i < len; i++) {
    out[2 * i] = d[data[i] >> 4];
    out[2 * i + 1] = d[data[i] & 0xf];
  }
  return out;
}

// iterate a flat {"hex": "hex", ...} object
bool json_hex_map(const std::string& body, std::vector<std::pair<bytes, bytes>>& out) {
  size_t p = body.find('{');
  if (p == std::string::npos) return false;
  p++;
  while (true) {
    size_t k0 = body.find('"', p);
    if (k0 == std::string::npos) return true;
    size_t k1 = body.find('"', k0 + 1);
    size_t c = body.find(':', k1);
    size_t v0 = body.find('"', c);
    size_t v1 = body.find('"', v0 + 1);
    if (k1 == std::string::npos || c == std::string::npos || v0 == std::string::npos ||
        v1 == std::string::npos)
      return false;
    bytes k, v;
    if (!hex_decode(body.substr(k0 + 1, k1 - k0 - 1), k)) return false;
    if (!hex_decode(body.substr(v0 + 1, v1 - v0 - 1), v)) return false;
    out.emplace_back(std::move(k), std::move(v));
    p = v1 + 1;
  }
}

// --------------------------------------------------------------------------
// wire building (parity: xaynet_tpu/core/message/{message,payloads}.py)
// --------------------------------------------------------------------------

constexpr size_t kHeader = 136;
constexpr uint8_t kTagSum = 1, kTagUpdate = 2, kTagSum2 = 3;
constexpr uint8_t kFlagMultipart = 1;

void put_u32be(uint8_t* p, uint32_t v) {
  p[0] = v >> 24;
  p[1] = v >> 16;
  p[2] = v >> 8;
  p[3] = v;
}

void put_u16be(uint8_t* p, uint16_t v) {
  p[0] = v >> 8;
  p[1] = v;
}

bytes build_message(const uint8_t sk64[64], const uint8_t pk[32], const uint8_t coord_pk[32],
                    uint8_t tag, bool multipart, const bytes& payload) {
  bytes out(kHeader + payload.size());
  std::memcpy(out.data() + 64, pk, 32);
  std::memcpy(out.data() + 96, coord_pk, 32);
  put_u32be(out.data() + 128, (uint32_t)out.size());
  out[132] = tag;
  out[133] = multipart ? kFlagMultipart : 0;
  std::memcpy(out.data() + kHeader, payload.data(), payload.size());
  crypto_sign_detached(out.data(), nullptr, out.data() + 64, out.size() - 64, sk64);
  return out;
}

// split a payload into signed chunk messages when oversized; every part is
// sealed for the coordinator (the send queue holds ready-to-POST bodies).
// Returns false (queueing nothing) if sealing fails — e.g. an invalid
// coordinator public key — so callers surface an error instead of
// advancing as if the message was delivered.
bool encode_and_seal(const uint8_t sk64[64], const uint8_t pk[32], const uint8_t coord_pk[32],
                     uint8_t tag, const bytes& payload, uint32_t max_message_size,
                     std::deque<bytes>& queue) {
  if (max_message_size == 0 || kHeader + payload.size() <= max_message_size) {
    bytes msg = build_message(sk64, pk, coord_pk, tag, false, payload);
    bytes sealed;
    if (!seal(msg.data(), msg.size(), coord_pk, sealed)) return false;
    queue.push_back(std::move(sealed));
    return true;
  }
  size_t budget = max_message_size > kHeader + 8 + 1 ? max_message_size - kHeader - 8 : 1;
  uint16_t message_id;
  randombytes_buf(&message_id, 2);
  size_t n_chunks = (payload.size() + budget - 1) / budget;
  for (size_t i = 0; i < n_chunks; i++) {
    size_t lo = i * budget;
    size_t hi = lo + budget < payload.size() ? lo + budget : payload.size();
    bytes chunk(8 + (hi - lo));
    put_u16be(chunk.data(), (uint16_t)(i + 1));
    put_u16be(chunk.data() + 2, message_id);
    chunk[4] = i + 1 == n_chunks ? 1 : 0;  // LAST_CHUNK
    std::memcpy(chunk.data() + 8, payload.data() + lo, hi - lo);
    bytes msg = build_message(sk64, pk, coord_pk, tag, true, chunk);
    bytes sealed;
    if (!seal(msg.data(), msg.size(), coord_pk, sealed)) {
      queue.clear();  // all-or-nothing: no partial multipart queue
      return false;
    }
    queue.push_back(std::move(sealed));
  }
  return true;
}

}  // namespace

// --------------------------------------------------------------------------
// C API surface
// --------------------------------------------------------------------------

// transport callback contract + exported prototypes live in the shared
// header (single source of truth for the C ABI)
#include "xaynet_participant.h"

namespace {

struct RoundParams {
  bytes coord_pk;  // 32
  double sum_prob = 0.0, update_prob = 0.0;
  bytes seed;  // 32
  uint8_t cfg_vect[4] = {0}, cfg_unit[4] = {0};
  uint64_t model_length = 0;
  std::string raw;  // raw body for freshness comparison + save/restore
};

enum class Phase { Awaiting, NewRound, Sum, Update, Sum2 };

struct Participant {
  // identity & settings
  uint8_t sign_seed[32];
  uint8_t sign_pk[32];
  uint8_t sign_sk64[64];
  int64_t scalar_num = 1;
  int64_t scalar_den = 1;
  uint32_t max_message_size = 4096;
  xn_transport_fn transport = nullptr;
  void* transport_user = nullptr;

  // round state
  Phase phase = Phase::Awaiting;
  RoundParams params;
  bool have_params = false;
  uint8_t sum_sig[64] = {0};
  uint8_t update_sig[64] = {0};
  bool have_ephm = false;
  uint8_t ephm_sk[32] = {0};
  uint8_t ephm_pk[32] = {0};
  std::deque<bytes> pending;  // sealed parts not yet delivered (O(1) pops)
  Phase after_send = Phase::Awaiting;

  // embedder interaction
  std::vector<float> model;
  std::vector<int64_t> model_i;  // integer data types (i32/i64 configs)
  std::vector<double> model_d;   // f64 configs (exact 192-bit encode)
  bool model_set = false;
  bool model_i_set = false;
  bool model_d_set = false;
  bool wants_model = false;
  bool made_progress = false;
  bool new_round_flag = false;
  std::vector<double> global_model;

  int fetch(const char* request, const uint8_t* body, uint64_t body_len, bytes& out) const {
    if (!transport) return XN_ERR_TRANSPORT;
    XnBuffer buf{nullptr, 0};
    int rc = transport(transport_user, request, body, body_len, &buf);
    if (rc < 0) return XN_ERR_TRANSPORT;
    if (rc == 0 && buf.data) {
      out.assign(buf.data, buf.data + buf.len);
      std::free(buf.data);
    } else {
      out.clear();
    }
    return rc;
  }
};

bool parse_params(const std::string& body, RoundParams& p) {
  std::string pk_hex, seed_hex;
  if (!json_string(body, "pk", pk_hex) || !json_string(body, "seed", seed_hex)) return false;
  if (!hex_decode(pk_hex, p.coord_pk) || p.coord_pk.size() != 32) return false;
  if (!hex_decode(seed_hex, p.seed) || p.seed.size() != 32) return false;
  if (!json_number(body, "sum", p.sum_prob) || !json_number(body, "update", p.update_prob))
    return false;
  if (!json_byte4(body, "vect", p.cfg_vect) || !json_byte4(body, "unit", p.cfg_unit)) return false;
  double ml;
  if (!json_number(body, "model_length", ml)) return false;
  p.model_length = (uint64_t)ml;
  p.raw = body;
  return true;
}

void reset_round(Participant& p) {
  p.phase = Phase::NewRound;
  p.have_ephm = false;
  p.pending.clear();
  p.new_round_flag = true;
  p.wants_model = false;
}

// returns XN_OK when everything queued was delivered
int drain(Participant& p) {
  while (!p.pending.empty()) {
    bytes resp;
    int rc = p.fetch("POST /message", p.pending.front().data(), p.pending.front().size(), resp);
    if (rc < 0) return XN_ERR_TRANSPORT;  // retry THIS part on a later tick
    p.pending.pop_front();
  }
  p.phase = p.after_send;
  return XN_OK;
}

int step_new_round(Participant& p) {
  bytes to_sign(p.params.seed);
  to_sign.insert(to_sign.end(), {'s', 'u', 'm'});
  crypto_sign_detached(p.sum_sig, nullptr, to_sign.data(), to_sign.size(), p.sign_sk64);
  bytes to_sign2(p.params.seed);
  const char* upd = "update";
  to_sign2.insert(to_sign2.end(), upd, upd + 6);
  crypto_sign_detached(p.update_sig, nullptr, to_sign2.data(), to_sign2.size(), p.sign_sk64);

  if (is_eligible(p.sum_sig, p.params.sum_prob)) {
    p.phase = Phase::Sum;
  } else if (is_eligible(p.update_sig, p.params.update_prob)) {
    p.phase = Phase::Update;
  } else {
    p.phase = Phase::Awaiting;
  }
  p.made_progress = true;
  return XN_OK;
}

int step_sum(Participant& p) {
  if (!p.have_ephm) {
    randombytes_buf(p.ephm_sk, 32);
    crypto_scalarmult_base(p.ephm_pk, p.ephm_sk);
    p.have_ephm = true;
  }
  bytes payload(64 + 32);
  std::memcpy(payload.data(), p.sum_sig, 64);
  std::memcpy(payload.data() + 64, p.ephm_pk, 32);
  if (!encode_and_seal(p.sign_sk64, p.sign_pk, p.params.coord_pk.data(), kTagSum, payload,
                       p.max_message_size, p.pending))
    return XN_ERR_CRYPTO;
  p.after_send = Phase::Sum2;
  return drain(p);
}

int step_update(Participant& p) {
  bytes sums_body;
  int rc = p.fetch("GET /sums", nullptr, 0, sums_body);
  if (rc < 0) return XN_ERR_TRANSPORT;
  if (rc != 0 || sums_body.empty()) return XN_OK;  // not available yet
  std::vector<std::pair<bytes, bytes>> sum_dict;
  if (!json_hex_map(std::string(sums_body.begin(), sums_body.end()), sum_dict))
    return XN_ERR_PARSE;
  if (sum_dict.empty()) return XN_OK;

  MaskCfg cfg_n, cfg_1;
  if (!lookup_cfg(p.params.cfg_vect, cfg_n) || !lookup_cfg(p.params.cfg_unit, cfg_1))
    return XN_ERR_CONFIG;
  // native FSM coverage is the full catalogue: f32 bounded (fused dd
  // kernel), i32/i64 any bound (__int128), f64 bounded (192-bit), and
  // float Bmax (arbitrary-width bignum)
  const bool is_int = cfg_n.raw[1] == 2 || cfg_n.raw[1] == 3;
  const bool is_f64 = cfg_n.raw[1] == 1 && !cfg_n.bmax_float;
  const bool is_bmax_float = cfg_n.bmax_float;
  if (is_int) {
    if (!cfg_n.exact_ae || !cfg_1.exact_ae) return XN_ERR_CONFIG;
    if (!p.model_i_set || p.model_i.size() != p.params.model_length) {
      p.wants_model = true;
      return XN_OK;
    }
  } else if (is_f64 || (is_bmax_float && cfg_n.raw[1] == 1)) {
    if (!p.model_d_set || p.model_d.size() != p.params.model_length) {
      p.wants_model = true;
      return XN_OK;
    }
  } else if (is_bmax_float) {  // f32 Bmax: model is float32
    if (!p.model_set || p.model.size() != p.params.model_length) {
      p.wants_model = true;
      return XN_OK;
    }
  } else {
    if (!cfg_n.fast_f32 || !cfg_1.fast_f32) return XN_ERR_CONFIG;
    if (!p.model_set || p.model.size() != p.params.model_length) {
      p.wants_model = true;
      return XN_OK;
    }
  }

  // fresh mask seed; unit draw first, then the vector draws continue on the
  // same keystream (parity: MaskSeed.derive_mask / Masker.mask)
  uint8_t mask_seed[32];
  randombytes_buf(mask_seed, 32);
  bytes rand1(cfg_1.order_nbytes);
  uint64_t offset =
      xn_sample_uniform(mask_seed, 0, 1, cfg_1.order_le, cfg_1.order_nbytes, rand1.data());

  const uint64_t n = p.params.model_length;
  bytes vect(n * cfg_n.elem_nbytes);
  if (is_bmax_float) {
    // Bmax float masking: arbitrary-width exact encode per element
    bytes draws(n * cfg_n.order_nbytes);
    xn_sample_uniform(mask_seed, offset, n, cfg_n.order_le, cfg_n.order_nbytes, draws.data());
    std::memset(vect.data(), 0, vect.size());
    for (uint64_t i = 0; i < n; i++) {
      double w = cfg_n.raw[1] == 1 ? p.model_d[i] : (double)p.model[i];
      BigU shifted = encode_bmax_exact(w, p.scalar_num, p.scalar_den, cfg_n.big_a,
                                       cfg_n.big_e, cfg_n.big_ae);
      uint8_t* dst = vect.data() + i * cfg_n.elem_nbytes;
      bu_write_le(shifted, dst, cfg_n.elem_nbytes);
      add_mod_le(dst, draws.data() + i * cfg_n.order_nbytes, cfg_n.order_le,
                 cfg_n.order_nbytes, cfg_n.elem_nbytes);
    }
  } else if (is_f64) {
    // exact f64 masking: 192-bit fixed-point encode per element
    bytes draws(n * cfg_n.order_nbytes);
    xn_sample_uniform(mask_seed, offset, n, cfg_n.order_le, cfg_n.order_nbytes, draws.data());
    std::memset(vect.data(), 0, vect.size());
    const unsigned long long a = (unsigned long long)cfg_n.a_int;
    for (uint64_t i = 0; i < n; i++) {
      unsigned __int128 shifted =
          encode_f64_exact(p.model_d[i], p.scalar_num, p.scalar_den, a, cfg_n.e_int);
      uint8_t* dst = vect.data() + i * cfg_n.elem_nbytes;
      for (uint32_t j = 0; j < cfg_n.elem_nbytes && shifted > 0; j++) {
        dst[j] = (uint8_t)(shifted & 0xff);
        shifted >>= 8;
      }
      add_mod_le(dst, draws.data() + i * cfg_n.order_nbytes, cfg_n.order_le,
                 cfg_n.order_nbytes, cfg_n.elem_nbytes);
    }
  } else if (is_int) {
    // exact integer masking: per element
    //   shifted = floor((clamp(num/den * w, -A, A) + A) * E)
    // num, den <= 2^31 (enforced at construction) keeps everything inside
    // __int128 via a quotient/remainder split of the division by den.
    bytes draws(n * cfg_n.order_nbytes);
    xn_sample_uniform(mask_seed, offset, n, cfg_n.order_le, cfg_n.order_nbytes, draws.data());
    const __int128 num = p.scalar_num, den = p.scalar_den;
    const __int128 a_den = (__int128)cfg_n.a_int * den;
    const __int128 e = (__int128)cfg_n.e_int;
    std::memset(vect.data(), 0, vect.size());
    for (uint64_t i = 0; i < n; i++) {
      __int128 c = num * (__int128)p.model_i[i];
      if (c > a_den) c = a_den;
      if (c < -a_den) c = -a_den;
      __int128 t = c + a_den;  // in [0, 2*A*den]
      __int128 shifted = (t / den) * e + ((t % den) * e) / den;
      uint8_t* dst = vect.data() + i * cfg_n.elem_nbytes;
      for (uint32_t j = 0; j < cfg_n.elem_nbytes && shifted > 0; j++) {
        dst[j] = (uint8_t)(shifted & 0xff);
        shifted >>= 8;
      }
      // accepted draws fit the element width; add modulo the order
      add_mod_le(dst, draws.data() + i * cfg_n.order_nbytes, cfg_n.order_le,
                 cfg_n.order_nbytes, cfg_n.elem_nbytes);
    }
  } else {
    // clamped scalar s = min(num/den, A1); dd split for the fused kernel
    double a1 = cfg_1.add_shift;
    double s_hi = (double)p.scalar_num / (double)p.scalar_den;
    double s_lo =
        std::fma(-s_hi, (double)p.scalar_den, (double)p.scalar_num) / (double)p.scalar_den;
    if (s_hi > a1 || (s_hi == a1 && s_lo > 0)) {
      s_hi = a1;
      s_lo = 0.0;
    }
    uint64_t end_off = xn_mask_f32(mask_seed, offset, p.model.data(), n, cfg_n.order_le,
                                   cfg_n.order_nbytes, cfg_n.elem_nbytes, cfg_n.add_shift,
                                   cfg_n.exp_shift, s_hi, s_lo, vect.data());
    if (end_off == 0) return XN_ERR_CONFIG;
  }

  // masked unit: floor((min(s, A1) + A1) * E1) + rand1 mod unit order —
  // exact __int128 for bounded configs (E1 <= 10^20; max intermediate
  // (t%den)*E1 <= 2^31 * 2^67 = 2^98); bignum for Bmax float units, where
  // A1 is astronomically larger than any scalar so min(s, A1) = s
  bytes unit_elem(cfg_1.elem_nbytes, 0);
  if (cfg_1.bmax_float) {
    BigU q = bu_div_u64(bu_mul_u64(cfg_1.big_e, (uint64_t)p.scalar_num),
                        (uint64_t)p.scalar_den);
    BigU s1 = bu_add(cfg_1.big_ae, q);
    bu_write_le(s1, unit_elem.data(), cfg_1.elem_nbytes);
    add_mod_le(unit_elem.data(), rand1.data(), cfg_1.order_le, cfg_1.order_nbytes,
               cfg_1.elem_nbytes);
  } else {
    const __int128 num = p.scalar_num, den = p.scalar_den;
    const __int128 a1_den = (__int128)cfg_1.a_int * den;
    const __int128 e1 = (__int128)cfg_1.e_int;
    __int128 s_num = num > a1_den ? a1_den : num;  // scalar clamped above by A1
    __int128 t = s_num + a1_den;
    __int128 shifted1 = (t / den) * e1 + ((t % den) * e1) / den;
    for (uint32_t i = 0; i < cfg_1.elem_nbytes && shifted1 > 0; i++) {
      unit_elem[i] = (uint8_t)(shifted1 & 0xff);
      shifted1 >>= 8;
    }
    add_mod_le(unit_elem.data(), rand1.data(), cfg_1.order_le, cfg_1.order_nbytes,
               cfg_1.elem_nbytes);
  }

  // payload: sum_sig(64) || update_sig(64) || MaskObject || LV seed dict
  bytes payload;
  payload.insert(payload.end(), p.sum_sig, p.sum_sig + 64);
  payload.insert(payload.end(), p.update_sig, p.update_sig + 64);
  payload.insert(payload.end(), cfg_n.raw, cfg_n.raw + 4);
  uint8_t cnt[4];
  put_u32be(cnt, (uint32_t)p.params.model_length);
  payload.insert(payload.end(), cnt, cnt + 4);
  payload.insert(payload.end(), vect.begin(), vect.end());
  payload.insert(payload.end(), cfg_1.raw, cfg_1.raw + 4);
  payload.insert(payload.end(), unit_elem.begin(), unit_elem.end());
  // LV seed dict: length includes the 4-byte length field
  uint8_t lv[4];
  put_u32be(lv, (uint32_t)(4 + sum_dict.size() * 112));
  payload.insert(payload.end(), lv, lv + 4);
  for (auto& kv : sum_dict) {
    if (kv.first.size() != 32 || kv.second.size() != 32) return XN_ERR_PARSE;
    bytes sealed;
    if (!seal(mask_seed, 32, kv.second.data(), sealed) || sealed.size() != 80)
      return XN_ERR_CRYPTO;
    payload.insert(payload.end(), kv.first.begin(), kv.first.end());
    payload.insert(payload.end(), sealed.begin(), sealed.end());
  }

  if (!encode_and_seal(p.sign_sk64, p.sign_pk, p.params.coord_pk.data(), kTagUpdate, payload,
                       p.max_message_size, p.pending))
    return XN_ERR_CRYPTO;
  p.after_send = Phase::Awaiting;
  p.made_progress = true;
  return drain(p);
}

int step_sum2(Participant& p) {
  std::string req = "GET /seeds?pk=" + hex_encode(p.sign_pk, 32);
  bytes body;
  int rc = p.fetch(req.c_str(), nullptr, 0, body);
  if (rc < 0) return XN_ERR_TRANSPORT;
  if (rc != 0 || body.empty()) return XN_OK;  // seeds not available yet
  std::vector<std::pair<bytes, bytes>> seeds;
  if (!json_hex_map(std::string(body.begin(), body.end()), seeds)) return XN_ERR_PARSE;
  if (seeds.empty()) return XN_OK;

  MaskCfg cfg_n, cfg_1;
  if (!lookup_cfg(p.params.cfg_vect, cfg_n) || !lookup_cfg(p.params.cfg_unit, cfg_1))
    return XN_ERR_CONFIG;

  // derive + modular-sum every mask (reference: sum2.rs:170-193)
  uint64_t n = p.params.model_length;
  bytes vect_acc(n * cfg_n.elem_nbytes, 0);
  bytes unit_acc(cfg_1.elem_nbytes, 0);
  bytes vect_one(n * cfg_n.elem_nbytes);
  bytes draw_buf(cfg_n.order_nbytes);
  for (auto& kv : seeds) {
    bytes seed;
    if (!seal_open(kv.second.data(), kv.second.size(), p.ephm_sk, p.ephm_pk, seed) ||
        seed.size() != 32)
      return XN_ERR_CRYPTO;
    bytes rand1(cfg_1.order_nbytes);
    uint64_t off =
        xn_sample_uniform(seed.data(), 0, 1, cfg_1.order_le, cfg_1.order_nbytes, rand1.data());
    add_mod_le(unit_acc.data(), rand1.data(), cfg_1.order_le, cfg_1.order_nbytes,
               cfg_1.elem_nbytes);
    if (cfg_n.order_nbytes == cfg_n.elem_nbytes) {
      xn_sample_uniform(seed.data(), off, n, cfg_n.order_le, cfg_n.order_nbytes, vect_one.data());
      for (uint64_t i = 0; i < n; i++)
        add_mod_le(vect_acc.data() + i * cfg_n.elem_nbytes,
                   vect_one.data() + i * cfg_n.elem_nbytes, cfg_n.order_le, cfg_n.order_nbytes,
                   cfg_n.elem_nbytes);
    } else {
      // draws are order-width; accepted values fit the element width
      uint64_t o = off;
      for (uint64_t i = 0; i < n; i++) {
        o = xn_sample_uniform(seed.data(), o, 1, cfg_n.order_le, cfg_n.order_nbytes,
                              draw_buf.data());
        add_mod_le(vect_acc.data() + i * cfg_n.elem_nbytes, draw_buf.data(), cfg_n.order_le,
                   cfg_n.order_nbytes, cfg_n.elem_nbytes);
      }
    }
  }

  // payload: sum_sig(64) || MaskObject(vect config+count+elems, unit)
  bytes payload;
  payload.insert(payload.end(), p.sum_sig, p.sum_sig + 64);
  payload.insert(payload.end(), cfg_n.raw, cfg_n.raw + 4);
  uint8_t cnt[4];
  put_u32be(cnt, (uint32_t)n);
  payload.insert(payload.end(), cnt, cnt + 4);
  payload.insert(payload.end(), vect_acc.begin(), vect_acc.end());
  payload.insert(payload.end(), cfg_1.raw, cfg_1.raw + 4);
  payload.insert(payload.end(), unit_acc.begin(), unit_acc.end());

  if (!encode_and_seal(p.sign_sk64, p.sign_pk, p.params.coord_pk.data(), kTagSum2, payload,
                       p.max_message_size, p.pending))
    return XN_ERR_CRYPTO;
  p.after_send = Phase::Awaiting;
  p.made_progress = true;
  return drain(p);
}

// save format: "XNP1" || seed(32) || scalar num/den (i64 LE each) ||
// mms(u32) || phase(u8) || after_send(u8) || flags(u8: have_params,
// have_ephm, model_set<<2) || ephm_sk(32) || sum_sig(64) || update_sig(64)
// || params_raw(LV u32) || pending(count u32, each LV u32) || model(LV u32,
// f32 LE)
void put_lv(bytes& out, const uint8_t* data, size_t len) {
  uint8_t l[4];
  put_u32be(l, (uint32_t)len);
  out.insert(out.end(), l, l + 4);
  out.insert(out.end(), data, data + len);
}

}  // namespace

XN_EXPORT uint32_t xaynet_ffi_abi_version(void) { return 2; }

XN_EXPORT int xaynet_ffi_crypto_init(void) { return sodium_init() >= 0 ? XN_OK : XN_ERR_CRYPTO; }

XN_EXPORT void* xaynet_ffi_participant_new(const uint8_t signing_seed[32], int64_t scalar_num,
                                           int64_t scalar_den, uint32_t max_message_size,
                                           xn_transport_fn transport, void* user) {
  // num/den bounded to 2^31-1 keeps every fixed-point encode inside __int128
  if (!signing_seed || !transport || scalar_den <= 0 || scalar_num < 0 ||
      scalar_den > 0x7FFFFFFF || scalar_num > 0x7FFFFFFF)
    return nullptr;
  if (sodium_init() < 0) return nullptr;
  auto* p = new Participant();
  std::memcpy(p->sign_seed, signing_seed, 32);
  crypto_sign_seed_keypair(p->sign_pk, p->sign_sk64, signing_seed);
  p->scalar_num = scalar_num;
  p->scalar_den = scalar_den;
  p->max_message_size = max_message_size;
  p->transport = transport;
  p->transport_user = user;
  return p;
}

XN_EXPORT void xaynet_ffi_participant_destroy(void* handle) {
  delete static_cast<Participant*>(handle);
}

XN_EXPORT int xaynet_ffi_participant_tick(void* handle) {
  auto* p = static_cast<Participant*>(handle);
  if (!p) return XN_ERR_NULL;
  p->made_progress = false;

  // round freshness first (parity: sdk phase.rs:160-200)
  bytes body;
  int rc = p->fetch("GET /params", nullptr, 0, body);
  if (rc != 0) return XN_ERR_TRANSPORT;
  std::string raw(body.begin(), body.end());
  if (!p->have_params || raw != p->params.raw) {
    RoundParams fresh;
    if (!parse_params(raw, fresh)) return XN_ERR_PARSE;
    p->params = std::move(fresh);
    p->have_params = true;
    reset_round(*p);
  }

  if (!p->pending.empty()) {
    int drc = drain(*p);
    if (drc == XN_OK) p->made_progress = true;
    return drc == XN_OK ? XN_OK : drc;
  }

  switch (p->phase) {
    case Phase::Awaiting:
      return XN_OK;
    case Phase::NewRound:
      return step_new_round(*p);
    case Phase::Sum:
      return step_sum(*p);
    case Phase::Update:
      return step_update(*p);
    case Phase::Sum2:
      return step_sum2(*p);
  }
  return XN_ERR_STATE;
}

XN_EXPORT int xaynet_ffi_participant_task(void* handle) {
  auto* p = static_cast<Participant*>(handle);
  if (!p) return XN_ERR_NULL;
  switch (p->phase) {
    case Phase::Sum:
    case Phase::Sum2:
      return XN_TASK_SUM;
    case Phase::Update:
      return XN_TASK_UPDATE;
    default:
      return XN_TASK_NONE;
  }
}

XN_EXPORT int xaynet_ffi_participant_made_progress(void* handle) {
  auto* p = static_cast<Participant*>(handle);
  return p && p->made_progress ? 1 : 0;
}

XN_EXPORT int xaynet_ffi_participant_should_set_model(void* handle) {
  auto* p = static_cast<Participant*>(handle);
  return p && p->wants_model ? 1 : 0;
}

XN_EXPORT int xaynet_ffi_participant_new_round(void* handle) {
  auto* p = static_cast<Participant*>(handle);
  if (!p) return 0;
  int f = p->new_round_flag ? 1 : 0;
  p->new_round_flag = false;
  return f;
}

XN_EXPORT int xaynet_ffi_participant_set_model(void* handle, const float* data, uint64_t len) {
  auto* p = static_cast<Participant*>(handle);
  if (!p || !data) return XN_ERR_NULL;
  p->model.assign(data, data + len);
  p->model_set = true;
  p->wants_model = false;
  return XN_OK;
}

// integer data types (i32/i64 mask configs) take their model as int64
XN_EXPORT int xaynet_ffi_participant_set_model_i64(void* handle, const int64_t* data,
                                                   uint64_t len) {
  auto* p = static_cast<Participant*>(handle);
  if (!p || !data) return XN_ERR_NULL;
  p->model_i.assign(data, data + len);
  p->model_i_set = true;
  p->wants_model = false;
  return XN_OK;
}

// f64 mask configs take their model as double (exact 192-bit encode)
XN_EXPORT int xaynet_ffi_participant_set_model_f64(void* handle, const double* data,
                                                   uint64_t len) {
  auto* p = static_cast<Participant*>(handle);
  if (!p || !data) return XN_ERR_NULL;
  p->model_d.assign(data, data + len);
  p->model_d_set = true;
  p->wants_model = false;
  return XN_OK;
}

// test shim: the exact Bmax float encode using the SAME cached constants
// as the production masking path; fills all out_cap bytes (zero-padded
// little-endian) and returns out_cap, or <0 when the value doesn't fit
XN_EXPORT int64_t xaynet_ffi_encode_bmax(double w, int64_t num, int64_t den, int is_f64,
                                         uint8_t* out, uint64_t out_cap) {
  if (den <= 0 || num < 0 || den > 0x7FFFFFFF || num > 0x7FFFFFFF) return XN_ERR_CONFIG;
  const BmaxConsts& c = bmax_consts(is_f64 != 0);
  BigU v = encode_bmax_exact(w, num, den, c.a, c.e, c.ae);
  uint64_t need = v.size() * 8;  // trim leading zero bytes for the exact size
  while (need > 0 && ((v[(need - 1) / 8] >> (8 * ((need - 1) % 8))) & 0xff) == 0) need--;
  if (need > out_cap) return XN_ERR_CONFIG;
  bu_write_le(v, out, (uint32_t)out_cap);
  return (int64_t)out_cap;
}

// test shim: the exact f64 encode, result as 16 little-endian bytes
XN_EXPORT int xaynet_ffi_encode_f64(double w, int64_t num, int64_t den, uint64_t a,
                                    uint32_t e_pow10, uint8_t out[16]) {
  if (den <= 0 || num < 0 || den > 0x7FFFFFFF || num > 0x7FFFFFFF || e_pow10 > 20 ||
      a > 1000000ull)  // documented precondition A <= 10^6 (bounded configs)
    return XN_ERR_CONFIG;
  unsigned __int128 e = 1;
  for (uint32_t i = 0; i < e_pow10; i++) e *= 10;
  unsigned __int128 v = encode_f64_exact(w, num, den, a, e);
  for (int i = 0; i < 16; i++) {
    out[i] = (uint8_t)(v & 0xff);
    v >>= 8;
  }
  return XN_OK;
}

// fetch the latest global model (f64 little-endian over the transport);
// returns element count (>=0) or an error code; *out borrowed until the
// next call/destroy
XN_EXPORT int64_t xaynet_ffi_participant_global_model(void* handle, const double** out) {
  auto* p = static_cast<Participant*>(handle);
  if (!p || !out) return XN_ERR_NULL;
  bytes body;
  int rc = p->fetch("GET /model", nullptr, 0, body);
  if (rc < 0) return XN_ERR_TRANSPORT;
  if (rc != 0 || body.empty()) {
    *out = nullptr;
    return 0;
  }
  p->global_model.resize(body.size() / 8);
  std::memcpy(p->global_model.data(), body.data(), p->global_model.size() * 8);
  *out = p->global_model.data();
  return (int64_t)p->global_model.size();
}

XN_EXPORT int xaynet_ffi_participant_save(void* handle, uint8_t** out, uint64_t* out_len) {
  auto* p = static_cast<Participant*>(handle);
  if (!p || !out || !out_len) return XN_ERR_NULL;
  bytes buf;
  const char magic[4] = {'X', 'N', 'P', '1'};
  buf.insert(buf.end(), magic, magic + 4);
  buf.insert(buf.end(), p->sign_seed, p->sign_seed + 32);
  for (int64_t v : {p->scalar_num, p->scalar_den})
    for (int i = 0; i < 8; i++) buf.push_back((uint8_t)(((uint64_t)v) >> (8 * i)));
  uint8_t mms[4];
  put_u32be(mms, p->max_message_size);
  buf.insert(buf.end(), mms, mms + 4);
  buf.push_back((uint8_t)p->phase);
  buf.push_back((uint8_t)p->after_send);
  buf.push_back((uint8_t)((p->have_params ? 1 : 0) | (p->have_ephm ? 2 : 0) |
                          (p->model_set ? 4 : 0) | (p->model_i_set ? 8 : 0) |
                          (p->model_d_set ? 16 : 0)));
  buf.insert(buf.end(), p->ephm_sk, p->ephm_sk + 32);
  buf.insert(buf.end(), p->sum_sig, p->sum_sig + 64);
  buf.insert(buf.end(), p->update_sig, p->update_sig + 64);
  put_lv(buf, (const uint8_t*)p->params.raw.data(), p->params.raw.size());
  uint8_t cnt[4];
  put_u32be(cnt, (uint32_t)p->pending.size());
  buf.insert(buf.end(), cnt, cnt + 4);
  for (auto& part : p->pending) put_lv(buf, part.data(), part.size());
  put_lv(buf, (const uint8_t*)p->model.data(), p->model.size() * 4);
  put_lv(buf, (const uint8_t*)p->model_i.data(), p->model_i.size() * 8);
  put_lv(buf, (const uint8_t*)p->model_d.data(), p->model_d.size() * 8);

  *out = (uint8_t*)std::malloc(buf.size());
  if (!*out) return XN_ERR_NULL;
  std::memcpy(*out, buf.data(), buf.size());
  *out_len = buf.size();
  return XN_OK;
}

XN_EXPORT void* xaynet_ffi_participant_restore(const uint8_t* data, uint64_t len,
                                               xn_transport_fn transport, void* user) {
  if (!data || len < 4 + 32 + 16 + 4 + 3 + 32 + 128 + 4 || std::memcmp(data, "XNP1", 4) != 0)
    return nullptr;
  if (sodium_init() < 0) return nullptr;
  auto* p = new Participant();
  size_t o = 4;
  auto take = [&](void* dst, size_t n) {
    std::memcpy(dst, data + o, n);
    o += n;
  };
  take(p->sign_seed, 32);
  crypto_sign_seed_keypair(p->sign_pk, p->sign_sk64, p->sign_seed);
  uint64_t num = 0, den = 0;
  take(&num, 8);
  take(&den, 8);
  p->scalar_num = (int64_t)num;
  p->scalar_den = (int64_t)den;
  if (p->scalar_den <= 0 || p->scalar_num < 0 || p->scalar_den > 0x7FFFFFFF ||
      p->scalar_num > 0x7FFFFFFF) {  // same contract as _new
    delete p;
    return nullptr;
  }
  uint8_t mms[4];
  take(mms, 4);
  p->max_message_size = ((uint32_t)mms[0] << 24) | (mms[1] << 16) | (mms[2] << 8) | mms[3];
  uint8_t ph, as, fl;
  take(&ph, 1);
  take(&as, 1);
  take(&fl, 1);
  p->phase = (Phase)ph;
  p->after_send = (Phase)as;
  p->have_params = fl & 1;
  p->have_ephm = fl & 2;
  p->model_set = fl & 4;
  p->model_i_set = fl & 8;
  p->model_d_set = fl & 16;
  take(p->ephm_sk, 32);
  if (p->have_ephm) crypto_scalarmult_base(p->ephm_pk, p->ephm_sk);
  take(p->sum_sig, 64);
  take(p->update_sig, 64);
  auto take_lv = [&](bytes& outb) -> bool {
    if (o + 4 > len) return false;
    uint32_t n = ((uint32_t)data[o] << 24) | (data[o + 1] << 16) | (data[o + 2] << 8) |
                 data[o + 3];
    o += 4;
    if (o + n > len) return false;
    outb.assign(data + o, data + o + n);
    o += n;
    return true;
  };
  bytes raw;
  if (!take_lv(raw)) {
    delete p;
    return nullptr;
  }
  if (p->have_params) {
    if (!parse_params(std::string(raw.begin(), raw.end()), p->params)) {
      delete p;
      return nullptr;
    }
  }
  if (o + 4 > len) {
    delete p;
    return nullptr;
  }
  uint32_t n_pending = ((uint32_t)data[o] << 24) | (data[o + 1] << 16) | (data[o + 2] << 8) |
                       data[o + 3];
  o += 4;
  for (uint32_t i = 0; i < n_pending; i++) {
    bytes part;
    if (!take_lv(part)) {
      delete p;
      return nullptr;
    }
    p->pending.push_back(std::move(part));
  }
  bytes model_raw;
  if (!take_lv(model_raw) || model_raw.size() % 4 != 0) {  // reject, don't overflow
    delete p;
    return nullptr;
  }
  p->model.resize(model_raw.size() / 4);
  std::memcpy(p->model.data(), model_raw.data(), model_raw.size());
  // trailing int/f64-model LVs: absent in blobs saved by older library
  // versions (treated as empty — format is append-only for compatibility)
  if (o < len) {
    bytes model_i_raw;
    if (!take_lv(model_i_raw) || model_i_raw.size() % 8 != 0) {
      delete p;
      return nullptr;
    }
    p->model_i.resize(model_i_raw.size() / 8);
    std::memcpy(p->model_i.data(), model_i_raw.data(), model_i_raw.size());
  } else {
    p->model_i_set = false;
  }
  if (o < len) {
    bytes model_d_raw;
    if (!take_lv(model_d_raw) || model_d_raw.size() % 8 != 0) {
      delete p;
      return nullptr;
    }
    p->model_d.resize(model_d_raw.size() / 8);
    std::memcpy(p->model_d.data(), model_d_raw.data(), model_d_raw.size());
  } else {
    p->model_d_set = false;
  }
  p->transport = transport;
  p->transport_user = user;
  return p;
}

// --- standalone crypto helpers (cross-language interop tests) -------------

XN_EXPORT int xaynet_ffi_seal(const uint8_t* msg, uint64_t len, const uint8_t pk[32],
                              uint8_t* out, uint64_t* out_len) {
  bytes sealed;
  if (!seal(msg, len, pk, sealed)) return XN_ERR_CRYPTO;
  std::memcpy(out, sealed.data(), sealed.size());
  *out_len = sealed.size();
  return XN_OK;
}

XN_EXPORT int xaynet_ffi_seal_open(const uint8_t* sealed, uint64_t len, const uint8_t sk[32],
                                   uint8_t* out, uint64_t* out_len) {
  uint8_t pk[32];
  crypto_scalarmult_base(pk, sk);
  bytes plain;
  if (!seal_open(sealed, len, sk, pk, plain)) return XN_ERR_CRYPTO;
  std::memcpy(out, plain.data(), plain.size());
  *out_len = plain.size();
  return XN_OK;
}

XN_EXPORT int xaynet_ffi_sign(const uint8_t seed[32], const uint8_t* msg, uint64_t len,
                              uint8_t sig[64]) {
  uint8_t pk[32], sk64[64];
  crypto_sign_seed_keypair(pk, sk64, seed);
  crypto_sign_detached(sig, nullptr, msg, len, sk64);
  return XN_OK;
}

XN_EXPORT int xaynet_ffi_is_eligible(const uint8_t sig[64], double threshold) {
  return is_eligible(sig, threshold) ? 1 : 0;
}

XN_EXPORT void xaynet_ffi_free(void* ptr) { std::free(ptr); }
