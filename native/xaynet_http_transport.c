/* Built-in HTTP/1.1 transport for the interpreter-free native participant.
 *
 * Parity with the reference's bundled client
 * (rust/xaynet-mobile/src/reqwest_client.rs): an embedder links this file
 * (or libxaynet_http_transport.so) and passes `xn_http_transport` +
 * `xn_http_client_new(host, port)` straight into
 * `xaynet_ffi_participant_new` — no caller-written transport required.
 *
 * Plain POSIX sockets, one request per connection (`Connection: close`),
 * no third-party link-time dependencies. TLS (reqwest_client.rs:58-71
 * parity: root-cert PINNING + optional in-process client identity) comes
 * from `xn_http_client_new_tls`, which loads the system's libssl at
 * runtime via dlopen — the plain-HTTP build and embedders that terminate
 * TLS at a sidecar pay nothing for it.
 *
 * Contract (native/xaynet_participant.cpp:745-753): `request` is
 * "METHOD /path", the body is sent for POSTs; return 0 on HTTP 200 with a
 * malloc'd body in *out (the participant library frees it), 1 on 204/empty,
 * negative on transport failure.
 */

#include <arpa/inet.h>
#include <ctype.h>
#include <dlfcn.h>
#include <errno.h>
#include <netdb.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include "xaynet_participant.h"

struct XnHttpClient {
  char host[256];
  char port[16];
  int use_tls;
  char ca_path[512];    /* pinned root(s); the ONLY trust anchors used */
  char cert_path[512];  /* optional client identity (mutual TLS) */
  char key_path[512];
};

XnHttpClient* xn_http_client_new(const char* host, uint16_t port) {
  if (!host || strlen(host) >= sizeof(((XnHttpClient*)0)->host)) return NULL;
  XnHttpClient* c = (XnHttpClient*)calloc(1, sizeof(XnHttpClient));
  if (!c) return NULL;
  snprintf(c->host, sizeof(c->host), "%s", host);
  snprintf(c->port, sizeof(c->port), "%u", (unsigned)port);
  return c;
}

void xn_http_client_free(XnHttpClient* c) { free(c); }

/* --- TLS via the system libssl, loaded at runtime ----------------------- */

/* Minimal prototypes for the stable OpenSSL (1.1+/3.x) C ABI we use; the
 * build needs no OpenSSL headers. Opaque pointers throughout. */
typedef struct {
  void* libssl;
  void* libcrypto;
  const void* (*TLS_client_method)(void);
  void* (*SSL_CTX_new)(const void*);
  void (*SSL_CTX_free)(void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_use_certificate_chain_file)(void*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int);
  int (*SSL_CTX_check_private_key)(const void*);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_get_error)(const void*, int);
  unsigned long (*ERR_peek_error)(void);
  void (*ERR_clear_error)(void);
  int (*SSL_shutdown)(void*);
  void* (*SSL_get0_param)(void*);
  long (*SSL_ctrl)(void*, int, long, void*);
  int (*X509_VERIFY_PARAM_set1_host)(void*, const char*, size_t);
  int (*X509_VERIFY_PARAM_set1_ip_asc)(void*, const char*);
} XnTlsApi;

#define XN_SSL_VERIFY_PEER 0x01
#define XN_SSL_FILETYPE_PEM 1
#define XN_SSL_ERROR_SSL 1
#define XN_SSL_ERROR_SYSCALL 5
#define XN_SSL_ERROR_ZERO_RETURN 6
/* OpenSSL 3.x reports a peer closing without close_notify as
 * SSL_ERROR_SSL with reason SSL_R_UNEXPECTED_EOF_WHILE_READING (294)
 * rather than 1.1.1's SSL_ERROR_SYSCALL with ret==0. Reason masks differ
 * across the two era layouts (3.x: low 23 bits; 1.1.1: low 12 bits). */
#define XN_SSL_R_UNEXPECTED_EOF 294
#define XN_SSL_CTRL_SET_TLSEXT_HOSTNAME 55
#define XN_TLSEXT_NAMETYPE_host_name 0

static void* xn_dl(void* lib, const char* name) { return lib ? dlsym(lib, name) : NULL; }

static const XnTlsApi* xn_tls_api(void) {
  static XnTlsApi api;
  static int state = 0; /* 0 unloaded, 1 ok, -1 failed */
  if (state) return state > 0 ? &api : NULL;
  /* RTLD_LOCAL: never pollute the embedder's symbol namespace */
  api.libssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_LOCAL);
  if (!api.libssl) api.libssl = dlopen("libssl.so.1.1", RTLD_NOW | RTLD_LOCAL);
  if (!api.libssl) api.libssl = dlopen("libssl.so", RTLD_NOW | RTLD_LOCAL);
  /* X509_* live in libcrypto; resolving them through the libssl handle
   * searches its own dependency chain, guaranteeing a version-matched
   * libssl/libcrypto pair */
  api.libcrypto = api.libssl;
  *(void**)&api.TLS_client_method = xn_dl(api.libssl, "TLS_client_method");
  *(void**)&api.SSL_CTX_new = xn_dl(api.libssl, "SSL_CTX_new");
  *(void**)&api.SSL_CTX_free = xn_dl(api.libssl, "SSL_CTX_free");
  *(void**)&api.SSL_CTX_load_verify_locations = xn_dl(api.libssl, "SSL_CTX_load_verify_locations");
  *(void**)&api.SSL_CTX_set_verify = xn_dl(api.libssl, "SSL_CTX_set_verify");
  *(void**)&api.SSL_CTX_use_certificate_chain_file =
      xn_dl(api.libssl, "SSL_CTX_use_certificate_chain_file");
  *(void**)&api.SSL_CTX_use_PrivateKey_file = xn_dl(api.libssl, "SSL_CTX_use_PrivateKey_file");
  *(void**)&api.SSL_CTX_check_private_key = xn_dl(api.libssl, "SSL_CTX_check_private_key");
  *(void**)&api.SSL_new = xn_dl(api.libssl, "SSL_new");
  *(void**)&api.SSL_free = xn_dl(api.libssl, "SSL_free");
  *(void**)&api.SSL_set_fd = xn_dl(api.libssl, "SSL_set_fd");
  *(void**)&api.SSL_connect = xn_dl(api.libssl, "SSL_connect");
  *(void**)&api.SSL_read = xn_dl(api.libssl, "SSL_read");
  *(void**)&api.SSL_write = xn_dl(api.libssl, "SSL_write");
  *(void**)&api.SSL_get_error = xn_dl(api.libssl, "SSL_get_error");
  *(void**)&api.ERR_peek_error = xn_dl(api.libssl, "ERR_peek_error");
  *(void**)&api.ERR_clear_error = xn_dl(api.libssl, "ERR_clear_error");
  *(void**)&api.SSL_shutdown = xn_dl(api.libssl, "SSL_shutdown");
  *(void**)&api.SSL_get0_param = xn_dl(api.libssl, "SSL_get0_param");
  *(void**)&api.SSL_ctrl = xn_dl(api.libssl, "SSL_ctrl");
  *(void**)&api.X509_VERIFY_PARAM_set1_host = xn_dl(api.libcrypto, "X509_VERIFY_PARAM_set1_host");
  *(void**)&api.X509_VERIFY_PARAM_set1_ip_asc =
      xn_dl(api.libcrypto, "X509_VERIFY_PARAM_set1_ip_asc");
  int ok = api.TLS_client_method && api.SSL_CTX_new && api.SSL_CTX_free &&
           api.SSL_CTX_load_verify_locations && api.SSL_CTX_set_verify && api.SSL_new &&
           api.SSL_free && api.SSL_set_fd && api.SSL_connect && api.SSL_read && api.SSL_write &&
           api.SSL_get_error && api.ERR_peek_error && api.ERR_clear_error &&
           api.SSL_shutdown && api.SSL_get0_param && api.SSL_ctrl &&
           api.X509_VERIFY_PARAM_set1_host && api.X509_VERIFY_PARAM_set1_ip_asc &&
           api.SSL_CTX_use_certificate_chain_file && api.SSL_CTX_use_PrivateKey_file &&
           api.SSL_CTX_check_private_key;
  state = ok ? 1 : -1;
  return ok ? &api : NULL;
}

XnHttpClient* xn_http_client_new_tls(const char* host, uint16_t port, const char* ca_pem_path,
                                     const char* client_cert_pem_path,
                                     const char* client_key_pem_path) {
  if (!ca_pem_path || strlen(ca_pem_path) >= sizeof(((XnHttpClient*)0)->ca_path)) return NULL;
  if (client_cert_pem_path &&
      strlen(client_cert_pem_path) >= sizeof(((XnHttpClient*)0)->cert_path))
    return NULL;
  if (client_key_pem_path && strlen(client_key_pem_path) >= sizeof(((XnHttpClient*)0)->key_path))
    return NULL;
  if ((client_cert_pem_path == NULL) != (client_key_pem_path == NULL)) return NULL;
  if (!xn_tls_api()) return NULL; /* no usable libssl on this system */
  XnHttpClient* c = xn_http_client_new(host, port);
  if (!c) return NULL;
  c->use_tls = 1;
  snprintf(c->ca_path, sizeof(c->ca_path), "%s", ca_pem_path);
  if (client_cert_pem_path) {
    snprintf(c->cert_path, sizeof(c->cert_path), "%s", client_cert_pem_path);
    snprintf(c->key_path, sizeof(c->key_path), "%s", client_key_pem_path);
  }
  return c;
}

/* One open connection: plain fd, or fd + TLS state. */
typedef struct {
  int fd;
  void* ssl;
  void* ctx;
} XnConn;

static void xn_conn_close(XnConn* conn) {
  /* the ctx may exist without an ssl object (early handshake-setup failure) */
  const XnTlsApi* t = (conn->ssl || conn->ctx) ? xn_tls_api() : NULL;
  if (t && conn->ssl) {
    t->SSL_shutdown(conn->ssl);
    t->SSL_free(conn->ssl);
  }
  if (t && conn->ctx) t->SSL_CTX_free(conn->ctx);
  if (conn->fd >= 0) close(conn->fd);
  conn->fd = -1;
  conn->ssl = conn->ctx = NULL;
}

/* TLS handshake on an already-connected fd: pinned roots, hostname/IP
 * binding, optional client identity. Returns 0 or -1 (conn closed). */
static int xn_tls_open(XnConn* conn, const XnHttpClient* c) {
  const XnTlsApi* t = xn_tls_api();
  if (!t) return -1;
  conn->ctx = t->SSL_CTX_new(t->TLS_client_method());
  if (!conn->ctx) return -1;
  /* pinning: the provided CA file is the entire trust store — the system
   * default roots are deliberately NOT loaded (reqwest_client.rs:58-63) */
  if (t->SSL_CTX_load_verify_locations(conn->ctx, c->ca_path, NULL) != 1) goto fail;
  t->SSL_CTX_set_verify(conn->ctx, XN_SSL_VERIFY_PEER, NULL);
  if (c->cert_path[0]) { /* in-process client identity (mutual TLS) */
    if (t->SSL_CTX_use_certificate_chain_file(conn->ctx, c->cert_path) != 1 ||
        t->SSL_CTX_use_PrivateKey_file(conn->ctx, c->key_path, XN_SSL_FILETYPE_PEM) != 1 ||
        t->SSL_CTX_check_private_key(conn->ctx) != 1)
      goto fail;
  }
  conn->ssl = t->SSL_new(conn->ctx);
  if (!conn->ssl || t->SSL_set_fd(conn->ssl, conn->fd) != 1) goto fail;
  /* bind the peer certificate to the host we dialed */
  {
    void* param = t->SSL_get0_param(conn->ssl);
    struct in_addr a4;
    struct in6_addr a6;
    if (inet_pton(AF_INET, c->host, &a4) == 1 || inet_pton(AF_INET6, c->host, &a6) == 1) {
      if (t->X509_VERIFY_PARAM_set1_ip_asc(param, c->host) != 1) goto fail;
    } else {
      if (t->X509_VERIFY_PARAM_set1_host(param, c->host, 0) != 1) goto fail;
      t->SSL_ctrl(conn->ssl, XN_SSL_CTRL_SET_TLSEXT_HOSTNAME, XN_TLSEXT_NAMETYPE_host_name,
                  (void*)c->host); /* SNI */
    }
  }
  if (t->SSL_connect(conn->ssl) != 1) goto fail; /* verify failure fails here */
  return 0;
fail:
  xn_conn_close(conn);
  return -1;
}

static int xn_connect(const XnHttpClient* c) {
  struct addrinfo hints, *res = NULL, *ai;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(c->host, c->port, &hints, &res) != 0) return -1;
  int fd = -1;
  for (ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

static int xn_write_all(XnConn* conn, const void* buf, size_t len) {
  const uint8_t* p = (const uint8_t*)buf;
  const XnTlsApi* t = conn->ssl ? xn_tls_api() : NULL;
  while (len) {
    ssize_t n;
    if (conn->ssl) {
      int chunk = len > (1u << 30) ? (int)(1u << 30) : (int)len;
      n = t->SSL_write(conn->ssl, p, chunk);
      if (n <= 0) return -1;
    } else {
      n = write(conn->fd, p, len);
      if (n < 0) {
        if (errno == EINTR) continue;
        return -1;
      }
    }
    p += n;
    len -= (size_t)n;
  }
  return 0;
}

/* Read the whole response (Connection: close => until EOF); the buffer is
 * NUL-terminated one past `*out_len` so bounded string scans are safe.
 * Under TLS only a close_notify (SSL_ERROR_ZERO_RETURN) is a *clean* EOF.
 * A peer that closes the TCP socket without close_notify (common: the
 * Python test server, many proxies) shows up as SSL_ERROR_SYSCALL with
 * ret==0 — that is reported as an *unclean* EOF via `*clean_eof` so the
 * caller can accept it only when the body is explicitly framed
 * (Content-Length / chunked); a mid-stream TLS error is a hard failure,
 * matching the plaintext read-error path. */
static int xn_read_all(XnConn* conn, uint8_t** out, size_t* out_len, int* clean_eof) {
  size_t cap = 8192, len = 0;
  const XnTlsApi* t = conn->ssl ? xn_tls_api() : NULL;
  uint8_t* buf = (uint8_t*)malloc(cap + 1);
  if (!buf) return -1;
  *clean_eof = 1;
  for (;;) {
    if (len == cap) {
      cap *= 2;
      uint8_t* next = (uint8_t*)realloc(buf, cap + 1);
      if (!next) {
        free(buf);
        return -1;
      }
      buf = next;
    }
    ssize_t n;
    if (conn->ssl) {
      size_t want = cap - len;
      /* SSL_get_error consults the thread's error queue; stale entries
       * from earlier calls would misclassify this read's result */
      t->ERR_clear_error();
      n = t->SSL_read(conn->ssl, buf + len, want > (1u << 30) ? (int)(1u << 30) : (int)want);
      if (n <= 0) {
        int err = t->SSL_get_error(conn->ssl, (int)n);
        if (err == XN_SSL_ERROR_ZERO_RETURN) break; /* close_notify: clean */
        if (err == XN_SSL_ERROR_SYSCALL && n == 0) {
          *clean_eof = 0; /* 1.1.1: TCP close without close_notify */
          break;
        }
        if (err == XN_SSL_ERROR_SSL) {
          unsigned long reason = t->ERR_peek_error();
          if ((reason & 0x7FFFFF) == XN_SSL_R_UNEXPECTED_EOF ||
              (reason & 0xFFF) == XN_SSL_R_UNEXPECTED_EOF) {
            *clean_eof = 0; /* 3.x: TCP close without close_notify */
            break;
          }
        }
        free(buf); /* mid-stream TLS failure */
        return -1;
      }
    } else {
      n = read(conn->fd, buf + len, cap - len);
      if (n < 0) {
        if (errno == EINTR) continue;
        free(buf);
        return -1;
      }
      if (n == 0) break;
    }
    len += (size_t)n;
  }
  buf[len] = 0;
  *out = buf;
  *out_len = len;
  return 0;
}

/* Case-insensitive header lookup inside [headers, headers_end). Returns the
 * value start (past ':' and spaces) or NULL. */
static const char* xn_find_header(const char* headers, const char* headers_end,
                                  const char* name) {
  size_t name_len = strlen(name);
  const char* line = headers;
  while (line < headers_end) {
    const char* eol = strstr(line, "\r\n");
    if (!eol || eol > headers_end) eol = headers_end;
    if ((size_t)(eol - line) > name_len && line[name_len] == ':' &&
        strncasecmp(line, name, name_len) == 0) {
      const char* v = line + name_len + 1;
      while (v < eol && (*v == ' ' || *v == '\t')) v++;
      return v;
    }
    line = eol + 2;
  }
  return NULL;
}

/* De-chunk a Transfer-Encoding: chunked body in place into a fresh buffer.
 * Returns 0 and fills out/out_len, or -1 on framing errors. */
static int xn_dechunk(const uint8_t* body, size_t body_len, uint8_t** out, size_t* out_len) {
  uint8_t* acc = (uint8_t*)malloc(body_len ? body_len : 1);
  if (!acc) return -1;
  size_t acc_len = 0, i = 0;
  for (;;) {
    /* chunk-size line (hex, optional extensions after ';') */
    size_t j = i;
    size_t size = 0;
    int digits = 0;
    while (j < body_len && isxdigit(body[j])) {
      int c = body[j];
      size = size * 16 + (size_t)(c <= '9' ? c - '0' : (c | 32) - 'a' + 10);
      j++;
      digits++;
    }
    if (!digits) goto fail;
    while (j < body_len && body[j] != '\n') j++; /* skip extensions + CR */
    if (j >= body_len) goto fail;
    j++; /* past LF */
    if (size == 0) break; /* terminal chunk */
    if (j + size > body_len) goto fail;
    memcpy(acc + acc_len, body + j, size);
    acc_len += size;
    i = j + size;
    if (i + 2 <= body_len && body[i] == '\r' && body[i + 1] == '\n') i += 2;
    else goto fail;
  }
  *out = acc;
  *out_len = acc_len;
  return 0;
fail:
  free(acc);
  return -1;
}

int xn_http_transport(void* user, const char* request, const uint8_t* body,
                      uint64_t body_len, XnBuffer* out) {
  const XnHttpClient* c = (const XnHttpClient*)user;
  if (!c || !request || !out) return -1;
  out->data = NULL;
  out->len = 0;

  const char* space = strchr(request, ' ');
  if (!space || strlen(space + 1) == 0) return -1;
  size_t method_len = (size_t)(space - request);
  const char* path = space + 1;

  XnConn conn = {xn_connect(c), NULL, NULL};
  if (conn.fd < 0) return -2;
  if (c->use_tls && xn_tls_open(&conn, c) != 0) return -4; /* handshake/verify failed */

  char header[1024];
  int hn = snprintf(header, sizeof(header),
                    "%.*s %s HTTP/1.1\r\n"
                    "Host: %s:%s\r\n"
                    "Connection: close\r\n"
                    "Content-Length: %llu\r\n"
                    "\r\n",
                    (int)method_len, request, path, c->host, c->port,
                    (unsigned long long)body_len);
  if (hn <= 0 || (size_t)hn >= sizeof(header) || xn_write_all(&conn, header, (size_t)hn) != 0 ||
      (body_len && xn_write_all(&conn, body, body_len) != 0)) {
    xn_conn_close(&conn);
    return -2;
  }

  uint8_t* resp = NULL;
  size_t resp_len = 0;
  int clean_eof = 1;
  int rr = xn_read_all(&conn, &resp, &resp_len, &clean_eof);
  xn_conn_close(&conn);
  if (rr != 0) return -2;

  /* status line: "HTTP/1.1 NNN ..." (xn_read_all NUL-terminates) */
  int status = 0;
  if (resp_len > 12 && memcmp(resp, "HTTP/1.", 7) == 0) status = atoi((const char*)resp + 9);

  /* locate the header/body split */
  const uint8_t* body_start = NULL;
  for (size_t i = 0; i + 3 < resp_len; i++) {
    if (resp[i] == '\r' && resp[i + 1] == '\n' && resp[i + 2] == '\r' && resp[i + 3] == '\n') {
      body_start = resp + i + 4;
      break;
    }
  }
  if (!body_start || status == 0) {
    free(resp);
    return -3;
  }
  const char* headers = (const char*)resp;
  const char* headers_end = (const char*)body_start - 2;
  size_t raw_len = resp_len - (size_t)(body_start - resp);

  /* body framing: chunked (a proxy may re-frame), else Content-Length,
   * else everything until EOF (Connection: close) */
  uint8_t* body_buf = NULL;
  size_t content_len = 0;
  // chunked must be the FINAL coding (RFC 7230): search the value's tokens
  const char* te = xn_find_header(headers, headers_end, "Transfer-Encoding");
  int is_chunked = 0;
  if (te) {
    const char* eol = strstr(te, "\r\n");
    const char* end = eol ? eol : headers_end;
    const char* last = end;
    while (last > te && (last[-1] == ' ' || last[-1] == '\t')) last--;
    if (last - te >= 7 && strncasecmp(last - 7, "chunked", 7) == 0) is_chunked = 1;
  }
  if (is_chunked) {
    if (xn_dechunk(body_start, raw_len, &body_buf, &content_len) != 0) {
      free(resp);
      return -3;
    }
  } else {
    const char* cl = xn_find_header(headers, headers_end, "Content-Length");
    if (!cl && !clean_eof) {
      /* body framed only by connection close, but the close was not a TLS
       * close_notify: a truncation would be indistinguishable from the
       * real end, so reject rather than accept a possibly short body */
      free(resp);
      return -3;
    }
    content_len = cl ? (size_t)strtoull(cl, NULL, 10) : raw_len;
    if (content_len > raw_len) { /* truncated response */
      free(resp);
      return -3;
    }
    body_buf = (uint8_t*)malloc(content_len ? content_len : 1);
    if (!body_buf) {
      free(resp);
      return -1;
    }
    memcpy(body_buf, body_start, content_len);
  }
  free(resp);

  if (status == 204 || (status == 200 && content_len == 0)) {
    free(body_buf);
    return 1;
  }
  if (status != 200) {
    free(body_buf);
    return -status;
  }
  out->data = body_buf;
  out->len = content_len;
  return 0;
}
