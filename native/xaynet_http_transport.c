/* Built-in HTTP/1.1 transport for the interpreter-free native participant.
 *
 * Parity with the reference's bundled client
 * (rust/xaynet-mobile/src/reqwest_client.rs): an embedder links this file
 * (or libxaynet_http_transport.so) and passes `xn_http_transport` +
 * `xn_http_client_new(host, port)` straight into
 * `xaynet_ffi_participant_new` — no caller-written transport required.
 *
 * Plain POSIX sockets, one request per connection (`Connection: close`),
 * no third-party dependencies. TLS termination is expected at a proxy /
 * sidecar, as in the k8s development overlay (deploy/k8s/.../ingress.yaml).
 *
 * Contract (native/xaynet_participant.cpp:745-753): `request` is
 * "METHOD /path", the body is sent for POSTs; return 0 on HTTP 200 with a
 * malloc'd body in *out (the participant library frees it), 1 on 204/empty,
 * negative on transport failure.
 */

#include <errno.h>
#include <netdb.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

typedef struct {
  uint8_t* data;
  uint64_t len;
} XnBuffer;

typedef struct {
  char host[256];
  char port[16];
} XnHttpClient;

XnHttpClient* xn_http_client_new(const char* host, uint16_t port) {
  if (!host || strlen(host) >= sizeof(((XnHttpClient*)0)->host)) return NULL;
  XnHttpClient* c = (XnHttpClient*)calloc(1, sizeof(XnHttpClient));
  if (!c) return NULL;
  snprintf(c->host, sizeof(c->host), "%s", host);
  snprintf(c->port, sizeof(c->port), "%u", (unsigned)port);
  return c;
}

void xn_http_client_free(XnHttpClient* c) { free(c); }

static int xn_connect(const XnHttpClient* c) {
  struct addrinfo hints, *res = NULL, *ai;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(c->host, c->port, &hints, &res) != 0) return -1;
  int fd = -1;
  for (ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

static int xn_write_all(int fd, const void* buf, size_t len) {
  const uint8_t* p = (const uint8_t*)buf;
  while (len) {
    ssize_t n = write(fd, p, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += n;
    len -= (size_t)n;
  }
  return 0;
}

/* Read the whole response (Connection: close => until EOF). */
static int xn_read_all(int fd, uint8_t** out, size_t* out_len) {
  size_t cap = 8192, len = 0;
  uint8_t* buf = (uint8_t*)malloc(cap);
  if (!buf) return -1;
  for (;;) {
    if (len == cap) {
      cap *= 2;
      uint8_t* next = (uint8_t*)realloc(buf, cap);
      if (!next) {
        free(buf);
        return -1;
      }
      buf = next;
    }
    ssize_t n = read(fd, buf + len, cap - len);
    if (n < 0) {
      if (errno == EINTR) continue;
      free(buf);
      return -1;
    }
    if (n == 0) break;
    len += (size_t)n;
  }
  *out = buf;
  *out_len = len;
  return 0;
}

int xn_http_transport(void* user, const char* request, const uint8_t* body,
                      uint64_t body_len, XnBuffer* out) {
  const XnHttpClient* c = (const XnHttpClient*)user;
  if (!c || !request || !out) return -1;
  out->data = NULL;
  out->len = 0;

  const char* space = strchr(request, ' ');
  if (!space || strlen(space + 1) == 0) return -1;
  size_t method_len = (size_t)(space - request);
  const char* path = space + 1;

  int fd = xn_connect(c);
  if (fd < 0) return -2;

  char header[1024];
  int hn = snprintf(header, sizeof(header),
                    "%.*s %s HTTP/1.1\r\n"
                    "Host: %s:%s\r\n"
                    "Connection: close\r\n"
                    "Content-Length: %llu\r\n"
                    "\r\n",
                    (int)method_len, request, path, c->host, c->port,
                    (unsigned long long)body_len);
  if (hn <= 0 || (size_t)hn >= sizeof(header) || xn_write_all(fd, header, (size_t)hn) != 0 ||
      (body_len && xn_write_all(fd, body, body_len) != 0)) {
    close(fd);
    return -2;
  }

  uint8_t* resp = NULL;
  size_t resp_len = 0;
  int rr = xn_read_all(fd, &resp, &resp_len);
  close(fd);
  if (rr != 0) return -2;

  /* status line: "HTTP/1.1 NNN ..." */
  int status = 0;
  if (resp_len > 12 && memcmp(resp, "HTTP/1.", 7) == 0) status = atoi((const char*)resp + 9);

  /* locate the header/body split */
  const uint8_t* body_start = NULL;
  for (size_t i = 0; i + 3 < resp_len; i++) {
    if (resp[i] == '\r' && resp[i + 1] == '\n' && resp[i + 2] == '\r' && resp[i + 3] == '\n') {
      body_start = resp + i + 4;
      break;
    }
  }
  if (!body_start || status == 0) {
    free(resp);
    return -3;
  }
  size_t content_len = resp_len - (size_t)(body_start - resp);

  if (status == 204 || (status == 200 && content_len == 0)) {
    free(resp);
    return 1;
  }
  if (status != 200) {
    free(resp);
    return -status;
  }
  out->data = (uint8_t*)malloc(content_len ? content_len : 1);
  if (!out->data) {
    free(resp);
    return -1;
  }
  memcpy(out->data, body_start, content_len);
  out->len = content_len;
  free(resp);
  return 0;
}
