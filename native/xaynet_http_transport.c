/* Built-in HTTP/1.1 transport for the interpreter-free native participant.
 *
 * Parity with the reference's bundled client
 * (rust/xaynet-mobile/src/reqwest_client.rs): an embedder links this file
 * (or libxaynet_http_transport.so) and passes `xn_http_transport` +
 * `xn_http_client_new(host, port)` straight into
 * `xaynet_ffi_participant_new` — no caller-written transport required.
 *
 * Plain POSIX sockets, one request per connection (`Connection: close`),
 * no third-party dependencies. TLS termination is expected at a proxy /
 * sidecar, as in the k8s development overlay (deploy/k8s/.../ingress.yaml).
 *
 * Contract (native/xaynet_participant.cpp:745-753): `request` is
 * "METHOD /path", the body is sent for POSTs; return 0 on HTTP 200 with a
 * malloc'd body in *out (the participant library frees it), 1 on 204/empty,
 * negative on transport failure.
 */

#include <ctype.h>
#include <errno.h>
#include <netdb.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include "xaynet_participant.h"

struct XnHttpClient {
  char host[256];
  char port[16];
};

XnHttpClient* xn_http_client_new(const char* host, uint16_t port) {
  if (!host || strlen(host) >= sizeof(((XnHttpClient*)0)->host)) return NULL;
  XnHttpClient* c = (XnHttpClient*)calloc(1, sizeof(XnHttpClient));
  if (!c) return NULL;
  snprintf(c->host, sizeof(c->host), "%s", host);
  snprintf(c->port, sizeof(c->port), "%u", (unsigned)port);
  return c;
}

void xn_http_client_free(XnHttpClient* c) { free(c); }

static int xn_connect(const XnHttpClient* c) {
  struct addrinfo hints, *res = NULL, *ai;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(c->host, c->port, &hints, &res) != 0) return -1;
  int fd = -1;
  for (ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

static int xn_write_all(int fd, const void* buf, size_t len) {
  const uint8_t* p = (const uint8_t*)buf;
  while (len) {
    ssize_t n = write(fd, p, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += n;
    len -= (size_t)n;
  }
  return 0;
}

/* Read the whole response (Connection: close => until EOF); the buffer is
 * NUL-terminated one past `*out_len` so bounded string scans are safe. */
static int xn_read_all(int fd, uint8_t** out, size_t* out_len) {
  size_t cap = 8192, len = 0;
  uint8_t* buf = (uint8_t*)malloc(cap + 1);
  if (!buf) return -1;
  for (;;) {
    if (len == cap) {
      cap *= 2;
      uint8_t* next = (uint8_t*)realloc(buf, cap + 1);
      if (!next) {
        free(buf);
        return -1;
      }
      buf = next;
    }
    ssize_t n = read(fd, buf + len, cap - len);
    if (n < 0) {
      if (errno == EINTR) continue;
      free(buf);
      return -1;
    }
    if (n == 0) break;
    len += (size_t)n;
  }
  buf[len] = 0;
  *out = buf;
  *out_len = len;
  return 0;
}

/* Case-insensitive header lookup inside [headers, headers_end). Returns the
 * value start (past ':' and spaces) or NULL. */
static const char* xn_find_header(const char* headers, const char* headers_end,
                                  const char* name) {
  size_t name_len = strlen(name);
  const char* line = headers;
  while (line < headers_end) {
    const char* eol = strstr(line, "\r\n");
    if (!eol || eol > headers_end) eol = headers_end;
    if ((size_t)(eol - line) > name_len && line[name_len] == ':' &&
        strncasecmp(line, name, name_len) == 0) {
      const char* v = line + name_len + 1;
      while (v < eol && (*v == ' ' || *v == '\t')) v++;
      return v;
    }
    line = eol + 2;
  }
  return NULL;
}

/* De-chunk a Transfer-Encoding: chunked body in place into a fresh buffer.
 * Returns 0 and fills out/out_len, or -1 on framing errors. */
static int xn_dechunk(const uint8_t* body, size_t body_len, uint8_t** out, size_t* out_len) {
  uint8_t* acc = (uint8_t*)malloc(body_len ? body_len : 1);
  if (!acc) return -1;
  size_t acc_len = 0, i = 0;
  for (;;) {
    /* chunk-size line (hex, optional extensions after ';') */
    size_t j = i;
    size_t size = 0;
    int digits = 0;
    while (j < body_len && isxdigit(body[j])) {
      int c = body[j];
      size = size * 16 + (size_t)(c <= '9' ? c - '0' : (c | 32) - 'a' + 10);
      j++;
      digits++;
    }
    if (!digits) goto fail;
    while (j < body_len && body[j] != '\n') j++; /* skip extensions + CR */
    if (j >= body_len) goto fail;
    j++; /* past LF */
    if (size == 0) break; /* terminal chunk */
    if (j + size > body_len) goto fail;
    memcpy(acc + acc_len, body + j, size);
    acc_len += size;
    i = j + size;
    if (i + 2 <= body_len && body[i] == '\r' && body[i + 1] == '\n') i += 2;
    else goto fail;
  }
  *out = acc;
  *out_len = acc_len;
  return 0;
fail:
  free(acc);
  return -1;
}

int xn_http_transport(void* user, const char* request, const uint8_t* body,
                      uint64_t body_len, XnBuffer* out) {
  const XnHttpClient* c = (const XnHttpClient*)user;
  if (!c || !request || !out) return -1;
  out->data = NULL;
  out->len = 0;

  const char* space = strchr(request, ' ');
  if (!space || strlen(space + 1) == 0) return -1;
  size_t method_len = (size_t)(space - request);
  const char* path = space + 1;

  int fd = xn_connect(c);
  if (fd < 0) return -2;

  char header[1024];
  int hn = snprintf(header, sizeof(header),
                    "%.*s %s HTTP/1.1\r\n"
                    "Host: %s:%s\r\n"
                    "Connection: close\r\n"
                    "Content-Length: %llu\r\n"
                    "\r\n",
                    (int)method_len, request, path, c->host, c->port,
                    (unsigned long long)body_len);
  if (hn <= 0 || (size_t)hn >= sizeof(header) || xn_write_all(fd, header, (size_t)hn) != 0 ||
      (body_len && xn_write_all(fd, body, body_len) != 0)) {
    close(fd);
    return -2;
  }

  uint8_t* resp = NULL;
  size_t resp_len = 0;
  int rr = xn_read_all(fd, &resp, &resp_len);
  close(fd);
  if (rr != 0) return -2;

  /* status line: "HTTP/1.1 NNN ..." (xn_read_all NUL-terminates) */
  int status = 0;
  if (resp_len > 12 && memcmp(resp, "HTTP/1.", 7) == 0) status = atoi((const char*)resp + 9);

  /* locate the header/body split */
  const uint8_t* body_start = NULL;
  for (size_t i = 0; i + 3 < resp_len; i++) {
    if (resp[i] == '\r' && resp[i + 1] == '\n' && resp[i + 2] == '\r' && resp[i + 3] == '\n') {
      body_start = resp + i + 4;
      break;
    }
  }
  if (!body_start || status == 0) {
    free(resp);
    return -3;
  }
  const char* headers = (const char*)resp;
  const char* headers_end = (const char*)body_start - 2;
  size_t raw_len = resp_len - (size_t)(body_start - resp);

  /* body framing: chunked (a proxy may re-frame), else Content-Length,
   * else everything until EOF (Connection: close) */
  uint8_t* body_buf = NULL;
  size_t content_len = 0;
  // chunked must be the FINAL coding (RFC 7230): search the value's tokens
  const char* te = xn_find_header(headers, headers_end, "Transfer-Encoding");
  int is_chunked = 0;
  if (te) {
    const char* eol = strstr(te, "\r\n");
    const char* end = eol ? eol : headers_end;
    const char* last = end;
    while (last > te && (last[-1] == ' ' || last[-1] == '\t')) last--;
    if (last - te >= 7 && strncasecmp(last - 7, "chunked", 7) == 0) is_chunked = 1;
  }
  if (is_chunked) {
    if (xn_dechunk(body_start, raw_len, &body_buf, &content_len) != 0) {
      free(resp);
      return -3;
    }
  } else {
    const char* cl = xn_find_header(headers, headers_end, "Content-Length");
    content_len = cl ? (size_t)strtoull(cl, NULL, 10) : raw_len;
    if (content_len > raw_len) { /* truncated response */
      free(resp);
      return -3;
    }
    body_buf = (uint8_t*)malloc(content_len ? content_len : 1);
    if (!body_buf) {
      free(resp);
      return -1;
    }
    memcpy(body_buf, body_start, content_len);
  }
  free(resp);

  if (status == 204 || (status == 200 && content_len == 0)) {
    free(body_buf);
    return 1;
  }
  if (status != 200) {
    free(body_buf);
    return -status;
  }
  out->data = body_buf;
  out->len = content_len;
  return 0;
}
