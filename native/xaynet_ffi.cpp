// C FFI for embedding a PET participant in non-python hosts.
//
// Functional analogue of the reference's mobile FFI surface (reference:
// rust/xaynet-mobile/src/ffi/ — xaynet_ffi_participant_{new,tick,set_model,
// global_model,save,restore,destroy} and error codes). The participant
// logic lives in the python package; this library embeds a CPython
// interpreter and drives `xaynet_tpu.sdk.participant.Participant`, so a
// C/C++/Dart host links one shared library and needs no python code of its
// own (a python runtime with the package installed must be present).
//
// Thread-model: all calls must come from one thread (the embedded
// interpreter owns the participant; the reference has the same
// single-caller contract for its tick loop).
//
// Build:  make -C native ffi    ->  libxaynet_ffi.so

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>

#define XN_EXPORT extern "C" __attribute__((visibility("default")))

// error codes (shape mirrors the reference's 0..n_ codes)
enum {
  XN_OK = 0,
  XN_ERR_INIT = 1,
  XN_ERR_NULL = 2,
  XN_ERR_PYTHON = 3,
  XN_ERR_BUFFER_TOO_SMALL = 4,
};

namespace {

bool g_initialized = false;

struct XnParticipant {
  PyObject* obj;  // xaynet_tpu.sdk.participant.Participant
};

int clear_error() {
  if (PyErr_Occurred()) {
    PyErr_Print();
    return XN_ERR_PYTHON;
  }
  return XN_OK;
}

PyObject* participant_class() {
  PyObject* mod = PyImport_ImportModule("xaynet_tpu.sdk.participant");
  if (!mod) return nullptr;
  PyObject* cls = PyObject_GetAttrString(mod, "Participant");
  Py_DECREF(mod);
  return cls;
}

}  // namespace

// Initialize the embedded interpreter. `repo_path` (optional, may be NULL)
// is prepended to sys.path so the package resolves without installation.
XN_EXPORT int xaynet_ffi_init(const char* repo_path) {
  if (g_initialized) return XN_OK;
  Py_Initialize();
  if (repo_path && *repo_path) {
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    PyObject* p = PyUnicode_FromString(repo_path);
    if (sys_path && p) PyList_Insert(sys_path, 0, p);
    Py_XDECREF(p);
  }
  g_initialized = true;
  return clear_error();
}

// Create a participant for the coordinator at `url`. Returns NULL on error.
XN_EXPORT XnParticipant* xaynet_ffi_participant_new(const char* url) {
  if (!g_initialized || !url) return nullptr;
  PyObject* cls = participant_class();
  if (!cls) {
    clear_error();
    return nullptr;
  }
  PyObject* obj = PyObject_CallFunction(cls, "s", url);
  Py_DECREF(cls);
  if (!obj) {
    clear_error();
    return nullptr;
  }
  auto* p = new XnParticipant{obj};
  return p;
}

// Restore a participant from a saved state blob. Returns NULL on error.
XN_EXPORT XnParticipant* xaynet_ffi_participant_restore(const char* url,
                                                        const uint8_t* state,
                                                        size_t state_len) {
  if (!g_initialized || !url || !state) return nullptr;
  PyObject* cls = participant_class();
  if (!cls) {
    clear_error();
    return nullptr;
  }
  PyObject* restore = PyObject_GetAttrString(cls, "restore");
  Py_DECREF(cls);
  if (!restore) {
    clear_error();
    return nullptr;
  }
  PyObject* obj = PyObject_CallFunction(restore, "y#s", (const char*)state,
                                        (Py_ssize_t)state_len, url);
  Py_DECREF(restore);
  if (!obj) {
    clear_error();
    return nullptr;
  }
  return new XnParticipant{obj};
}

// One state-machine transition.
XN_EXPORT int xaynet_ffi_participant_tick(XnParticipant* p) {
  if (!p) return XN_ERR_NULL;
  PyObject* r = PyObject_CallMethod(p->obj, "tick", nullptr);
  Py_XDECREF(r);
  return clear_error();
}

// 1 if the last tick made progress, 0 if pending, negative on error.
XN_EXPORT int xaynet_ffi_participant_made_progress(XnParticipant* p) {
  if (!p) return -XN_ERR_NULL;
  PyObject* r = PyObject_CallMethod(p->obj, "made_progress", nullptr);
  if (!r) return -clear_error();
  int v = PyObject_IsTrue(r);
  Py_DECREF(r);
  return v;
}

// 1 if the FSM wants a trained model, 0 otherwise, negative on error.
XN_EXPORT int xaynet_ffi_participant_should_set_model(XnParticipant* p) {
  if (!p) return -XN_ERR_NULL;
  PyObject* r = PyObject_CallMethod(p->obj, "should_set_model", nullptr);
  if (!r) return -clear_error();
  int v = PyObject_IsTrue(r);
  Py_DECREF(r);
  return v;
}

// Current task: 0 none, 1 sum, 2 update; negative on error.
XN_EXPORT int xaynet_ffi_participant_task(XnParticipant* p) {
  if (!p) return -XN_ERR_NULL;
  PyObject* r = PyObject_CallMethod(p->obj, "task", nullptr);
  if (!r) return -clear_error();
  PyObject* v = PyObject_GetAttrString(r, "value");
  Py_DECREF(r);
  if (!v) return -clear_error();
  const char* s = PyUnicode_AsUTF8(v);
  int code = 0;
  if (s && strcmp(s, "sum") == 0) code = 1;
  if (s && strcmp(s, "update") == 0) code = 2;
  Py_DECREF(v);
  return code;
}

// Provide the locally trained model (float32 weights).
XN_EXPORT int xaynet_ffi_participant_set_model(XnParticipant* p, const float* weights,
                                               size_t len) {
  if (!p || !weights) return XN_ERR_NULL;
  PyObject* list = PyList_New((Py_ssize_t)len);
  if (!list) return clear_error();
  for (size_t i = 0; i < len; i++) {
    PyList_SET_ITEM(list, (Py_ssize_t)i, PyFloat_FromDouble((double)weights[i]));
  }
  PyObject* r = PyObject_CallMethod(p->obj, "set_model", "O", list);
  Py_DECREF(list);
  Py_XDECREF(r);
  return clear_error();
}

// Fetch the latest global model into `out` (float32). Returns the model
// length, 0 when no model is available, or a negative error code. When the
// buffer is too small, returns -XN_ERR_BUFFER_TOO_SMALL.
XN_EXPORT long xaynet_ffi_participant_global_model(XnParticipant* p, float* out,
                                                   size_t capacity) {
  if (!p) return -XN_ERR_NULL;
  PyObject* r = PyObject_CallMethod(p->obj, "global_model", nullptr);
  if (!r) return -clear_error();
  if (r == Py_None) {
    Py_DECREF(r);
    return 0;
  }
  PyObject* seq = PySequence_Fast(r, "global model is not a sequence");
  Py_DECREF(r);
  if (!seq) return -clear_error();
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (out == nullptr || (size_t)n > capacity) {
    Py_DECREF(seq);
    return out == nullptr ? (long)n : -(long)XN_ERR_BUFFER_TOO_SMALL;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    out[i] = (float)PyFloat_AsDouble(PySequence_Fast_GET_ITEM(seq, i));
  }
  Py_DECREF(seq);
  if (PyErr_Occurred()) return -clear_error();
  return (long)n;
}

// Serialize the participant into `out`; the instance is consumed (mirrors
// the reference's move semantics). Returns the state length or negative.
XN_EXPORT long xaynet_ffi_participant_save(XnParticipant* p, uint8_t* out,
                                           size_t capacity) {
  if (!p) return -XN_ERR_NULL;
  PyObject* r = PyObject_CallMethod(p->obj, "save", nullptr);
  if (!r) return -clear_error();
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    return -clear_error();
  }
  if (out != nullptr && (size_t)n <= capacity) {
    memcpy(out, buf, (size_t)n);
  }
  long result = (out == nullptr || (size_t)n <= capacity)
                    ? (long)n
                    : -(long)XN_ERR_BUFFER_TOO_SMALL;
  Py_DECREF(r);
  Py_DECREF(p->obj);
  delete p;
  return result;
}

XN_EXPORT void xaynet_ffi_participant_destroy(XnParticipant* p) {
  if (!p) return;
  Py_XDECREF(p->obj);
  delete p;
}

XN_EXPORT uint32_t xaynet_ffi_abi_version(void) { return 1; }
