/* Public C ABI of the interpreter-free native participant
 * (libxaynet_participant.so) and the bundled HTTP transport
 * (libxaynet_http_transport.so).
 *
 * The single source of truth for the transport callback contract and the
 * exported prototypes — included by xaynet_participant.cpp,
 * xaynet_http_transport.c and every embedder (http_demo.c), so an ABI
 * change is a compile error everywhere instead of a silent runtime
 * mismatch. Reference analogue: the cbindgen-generated header of
 * rust/xaynet-mobile/src/ffi/.
 */

#ifndef XAYNET_PARTICIPANT_H
#define XAYNET_PARTICIPANT_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Transport callback: method+path in `request` ("GET /params",
 * "POST /message", "GET /seeds?pk=<hex>", "GET /model"), body for POSTs.
 * Returns 0 on HTTP 200 (fill *out with malloc'd bytes — the participant
 * library frees them), 1 on 204/empty, negative on transport failure. */
typedef struct {
  uint8_t* data;
  uint64_t len;
} XnBuffer;
typedef int (*xn_transport_fn)(void* user, const char* request, const uint8_t* body,
                               uint64_t body_len, XnBuffer* out);

enum XnTask { XN_TASK_NONE = 0, XN_TASK_SUM = 1, XN_TASK_UPDATE = 2 };
enum {
  XN_OK = 0,
  XN_ERR_NULL = -1,
  XN_ERR_TRANSPORT = -2,
  XN_ERR_PARSE = -3,
  XN_ERR_CRYPTO = -4,
  XN_ERR_STATE = -5,
  XN_ERR_CONFIG = -6,
  XN_ERR_MODEL = -7,
  XN_ERR_RESTORE = -8,
};

/* --- participant lifecycle (libxaynet_participant.so) ------------------- */
uint32_t xaynet_ffi_abi_version(void);
int xaynet_ffi_crypto_init(void);
void* xaynet_ffi_participant_new(const uint8_t signing_seed[32], int64_t scalar_num,
                                 int64_t scalar_den, uint32_t max_message_size,
                                 xn_transport_fn transport, void* user);
void* xaynet_ffi_participant_restore(const uint8_t* data, uint64_t len,
                                     xn_transport_fn transport, void* user);
void xaynet_ffi_participant_destroy(void* handle);
int xaynet_ffi_participant_tick(void* handle);
int xaynet_ffi_participant_task(void* handle);
int xaynet_ffi_participant_made_progress(void* handle);
int xaynet_ffi_participant_should_set_model(void* handle);
int xaynet_ffi_participant_new_round(void* handle);
int xaynet_ffi_participant_set_model(void* handle, const float* data, uint64_t len);
int xaynet_ffi_participant_set_model_i64(void* handle, const int64_t* data, uint64_t len);
int xaynet_ffi_participant_set_model_f64(void* handle, const double* data, uint64_t len);
int64_t xaynet_ffi_participant_global_model(void* handle, const double** out);
int xaynet_ffi_participant_save(void* handle, uint8_t** out, uint64_t* out_len);
void xaynet_ffi_free(void* ptr);

/* --- crypto helpers (cross-language interop tests) ---------------------- */
int xaynet_ffi_seal(const uint8_t* msg, uint64_t len, const uint8_t pk[32], uint8_t* out,
                    uint64_t* out_len);
int xaynet_ffi_seal_open(const uint8_t* sealed, uint64_t len, const uint8_t sk[32], uint8_t* out,
                         uint64_t* out_len);
int xaynet_ffi_sign(const uint8_t seed[32], const uint8_t* msg, uint64_t len, uint8_t sig[64]);
int xaynet_ffi_is_eligible(const uint8_t sig[64], double threshold);

/* --- bundled HTTP/1.1 transport (libxaynet_http_transport.so) ----------- */
typedef struct XnHttpClient XnHttpClient;
XnHttpClient* xn_http_client_new(const char* host, uint16_t port);
/* TLS client with root-cert PINNING: `ca_pem_path` becomes the entire
 * trust store (system roots are NOT consulted), and the peer cert is bound
 * to `host` (hostname or IP SAN). Pass both `client_cert_pem_path` and
 * `client_key_pem_path` for in-process client identity (mutual TLS), or
 * both NULL. Parity: rust/xaynet-mobile/src/reqwest_client.rs:58-71.
 * Returns NULL if no usable libssl is present at runtime (dlopen). */
XnHttpClient* xn_http_client_new_tls(const char* host, uint16_t port, const char* ca_pem_path,
                                     const char* client_cert_pem_path,
                                     const char* client_key_pem_path);
void xn_http_client_free(XnHttpClient* c);
int xn_http_transport(void* user, const char* request, const uint8_t* body, uint64_t body_len,
                      XnBuffer* out);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* XAYNET_PARTICIPANT_H */
