/* Demo host program for the C FFI: drives a PET participant from C.
 *
 * Usage: ffi_demo <coordinator_url> <repo_path>
 *
 * Creates a participant, ticks it a few times against the coordinator,
 * reports task/progress, exercises set_model and save/restore, and prints
 * one status line per step (consumed by tests/test_ffi.py).
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

typedef struct XnParticipant XnParticipant;

extern int xaynet_ffi_init(const char* repo_path);
extern uint32_t xaynet_ffi_abi_version(void);
extern XnParticipant* xaynet_ffi_participant_new(const char* url);
extern XnParticipant* xaynet_ffi_participant_restore(const char* url, const uint8_t* state,
                                                     size_t state_len);
extern int xaynet_ffi_participant_tick(XnParticipant* p);
extern int xaynet_ffi_participant_made_progress(XnParticipant* p);
extern int xaynet_ffi_participant_should_set_model(XnParticipant* p);
extern int xaynet_ffi_participant_task(XnParticipant* p);
extern int xaynet_ffi_participant_set_model(XnParticipant* p, const float* w, size_t len);
extern long xaynet_ffi_participant_global_model(XnParticipant* p, float* out, size_t cap);
extern long xaynet_ffi_participant_save(XnParticipant* p, uint8_t* out, size_t cap);
extern void xaynet_ffi_participant_destroy(XnParticipant* p);

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <coordinator_url> <repo_path>\n", argv[0]);
    return 2;
  }
  if (xaynet_ffi_init(argv[2]) != 0) {
    fprintf(stderr, "init failed\n");
    return 1;
  }
  printf("abi=%u\n", xaynet_ffi_abi_version());

  XnParticipant* p = xaynet_ffi_participant_new(argv[1]);
  if (!p) {
    fprintf(stderr, "participant_new failed\n");
    return 1;
  }

  for (int i = 0; i < 5; i++) {
    if (xaynet_ffi_participant_tick(p) != 0) {
      fprintf(stderr, "tick failed\n");
      return 1;
    }
    printf("tick=%d progress=%d task=%d should_set_model=%d\n", i,
           xaynet_ffi_participant_made_progress(p), xaynet_ffi_participant_task(p),
           xaynet_ffi_participant_should_set_model(p));
  }

  float model[4] = {0.1f, 0.2f, 0.3f, 0.4f};
  if (xaynet_ffi_participant_set_model(p, model, 4) != 0) {
    fprintf(stderr, "set_model failed\n");
    return 1;
  }
  printf("set_model=ok\n");

  long n = xaynet_ffi_participant_global_model(p, NULL, 0);
  printf("global_model_len=%ld\n", n);

  uint8_t state[4096];
  long len = xaynet_ffi_participant_save(p, state, sizeof(state));
  if (len <= 0) {
    fprintf(stderr, "save failed: %ld\n", len);
    return 1;
  }
  printf("saved=%ld\n", len);

  XnParticipant* q = xaynet_ffi_participant_restore(argv[1], state, (size_t)len);
  if (!q) {
    fprintf(stderr, "restore failed\n");
    return 1;
  }
  if (xaynet_ffi_participant_tick(q) != 0) {
    fprintf(stderr, "tick after restore failed\n");
    return 1;
  }
  printf("restored_tick=ok task=%d\n", xaynet_ffi_participant_task(q));
  xaynet_ffi_participant_destroy(q);
  printf("done\n");
  return 0;
}
