#!/usr/bin/env bash
# Bring up the FULL composed stack (coordinator + redis + minio + influxdb)
# and complete PET rounds against it over the real socket.
#
#   deploy/compose_smoke.sh [rounds]
#
# Succeeds only if examples/test_drive.py finishes the rounds, which proves:
# redis-backed dictionaries (Lua scripts in a real Redis), minio-backed
# global models (SigV4), influx metrics, and the full message pipeline.
set -euo pipefail
cd "$(dirname "$0")/.."

ROUNDS="${1:-2}"
# a dedicated project name namespaces containers AND volumes away from any
# standing deployment: the cleanup's `down -v` can only remove smoke state
COMPOSE=(docker compose -p xaynet-smoke -f deploy/docker-compose.yml --profile full)

cleanup() { "${COMPOSE[@]}" down -v; }
trap cleanup EXIT

"${COMPOSE[@]}" up -d --build

echo "waiting for the coordinator to answer /params ..."
for i in $(seq 1 60); do
  if curl -fsS -o /dev/null http://127.0.0.1:8082/params; then
    break
  fi
  [ "$i" = 60 ] && { echo "coordinator never came up"; "${COMPOSE[@]}" logs coordinator-full | tail -50; exit 1; }
  sleep 2
done

# -n/-l must match the coordinator-full PET window + model length env
JAX_PLATFORMS=cpu python examples/test_drive.py --url http://127.0.0.1:8082 -n 20 -l 1000 -r "$ROUNDS"

echo "checking metrics landed in influxdb ..."
"${COMPOSE[@]}" exec -T influxdb \
  influx -database metrics -execute 'SHOW MEASUREMENTS' | head -20 || true

echo "compose smoke OK"
