"""Pytest configuration: force a deterministic multi-device CPU platform.

Sharding tests run on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count``); benchmarks use real TPU
hardware separately via ``bench.py``.

The image's sitecustomize registers the axon TPU backend and overrides
``jax_platforms``, so forcing the env var alone is not enough — the config
must be re-set after import and before first backend use.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
