"""Bench regression gate: replay BENCH_HISTORY.jsonl, fail on regression.

The perf trajectory (BENCH.md) must only move up: this gate replays the
bench history, finds the HEADLINE series — masked-update aggregation
throughput in updates/s — and exits 1 when the latest recorded round
regresses more than ``--threshold`` (default 10%) against the best prior
round. Wire it as a tier-2 check after appending a fresh bench round:

  python bench.py ... && python tools/bench_gate.py

Entries are heterogeneous (several generations of writers appended here);
a record contributes when its metric/value/unit can be found either at the
top level or under ``parsed``. Unmatched lines are skipped, never fatal —
the gate must keep working as writers evolve.

Usage:
  python tools/bench_gate.py [--history BENCH_HISTORY.jsonl]
                             [--metric-prefix "masked-update aggregation throughput"]
                             [--threshold 0.10] [--list]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_HISTORY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BENCH_HISTORY.jsonl"
)
HEADLINE_PREFIX = "masked-update aggregation throughput"
HEADLINE_UNIT = "updates/s"


def extract(record: dict) -> tuple[str, float, str, str] | None:
    """(metric, value, unit, config) from one history record, wherever the
    writer put it; None when the record carries no scalar metric.

    ``config`` is the measurement-configuration fingerprint: the fold
    kernel plus the pinned thread counts (and mesh size) when the writer
    recorded them. A kernel or thread-config change is a DIFFERENT
    experiment — BENCH_r05 re-measured 29.46 updates/s where r03 recorded
    ~49 on the same code purely from an implicit thread-default shift — so
    the gate compares only within one exact (metric, config) series
    instead of flagging the config change as a regression."""
    for node in (record, record.get("parsed") or {}):
        metric = node.get("metric")
        value = node.get("value")
        unit = node.get("unit")
        if metric and isinstance(value, (int, float)):
            parts = []
            for field in ("kernel", "native_threads", "shard_threads", "mesh"):
                if node.get(field) is not None:
                    parts.append(f"{field}={node[field]}")
            return str(metric), float(value), str(unit or ""), ",".join(parts)
    return None


def load_series(
    path: str, metric_prefix: str, unit: str
) -> list[tuple[float, str, float, str]]:
    """Chronological (ts, metric, value, config) for the headline series."""
    series = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn append must not kill the gate
            found = extract(record)
            if found is None:
                continue
            metric, value, rec_unit, config = found
            if metric.startswith(metric_prefix) and rec_unit == unit:
                series.append((float(record.get("ts", 0.0)), metric, value, config))
    series.sort(key=lambda item: item[0])
    return series


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history", default=DEFAULT_HISTORY)
    ap.add_argument(
        "--metric-prefix",
        default=HEADLINE_PREFIX,
        help="headline series selector (metric name prefix)",
    )
    ap.add_argument("--unit", default=HEADLINE_UNIT)
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="maximum tolerated fractional regression vs the best prior round",
    )
    ap.add_argument(
        "--list", action="store_true", help="print the headline series and exit 0"
    )
    args = ap.parse_args()
    if not (0.0 < args.threshold < 1.0):
        ap.error("--threshold must be in (0, 1)")

    series = load_series(args.history, args.metric_prefix, args.unit)
    if args.list:
        for ts, metric, value, config in series:
            suffix = f"  [{config}]" if config else ""
            print(f"{ts:.0f}  {value:10.2f} {args.unit}  {metric}{suffix}")
        return 0
    if len(series) < 2:
        # nothing to gate against: a fresh repo (or a renamed headline) must
        # not hard-fail CI, but say so loudly
        print(
            f"bench-gate: only {len(series)} headline round(s) in "
            f"{args.history}; nothing to compare",
            file=sys.stderr,
        )
        return 0

    # gate within ONE exact series: the prefix family carries variants
    # (@25M params vs @200k params) whose absolute numbers are worlds
    # apart, and a kernel/thread-config change is a different experiment —
    # the latest record picks which (metric, config) series is being gated
    latest_metric, latest_config = series[-1][1], series[-1][3]
    same_metric = [item for item in series if item[1] == latest_metric]
    series = [item for item in same_metric if item[3] == latest_config]
    if len(series) < 2:
        if len(same_metric) >= 2:
            print(
                f"bench-gate: first round of '{latest_metric}' with config "
                f"[{latest_config or 'none recorded'}] — a kernel/thread-config "
                "change starts a NEW series, not a regression; nothing to compare",
                file=sys.stderr,
            )
        else:
            print(
                f"bench-gate: first round of '{latest_metric}'; nothing to compare",
                file=sys.stderr,
            )
        return 0
    *prior, (_, _, latest, _) = series
    best_ts, best_metric, best, _best_cfg = max(prior, key=lambda item: item[2])
    floor = best * (1.0 - args.threshold)
    verdict = {
        "latest": latest,
        "best_prior": best,
        "floor": round(floor, 3),
        "threshold": args.threshold,
        "unit": args.unit,
        "rounds": len(series),
        "metric": latest_metric,
        "config": latest_config,
    }
    if latest < floor:
        verdict["result"] = "REGRESSION"
        print(json.dumps(verdict))
        print(
            f"bench-gate: FAIL — latest {latest:.2f} {args.unit} is "
            f"{(1 - latest / best) * 100:.1f}% below the best prior round "
            f"({best:.2f} @ ts {best_ts:.0f}, '{best_metric}'); "
            f"tolerated: {args.threshold * 100:.0f}%",
            file=sys.stderr,
        )
        return 1
    verdict["result"] = "ok"
    print(json.dumps(verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
