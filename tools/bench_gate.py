"""Bench regression gate: replay BENCH_HISTORY.jsonl, fail on regression.

The perf trajectory (BENCH.md) must only move up: this gate replays the
bench history, finds the HEADLINE series — masked-update aggregation
throughput in updates/s — and exits 1 when the latest recorded round
regresses more than ``--threshold`` (default 10%) against the best prior
round. Wire it as a tier-2 check after appending a fresh bench round:

  python bench.py ... && python tools/bench_gate.py

Entries are heterogeneous (several generations of writers appended here);
a record contributes when its metric/value/unit can be found either at the
top level or under ``parsed``. Unmatched lines are skipped, never fatal —
the gate must keep working as writers evolve.

Usage:
  python tools/bench_gate.py [--history BENCH_HISTORY.jsonl]
                             [--metric-prefix "masked-update aggregation throughput"]
                             [--threshold 0.10] [--list]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_HISTORY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BENCH_HISTORY.jsonl"
)
HEADLINE_PREFIX = "masked-update aggregation throughput"
HEADLINE_UNIT = "updates/s"


def extract(record: dict) -> tuple[str, float, str] | None:
    """(metric, value, unit) from one history record, wherever the writer
    put it; None when the record carries no scalar metric."""
    for node in (record, record.get("parsed") or {}):
        metric = node.get("metric")
        value = node.get("value")
        unit = node.get("unit")
        if metric and isinstance(value, (int, float)):
            return str(metric), float(value), str(unit or "")
    return None


def load_series(path: str, metric_prefix: str, unit: str) -> list[tuple[float, str, float]]:
    """Chronological (ts, metric, value) for the headline series."""
    series = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn append must not kill the gate
            found = extract(record)
            if found is None:
                continue
            metric, value, rec_unit = found
            if metric.startswith(metric_prefix) and rec_unit == unit:
                series.append((float(record.get("ts", 0.0)), metric, value))
    series.sort(key=lambda item: item[0])
    return series


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history", default=DEFAULT_HISTORY)
    ap.add_argument(
        "--metric-prefix",
        default=HEADLINE_PREFIX,
        help="headline series selector (metric name prefix)",
    )
    ap.add_argument("--unit", default=HEADLINE_UNIT)
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="maximum tolerated fractional regression vs the best prior round",
    )
    ap.add_argument(
        "--list", action="store_true", help="print the headline series and exit 0"
    )
    args = ap.parse_args()
    if not (0.0 < args.threshold < 1.0):
        ap.error("--threshold must be in (0, 1)")

    series = load_series(args.history, args.metric_prefix, args.unit)
    if args.list:
        for ts, metric, value in series:
            print(f"{ts:.0f}  {value:10.2f} {args.unit}  {metric}")
        return 0
    if len(series) < 2:
        # nothing to gate against: a fresh repo (or a renamed headline) must
        # not hard-fail CI, but say so loudly
        print(
            f"bench-gate: only {len(series)} headline round(s) in "
            f"{args.history}; nothing to compare",
            file=sys.stderr,
        )
        return 0

    # gate within ONE exact series: the prefix family carries variants
    # (@25M params vs @200k params) whose absolute numbers are worlds
    # apart — the latest record picks which variant is being gated
    latest_metric = series[-1][1]
    series = [item for item in series if item[1] == latest_metric]
    if len(series) < 2:
        print(
            f"bench-gate: first round of '{latest_metric}'; nothing to compare",
            file=sys.stderr,
        )
        return 0
    *prior, (_, _, latest) = series
    best_ts, best_metric, best = max(prior, key=lambda item: item[2])
    floor = best * (1.0 - args.threshold)
    verdict = {
        "latest": latest,
        "best_prior": best,
        "floor": round(floor, 3),
        "threshold": args.threshold,
        "unit": args.unit,
        "rounds": len(series),
        "metric": latest_metric,
    }
    if latest < floor:
        verdict["result"] = "REGRESSION"
        print(json.dumps(verdict))
        print(
            f"bench-gate: FAIL — latest {latest:.2f} {args.unit} is "
            f"{(1 - latest / best) * 100:.1f}% below the best prior round "
            f"({best:.2f} @ ts {best_ts:.0f}, '{best_metric}'); "
            f"tolerated: {args.threshold * 100:.0f}%",
            file=sys.stderr,
        )
        return 1
    verdict["result"] = "ok"
    print(json.dumps(verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
