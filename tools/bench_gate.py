"""Bench regression gate: replay BENCH_HISTORY.jsonl, fail on regression.

The perf trajectory (BENCH.md) must only move up: this gate replays the
bench history and exits 1 when, for any gated HEADLINE FAMILY, the latest
recorded round regresses more than ``--threshold`` (default 10%) against
the best prior round of the SAME series. Two families gate independently
by default:

  - the fold headline — masked-update aggregation throughput, updates/s;
  - the sim headline — in-graph federated simulation, participants/s.

Wire it as a tier-2 check after appending a fresh bench round:

  python bench.py ... && python tools/bench_gate.py

Entries are heterogeneous (several generations of writers appended here);
a record contributes when its metric/value/unit can be found either at the
top level or under ``parsed``. Unmatched lines are skipped, never fatal —
the gate must keep working as writers evolve.

Usage:
  python tools/bench_gate.py [--history BENCH_HISTORY.jsonl]
                             [--metric-prefix "masked-update aggregation throughput"
                              --unit "updates/s"]
                             [--threshold 0.10] [--list] [--with-analysis]

``--with-analysis`` additionally runs the static-analysis gate
(tools/analysis, same checks as ``python tools/lint.py --strict``,
including the cross-file deep passes — locks/purity/invariants/metrics/
spans and the secret-flow taint analysis, DESIGN §18) through its
persistent result cache — in CI the lint job has already warmed
``.lint-cache.json`` for the checkout, so the bench leg re-verifies the
tree (taint artifacts included: the deep passes memoize as one unit
keyed by the whole-tree digest) for effectively free instead of
re-analyzing it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

DEFAULT_HISTORY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BENCH_HISTORY.jsonl"
)
HEADLINE_PREFIX = "masked-update aggregation throughput"
HEADLINE_UNIT = "updates/s"
SIM_PREFIX = "sim round throughput"
SIM_UNIT = "participants/s"
# full-round-path families (tools/bench_round.py): the sum2 mask
# derive+sum and unmask+decode walls recorded as element rates, so the
# higher-is-better floor logic applies unchanged
SUM2_PREFIX = "e2e sum2 mask throughput"
UNMASK_PREFIX = "e2e unmask throughput"
ELEMENTS_UNIT = "elements/s"
# packed-reduction family (bench.py:bytes): staging + cross-shard combine
# traffic per fold. LOWER is better — the floor logic inverts (see
# LOWER_IS_BETTER_UNITS): the gate fails when the latest round MOVES MORE
# bytes than the best (smallest) prior round tolerates.
BYTES_PREFIX = "bytes moved per fold"
BYTES_UNIT = "bytes/fold"
# round-wall family (tools/bench_round.py, DESIGN §20): the end-to-end
# round wall the SLO engine budgets in production. LOWER is better, like
# the bytes family — the gate fails when the latest round takes LONGER
# than the best (fastest) prior round tolerates.
ROUND_WALL_PREFIX = "round wall"
ROUND_WALL_UNIT = "s/round"
# crash-recovery family (tools/soak.py --kill-matrix, DESIGN §9): the
# restarted coordinator's boot-to-serving wall (``xaynet_recovery_seconds``)
# per kill coordinate. LOWER is better — the gate fails when a restart
# takes LONGER than the best (fastest) prior recovery tolerates.
RECOVERY_PREFIX = "restart recovery wall"
RECOVERY_UNIT = "s/recovery"
LOWER_IS_BETTER_UNITS = frozenset(
    {BYTES_UNIT, ROUND_WALL_UNIT, "s/onboard", RECOVERY_UNIT}
)
# multi-tenant interleaved fold (bench.py:multi_tenant, DESIGN §19): two
# tenants' concurrent folds through the paged pool + tenant scheduler,
# in 25M-equivalent updates/s (tenant B's updates scaled by its length
# fraction); the record also carries the scheduler's fairness split
TENANT_PREFIX = "multi-tenant interleaved fold"
# coordinator-ingress family (tools/loadgen_soak.py, DESIGN §21): accepted
# updates/s at the REST boundary for a loadgen-driven round — the
# million-participant ingress headline. Its sibling series, "ingress
# staging bytes per accepted update", is recorded alongside for the
# packed-vs-legacy comparison but not gated (bytes/update depends on the
# negotiated wire mix, which the soak varies deliberately).
INGRESS_PREFIX = "ingress accepted updates"
# tenant-lifecycle family (tools/bench_tenancy.py, DESIGN §23): seconds
# from the authenticated admin onboard POST to the new tenant's first
# completed round. LOWER is better; cold/warm/density legs are distinct
# metric names so each gates against its own history.
ONBOARD_PREFIX = "tenant onboard-to-first-round latency"
ONBOARD_UNIT = "s/onboard"
# families gated independently when no explicit --metric-prefix is given
DEFAULT_FAMILIES = (
    (HEADLINE_PREFIX, HEADLINE_UNIT),
    (SIM_PREFIX, SIM_UNIT),
    (SUM2_PREFIX, ELEMENTS_UNIT),
    (UNMASK_PREFIX, ELEMENTS_UNIT),
    (BYTES_PREFIX, BYTES_UNIT),
    (TENANT_PREFIX, HEADLINE_UNIT),
    (ROUND_WALL_PREFIX, ROUND_WALL_UNIT),
    (INGRESS_PREFIX, HEADLINE_UNIT),
    (ONBOARD_PREFIX, ONBOARD_UNIT),
    (RECOVERY_PREFIX, RECOVERY_UNIT),
)


def extract(record: dict) -> tuple[str, float, str, str] | None:
    """(metric, value, unit, config) from one history record, wherever the
    writer put it; None when the record carries no scalar metric.

    ``config`` is the measurement-configuration fingerprint: the fold
    kernel plus the pinned thread counts (and mesh size) when the writer
    recorded them — extended with the sim series' population/block shape.
    A kernel or thread-config change is a DIFFERENT experiment —
    BENCH_r05 re-measured 29.46 updates/s where r03 recorded ~49 on the
    same code purely from an implicit thread-default shift — so the gate
    compares only within one exact (metric, config) series instead of
    flagging the config change as a regression."""
    for node in (record, record.get("parsed") or {}):
        metric = node.get("metric")
        value = node.get("value")
        unit = node.get("unit")
        if metric and isinstance(value, (int, float)):
            parts = []
            for field in (
                "kernel",
                "native_threads",
                "shard_threads",
                "mesh",
                "participants",
                "block",
                # loadgen_soak ingress records: the driver-tier shape and
                # negotiated wire format are the experiment (absent from
                # every older writer's records, so existing series keep
                # their fingerprints)
                "drivers",
                "tenants",
                "wire",
                # host core count: a 1-cpu container re-measuring a 4-cpu
                # record is the BENCH_r05 thread-shift incident in hardware
                # form — walls and rates alike scale with the cores the
                # kernels thread across, so a cpus change is a different
                # experiment, not a regression. Absent from every older
                # writer's records, so existing series keep their
                # fingerprints (the drivers/tenants/wire precedent).
                "cpus",
            ):
                if node.get(field) is not None:
                    parts.append(f"{field}={node[field]}")
            return str(metric), float(value), str(unit or ""), ",".join(parts)
    return None


def load_series(
    path: str, metric_prefix: str, unit: str
) -> list[tuple[float, str, float, str]]:
    """Chronological (ts, metric, value, config) for one headline family."""
    series = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn append must not kill the gate
            found = extract(record)
            if found is None:
                continue
            metric, value, rec_unit, config = found
            if metric.startswith(metric_prefix) and rec_unit == unit:
                series.append((float(record.get("ts", 0.0)), metric, value, config))
    series.sort(key=lambda item: item[0])
    return series


def gate_family(
    history: str, metric_prefix: str, unit: str, threshold: float
) -> int:
    """Gate one headline family; returns a process exit code."""
    series = load_series(history, metric_prefix, unit)
    if len(series) < 2:
        # nothing to gate against: a fresh repo (or a renamed headline) must
        # not hard-fail CI, but say so loudly
        print(
            f"bench-gate: only {len(series)} '{metric_prefix}' round(s) in "
            f"{history}; nothing to compare",
            file=sys.stderr,
        )
        return 0

    # gate within ONE exact series: the prefix family carries variants
    # (@25M params vs @200k params) whose absolute numbers are worlds
    # apart, and a kernel/thread-config change is a different experiment —
    # the latest record picks which (metric, config) series is being gated
    latest_metric, latest_config = series[-1][1], series[-1][3]
    same_metric = [item for item in series if item[1] == latest_metric]
    series = [item for item in same_metric if item[3] == latest_config]
    if len(series) < 2:
        if len(same_metric) >= 2:
            print(
                f"bench-gate: first round of '{latest_metric}' with config "
                f"[{latest_config or 'none recorded'}] — a kernel/thread-config "
                "change starts a NEW series, not a regression; nothing to compare",
                file=sys.stderr,
            )
        else:
            print(
                f"bench-gate: first round of '{latest_metric}'; nothing to compare",
                file=sys.stderr,
            )
        return 0
    *prior, (_, _, latest, _) = series
    lower_better = unit in LOWER_IS_BETTER_UNITS
    if lower_better:
        # bytes-style family: best prior is the SMALLEST, the gate fails
        # when the latest moves more than threshold ABOVE it
        best_ts, best_metric, best, _best_cfg = min(prior, key=lambda item: item[2])
        floor = best * (1.0 + threshold)
        regressed = latest > floor
    else:
        best_ts, best_metric, best, _best_cfg = max(prior, key=lambda item: item[2])
        floor = best * (1.0 - threshold)
        regressed = latest < floor
    verdict = {
        "latest": latest,
        "best_prior": best,
        "floor": round(floor, 3),
        "threshold": threshold,
        "unit": unit,
        "rounds": len(series),
        "metric": latest_metric,
        "config": latest_config,
        "direction": "lower-is-better" if lower_better else "higher-is-better",
    }
    if regressed:
        verdict["result"] = "REGRESSION"
        print(json.dumps(verdict))
        pct = abs(1 - latest / best) * 100
        word = "above" if lower_better else "below"
        print(
            f"bench-gate: FAIL — latest {latest:.2f} {unit} is "
            f"{pct:.1f}% {word} the best prior round "
            f"({best:.2f} @ ts {best_ts:.0f}, '{best_metric}'); "
            f"tolerated: {threshold * 100:.0f}%",
            file=sys.stderr,
        )
        return 1
    verdict["result"] = "ok"
    print(json.dumps(verdict))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history", default=DEFAULT_HISTORY)
    ap.add_argument(
        "--metric-prefix",
        default=None,
        help="gate ONLY this headline family (metric name prefix); the "
        "default gates every known family independently",
    )
    ap.add_argument("--unit", default=None)
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="maximum tolerated fractional regression vs the best prior round",
    )
    ap.add_argument(
        "--list", action="store_true", help="print the headline series and exit 0"
    )
    ap.add_argument(
        "--with-analysis",
        action="store_true",
        help="also run the static-analysis gate, reusing its result cache",
    )
    args = ap.parse_args()
    if not (0.0 < args.threshold < 1.0):
        ap.error("--threshold must be in (0, 1)")

    if args.metric_prefix is not None:
        unit = args.unit
        if unit is None:
            # infer the unit for known families — a bare
            # `--metric-prefix "sim round throughput"` must not fall back
            # to updates/s, match zero records, and soft-pass a regression.
            # Unknown prefixes must say their unit: a silent default would
            # reintroduce exactly that match-nothing soft-pass for them.
            unit = next(
                (
                    u
                    for p, u in DEFAULT_FAMILIES
                    if args.metric_prefix.startswith(p) or p.startswith(args.metric_prefix)
                ),
                None,
            )
            if unit is None:
                ap.error(
                    f"cannot infer the unit for metric prefix {args.metric_prefix!r}; "
                    "pass --unit explicitly"
                )
        families = [(args.metric_prefix, unit)]
    else:
        if args.unit is not None:
            ap.error("--unit without --metric-prefix is ambiguous")
        families = list(DEFAULT_FAMILIES)

    if args.list:
        for prefix, unit in families:
            for ts, metric, value, config in load_series(args.history, prefix, unit):
                suffix = f"  [{config}]" if config else ""
                print(f"{ts:.0f}  {value:10.2f} {unit}  {metric}{suffix}")
        return 0

    analysis_rc = 0
    if args.with_analysis:
        repo = Path(__file__).resolve().parent.parent
        if str(repo) not in sys.path:
            sys.path.insert(0, str(repo))
        from tools.analysis import driver as analysis_driver

        # cached (content-hash keyed): a warm .lint-cache.json from the
        # lint job makes this a sub-second re-verification
        analysis_rc = analysis_driver.run(repo, strict=True)

    # every family gates independently; any regression fails the run
    return max(
        analysis_rc,
        *(
            gate_family(args.history, prefix, unit, args.threshold)
            for prefix, unit in families
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
