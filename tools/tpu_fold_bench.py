"""Minimal, failure-resilient TPU fold capture.

`bench.py` is the driver-facing headline (one JSON line at the very end) —
which means a tunnel that dies mid-run leaves NOTHING. This tool is the
opportunistic-capture complement (VERDICT r02 item 1): it prints one JSON
line per stage the moment that stage has a number, so partial evidence
survives any mid-run failure. Stages:

  1. device transfer (device_put of the masked-update stack, timed)
  2. XLA single-pass lazy-carry fold (ops/fold_jax.fold_planar_batch)
  3. Pallas fold at a couple of tile sizes (ops/fold_pallas) — the first
     time this kernel ever runs on real hardware, so each tile is isolated
     in try/except and reported individually
  4. a final headline-format line with the best kernel

Every line is also appended to BENCH_HISTORY.jsonl with platform tags.

Run:  python tools/tpu_fold_bench.py [--model-len 25000000] [--k 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
HISTORY = os.path.join(REPO, "BENCH_HISTORY.jsonl")


def emit(rec: dict) -> None:
    rec = {"ts": round(time.time(), 3), "source": "tpu_fold_bench", **rec}
    line = json.dumps(rec)
    print(line, flush=True)
    with open(HISTORY, "a") as f:
        f.write(line + "\n")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-len", type=int, default=25_000_000)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--folds", type=int, default=8)
    ap.add_argument(
        "--platform",
        default=None,
        help="pin the jax platform (e.g. cpu for a local smoke); default: let the accelerator plugin claim the backend",
    )
    ap.add_argument(
        "--auto-stage",
        action="store_true",
        help="also drive ShardedAggregator(kernel='auto') on the staged batch so the "
        "calibration branch (parallel/aggregator._resolve_kernel) runs on this backend "
        "and the resolved winner is captured",
    )
    args = ap.parse_args()

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    else:
        os.environ.pop("JAX_PLATFORMS", None)
    import jax

    if args.platform:
        # the env var alone is not enough in images whose sitecustomize
        # registers an accelerator plugin and overrides jax_platforms at
        # import time (see conftest.py) — re-pin on the live config
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from xaynet_tpu.utils.jaxcache import silence_cpu_cache

    if not silence_cpu_cache(jax):
        # accelerator backend: the persistent cache saves tunnel-window
        # recompiles (on CPU it only buys the cross-machine SIGILL warning
        # wall over the bench tail — see utils/jaxcache.py)
        try:
            cache_dir = os.environ.get("XAYNET_JAX_CACHE", "/tmp/xaynet_jax_cache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:
            print(f"compile cache unavailable: {e}", file=sys.stderr)

    from xaynet_tpu.core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType
    from xaynet_tpu.ops import limbs as host_limbs
    from xaynet_tpu.ops.fold_jax import fold_planar_batch

    platform = jax.devices()[0].platform
    emit({"stage": "backend", "platform": platform, "device": str(jax.devices()[0])})

    config = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    order = config.order
    n_limb = host_limbs.n_limbs_for_order(order)
    model_len, k = args.model_len, args.k

    rng = np.random.default_rng(0)
    host_stack = rng.integers(0, 2**32, size=(k, n_limb, model_len), dtype=np.uint32)
    host_stack[:, n_limb - 1, :] &= np.uint32((1 << 20) - 1)
    nbytes = host_stack.nbytes

    # per-update transfers (~200 MB each @25M) — the round-3 headline
    # capture died with UNAVAILABLE inside one 3.2 GB device_put, so never
    # hand the tunnel a multi-GB single transfer
    t0 = time.perf_counter()
    slices = []
    for i in range(k):
        s = jax.device_put(host_stack[i])
        jax.block_until_ready(s)
        slices.append(s)
    stack = jnp.stack(slices)
    jax.block_until_ready(stack)
    del slices
    dt = time.perf_counter() - t0
    emit(
        {
            "stage": "transfer",
            "platform": platform,
            "bytes": nbytes,
            "seconds": round(dt, 3),
            "gb_per_s": round(nbytes / dt / 1e9, 3),
        }
    )
    del host_stack

    def sync(x):
        np.asarray(x[:1, :8])

    results = {}

    def run_kernel(name: str, fn) -> None:
        try:
            acc = jnp.zeros((n_limb, model_len), dtype=jnp.uint32)
            t0 = time.perf_counter()
            acc = fn(acc, stack)
            sync(acc)
            compile_s = time.perf_counter() - t0
            acc = fn(acc, stack)  # warmup post-compile
            sync(acc)
            t0 = time.perf_counter()
            for _ in range(args.folds):
                acc = fn(acc, stack)
            sync(acc)
            dt = time.perf_counter() - t0
            ups = args.folds * k / dt
            results[name] = ups
            emit(
                {
                    "stage": f"fold:{name}",
                    "platform": platform,
                    "model_len": model_len,
                    "k": k,
                    "compile_seconds": round(compile_s, 2),
                    "updates_per_s": round(ups, 2),
                    "hbm_gb_per_s": round(args.folds * nbytes / dt / 1e9, 2),
                    "vs_baseline": round(ups / (10_000 / 60.0), 3),
                }
            )
        except Exception as e:
            emit({"stage": f"fold:{name}", "platform": platform, "error": f"{type(e).__name__}: {e}"[:500]})

    run_kernel("xla", lambda a, s: fold_planar_batch(a, s, order))

    if platform != "cpu":
        try:
            from xaynet_tpu.ops.fold_pallas import fold_planar_batch_pallas

            for tile in (2048, 8192):
                run_kernel(
                    f"pallas-t{tile}",
                    lambda a, s, _t=tile: fold_planar_batch_pallas(a, s, order, tile_size=_t),
                )
        except Exception as e:
            emit({"stage": "pallas-import", "error": f"{type(e).__name__}: {e}"[:300]})

    # device wire ingest: raw serialized element blocks (bpn/(4L) the bytes
    # of the limb layout) -> unpack + per-update validity + fold on device.
    # Measures the whole coordinator ingest as it would run on TPU, incl.
    # the smaller host->device transfer.
    try:
        from xaynet_tpu.parallel.aggregator import ShardedAggregator

        bpn = config.bytes_per_number
        rng2 = np.random.default_rng(1)
        raw = rng2.integers(0, 256, size=(k, model_len * bpn), dtype=np.uint8)
        # keep every element's top byte below the order's top byte -> valid
        top_byte = (order >> (8 * (bpn - 1))) & 0xFF
        raw[:, bpn - 1 :: bpn] = rng2.integers(0, max(1, top_byte), size=(k, model_len), dtype=np.uint8)
        w_agg = ShardedAggregator(config, model_len, kernel="xla")
        # per-update ingest calls: each device_put stays at one update's
        # wire bytes (~175 MB at 25M/bpn=7) — this file's own rule after a
        # 3.2 GB single transfer killed the round-3 tunnel window
        t0 = time.perf_counter()
        ok = w_agg.add_wire_batch(raw[:1])  # two-step path: unpack + fold compile
        # second warmup: on accelerator backends the steady state switches to
        # the FUSED ingest jit after the kernel resolves — compile it here,
        # not inside the timed loop
        w_agg.add_wire_batch(raw[:1])
        jax.block_until_ready(w_agg.acc)
        compile_s = time.perf_counter() - t0
        assert ok.all()
        t0 = time.perf_counter()
        for _ in range(args.folds):
            for i in range(k):
                w_agg.add_wire_batch(raw[i : i + 1])
        jax.block_until_ready(w_agg.acc)
        dt = time.perf_counter() - t0
        ups = args.folds * k / dt
        emit(
            {
                "stage": "wire_ingest",
                "platform": platform,
                "model_len": model_len,
                "k": k,
                "wire_bytes_per_update": model_len * bpn,
                "compile_seconds": round(compile_s, 2),
                "updates_per_s": round(ups, 2),
                "vs_baseline": round(ups / (10_000 / 60.0), 3),
            }
        )
        del raw, w_agg
    except Exception as e:
        emit({"stage": "wire_ingest", "platform": platform, "error": f"{type(e).__name__}: {e}"[:500]})

    if args.auto_stage:
        # the production selection path: ShardedAggregator(kernel="auto")
        # compiles+times both kernels on the real staged batch and keeps the
        # winner (falling back to XLA on a Mosaic failure). On an accelerator
        # this is the first time the calibration branch meets real hardware,
        # so isolate it and report the resolved kernel either way.
        try:
            from xaynet_tpu.parallel.aggregator import ShardedAggregator

            agg = ShardedAggregator(config, model_len, kernel="auto")
            t0 = time.perf_counter()
            agg.add_planar_batch(stack)
            jax.block_until_ready(agg.acc)
            calib_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            agg.add_planar_batch(stack)
            jax.block_until_ready(agg.acc)
            steady_s = time.perf_counter() - t0
            ups = k / steady_s
            results[f"auto->{agg.kernel_used}"] = ups
            emit(
                {
                    "stage": "fold:auto",
                    "platform": platform,
                    "model_len": model_len,
                    "k": k,
                    "kernel_used": agg.kernel_used,
                    "calibration_seconds": round(calib_s, 2),
                    "updates_per_s": round(ups, 2),
                    "vs_baseline": round(ups / (10_000 / 60.0), 3),
                }
            )
        except Exception as e:
            emit({"stage": "fold:auto", "platform": platform, "error": f"{type(e).__name__}: {e}"[:500]})

    if results:
        best = max(results, key=results.get)
        # roofline: the single-pass fold reads the staged batch once and
        # reads+writes the accumulator per batch; on a v5e (~819 GB/s HBM)
        # that bounds updates/s at hbm_bw / bytes_per_update
        acc_bytes = n_limb * model_len * 4
        bytes_per_update = (nbytes + 2 * acc_bytes) / k
        bw = 819e9  # v5e nominal HBM bandwidth
        roofline = {
            "stage": "roofline",
            "platform": platform,
            "model_len": model_len,
            "bytes_per_update": int(bytes_per_update),
            "assumed_hbm_gb_per_s": round(bw / 1e9),
            "roofline_updates_per_s": round(bw / bytes_per_update, 1),
            "baseline_updates_per_s": round(10_000 / 60.0, 1),
            "best_measured_updates_per_s": round(results[best], 2),
            "roofline_fraction": round(results[best] * bytes_per_update / bw, 4),
        }
        if platform == "cpu":
            # the v5e-bandwidth model says nothing about a CPU smoke run;
            # keep the line for tooling coverage but mark it inapplicable
            roofline["note"] = "informational only: v5e HBM model does not apply to cpu"
            roofline["roofline_fraction"] = None
        emit(roofline)
        emit(
            {
                "stage": "headline",
                "metric": "masked-update aggregation throughput @25M params (PET update phase)"
                if model_len == 25_000_000
                else f"masked-update aggregation throughput @{model_len} params",
                "value": round(results[best], 2),
                "unit": "updates/s",
                "vs_baseline": round(results[best] / (10_000 / 60.0), 3),
                "platform": platform,
                "kernel": best,
                "model_len": model_len,
            }
        )
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
