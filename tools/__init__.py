"""Repo tooling (lint gate, bench gates, soak drivers).

An ``__init__`` so ``tools.analysis`` is importable as a package from
``tools/lint.py`` and the tests; the scripts in this directory remain
directly runnable (``python tools/<script>.py``).
"""
