"""Self-contained lint gate (no third-party deps).

The reference CI runs fmt + clippy (.github/workflows/rust.yml); this is
the equivalent gate for the Python tree, runnable in any environment with
a bare interpreter — including the build image, which has no ruff/pyflakes
and no network to fetch them.

Checks:
  - files parse (syntax errors fail the gate);
  - unused imports (module scope; ``__init__.py`` re-export indexes are
    exempt, ``import x as x`` / ``__all__`` mark intentional re-exports);
  - ``from x import *``;
  - mutable default arguments (list/dict/set literals);
  - bare ``except:`` clauses;
  - duplicate literal keys in dict displays;
  - tabs in indentation, trailing whitespace, missing final newline;
  - lines over 120 characters (URLs exempt);
  - raw ``time.perf_counter()`` in the hot-path trees (``xaynet_tpu/parallel``,
    ``xaynet_tpu/server``): timing there must flow through
    ``xaynet_tpu.telemetry`` (profiling hooks / histogram timers) so it shows
    up on ``GET /metrics`` and in round reports. Annotate a deliberate
    exception with ``# telemetry-exempt`` on the offending line.
  - bare unbounded ``asyncio.Queue()`` construction under
    ``xaynet_tpu/server`` and ``xaynet_tpu/ingest``: every coordinator-side
    queue must either carry a maxsize or sit behind the admission-controlled
    intake. Annotate a deliberate exception (e.g. the request channel whose
    bound lives upstream, or a shutdown sentinel channel) with
    ``# lint: unbounded-ok`` on the offending line.
  - direct ``jax.device_put`` under ``xaynet_tpu/server`` and
    ``xaynet_tpu/ingest``: update-batch staging must flow through the
    streaming pipeline's buffer ring (``parallel.streaming``) so host
    staging overlaps the in-flight folds and the per-batch pad/stack
    allocations stay dead. Annotate a deliberate exception (tiny
    non-update tensors) with ``# lint: device-put-ok`` on the offending
    line.
  - raw HTTP/socket transport calls under ``xaynet_tpu/sdk``
    (``urllib.request.urlopen``, ``socket.create_connection``,
    ``asyncio.open_connection``, bare ``socket()``): every coordinator
    conversation must flow through the client layer so the resilient
    wrapper's retry/Retry-After/typed-error semantics apply. The one
    legitimate transport (``HttpClient._request``) is annotated with
    ``# lint: raw-http-ok``.
  - blocking host syncs (``np.asarray`` / ``block_until_ready``) inside
    fold-worker code paths under ``xaynet_tpu/parallel`` (functions whose
    names mark the worker/submit/fold call graph — see
    ``_WORKER_SYNC_PREFIXES``): the streaming pipeline's whole point is
    that the only sanctioned synchronization point is ``drain()`` (exempt
    by name), so a stray sync in a worker or submit path silently
    serializes the overlap. A deliberate sync (a transfer barrier before
    ring-buffer reuse, the native kernel's host-view materialization, a
    degraded-path acceptance resolve) must carry ``# lint: sync-ok`` on
    the offending line.
  - host round-trips inside the simulation's jitted program bodies
    (functions prefixed ``_prog`` under ``xaynet_tpu/sim``): the whole
    point of ``sim.SimRound`` is that a federated round traces into ONE
    device program, so ``np.asarray`` / ``block_until_ready`` (host
    syncs) and Python-int limb math (``limbs_to_int``/``int_to_limbs``/
    ``.item()``/``.tolist()``/``int()``) inside a program body silently
    reintroduce the per-phase host round-trips the subsystem exists to
    eliminate. The host boundary (encode before, decode after the
    program) lives OUTSIDE ``_prog*`` functions; a deliberate in-body
    materialization must carry ``# lint: sync-ok`` on the offending line.
  - silent broad-exception swallows (``except Exception: pass`` and
    friends) under ``xaynet_tpu/server`` and ``xaynet_tpu/storage``: a
    coordinator-side failure must be logged, metered, retried or
    re-raised — silently dropping it hides outages (the unmask-phase
    pointer update did exactly this until a metric made it visible).
    Narrow handlers (``except ValueError: pass``) are allowed; a
    deliberate broad swallow (best-effort socket teardown) must carry
    ``# lint: swallow-ok`` on the ``except`` line.

Usage: python tools/lint.py [paths...]   (default: the repo tree)
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DEFAULT_TARGETS = [
    "xaynet_tpu",
    "tests",
    "tools",
    "examples",
    "bench.py",
    "__graft_entry__.py",
    "conftest.py",
]
MAX_LINE = 120


class _ImportVisitor(ast.NodeVisitor):
    """Collects module-scope imports and every name used anywhere."""

    def __init__(self):
        self.imports: dict[str, tuple[int, str]] = {}  # local name -> (line, display)
        self.used: set[str] = set()
        self.star_imports: list[int] = []

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            if alias.asname == alias.name:
                continue  # `import x as x` is an explicit re-export
            self.imports[local] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for alias in node.names:
            if alias.name == "*":
                self.star_imports.append(node.lineno)
                continue
            if alias.asname == alias.name:
                continue  # explicit re-export idiom
            local = alias.asname or alias.name
            self.imports[local] = (node.lineno, alias.name)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # record the root name of attribute chains (module.attr)
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)


def _used_in_annotations(tree: ast.AST) -> set[str]:
    """Names referenced inside *string* type annotations (``x: "Foo"``).

    Only annotation positions count — a module name mentioned in a docstring
    or assert message must NOT exempt a dead import.
    """
    out: set[str] = set()

    def collect(ann) -> None:
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                expr = ast.parse(ann.value, mode="eval")
            except SyntaxError:
                return
            for n in ast.walk(expr):
                if isinstance(n, ast.Name):
                    out.add(n.id)

    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            collect(node.annotation)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            collect(node.returns)
            for arg in (
                node.args.args + node.args.posonlyargs + node.args.kwonlyargs
                + ([node.args.vararg] if node.args.vararg else [])
                + ([node.args.kwarg] if node.args.kwarg else [])
            ):
                collect(arg.annotation)
    return out


def _is_unbounded_queue(node: ast.Call) -> bool:
    """True for ``asyncio.Queue()`` / ``Queue()`` constructed without a size,
    or with a literal non-positive one (asyncio treats ``maxsize <= 0`` as
    unbounded). Non-constant sizes are trusted — the rule is syntactic."""
    func = node.func
    if isinstance(func, ast.Attribute):
        is_queue = func.attr == "Queue" and (
            isinstance(func.value, ast.Name) and func.value.id == "asyncio"
        )
    elif isinstance(func, ast.Name):
        is_queue = func.id == "Queue"
    else:
        is_queue = False
    if not is_queue:
        return False
    size = node.args[0] if node.args else None
    if size is None:
        for kw in node.keywords:
            if kw.arg == "maxsize":
                size = kw.value
                break
    if size is None:
        return True
    if isinstance(size, ast.Constant) and isinstance(size.value, (int, float)):
        return size.value <= 0
    if isinstance(size, ast.UnaryOp) and isinstance(size.op, ast.USub):
        return isinstance(size.operand, ast.Constant)
    return False


def _is_silent_broad_swallow(node: ast.ExceptHandler) -> bool:
    """True for a handler that (a) catches Exception/BaseException —
    directly or inside a tuple — and (b) whose body does nothing but
    ``pass``/``...``/``continue``. Narrow handlers and handlers that log,
    meter, assign or re-raise are fine."""

    def names(t) -> list:
        if t is None:
            return []
        if isinstance(t, ast.Tuple):
            return [n for elt in t.elts for n in names(elt)]
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, ast.Attribute):
            return [t.attr]
        return []

    if not any(n in ("Exception", "BaseException") for n in names(node.type)):
        return False
    for stmt in node.body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / Ellipsis
        return False
    return True


# transport entry points that bypass the resilient client wrapper when
# called directly from SDK code
_RAW_HTTP_CALLEES = frozenset(
    {"urlopen", "urlretrieve", "open_connection", "create_connection", "socket"}
)


def _is_raw_http_call(node: ast.Call) -> bool:
    """True for direct transport constructions (urllib/socket/asyncio
    streams) — syntactic, like the queue rule: any spelling that resolves
    to one of the raw entry points counts."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _RAW_HTTP_CALLEES
    return isinstance(func, ast.Name) and func.id in _RAW_HTTP_CALLEES


# fold entry points that bypass the EdgeAggregator accounting path when
# called directly from edge code: a modular add without the matching
# member/seed-dict accounting ships an envelope whose nb_models disagrees
# with its content and breaks the coordinator's nb_models == seed-watermark
# unmask invariant (docs/DESIGN.md §11)
_FOLD_CALLEES = frozenset(
    {
        "aggregate",
        "aggregate_batch",
        "aggregate_partial",
        "fold_partial",
        "mod_add",
        "batch_mod_sum",
        "fold_wire_batch_host",
        "fold_planar_batch_host",
        "masked_add",
    }
)


def _is_fold_call(node: ast.Call) -> bool:
    """True for any spelling that resolves to a masked-add/fold entry point
    (syntactic, like the queue rule)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _FOLD_CALLEES
    return isinstance(func, ast.Name) and func.id in _FOLD_CALLEES


# fold-worker call-graph function-name prefixes under xaynet_tpu/parallel:
# the producers (submit_*), the per-batch/per-shard fold paths (_fold*,
# fold*, _credit, _dispatch*, _retry*, _shard*), and the worker loops
# (_process*, _worker*). drain()/_drain* are the sanctioned sync points and
# deliberately NOT listed.
_WORKER_SYNC_PREFIXES = (
    "_process",
    "_fold",
    "fold",
    "_dispatch",
    "_credit",
    "_retry",
    "_shard",
    "_worker",
    "submit",
    "_submit",
)

# host-blocking entry points: np.asarray materializes a device value on the
# host; block_until_ready is an explicit device barrier
_SYNC_CALLEES = frozenset({"asarray", "block_until_ready"})

# simulation program bodies: functions with these name prefixes under
# xaynet_tpu/sim are jitted whole-round program code — pure traced JAX
_SIM_PROGRAM_PREFIXES = ("_prog",)

# Python-int limb math: pulls group elements out of the graph one integer
# at a time (the pattern the in-graph simulation exists to eliminate)
_HOST_INT_CALLEES = frozenset(
    {"limbs_to_int", "limbs_to_ints", "int_to_limbs", "ints_to_limbs", "item", "tolist", "int"}
)


def _is_host_roundtrip(node: ast.Call) -> bool:
    """True for host syncs AND Python-int limb math (syntactic, any
    spelling that resolves to one of the entry points)."""
    if _is_blocking_sync(node):
        return True
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _HOST_INT_CALLEES
    return isinstance(func, ast.Name) and func.id in _HOST_INT_CALLEES


def _is_blocking_sync(node: ast.Call) -> bool:
    """True for any spelling of ``np.asarray(...)`` /
    ``jax.block_until_ready(...)`` / ``x.block_until_ready()`` (syntactic,
    like the other rules)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _SYNC_CALLEES
    return isinstance(func, ast.Name) and func.id in _SYNC_CALLEES


def _is_device_put(node: ast.Call) -> bool:
    """True for ``jax.device_put(...)`` / ``device_put(...)`` calls (the
    rule is syntactic, like the queue rule: any spelling that resolves to
    the jax transfer entry point counts)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr == "device_put"
    return isinstance(func, ast.Name) and func.id == "device_put"


def check_file(path: Path) -> list[str]:
    problems: list[str] = []
    rel = path.relative_to(REPO)
    raw = path.read_bytes()
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as e:
        return [f"{rel}: not valid UTF-8: {e}"]

    # --- format-level checks ----------------------------------------------
    generated = "generated by" in text[:200]
    if text and not text.endswith("\n"):
        problems.append(f"{rel}:{text.count(chr(10)) + 1}: missing final newline")
    for i, line in enumerate(text.splitlines(), 1):
        stripped = line.rstrip("\n")
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            problems.append(f"{rel}:{i}: tab in indentation")
        if stripped != stripped.rstrip():
            problems.append(f"{rel}:{i}: trailing whitespace")
        if len(stripped) > MAX_LINE and "http" not in stripped and not generated:
            problems.append(f"{rel}:{i}: line longer than {MAX_LINE} chars ({len(stripped)})")

    # --- AST checks --------------------------------------------------------
    try:
        tree = ast.parse(text, filename=str(rel))
    except SyntaxError as e:
        problems.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
        return problems

    visitor = _ImportVisitor()
    visitor.visit(tree)

    for line in visitor.star_imports:
        problems.append(f"{rel}:{line}: star import")

    if path.name != "__init__.py":  # __init__ files are re-export indexes
        exported = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        if isinstance(node.value, (ast.List, ast.Tuple)):
                            for elt in node.value.elts:
                                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                                    exported.add(elt.value)
        string_refs = _used_in_annotations(tree)
        for name, (line, display) in sorted(visitor.imports.items()):
            if name in visitor.used or name in exported or name in string_refs:
                continue
            problems.append(f"{rel}:{line}: unused import '{display}'")

    # hot-path trees: raw perf_counter timing bypasses the telemetry layer
    hot_path = str(rel).startswith(("xaynet_tpu/parallel", "xaynet_tpu/server"))
    # coordinator queue trees: unbounded queues defeat admission control
    bounded_tree = str(rel).startswith(
        ("xaynet_tpu/server", "xaynet_tpu/ingest", "xaynet_tpu/edge")
    )
    # edge tree: every fold must flow through the EdgeAggregator accounting
    # path (admit/seal), never a direct masked_add
    edge_tree = str(rel).startswith("xaynet_tpu/edge")
    # coordinator/storage trees: silent broad swallows hide infrastructure
    # failures from the resilience layer and the operator
    no_swallow_tree = str(rel).startswith(("xaynet_tpu/server", "xaynet_tpu/storage"))
    # SDK tree: raw transports bypass the resilient client wrapper
    sdk_tree = str(rel).startswith("xaynet_tpu/sdk")
    src_lines = text.splitlines()

    def line_of(node: ast.AST) -> str:
        return src_lines[node.lineno - 1] if node.lineno <= len(src_lines) else ""

    # sim tree: host round-trips inside jitted program bodies reintroduce
    # the per-phase host syncs the in-graph round exists to eliminate
    if str(rel).startswith("xaynet_tpu/sim"):
        flagged_sim: set[int] = set()
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not fn.name.startswith(_SIM_PROGRAM_PREFIXES):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and _is_host_roundtrip(node)
                    and node.lineno not in flagged_sim
                ):
                    flagged_sim.add(node.lineno)
                    if "lint: sync-ok" not in line_of(node):
                        problems.append(
                            f"{rel}:{node.lineno}: host round-trip in sim program "
                            f"body '{fn.name}' (np.asarray/block_until_ready/"
                            "Python-int limb math must stay outside jitted round "
                            "programs; move it to the host boundary or annotate a "
                            "deliberate materialization with '# lint: sync-ok')"
                        )

    # parallel tree: blocking host syncs inside fold-worker code paths
    # serialize the pipeline overlap; drain() is the sanctioned sync point
    if str(rel).startswith("xaynet_tpu/parallel"):
        flagged: set[int] = set()
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not fn.name.startswith(_WORKER_SYNC_PREFIXES):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and _is_blocking_sync(node)
                    and node.lineno not in flagged
                ):
                    if "lint: sync-ok" not in line_of(node):
                        flagged.add(node.lineno)
                        problems.append(
                            f"{rel}:{node.lineno}: blocking host sync in fold-worker "
                            f"code path '{fn.name}' (synchronize in drain(), or "
                            "annotate a deliberate transfer barrier / host-kernel "
                            "materialization with '# lint: sync-ok')"
                        )
                    else:
                        flagged.add(node.lineno)

    for node in ast.walk(tree):
        if hot_path and isinstance(node, ast.Call):
            func = node.func
            callee = (
                func.attr
                if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            if callee == "perf_counter":
                if "telemetry-exempt" not in line_of(node):
                    problems.append(
                        f"{rel}:{node.lineno}: raw perf_counter timing bypasses the "
                        "telemetry registry (use xaynet_tpu.telemetry.profiling or a "
                        "registry histogram timer)"
                    )
        if bounded_tree and isinstance(node, ast.Call) and _is_unbounded_queue(node):
            if "lint: unbounded-ok" not in line_of(node):
                problems.append(
                    f"{rel}:{node.lineno}: unbounded asyncio.Queue() in the "
                    "coordinator tree (pass a maxsize, or annotate a deliberate "
                    "sentinel/upstream-bounded channel with '# lint: unbounded-ok')"
                )
        if sdk_tree and isinstance(node, ast.Call) and _is_raw_http_call(node):
            if "lint: raw-http-ok" not in line_of(node):
                problems.append(
                    f"{rel}:{node.lineno}: raw HTTP/socket call in the SDK tree "
                    "bypasses the resilient client wrapper (route coordinator "
                    "traffic through sdk.client.HttpClient/ResilientClient, or "
                    "annotate the transport itself with '# lint: raw-http-ok')"
                )
        if edge_tree and isinstance(node, ast.Call) and _is_fold_call(node):
            if "lint: fold-ok" not in line_of(node):
                problems.append(
                    f"{rel}:{node.lineno}: direct masked_add/fold call in the edge "
                    "tree bypasses the partial-aggregate accounting path (fold "
                    "through EdgeAggregator.admit/seal, or annotate the accounting "
                    "path's own fold site with '# lint: fold-ok')"
                )
        if bounded_tree and isinstance(node, ast.Call) and _is_device_put(node):
            if "lint: device-put-ok" not in line_of(node):
                problems.append(
                    f"{rel}:{node.lineno}: direct jax.device_put in the coordinator "
                    "tree (stage update batches through the streaming pipeline's "
                    "buffer ring — parallel.streaming — or annotate a deliberate "
                    "non-update-tensor upload with '# lint: device-put-ok')"
                )
        if (
            no_swallow_tree
            and isinstance(node, ast.ExceptHandler)
            and _is_silent_broad_swallow(node)
        ):
            if "lint: swallow-ok" not in line_of(node):
                problems.append(
                    f"{rel}:{node.lineno}: silent broad-exception swallow in the "
                    "coordinator/storage tree (log, meter, retry or re-raise — or "
                    "annotate a deliberate best-effort cleanup with "
                    "'# lint: swallow-ok')"
                )
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        f"{rel}:{default.lineno}: mutable default argument in '{node.name}'"
                    )
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(f"{rel}:{node.lineno}: bare 'except:'")
        elif isinstance(node, ast.Dict):
            seen: set[object] = set()
            for key in node.keys:
                if isinstance(key, ast.Constant):
                    marker = (type(key.value).__name__, key.value)
                    if marker in seen:
                        problems.append(
                            f"{rel}:{key.lineno}: duplicate dict key {key.value!r}"
                        )
                    seen.add(marker)
    return problems


def main(argv: list[str]) -> int:
    targets = argv or DEFAULT_TARGETS
    files: list[Path] = []
    for t in targets:
        p = (REPO / t) if not Path(t).is_absolute() else Path(t)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.exists():
            files.append(p)
    problems: list[str] = []
    for f in files:
        problems.extend(check_file(f))
    for p in problems:
        print(p)
    print(f"lint: {len(files)} files, {len(problems)} problems", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
