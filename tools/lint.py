"""Self-contained lint gate (no third-party deps) — CLI for tools/analysis.

The reference CI runs fmt + clippy (.github/workflows/rust.yml); this is
the equivalent gate for the Python tree, runnable in any environment with
a bare interpreter — including the build image, which has no ruff/pyflakes
and no network to fetch them.

The checks themselves live in the pass-based framework under
``tools/analysis/`` (ISSUE 9): the classic per-file rules
(``analysis/filerules.py`` — parse errors, unused imports, star imports,
mutable defaults, bare excepts, duplicate dict keys, formatting, and the
tree-scoped hot-path rules: perf_counter/telemetry, unbounded queues,
device_put staging, SDK raw transports, edge fold accounting, worker/sim
host-sync prefixes) plus the cross-file deep passes (lock-discipline
``# guarded-by:`` race lint, call-graph host-sync/purity, accounting
invariants, metrics/span <-> DESIGN.md parity, and the interprocedural
secret-flow taint pass proving mask seeds / key halves / keystreams /
the edge token never reach logs, span attrs, metric labels, JSON dumps,
flight-recorder payloads or raised exception messages — docs/DESIGN.md
§18). Suppressions are per-rule (``# lint: <rule>-ok``, rationale
required for ``guarded``/``invariant``/``taint``) and known findings can
be baselined in ``tools/analysis/baseline.json``. docs/DESIGN.md §14 is
the user guide.

Usage:
  python tools/lint.py [paths...]          # classic: lint these paths
  python tools/lint.py                     # full tree + deep passes
  python tools/lint.py --strict            # CI gate: full tree + all passes, always
  python tools/lint.py --changed           # only files off the merge-base
  python tools/lint.py --json              # machine-readable findings
  python tools/lint.py --update-baseline   # accept current findings
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.analysis import cache as _cache  # noqa: E402
from tools.analysis import driver as _driver  # noqa: E402
from tools.analysis import filerules as _filerules  # noqa: E402

DEFAULT_TARGETS = list(_driver.DEFAULT_TARGETS)
MAX_LINE = _filerules.MAX_LINE


def check_file(path: Path) -> list[str]:
    """Per-file rules for one file, in the classic ``rel:line: message``
    format. Reads the module-level ``REPO`` at call time (tests point it
    at fixture trees to exercise the tree-scoped rules)."""
    info = _cache.FileInfo(REPO, Path(path))
    return [f.legacy() for f in _filerules.check_file_info(info)]


def main(argv: list[str]) -> int:
    return _driver.main(argv, repo=REPO)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
