"""End-to-end update-phase benchmark: wire bytes -> global model.

Measures the full coordinator-side PET round hot path as one script, with a
per-leg wall-clock breakdown (VERDICT round-1 item 3):

  1. wire parse         — serialized masked-model bytes -> limb tensors
                          (thread-pool, like the REST ingest path)
  2. validate           — config/length/element-validity per update
                          (reference ordering: validate -> seed dict ->
                          aggregate, update.rs:119-152)
  3. seed-dict insert   — atomic conditional insert per update
  4. stage + fold       — accelerator: wire->planar, device_put, lazy-carry
                          fold into the sharded HBM accumulator (device work
                          overlaps the next batch's parse via async
                          dispatch); CPU: the host Aggregation path a
                          CPU-only coordinator runs (native wire fold)
  5. sum2 (participant) — ONE sum participant deriving + summing k2 masks
                          on device (the client-side hot loop)
  6. unmask + decode    — modular subtract + fixed-point decode -> float32

Usage:
  python tools/bench_round.py                    # scaled CPU smoke
  python tools/bench_round.py --updates 10000 --model-len 25000000  # TPU
Prints a human breakdown table, plus one JSON line (machine-readable tail).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--updates", type=int, default=None, help="total updates (default: scaled to platform)")
    ap.add_argument("--model-len", type=int, default=None)
    ap.add_argument("--batch", type=int, default=16, help="updates per staged batch")
    ap.add_argument("--sum2-seeds", type=int, default=None, help="seeds for the sum2 participant leg")
    ap.add_argument(
        "--mask-kernel",
        default=None,
        help="pin the sum2 mask derive+sum route (utils.kernels.MASK_KERNELS); "
        "default: masking_jax's auto-calibrated winner",
    )
    ap.add_argument("--cpu", action="store_true", help="force the CPU backend")
    ap.add_argument(
        "--overlap",
        action="store_true",
        help="phase-overlap round: speculatively derive the sum2 masks in a "
        "background worker DURING the update phase (ops.speculation, "
        "docs/DESIGN.md §22); the sum2 leg then settles (reconciliation "
        "only) and the hidden derive seconds come off the round wall",
    )
    ap.add_argument(
        "--calib-cache",
        default=None,
        metavar="PATH",
        help="persist/load kernel auto-calibration verdicts at PATH "
        "(utils.calibcache; XAYNET_CALIB_CACHE works too) — a warm run "
        "skips the fold/mask probe races entirely",
    )
    ap.add_argument(
        "--assert-flat-rss-mb",
        type=float,
        default=None,
        help="fail (exit 2) if RSS grows more than this many MB across the "
        "update phase — sustained-ingest proof for the north-star count "
        "(the per-update loop is unbounded by design, update.rs:119-152)",
    )
    ap.add_argument(
        "--history",
        action="store_true",
        help="append the JSON result line to BENCH_HISTORY.jsonl",
    )
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from xaynet_tpu.utils.jaxcache import silence_cpu_cache

    silence_cpu_cache(jax)  # no cross-machine SIGILL warning wall on CPU
    from xaynet_tpu.utils import calibcache

    if args.calib_cache:
        calibcache.configure(args.calib_cache)
    else:
        calibcache.configure_from_env()
    import numpy as np

    from xaynet_tpu.core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType
    from xaynet_tpu.core.mask.encode import decode_vect_fast
    from xaynet_tpu.core.mask.object import MaskObject, MaskUnit, MaskVect
    from xaynet_tpu.core.mask.serialization import parse_mask_vect, serialize_mask_vect
    from xaynet_tpu.ops import limbs as host_limbs
    from xaynet_tpu.storage.memory import InMemoryCoordinatorStorage

    platform = jax.devices()[0].platform
    # XAYNET_BENCH_FORCE_DEVICE_PATH=1 drives the accelerator CODE PATH on
    # the virtual CPU mesh — the smoke that keeps the rare-TPU-window branch
    # continuously tested. It must not also flip the workload defaults to
    # TPU scale (that would make the "smoke" a multi-hour 25M run).
    real_tpu = platform != "cpu"
    device_forced = bool(os.environ.get("XAYNET_BENCH_FORCE_DEVICE_PATH"))
    on_tpu = real_tpu or device_forced
    model_len = args.model_len or (25_000_000 if real_tpu else 1_000_000)
    n_updates = args.updates or (10_000 if real_tpu else 96)
    k_batch = args.batch
    k_sum2 = args.sum2_seeds or (1_000 if real_tpu else 8)

    config = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    order = config.order
    n_limb = host_limbs.n_limbs_for_order(order)
    ol = host_limbs.order_limbs_for(order)

    # --- synthesize one batch of wire messages (reused; generation excluded
    # from timings) -------------------------------------------------------
    rng = np.random.default_rng(0)
    top = int(order >> (32 * (n_limb - 1)))
    batch_limbs = rng.integers(0, 1 << 32, size=(k_batch, model_len, n_limb), dtype=np.uint32)
    batch_limbs[:, :, n_limb - 1] = rng.integers(
        0, top, size=(k_batch, model_len), dtype=np.uint32
    )
    wire_msgs = [
        serialize_mask_vect(MaskVect(config, batch_limbs[i])) for i in range(k_batch)
    ]
    del batch_limbs

    if on_tpu:
        # the PRODUCTION integrated wire-ingest path (aggregation.wire_ingest):
        # per-update device validation (one <=~175 MB transfer each — never a
        # multi-GB batch put, the round-3 tunnel killer) + chunked device
        # flush, via the same StagedAggregator the coordinator runs
        from xaynet_tpu.server.aggregation import StagedAggregator

        staged = StagedAggregator(
            config.pair(), model_len, device=True, batch_size=k_batch, kernel="auto"
        )
        agg_validate = staged.validate_aggregation
        agg_stage = staged.aggregate
        zero_unit_obj = MaskUnit.from_int(config, 0)

        class _WireAggregator:
            """Adapter keeping this script's acc/nb_models/unmask surface."""

            @property
            def acc(self):
                return staged._device.acc

            @property
            def nb_models(self):
                return staged.nb_models

            def unmask_limbs(self, mask_vect):
                return staged._device.unmask_limbs(mask_vect)

            def flush(self):
                # drain, not flush: this script reads .acc right after, so
                # the streaming pipeline must have fully folded the batch
                staged.drain()

        agg = _WireAggregator()
    else:
        # CPU smoke measures the path a CPU-only coordinator actually runs
        # ([aggregation] device=false default: Aggregation.aggregate_batch
        # -> native single-pass wire fold), mirroring the sum2 leg's
        # real-CPU-participant philosophy; the device path's transposes/
        # padding belong to the accelerator scenario only. Delegating (not
        # copying) keeps this timing honest if the coordinator path evolves.
        from xaynet_tpu.core.mask.masking import Aggregation

        class _HostAggregator:
            def __init__(self):
                self._agg = Aggregation(config.pair(), model_len)
                unit_l = host_limbs.n_limbs_for_order(config.pair().unit.order)
                self._zero_units = np.zeros((k_batch, unit_l), dtype=np.uint32)

            @property
            def acc(self):
                return self._agg.object.vect.data

            @property
            def nb_models(self):
                return self._agg.nb_models

            def add_batch(self, stack):
                self._agg.aggregate_batch(stack, self._zero_units[: stack.shape[0]])

            def unmask_limbs(self, mask_vect):
                return host_limbs.mod_sub(self.acc, mask_vect, ol)

        agg = _HostAggregator()
    store = InMemoryCoordinatorStorage()
    sum_pks = [bytes([i + 1]) * 32 for i in range(3)]

    async def _seed_store():
        for i, pk in enumerate(sum_pks):
            await store.add_sum_participant(pk, bytes([i + 9]) * 32)

    import asyncio

    asyncio.run(_seed_store())

    def _rss_mb() -> float:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
        return 0.0

    # --- speculative sum2 derive (--overlap, docs/DESIGN.md §22): the mask
    # seeds are known at the sum→update transition (the sum dictionary is
    # sealed), so a background worker derives + folds them WHILE the
    # update-phase folds below run — the sum2 leg then settles to
    # reconciliation only and the derive seconds are hidden under the
    # update wall instead of extending the round
    from xaynet_tpu.ops import masking_jax

    seeds = [bytes([i & 0xFF, i >> 8]) + b"\x33" * 30 for i in range(k_sum2)]
    spec = None
    if args.overlap:
        from xaynet_tpu.ops.speculation import SpeculativeMaskSession

        if (args.mask_kernel or "auto") == "auto":
            # resolve the route BEFORE offering: the probe race is a
            # one-time process cost, not speculation work to hide
            masking_jax.calibrate_mask_kernel(seeds, model_len, config.pair())
        spec = SpeculativeMaskSession(model_len, config.pair(), kernel=args.mask_kernel)
        spec.offer(seeds)

    stage_label = "stage + fold (device)" if on_tpu else "stage + fold (host)"
    t_parse = t_validate = t_seed = t_stage = 0.0
    pool = ThreadPoolExecutor(max_workers=max(2, (os.cpu_count() or 2)))
    rss_start = _rss_mb()
    rss_peak = rss_start
    t_total0 = time.perf_counter()

    if n_updates < k_batch:
        ap.error(f"--updates ({n_updates}) must be >= --batch ({k_batch})")
    n_batches = round(n_updates / k_batch)  # nearest whole batch, >= 1
    if n_batches * k_batch != n_updates:
        print(
            f"note: rounding {n_updates} updates to {n_batches * k_batch} "
            f"(whole {k_batch}-update batches)",
            file=sys.stderr,
        )
    seed_entry = {pk: b"\x07" * 80 for pk in sum_pks}
    for b in range(n_batches):
        if on_tpu:
            # device ingest, the integrated coordinator path: the LAZY parse
            # keeps the raw element block (header checks + zero-copy view),
            # then per-update device unpack + validity runs in the validate
            # leg — exactly [aggregation] wire_ingest = true
            t0 = time.perf_counter()
            lazy_objs = [
                MaskObject(parse_mask_vect(w, lazy=True)[0], zero_unit_obj) for w in wire_msgs
            ]
            t_parse += time.perf_counter() - t0

            t0 = time.perf_counter()
            for obj in lazy_objs:
                agg_validate(obj)  # device transfer + unpack + validity
            t_validate += time.perf_counter() - t0
            parsed = None
        else:
            # 1. wire parse on the thread pool
            t0 = time.perf_counter()
            parsed = list(pool.map(lambda w: parse_mask_vect(w)[0], wire_msgs))
            t_parse += time.perf_counter() - t0

            # 2. validate (is_valid is part of parse; re-assert config +
            # length, the validate_aggregation ordering of update.rs:119-152)
            t0 = time.perf_counter()
            for v in parsed:
                assert v.config == config and len(v) == model_len
            t_validate += time.perf_counter() - t0

        async def _inserts(base, accepted):
            for i in range(k_batch):
                if not accepted[i]:
                    continue
                pk = (b"%16d" % (base + i)).ljust(32, b"u")
                err = await store.add_local_seed_dict(pk, dict(seed_entry))
                assert err is None, err

        if on_tpu:
            # validate (device) already ran above, preserving the reference's
            # validate -> seed-dict -> aggregate ordering (update.rs:119-152)
            t0 = time.perf_counter()
            asyncio.run(_inserts(b * k_batch, [True] * k_batch))
            t_seed += time.perf_counter() - t0

            t0 = time.perf_counter()
            for obj in lazy_objs:
                agg_stage(obj)  # stages the cached device planar; flushes per batch
            t_stage += time.perf_counter() - t0
        else:
            # 3. seed-dict conditional insert per update
            t0 = time.perf_counter()
            asyncio.run(_inserts(b * k_batch, [True] * k_batch))
            t_seed += time.perf_counter() - t0

            # 4. stage + fold (device dispatch is async: the fold of batch b
            # overlaps the parse of batch b+1)
            t0 = time.perf_counter()
            stack = np.stack([v.data for v in parsed])
            agg.add_batch(stack)
            t_stage += time.perf_counter() - t0
        if b == 2:
            # steady-state baseline: the first batches pay one-time costs
            # (thread-pool arenas, parse buffers, kernel warmup) that are
            # not per-update growth
            rss_warm = _rss_mb()
        if b % 50 == 0 or b == n_batches - 1:
            rss_peak = max(rss_peak, _rss_mb())

    if on_tpu:
        agg.flush()  # remainder batch through the same chunked device path
    jax.block_until_ready(agg.acc)
    t_update_phase = time.perf_counter() - t_total0
    rss_end = _rss_mb()
    if n_batches <= 2:
        rss_warm = rss_end
    rss_peak = max(rss_peak, rss_end)
    agg_kernel_used = staged.kernel_used if on_tpu else "host"

    # 5. sum2 participant leg: derive + sum k_sum2 masks through the
    # PRODUCTION promoted pipeline (state_machine.py device_sum2 ->
    # masking_jax.sum_masks): every route batches the derivations in-graph
    # (or fuses them in the Pallas kernel) and streams the mask planes
    # through the shard pipeline — the chunked per-seed StreamSampler loop
    # this leg used to run stopped being representative of production when
    # the fused mask pipeline landed.
    speculated = 0
    if spec is not None:
        # overlap round: everything the worker folded during the update
        # phase is a hit; settle() reconciles (misses derive on demand,
        # discards subtract back out) — byte-identical to sum_masks
        t0 = time.perf_counter()
        speculated = spec.speculated()
        _, mask_acc = spec.settle(seeds)
        t_sum2 = time.perf_counter() - t0
    else:
        if (args.mask_kernel or "auto") == "auto":
            # resolve the route BEFORE the wall: the probe race is a one-time
            # process cost a long-running participant amortizes across rounds
            masking_jax.calibrate_mask_kernel(seeds, model_len, config.pair())
        t0 = time.perf_counter()
        _, mask_acc = masking_jax.sum_masks(
            seeds, model_len, config.pair(), kernel=args.mask_kernel
        )
        jax.block_until_ready(mask_acc)
        t_sum2 = time.perf_counter() - t0
    mask_kernel_used = masking_jax.resolved_mask_kernel() or "unknown"

    # 6. unmask + fixed-point decode to float
    t0 = time.perf_counter()
    unmasked_wire = agg.unmask_limbs(np.asarray(mask_acc))
    from fractions import Fraction

    out = decode_vect_fast(unmasked_wire, config, agg.nb_models, Fraction(agg.nb_models))
    t_unmask = time.perf_counter() - t0
    assert out.shape == (model_len,)

    total = t_update_phase + t_sum2 + t_unmask
    ups = (n_batches * k_batch) / t_update_phase

    overlap_info = None
    if spec is not None:
        from xaynet_tpu.telemetry.timeline import drain_overlap_window

        entries = drain_overlap_window()
        spec_entries = [e for e in entries if e.get("kind") == "spec_derive"]
        hidden_s = sum(e["seconds"] for e in spec_entries)
        tail = spec_entries[-1] if spec_entries else {}
        overlap_info = {
            "speculated": speculated,
            "hidden_derive_s": round(hidden_s, 3),
            "hits": int(tail.get("hits", 0)),
            "misses": int(tail.get("misses", 0)),
            "discards": int(tail.get("discards", 0)),
        }

    rows = [
        ("wire parse (thread pool)", t_parse),
        ("validate", t_validate),
        ("seed-dict inserts", t_seed),
        (stage_label, t_stage),
        ("update phase wall", t_update_phase),
        (f"sum2 mask derive+sum ({k_sum2} seeds)", t_sum2),
        ("unmask + decode", t_unmask),
        ("TOTAL", total),
    ]
    print(f"# E2E round bench: platform={platform} model_len={model_len} "
          f"updates={n_batches * k_batch} batch={k_batch}", file=sys.stderr)
    for name, t in rows:
        print(f"  {name:<38} {t:8.2f}s", file=sys.stderr)
    print(f"  update-phase throughput: {ups:.1f} updates/s", file=sys.stderr)
    if overlap_info is not None:
        print(
            "  overlap: {h}/{n} seeds speculated during the update phase "
            "({s:.2f}s of derive hidden; {hit} hit / {miss} miss / "
            "{disc} discard)".format(
                h=overlap_info["speculated"],
                n=k_sum2,
                s=overlap_info["hidden_derive_s"],
                hit=overlap_info["hits"],
                miss=overlap_info["misses"],
                disc=overlap_info["discards"],
            ),
            file=sys.stderr,
        )
    rss_growth = rss_end - rss_warm
    print(
        f"  RSS start/warm/peak/end: {rss_start:.1f}/{rss_warm:.1f}/{rss_peak:.1f}/"
        f"{rss_end:.1f} MB (steady-state growth {rss_growth:+.1f} MB over "
        f"{n_batches * k_batch} updates, seed dict {n_batches * k_batch} entries)",
        file=sys.stderr,
    )

    # series identity for the regression gate: (metric, kernel, mesh,
    # threads) — a kernel or mesh change starts a NEW series instead of
    # reading as a regression (the BENCH_r05 lesson)
    mesh_size = len(jax.devices())
    native_threads = os.environ.get("XAYNET_NATIVE_THREADS")
    common = {
        "platform": platform,
        # a forced smoke measured the DEVICE branch on cpu — never mix it
        # with genuine cpu-coordinator baselines in history comparisons
        **({"device_path_forced": True} if device_forced else {}),
        **({"native_threads": int(native_threads)} if native_threads else {}),
        "model_len": model_len,
        "mesh": mesh_size,
        # host core count: the gate splits every series on it — a 1-cpu
        # box re-measuring a 4-cpu record is a different experiment
        "cpus": os.cpu_count(),
    }
    # the sum2 + unmask walls as their own gated families (higher-is-better
    # element rates, so the gate's best-prior floor logic applies unchanged;
    # the raw walls ride along for humans)
    # the workload shape rides in the METRIC NAME (the fold headline's
    # "@25M params" variant idiom): a 1M smoke and a 25M run are different
    # series, not a regression of one another
    extra_records = [
        # with --overlap the sum2 leg wall is RECONCILIATION time (the
        # derive ran speculatively under the update phase), so a
        # model_len/t_sum2 "throughput" would be a nonsense record future
        # serial runs regress against — the derive cost lives in the
        # round-wall record's overlap section instead
        *(
            []
            if overlap_info
            else [
                {
                    "metric": (
                        f"e2e sum2 mask throughput @{model_len} params "
                        f"({k_sum2} seeds)"
                    ),
                    "value": round(k_sum2 * model_len / max(t_sum2, 1e-9), 2),
                    "unit": "elements/s",
                    "kernel": mask_kernel_used,
                    "seeds": k_sum2,
                    "wall_s": round(t_sum2, 3),
                    **common,
                }
            ]
        ),
        {
            "metric": f"e2e unmask throughput @{model_len} params",
            "value": round(model_len / max(t_unmask, 1e-9), 2),
            "unit": "elements/s",
            "kernel": agg_kernel_used,
            "wall_s": round(t_unmask, 3),
            **common,
        },
        # the operator headline (docs/DESIGN.md §20): end-to-end round wall
        # — update phase + sum2 + unmask, the same bracket the always-on
        # timeline fold reports in production. LOWER is better: the gate
        # inverts its floor for the s/round unit (the §17 bytes idiom)
        {
            "metric": f"round wall @{model_len} params",
            "value": round(total, 3),
            "unit": "s/round",
            "kernel": agg_kernel_used,
            "updates": n_batches * k_batch,
            # overlap rides ALONG the series (not in the gate's config
            # fingerprint): an overlapped round is the same experiment
            # measured with the engines on, and a lower wall is the win
            **({"overlap": overlap_info} if overlap_info else {}),
            **common,
        },
    ]
    result = {
        "metric": "e2e update-phase throughput",
        "value": round(ups, 2),
        "unit": "updates/s",
        "kernel": agg_kernel_used,
        **common,
        "updates": n_batches * k_batch,
        "breakdown_s": {name: round(t, 3) for name, t in rows},
        "rss_mb": {
            "start": round(rss_start, 1),
            "warm": round(rss_warm, 1),
            "peak": round(rss_peak, 1),
            "end": round(rss_end, 1),
        },
    }
    for rec in extra_records:
        print(json.dumps(rec))
    print(json.dumps(result))  # the machine-readable tail stays LAST
    if args.history:
        hist = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BENCH_HISTORY.jsonl"
        )
        with open(hist, "a") as f:
            for rec in (*extra_records, result):
                f.write(
                    json.dumps({"ts": round(time.time(), 3), "source": "bench_round", **rec})
                    + "\n"
                )
    if args.assert_flat_rss_mb is not None and rss_growth > args.assert_flat_rss_mb:
        print(
            f"RSS NOT FLAT: grew {rss_growth:.1f} MB > allowed {args.assert_flat_rss_mb} MB",
            file=sys.stderr,
        )
        sys.exit(2)


if __name__ == "__main__":
    main()
