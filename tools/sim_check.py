"""Differential oracle sweep: replay seeded rounds through the in-graph
simulation AND the in-process production server; fail on any byte mismatch.

The cheap nightly cross-check for docs/DESIGN.md §13: every combination
drives ONE production round (real coordinator state machine + SDK
participant FSMs, in-process transport, pinned mask seeds) and then checks
the jitted whole-round program against it — single-device and, when the
host exposes a multi-device (virtual) mesh, mesh-sharded — byte for byte
on the float64 global model.

Usage:
  python tools/sim_check.py [--combos N] [--seed S] [--no-mesh] [--json]

``--combos N`` draws N (mask config x model size x participant count)
combinations from a seeded menu, so successive nightly runs with different
``--seed`` values walk the config space deterministically.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# the oracle compares CPU-reproducible byte streams; force the CPU backend
# (and a virtual mesh) BEFORE jax initializes, like conftest.py does
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--combos", type=int, default=3, help="seeded combinations to replay")
    ap.add_argument("--seed", type=int, default=0, help="menu + population root seed")
    ap.add_argument("--no-mesh", action="store_true", help="skip the mesh-sharded sim leg")
    ap.add_argument("--json", action="store_true", help="one JSON line per combination")
    args = ap.parse_args()

    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from xaynet_tpu.core.mask.config import GroupType
    from xaynet_tpu.parallel.mesh import make_mesh
    from xaynet_tpu.sim import OracleCase, OracleMismatch, run_oracle_case, run_production_round

    rng = np.random.default_rng(args.seed)
    groups = [GroupType.INTEGER, GroupType.PRIME, GroupType.POWER2]
    lengths = [13, 64, 257, 600]
    populations = [3, 4, 5, 7]

    mesh = None
    if not args.no_mesh and len(jax.devices()) > 1:
        mesh = make_mesh()

    # the production leg's sum2 route walks the promoted pipeline too:
    # every third combo pins a MASK_KERNELS route (device_sum2 strict, so
    # a broken kernel trips the sweep instead of hiding in the fallback)
    sum2_routes = [None, None, "batch", "fused-pallas-interpret", "host-threaded"]

    failures = 0
    for i in range(args.combos):
        route = sum2_routes[int(rng.integers(len(sum2_routes)))]
        case = OracleCase(
            group_type=groups[int(rng.integers(len(groups)))],
            model_length=int(lengths[int(rng.integers(len(lengths)))]),
            n_update=int(populations[int(rng.integers(len(populations)))]),
            seed=int(rng.integers(1 << 30)),
            block_size=int(rng.choice([2, 3, 4, 8])),
            device_sum2=route is not None,
            mask_kernel=route or "auto",
        )
        t0 = time.time()
        outcome = {"case": case.describe(), "block": case.block_size}
        if route is not None:
            outcome["sum2"] = route
        try:
            production = run_production_round(case)
            report = run_oracle_case(case, production_model=production)
            outcome["single_device"] = "byte-identical"
            if mesh is not None:
                run_oracle_case(case, mesh=mesh, production_model=production)
                outcome["mesh"] = f"byte-identical (x{len(mesh.devices.flat)})"
            outcome["sha256"] = report.sim_sha[:16]
            outcome["seconds"] = round(time.time() - t0, 1)
            outcome["result"] = "ok"
        except OracleMismatch as err:
            outcome["result"] = "MISMATCH"
            outcome["error"] = str(err)
            failures += 1
        except Exception as err:  # infra failure: report, still fail the run
            outcome["result"] = "ERROR"
            outcome["error"] = f"{type(err).__name__}: {err}"
            failures += 1
        if args.json:
            print(json.dumps(outcome))
        else:
            status = outcome["result"]
            extra = outcome.get("error", outcome.get("seconds", ""))
            print(f"[{i + 1}/{args.combos}] {outcome['case']}: {status} {extra}")

    if failures:
        print(f"sim-check: {failures}/{args.combos} combination(s) FAILED", file=sys.stderr)
        return 1
    print(f"sim-check: {args.combos} combination(s) byte-identical", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
