"""Tracing-overhead bench leg: the streaming fold path, spans on vs off.

The fold headline's span surface is the streaming pipeline (stage/fold/
commit/drain spans per batch) — the raw kernel loop carries no spans, so
measuring it would trivially show zero. This leg drives the PRODUCTION
submit/drain path at the headline batch shape with tracing ``on`` and
``off`` and reports the relative delta; BENCH.md records the number, and
the DESIGN §16 policy is: the default stays ``[metrics] trace = "on"``
while the overhead is <2%, else the default flips to failure-only
sampling.

A second leg bounds the ALWAYS-ON timeline fold (DESIGN §20): the per-round
``fold_spans`` pass over a realistic synthetic buffer, reported in µs and
as a share of the measured window wall — the §20 policy keeps the fold
always-on while that share is ≤0.1%.

Usage:
  JAX_PLATFORMS=cpu python tools/trace_overhead.py [--model-len N]
                    [--k K] [--batches B] [--reps R]
Prints one JSON line: {updates_per_s_on, updates_per_s_off, overhead_pct,
timeline_fold_us, timeline_fold_pct_of_window, ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one_window(mode: str, stack, config, model_len: int, n_batches: int) -> float:
    """updates/s of one submit+drain window in ``mode``."""
    from xaynet_tpu.parallel.aggregator import ShardedAggregator
    from xaynet_tpu.parallel.streaming import StreamingAggregator
    from xaynet_tpu.telemetry import tracing

    tracing.get_tracer().configure(mode=mode, trace_dir="")
    k = stack.shape[0]
    agg = ShardedAggregator(config.vect, model_len)
    stream = StreamingAggregator(agg, max_batch=k)
    try:
        # one untimed window resolves the kernel + warms the rings
        stream.submit_batch(stack)
        stream.drain()
        t0 = time.perf_counter()
        for _ in range(n_batches):
            stream.submit_batch(stack)
        stream.drain()
        return k * n_batches / (time.perf_counter() - t0)
    finally:
        stream.close()


def measure(mode: str, stack, config, model_len: int, n_batches: int, reps: int) -> float:
    """Median updates/s over ``reps`` windows in ``mode`` (standalone use;
    ``main`` interleaves on/off windows instead — see below)."""
    import numpy as np

    return float(
        np.median(
            [_one_window(mode, stack, config, model_len, n_batches) for _ in range(reps)]
        )
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-len", type=int, default=1_000_000)
    ap.add_argument("--k", type=int, default=8, help="updates per batch")
    ap.add_argument("--batches", type=int, default=6, help="batches per timed window")
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    import numpy as np

    from xaynet_tpu.core.mask.config import (
        BoundType, DataType, GroupType, MaskConfig, ModelType,
    )
    from xaynet_tpu.ops import limbs as host_limbs
    from xaynet_tpu.utils.jaxcache import silence_cpu_cache

    import jax

    if jax.devices()[0].platform == "cpu":
        silence_cpu_cache(jax)
    config = MaskConfig(
        GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6
    ).pair()
    n_limb = host_limbs.n_limbs_for_order(config.vect.order)
    rng = np.random.default_rng(0)
    # wire layout [K, model_len, L] — what submit_batch stages
    stack = rng.integers(
        0, 2**32, size=(args.k, args.model_len, n_limb), dtype=np.uint32
    )
    stack[:, :, n_limb - 1] &= np.uint32((1 << 20) - 1)

    # PAIRED off/on windows, ALTERNATING order, median-of-ratios: this
    # bench box throttles (walls drift 2-3x across a run), so two
    # back-to-back whole passes measure the drift, not the spans — the
    # first draft of this tool did exactly that and "measured" ~10%.
    # Pairing adjacent windows cancels the slow drift; alternating which
    # mode runs first cancels the intra-pair heat-up bias (an A/A off-vs-
    # off control showed the SECOND window of a pair runs up to ~10%
    # different on its own); the median ratio resists contended outlier
    # draws. One discarded warm window pays the jit compile + kernel-race
    # one-time costs for both modes.
    _one_window("off", stack, config, args.model_len, args.batches)
    off_ups, on_ups, ratios = [], [], []
    for i in range(args.reps):
        first, second = ("off", "on") if i % 2 == 0 else ("on", "off")
        x = _one_window(first, stack, config, args.model_len, args.batches)
        y = _one_window(second, stack, config, args.model_len, args.batches)
        on_i, off_i = (y, x) if first == "off" else (x, y)
        on_ups.append(on_i)
        off_ups.append(off_i)
        ratios.append(on_i / off_i)
        time.sleep(1.0)  # breather between pairs (thermal)
    off = float(np.median(off_ups))
    on = float(np.median(on_ups))
    ratio = float(np.median(ratios))
    overhead = (1.0 - ratio) * 100.0

    # the analytic bound alongside the noisy end-to-end number: spans per
    # batch are a handful, so cost-per-span x spans-per-batch / batch wall
    # bounds the overhead independently of machine noise
    from xaynet_tpu.telemetry import tracing

    tracer = tracing.get_tracer()
    tracer.configure(mode="on")
    name = tracing.declared_span_names()
    probe = "trace.overhead_probe"
    if probe not in name:
        tracing.declare_span(probe)
    n_probe = 20_000
    t0 = time.perf_counter()
    for _ in range(n_probe):
        with tracer.span(probe, batch=1):
            pass
    span_cost_us = (time.perf_counter() - t0) / n_probe * 1e6

    # the always-on timeline fold (DESIGN §20): one O(n) pass per round
    # over the span buffer. Time it on a synthetic buffer shaped like a
    # real round (phase spans + streaming children, half the 8192 cap) and
    # bound it against the measured ON window wall — a real round wall is
    # LONGER than one window, so the reported share is conservative. The
    # §20 policy: the fold stays always-on while this is <=0.1%.
    from xaynet_tpu.telemetry.timeline import fold_spans
    from xaynet_tpu.telemetry.tracing import Span

    def _synthetic_round(n_children: int) -> list:
        spans = []
        t = 1000.0
        idle = Span("phase.idle", "t", "s0", None, t, {"tenant": "default"})
        idle.duration = 0.05
        spans.append(idle)
        t += idle.duration
        for j, phase in enumerate(("sum", "update", "sum2", "unmask")):
            p = Span(f"phase.{phase}", "t", f"p{j}", None, t, {
                "tenant": "default", "round_id": 7, "outcome": "full",
            })
            p.duration = 2.0
            spans.append(p)
            per = max(1, n_children // 4)
            for c in range(per):
                ch = Span("stream.fold", "t", f"c{j}-{c}", f"p{j}",
                          t + c * (p.duration / per), {"batch": c})
                ch.duration = p.duration / per
                spans.append(ch)
            t += p.duration
        root = Span("round", "t", "r", None, spans[0].start, {"round_id": 7})
        root.duration = t - spans[0].start
        spans.append(root)
        return spans

    buffer = _synthetic_round(4096)
    n_folds = 50
    t0 = time.perf_counter()
    for _ in range(n_folds):
        decomp = fold_spans(7, buffer)
    fold_cost_us = (time.perf_counter() - t0) / n_folds * 1e6
    assert decomp is not None and decomp["spans"] == len(buffer)
    window_wall_s = args.k * args.batches / on
    fold_pct_of_window = fold_cost_us / 1e6 / window_wall_s * 100.0
    print(
        json.dumps(
            {
                "updates_per_s_on": round(on, 2),
                "updates_per_s_off": round(off, 2),
                "overhead_pct": round(overhead, 2),
                "pair_ratios": [round(r, 4) for r in ratios],
                "span_cost_us": round(span_cost_us, 2),
                "timeline_fold_us": round(fold_cost_us, 2),
                "timeline_fold_spans": len(buffer),
                "timeline_fold_pct_of_window": round(fold_pct_of_window, 4),
                "model_len": args.model_len,
                "k": args.k,
                "batches": args.batches,
                "reps": args.reps,
            }
        )
    )


if __name__ == "__main__":
    main()
