"""Round-trace consumer: text timeline, critical path, and CI validation.

Reads the per-round Chrome-trace JSON the tracer exports
(``[metrics] trace_dir`` / ``XAYNET_TRACE_DIR``; loadable as-is in
``chrome://tracing`` / Perfetto) and renders what an operator actually
asks of it:

- ``timeline``  — a per-round text timeline: spans ordered by start,
  indented by parent depth, with wall offsets and durations;
- ``summary``   — per-stage (span-name) totals and the round's
  critical-path decomposition: how much of the round wall each phase span
  accounts for, and inside the update/sum2 phases how much the streaming
  stage/fold legs overlap;
- ``--validate`` — the CI schema gate: timestamps monotonic and finite,
  no orphan parents (every ``parent`` resolves within the bundle — remote
  hops ride ``link`` attributes precisely so this stays strict), children
  inside their parents' windows (small tolerance), and the round's phase
  spans covering the round span;
- ``--round-report`` — cross-check the trace's phase walls against the
  round report JSONL (``[metrics] round_report_path``): the two artifacts
  measure the same bracket, so a drift beyond tolerance means one of them
  is lying.
- ``--slo <config>`` — the offline §20 check: recompute the round wall
  (Idle-close -> Unmask-complete) from the trace events, require it to
  agree with the report's in-process ``round_wall`` fold to within the
  span clock's resolution, and flag a breach of the ``[slo]`` target.

Usage:
  python tools/trace_report.py round_3.trace.json
  python tools/trace_report.py --validate round_3.trace.json
  python tools/trace_report.py --round-report reports.jsonl round_3.trace.json
  python tools/trace_report.py --slo config.toml --round-report r.jsonl round_3.trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# child may start marginally before its parent's first sample or end after
# (thread scheduling between the monotonic reads); anything past this is a
# real containment violation
_NEST_TOLERANCE_US = 50_000.0

# phase spans the round must contain to count as covered (idle/failure/
# shutdown are round-boundary or error phases and legitimately absent)
_REQUIRED_PHASES = ("phase.sum", "phase.update", "phase.sum2", "phase.unmask")

# round-report cross-check tolerance: the trace span and the report wall
# bracket the same process+purge region, so they agree to scheduling noise
_PHASE_WALL_REL_TOL = 0.25
_PHASE_WALL_ABS_TOL_S = 0.25

# --slo wall agreement: the in-process fold and the Chrome export read the
# SAME monotonic samples, so the only drift is quantization — the export's
# 0.1 us grid and the decomposition's 1 us rounding. Two ticks of the
# coarser (1 us) clock covers both edges' rounding compounding.
_SLO_WALL_TOL_S = 2e-6


def load_events(path: str) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    return [e for e in events if e.get("ph") == "X"]


def _span_id(event: dict) -> str | None:
    return (event.get("args") or {}).get("span")


def _parent_id(event: dict) -> str | None:
    return (event.get("args") or {}).get("parent")


def validate(events: list[dict]) -> list[str]:
    """Schema checks; returns human-readable problems (empty = valid)."""
    problems: list[str] = []
    if not events:
        return ["trace contains no complete (ph=X) events"]
    by_span: dict[str, dict] = {}
    for e in events:
        ts, dur = e.get("ts"), e.get("dur")
        if not isinstance(ts, (int, float)) or not isinstance(dur, (int, float)):
            problems.append(f"{e.get('name')}: non-numeric ts/dur")
            continue
        if ts < 0 or dur < 0 or ts != ts or dur != dur:
            problems.append(f"{e.get('name')}: negative or NaN ts/dur ({ts}, {dur})")
        sid = _span_id(e)
        if sid:
            if sid in by_span:
                problems.append(f"duplicate span id {sid} ({e.get('name')})")
            by_span[sid] = e
    for e in events:
        pid = _parent_id(e)
        if not pid:
            continue
        parent = by_span.get(pid)
        if parent is None:
            problems.append(
                f"{e.get('name')} (span {_span_id(e)}): orphan parent {pid}"
            )
            continue
        if e["ts"] + _NEST_TOLERANCE_US < parent["ts"] or (
            e["ts"] + e["dur"]
            > parent["ts"] + parent["dur"] + _NEST_TOLERANCE_US
        ):
            problems.append(
                f"{e.get('name')} (span {_span_id(e)}) escapes its parent "
                f"{parent.get('name')}'s window"
            )
    rounds = [e for e in events if e.get("name") == "round"]
    if len(rounds) != 1:
        problems.append(f"expected exactly one round span, found {len(rounds)}")
        return problems
    rnd = rounds[0]
    lo, hi = rnd["ts"] - _NEST_TOLERANCE_US, rnd["ts"] + rnd["dur"] + _NEST_TOLERANCE_US
    names = {e.get("name") for e in events}
    for required in _REQUIRED_PHASES:
        if required not in names:
            problems.append(f"round not covered: no {required} span")
    for e in events:
        if not str(e.get("name", "")).startswith("phase.") or e.get("name") in (
            "phase.idle",
        ):
            continue
        if e["ts"] < lo or e["ts"] + e["dur"] > hi:
            problems.append(f"{e['name']} lies outside the round span")
    return problems


def phase_walls(events: list[dict]) -> dict[str, float]:
    """Seconds per phase span name (summed — a resumed phase runs twice)."""
    out: dict[str, float] = {}
    for e in events:
        name = str(e.get("name", ""))
        if name.startswith("phase."):
            out[name[len("phase."):]] = out.get(name[len("phase."):], 0.0) + (
                e["dur"] / 1e6
            )
    return out


def cross_check(events: list[dict], report: dict) -> list[str]:
    """Trace phase walls vs the round report's phase_durations."""
    problems: list[str] = []
    walls = phase_walls(events)
    for phase, reported in (report.get("phase_durations") or {}).items():
        traced = walls.get(phase)
        if traced is None:
            if reported > _PHASE_WALL_ABS_TOL_S:
                problems.append(
                    f"report has {phase} at {reported:.3f}s but the trace has "
                    "no such phase span"
                )
            continue
        if abs(traced - reported) > max(
            _PHASE_WALL_ABS_TOL_S, reported * _PHASE_WALL_REL_TOL
        ):
            problems.append(
                f"{phase}: trace wall {traced:.3f}s vs report {reported:.3f}s "
                "(beyond tolerance)"
            )
    return problems


def trace_round_wall(events: list[dict]) -> float | None:
    """The round wall recomputed from trace events alone: Idle-close ->
    Unmask-complete, the exact bracket the in-process timeline fold uses
    (docs/DESIGN.md §20); ``None`` when the trace never reached unmask."""
    unmask_end = max(
        (e["ts"] + e["dur"] for e in events if e.get("name") == "phase.unmask"),
        default=None,
    )
    if unmask_end is None:
        return None
    idle_end = max(
        (e["ts"] + e["dur"] for e in events if e.get("name") == "phase.idle"),
        default=None,
    )
    if idle_end is None:
        # same fallback as the fold: a buffer that lost idle brackets from
        # the earliest work-phase start
        idle_end = min(
            (
                e["ts"]
                for e in events
                if str(e.get("name", "")).startswith("phase.")
                and e.get("name") != "phase.unmask"
            ),
            default=unmask_end,
        )
    return max(0.0, (unmask_end - idle_end) / 1e6)


def slo_check(
    events: list[dict], report: dict | None, config_path: str
) -> list[str]:
    """Offline SLO cross-check (§20): the trace-recomputed round wall must
    match the report's in-process ``round_wall`` fold to within the span
    clock's quantization, and a wall over the ``[slo]`` target is flagged
    as a breach."""
    from xaynet_tpu.server.settings import Settings

    problems: list[str] = []
    settings = Settings.load(config_path)
    wall = trace_round_wall(events)
    if wall is None:
        return ["slo: trace has no phase.unmask span — no round wall to check"]
    tenant = (report or {}).get("tenant") or "default"
    section = (report or {}).get("round_wall")
    if section is not None:
        folded = float(section.get("wall_s", -1.0))
        if abs(wall - folded) > _SLO_WALL_TOL_S:
            problems.append(
                f"slo: trace round wall {wall:.6f}s disagrees with the "
                f"report's timeline fold {folded:.6f}s (beyond the span "
                f"clock's {_SLO_WALL_TOL_S * 1e6:.0f} us tolerance)"
            )
    elif report is not None:
        problems.append(
            "slo: round report carries no round_wall section (timeline fold "
            "missing or tracing off)"
        )
    target = settings.slo.tenant_targets().get(tenant, settings.slo.round_wall_s)
    if settings.slo.enabled and wall > target:
        problems.append(
            f"slo: BREACH — round wall {wall:.3f}s exceeds tenant "
            f"{tenant!r} target {target:.3f}s"
        )
    else:
        print(
            f"slo: round wall {wall:.6f}s within tenant {tenant!r} "
            f"target {target:.3f}s",
            file=sys.stderr,
        )
    return problems


def _merge(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted union of half-open intervals (the timeline fold's idiom)."""
    merged: list[tuple[float, float]] = []
    for lo, hi in sorted(intervals):
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def _length(intervals: list[tuple[float, float]]) -> float:
    return sum(hi - lo for lo, hi in intervals)


# the identity re-derivation below re-does the in-process fold's float
# arithmetic from 0.1 us-quantized trace timestamps; a few microseconds
# per contributing span of drift is quantization, anything more is a bug
_IDENTITY_TOL_S = 1e-3

_WORK_PHASES = ("sum", "update", "sum2", "unmask")


def overlap_report(events: list[dict]) -> tuple[str, list[str]]:
    """Cross-phase concurrency lanes + the timeline identity assertion
    (docs/DESIGN.md §22). Each ``overlap.*`` span carries a ``phase``
    attribute naming its HOME phase (whose work it is); merging it into
    that phase's interval set makes phases genuinely intersect, and the
    identity ``sum(phase walls) − overlap + gap == wall`` must still
    balance — with the overlap engines on, wall < sum of phase walls
    (negative slack) is the measured win, not an accounting error."""
    problems: list[str] = []
    lines: list[str] = []
    phase_iv: dict[str, list[tuple[float, float]]] = {}
    for e in events:
        name = str(e.get("name", ""))
        if name.startswith("phase."):
            p = name[len("phase."):]
            if p in _WORK_PHASES:
                phase_iv.setdefault(p, []).append(
                    (e["ts"] / 1e6, (e["ts"] + e["dur"]) / 1e6)
                )
    ov_spans = [e for e in events if str(e.get("name", "")).startswith("overlap.")]
    plain_walls = {p: _length(_merge(iv)) for p, iv in phase_iv.items()}
    lines.append("cross-phase concurrency lanes:")
    if not ov_spans:
        lines.append("  (no overlap.* spans — overlap engines off or idle)")
    for e in sorted(ov_spans, key=lambda e: e["ts"]):
        home = str((e.get("args") or {}).get("phase") or "")
        lo, hi = e["ts"] / 1e6, (e["ts"] + e["dur"]) / 1e6
        if home not in _WORK_PHASES:
            problems.append(
                f"{e['name']}: overlap span without a work-phase 'phase' "
                f"attribute (got {home!r})"
            )
            continue
        if e["dur"] > 0:
            phase_iv.setdefault(home, []).append((lo, hi))
        # the lane: which OTHER phases' walls this span actually ran under
        hidden_under = [
            p
            for p, iv in phase_iv.items()
            if p != home
            and any(lo < phi and plo < hi for plo, phi in iv)
        ]
        lines.append(
            "  {name:<22} {dur:9.4f}s  {home}-work under {under}".format(
                name=e["name"],
                dur=e["dur"] / 1e6,
                home=home,
                under=", ".join(sorted(hidden_under)) or "its own phase",
            )
        )
    merged = {p: _merge(iv) for p, iv in phase_iv.items()}
    walls = {p: _length(iv) for p, iv in merged.items()}
    union = _merge([t for iv in merged.values() for t in iv])
    union_len = _length(union)
    overlap = sum(walls.values()) - union_len
    wall = trace_round_wall(events)
    if wall is None:
        problems.append("overlap: trace has no phase.unmask span — no round wall")
        return "\n".join(lines), problems
    gap = max(0.0, wall - union_len)
    residual = sum(walls.values()) - overlap + gap - wall
    slack = wall - sum(walls.values())
    lines.append(
        "\ntimeline identity: sum(walls) {s:.4f}s − overlap {o:.4f}s + "
        "gap {g:.4f}s == wall {w:.4f}s (residual {r:+.6f}s)".format(
            s=sum(walls.values()), o=overlap, g=gap, w=wall, r=residual
        )
    )
    lines.append(
        "negative slack: {sl:+.4f}s ({verdict})".format(
            sl=slack,
            verdict=(
                "wall beat the serial sum of phase walls"
                if slack < 0
                else "no measured cross-phase overlap win"
            ),
        )
    )
    for p in _WORK_PHASES:
        if p in walls and walls[p] - plain_walls.get(p, 0.0) > 1e-9:
            lines.append(
                "  phase {p}: wall {w:.4f}s (+{d:.4f}s of its work ran under "
                "other phases)".format(
                    p=p, w=walls[p], d=walls[p] - plain_walls.get(p, 0.0)
                )
            )
    if abs(residual) > _IDENTITY_TOL_S:
        problems.append(
            f"overlap: timeline identity does not balance (residual "
            f"{residual:+.6f}s beyond {_IDENTITY_TOL_S}s)"
        )
    if gap > 0 and overlap > 0 and gap < 1e-9:
        pass  # both sides active: nothing further to assert
    return "\n".join(lines), problems


def _children(events: list[dict]) -> dict[str | None, list[dict]]:
    kids: dict[str | None, list[dict]] = {}
    for e in events:
        kids.setdefault(_parent_id(e), []).append(e)
    for lst in kids.values():
        lst.sort(key=lambda e: e["ts"])
    return kids


def timeline(events: list[dict], limit: int = 200) -> str:
    """Indented per-round text timeline (earliest ``limit`` spans)."""
    if not events:
        return "(empty trace)"
    t0 = min(e["ts"] for e in events)
    kids = _children(events)
    by_span = {_span_id(e): e for e in events if _span_id(e)}
    lines: list[str] = []

    def emit(e: dict, depth: int) -> None:
        if len(lines) >= limit:
            return
        attrs = {
            k: v
            for k, v in (e.get("args") or {}).items()
            if k not in ("trace", "span", "parent")
        }
        extra = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"{(e['ts'] - t0) / 1e6:10.4f}s {'  ' * depth}{e['name']:<24} "
            f"{e['dur'] / 1e6:9.4f}s  {extra}"
        )
        for child in kids.get(_span_id(e), []):
            emit(child, depth + 1)

    roots = [e for e in events if _parent_id(e) not in by_span]
    roots.sort(key=lambda e: e["ts"])
    for root in roots:
        emit(root, 0)
    if len(events) > limit:
        lines.append(f"... ({len(events) - limit} more spans)")
    return "\n".join(lines)


def summary(events: list[dict]) -> str:
    """Per-stage totals + the round's critical-path decomposition."""
    if not events:
        return "(empty trace)"
    per_name: dict[str, tuple[int, float]] = {}
    for e in events:
        n, s = per_name.get(e["name"], (0, 0.0))
        per_name[e["name"]] = (n + 1, s + e["dur"] / 1e6)
    lines = ["per-stage totals:"]
    for name, (n, secs) in sorted(per_name.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"  {name:<24} {n:6d} spans  {secs:10.4f}s")
    rounds = [e for e in events if e["name"] == "round"]
    if rounds:
        wall = rounds[0]["dur"] / 1e6
        lines.append(f"\ncritical path (round wall {wall:.4f}s):")
        walls = phase_walls(events)
        accounted = 0.0
        for phase in ("sum", "update", "sum2", "unmask", "failure"):
            if phase in walls:
                accounted += walls[phase]
                lines.append(
                    f"  phase.{phase:<18} {walls[phase]:10.4f}s "
                    f"({100 * walls[phase] / wall:5.1f}% of round)"
                    if wall > 0
                    else f"  phase.{phase:<18} {walls[phase]:10.4f}s"
                )
        if wall > 0:
            lines.append(
                f"  (other: idle/transitions) {max(0.0, wall - accounted):10.4f}s"
            )
        stage = sum(e["dur"] for e in events if e["name"] == "stream.stage") / 1e6
        fold = sum(e["dur"] for e in events if e["name"] == "stream.fold") / 1e6
        if fold > 0:
            lines.append(
                f"  streaming legs: stage {stage:.4f}s, fold {fold:.4f}s "
                "(overlapped; per-shard folds run concurrently)"
            )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="round trace report / validator")
    ap.add_argument("trace", help="per-round Chrome-trace JSON (tracer export)")
    ap.add_argument(
        "--validate",
        action="store_true",
        help="schema gate: exit 1 on monotonicity/orphan/coverage violations",
    )
    ap.add_argument(
        "--round-report",
        default=None,
        metavar="JSONL",
        help="cross-check phase walls against this round-report JSONL "
        "(matched on round_id when present, else the last line)",
    )
    ap.add_argument(
        "--slo",
        default=None,
        metavar="CONFIG",
        help="offline SLO check against this config's [slo] section: trace "
        "round wall vs the report's timeline fold (needs --round-report "
        "for the fold comparison) + target-breach flagging",
    )
    ap.add_argument(
        "--overlap",
        action="store_true",
        help="cross-phase concurrency lanes for overlap.* spans + assert the "
        "timeline identity sum(walls) − overlap + gap == wall still balances",
    )
    ap.add_argument("--limit", type=int, default=200, help="timeline rows")
    args = ap.parse_args(argv)

    events = load_events(args.trace)
    problems: list[str] = []
    report = None
    if args.validate:
        problems.extend(validate(events))
    if args.round_report:
        round_ids = {
            (e.get("args") or {}).get("round_id")
            for e in events
            if e.get("name") == "round"
        }
        matched = False
        with open(args.round_report) as f:
            for line in f:
                if not line.strip():
                    continue
                candidate = json.loads(line)
                if candidate.get("round_id") in round_ids:
                    report, matched = candidate, True
                elif not matched:
                    report = candidate  # fallback: the LAST line wins
        if report is None:
            problems.append("round report file has no reports")
        else:
            problems.extend(cross_check(events, report))
    if args.slo:
        problems.extend(slo_check(events, report, args.slo))
    if args.overlap:
        lanes, ov_problems = overlap_report(events)
        print(lanes)
        print()
        problems.extend(ov_problems)

    if not args.validate:
        print(timeline(events, args.limit))
        print()
        print(summary(events))
    if problems:
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        print(f"trace INVALID: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    if args.validate:
        print(
            f"trace valid: {len(events)} spans, "
            f"{len({(e.get('args') or {}).get('trace') for e in events})} trace id(s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
