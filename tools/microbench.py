"""Micro-benchmarks mirroring the reference's criterion harness.

The reference benches model<->primitive conversion at 4B/100kB/1MB and
update-message serde at sizes up to ~10MB with 10k-entry seed dicts
(reference: rust/benches/). This prints the same matrix for this
implementation so regressions in the host paths are visible over commits.

Run:  python tools/microbench.py [--json]

``--json`` appends one JSON record (git rev + every timing) to
BENCH_HISTORY.jsonl at the repo root, the criterion-style
tracked-over-commits record.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from xaynet_tpu.core.crypto.encrypt import EncryptKeyPair
from xaynet_tpu.core.crypto.prng import StreamSampler
from xaynet_tpu.core.crypto.sign import SigningKeyPair
from xaynet_tpu.core.mask import (
    BoundType,
    DataType,
    GroupType,
    Masker,
    MaskConfig,
    MaskObject,
    MaskSeed,
    MaskUnit,
    MaskVect,
    ModelType,
    Scalar,
)
from xaynet_tpu.core.mask.serialization import parse_mask_object, serialize_mask_object
from xaynet_tpu.core.message import Message, Update

CFG = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3)


RESULTS: dict[str, float] = {}


def timeit(label: str, fn, repeat: int = 3) -> None:
    best = min(_once(fn) for _ in range(repeat))
    RESULTS[label] = round(best * 1e3, 3)
    print(f"{label:<56} {best * 1e3:10.2f} ms")


def _once(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def masked_object(n: int) -> MaskObject:
    sampler = StreamSampler(b"\x05" * 32)
    unit = sampler.draw_limbs(1, CFG.order)[0]
    vect = sampler.draw_limbs(n, CFG.order)
    return MaskObject(MaskVect(CFG, vect), MaskUnit(CFG, unit))


def main() -> None:
    rng = np.random.default_rng(0)

    # --- model <-> wire conversion (reference: benches/models/) -----------
    for n in (1, 25_000, 250_000, 2_500_000):  # ~4B / 100kB / 1MB / 10MB wire
        w = rng.uniform(-1, 1, n).astype(np.float32)
        masker = Masker(CFG.pair(), MaskSeed(b"\x01" * 32))
        timeit(f"mask model (fixed-point + PRNG + mod add), n={n}", lambda: masker.mask(Scalar.unit(), w))

    # --- mask object serde (reference: benches/messages/) -----------------
    for n in (1, 25_000, 250_000, 2_500_000):
        obj = masked_object(n)
        wire = serialize_mask_object(obj)
        timeit(f"serialize mask object, n={n} ({len(wire)} B)", lambda: serialize_mask_object(obj))
        timeit(f"parse mask object, n={n}", lambda: parse_mask_object(wire))

    # --- update message with a 10k-entry seed dict ------------------------
    keys = SigningKeyPair.generate()
    ephm = EncryptKeyPair.generate()
    seed = MaskSeed.generate()
    enc = seed.encrypt(ephm.public)
    seed_dict = {i.to_bytes(32, "little"): enc for i in range(10_000)}
    obj = masked_object(250_000)
    upd = Update(
        sum_signature=b"\x01" * 64,
        update_signature=b"\x02" * 64,
        masked_model=obj,
        local_seed_dict=seed_dict,
    )
    msg = Message(participant_pk=keys.public, coordinator_pk=b"\x09" * 32, payload=upd)
    wire = msg.to_bytes(keys.secret)
    timeit(f"update message serialize+sign ({len(wire)} B, 10k seeds)", lambda: msg.to_bytes(keys.secret))
    timeit("update message parse+verify", lambda: Message.from_bytes(wire))

    if "--json" in sys.argv:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True, text=True
        ).stdout.strip()
        record = {"ts": time.time(), "rev": rev or "unknown", "timings_ms": RESULTS}
        path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "BENCH_HISTORY.jsonl")
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
        print(f"appended {len(RESULTS)} timings for {rev} to BENCH_HISTORY.jsonl")


if __name__ == "__main__":
    main()
