"""Coordinator-ingress soak: the loadgen subsystem against a real coordinator.

Boots the production coordinator entry point, drives the sum leg with a
real ``Participant``, then replays a forged population through the
process-sharded loadgen driver tier (``xaynet_tpu.loadgen.runner``) over
real REST — packed (wire v2) by default — and reports the INGRESS
HEADLINE: accepted updates/s at the REST boundary, plus the staging
bytes actually moved per accepted update, read off ``/metrics``
(``xaynet_bytes_staged_total``).

Legs (one JSON result each, combined into one line on stdout):

- **headline** — one loadgen-driven round at ``--participants`` across
  ``--drivers`` processes (optionally spread over ``--tenants`` routes or
  ``--edges`` two-tier fan-in); scrapes ``/healthz`` ingress and asserts
  every update landed.
- **identity** (``--identity``) — a small loadgen(packed) round followed
  by a flood-driven (state-machine encode path, legacy wire) control
  round with the same weights/scalar: the two global models must be
  byte-identical (the loadgen traffic is byte-correct, not fuzz).
- **legacy control** (``--legacy-control N``) — reboots the coordinator
  in the pre-v2 shape (legacy wire, host parse, unpacked uint32 staging)
  and replays N updates, to pin the bytes-per-accepted-update comparison:
  the packed path must move STRICTLY fewer bytes.

``--append-history`` appends the gated records to BENCH_HISTORY.jsonl
(family: ``ingress accepted updates`` — tools/bench_gate.py).

Usage (CI smoke):
  python tools/loadgen_soak.py --participants 2000 --drivers 2 --tenants 2 \
      --identity --legacy-control 400 --append-history
Headline (the 100k+ run):
  python tools/loadgen_soak.py --participants 100000 --drivers 2 \
      --model-len 64 --legacy-control 2000 --append-history
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from fractions import Fraction
from urllib.request import urlopen

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HISTORY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BENCH_HISTORY.jsonl"
)

CONFIG = """
[api]
bind_address = "127.0.0.1:{port}"

[pet.sum]
prob = 0.5
[pet.sum.count]
min = 1
max = 1
[pet.sum.time]
min = 0.0
max = {phase_max}

[pet.update]
prob = 0.9
[pet.update.count]
min = {update_n}
max = {update_n}
[pet.update.time]
min = 0.0
max = {phase_max}

[pet.sum2.count]
min = 1
max = 1
[pet.sum2.time]
min = 0.0
max = {phase_max}

[mask]
# capacity must cover the round's update count: validate_aggregation
# rejects fold n with TooManyModels once nb_models reaches the config's
# max_nb_models (10^k for m<k>) — the production m3 default caps a round
# at 1e3 updates, far under the soak populations this harness drives
model_type = "{model_type}"

[model]
length = {model_len}

[aggregation]
device = true
batch_size = {agg_batch}
kernel = "auto"
wire_ingest = {wire_ingest}
packed_staging = {packed_staging}

[ingest]
enabled = true
shards = 2
queue_bound = 4096
retry_after_seconds = 0.2
wire_format = "{wire_format}"

[storage]
backend = "filesystem"
model_dir = "{model_dir}"

[log]
filter = "info"
{tenancy}
"""

EDGE_CONFIG = """
[api]
bind_address = "127.0.0.1:{port}"

[edge]
upstream_url = "http://127.0.0.1:{upstream_port}"
edge_id = "{edge_id}"
max_members = {max_members}
linger_s = 0.5
poll_s = 0.1

[log]
filter = "info"
"""


def _model_type(update_n: int) -> str:
    """Smallest catalogue mask capacity that admits ``update_n`` folds."""
    for mt, cap in (("m3", 10**3), ("m6", 10**6), ("m9", 10**9)):
        if update_n <= cap:
            return mt
    return "m12"


def _wait_listening(port: int, proc, timeout_s: float = 120.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError("server process exited during startup")
            time.sleep(0.25)
    raise RuntimeError(f"port {port} did not start listening in {timeout_s}s")


def _fetch_params(url: str):
    from xaynet_tpu.sdk.client import HttpClient

    return asyncio.run(HttpClient(url, keep_alive=False).get_round_params())


def _fetch_sums(url: str):
    from xaynet_tpu.sdk.client import HttpClient

    return asyncio.run(HttpClient(url, keep_alive=False).get_sums())


def _fetch_model_bytes(url: str):
    import numpy as np

    from xaynet_tpu.sdk.client import HttpClient

    m = asyncio.run(HttpClient(url, keep_alive=False).get_model())
    return None if m is None else np.asarray(m, np.float64).tobytes()


def _scrape_json(url: str) -> dict:
    with urlopen(url, timeout=15) as resp:
        return json.loads(resp.read())


def _staged_bytes(base_url: str) -> dict:
    """xaynet_bytes_staged_total by layout, off /metrics."""
    with urlopen(f"{base_url}/metrics", timeout=15) as resp:
        text = resp.read().decode("utf-8", "replace")
    out = {}
    for line in text.splitlines():
        if line.startswith("xaynet_bytes_staged_total{"):
            layout = line.split('layout="', 1)[1].split('"', 1)[0]
            out[layout] = float(line.rsplit(None, 1)[1])
    return out


class RoundDriver:
    """Sum/sum2 leg for one coordinator (or tenant route): a real
    ``Participant`` opens the round, the caller lands the updates, then
    the summer closes sum2 and the round completes."""

    def __init__(self, url: str, n_updates: int, poll_s: float = 0.05):
        self.url = url
        self.n = n_updates
        self.poll_s = poll_s

    def open_round(self):
        from xaynet_tpu.sdk.participant import Participant
        from xaynet_tpu.sdk.simulation import keys_for_task

        last = None
        while True:
            params = _fetch_params(self.url)
            if params.seed.as_bytes() != last:
                break
            time.sleep(0.02)
        seed = params.seed.as_bytes()
        self.params = params
        self.summer = Participant(
            self.url,
            keys=keys_for_task(seed, params.sum, params.update, "sum"),
            scalar=Fraction(1, max(1, self.n)),
        )
        for _ in range(600):
            self.summer.tick()
            sums = _fetch_sums(self.url)
            if sums:
                return params, sums
            time.sleep(self.poll_s)
        raise RuntimeError(f"{self.url}: sum dict never appeared")

    def close_round(self, timeout_s: float = 3600.0) -> bytes:
        seed = self.params.seed.as_bytes()
        deadline = time.time() + timeout_s
        try:
            while time.time() < deadline:
                self.summer.tick()
                if _fetch_params(self.url).seed.as_bytes() != seed:
                    model = _fetch_model_bytes(self.url)
                    if model is None:
                        raise RuntimeError(f"{self.url}: round closed without a model")
                    return model
                time.sleep(self.poll_s)
        finally:
            self.summer.close()
        raise RuntimeError(f"{self.url}: round did not complete in {timeout_s}s")


class Coordinator:
    """One coordinator subprocess (plus optional edge tier) from a config."""

    def __init__(self, tmp: str, port: int, *, update_n: int, model_len: int,
                 wire_format: str = "packed", wire_ingest: bool = True,
                 packed_staging: bool = True, agg_batch: int = 32,
                 phase_max: float = 14400.0, tenants: list | None = None,
                 edges: int = 0, edge_members: int = 0):
        self.port = port
        self.url = f"http://127.0.0.1:{port}"
        self.tenants = tenants or []
        self.edge_urls = []
        self._procs = []
        self._logs = []
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        tenancy = ""
        if self.tenants:
            cfg_dir = os.path.join(tmp, f"tenants-{port}")
            os.makedirs(cfg_dir, exist_ok=True)
            for tid in self.tenants:
                with open(os.path.join(cfg_dir, f"{tid}.toml"), "w") as f:
                    f.write(self._render(
                        tmp, port, update_n, model_len, wire_format,
                        wire_ingest, packed_staging, agg_batch, phase_max,
                        "", suffix=tid,
                    ))
            tenancy = (
                "\n[tenancy]\nenabled = true\n"
                f'tenants = "{",".join(self.tenants)}"\n'
                f'config_dir = "{cfg_dir}"\n'
            )
        if edges:
            # the coordinator must serve /edge/round + /edge/envelope
            tenancy += "\n[edge]\nenabled = true\n"
        cfg_path = os.path.join(tmp, f"coordinator-{port}.toml")
        with open(cfg_path, "w") as f:
            f.write(self._render(
                tmp, port, update_n, model_len, wire_format, wire_ingest,
                packed_staging, agg_batch, phase_max, tenancy,
            ))
        self.log_path = os.path.join(tmp, f"coordinator-{port}.log")
        log = open(self.log_path, "w")
        self._logs.append(log)
        self._procs.append(subprocess.Popen(
            [sys.executable, "-m", "xaynet_tpu.server.runner", "-c", cfg_path],
            env=env, stdout=log, stderr=subprocess.STDOUT))
        _wait_listening(port, self._procs[0])
        for i in range(edges):
            eport = port + 1 + i
            ecfg = os.path.join(tmp, f"edge-{eport}.toml")
            with open(ecfg, "w") as f:
                f.write(EDGE_CONFIG.format(
                    port=eport, upstream_port=port, edge_id=f"edge-{i}",
                    max_members=edge_members))
            elog = open(os.path.join(tmp, f"edge-{eport}.log"), "w")
            self._logs.append(elog)
            self._procs.append(subprocess.Popen(
                [sys.executable, "-m", "xaynet_tpu.edge.runner", "-c", ecfg],
                env=env, stdout=elog, stderr=subprocess.STDOUT))
            _wait_listening(eport, self._procs[-1])
            self.edge_urls.append(f"http://127.0.0.1:{eport}")

    @staticmethod
    def _render(tmp, port, update_n, model_len, wire_format, wire_ingest,
                packed_staging, agg_batch, phase_max, tenancy, suffix="base"):
        return CONFIG.format(
            port=port, update_n=update_n, model_len=model_len,
            model_type=_model_type(update_n),
            wire_format=wire_format,
            wire_ingest="true" if wire_ingest else "false",
            packed_staging="true" if packed_staging else "false",
            agg_batch=agg_batch, phase_max=phase_max,
            model_dir=os.path.join(tmp, f"models-{port}-{suffix}"),
            tenancy=tenancy,
        )

    def stop(self) -> None:
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)
        for log in self._logs:
            log.close()

    def log_tail(self, n: int = 3000) -> str:
        try:
            with open(self.log_path) as f:
                return f.read()[-n:]
        except OSError:
            return ""


def run_loadgen_round(coord: Coordinator, cfg: dict, close_timeout: float):
    """One full loadgen-driven round: open every target round, replay the
    tier, close every round. Returns (runner stats, {url: model bytes})."""
    import threading

    from xaynet_tpu.loadgen import runner as lg_runner

    if coord.tenants:
        routes = [f"{coord.url}/t/{t}" for t in coord.tenants]
    else:
        routes = [coord.url]
    per_route = [
        len(range(i, cfg["participants"], len(routes))) for i in range(len(routes))
    ]
    drivers = [
        RoundDriver(url, n) for url, n in zip(routes, per_route)
    ]
    for d in drivers:
        d.open_round()
    stats = lg_runner.run(cfg)
    models, errs = {}, []

    def close(d):
        try:
            models[d.url] = d.close_round(timeout_s=close_timeout)
        except BaseException as e:  # noqa: BLE001 - join + report below
            errs.append(e)

    threads = [threading.Thread(target=close, args=(d,), daemon=True) for d in drivers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[0]
    return stats, models


def leg_headline(tmp: str, args) -> dict:
    from xaynet_tpu.loadgen import runner as lg_runner

    tenants = [f"t{i}" for i in range(args.tenants)] if args.tenants else []
    coord = Coordinator(
        tmp, args.port,
        # per-tenant rounds each see their own slice of the population
        update_n=(
            len(range(0, args.participants, max(1, len(tenants))))
            if tenants else args.participants
        ),
        model_len=args.model_len, wire_format=args.wire,
        tenants=tenants, edges=args.edges,
        edge_members=max(1, args.participants // max(1, args.edges))
        if args.edges else 0,
    )
    try:
        cfg = lg_runner.default_cfg()
        cfg.update(
            url=coord.url, participants=args.participants, drivers=args.drivers,
            tenants=",".join(tenants), wire="auto", seed=args.seed,
            block_size=args.block_size, concurrency=args.concurrency,
            sum_wait_s=600.0, timeout=120.0,
            # a soak must land EVERY update: shed uploads keep retrying and
            # Retry-After paces them against the intake queues
            max_shed_retries=1_000_000,
        )
        if args.edges:
            cfg["targets"] = coord.edge_urls
            cfg["shared_round"] = True
        stats, models = run_loadgen_round(coord, cfg, args.close_timeout)
        assert stats["accepted"] == args.participants, stats
        health = _scrape_json(f"{coord.url}/healthz")
        staged = _staged_bytes(coord.url)
        ingress = health.get("ingress")
        if ingress is None and tenants:
            ingress = _scrape_json(f"{coord.url}/t/{tenants[0]}/healthz").get("ingress")
        with urlopen(f"{coord.url}/statusz", timeout=15) as resp:
            statusz_ok = resp.status == 200 and b"ingress" in resp.read().lower()
        wire_layout = "wire-planar" if args.wire == "packed" else "wire"
        return {
            "participants": args.participants,
            "drivers": args.drivers,
            "tenants": len(tenants),
            "edges": args.edges,
            "wire": args.wire,
            "model_len": args.model_len,
            "accepted": stats["accepted"],
            "accepted_per_s": stats["accepted_per_s"],
            "replay_wall_s": stats["wall_s"],
            "total_wall_s": stats["total_wall_s"],
            "shed": stats["shed"],
            "errors": stats["errors"],
            "bytes_staged": staged,
            "bytes_per_accepted": (
                round(staged.get(wire_layout, 0.0) / stats["accepted"], 1)
                if stats["accepted"] else None
            ),
            "ingress": ingress,
            "statusz_ingress": statusz_ok,
            "models": {u: len(m) for u, m in models.items()},
        }
    finally:
        coord.stop()


def leg_identity(tmp: str, args) -> dict:
    """loadgen(packed) round vs flood(legacy, state-machine encode path)
    control round with identical weights/scalar: byte-identical models."""
    import numpy as np

    from xaynet_tpu.loadgen import runner as lg_runner
    from xaynet_tpu.sdk.client import HttpClient
    from xaynet_tpu.sdk.simulation import build_update_message, flood, keys_for_task

    n = args.identity_n
    coord = Coordinator(tmp, args.port, update_n=n, model_len=args.model_len,
                        wire_format="packed", phase_max=1800.0)
    try:
        cfg = lg_runner.default_cfg()
        cfg.update(url=coord.url, participants=n, drivers=2, wire="auto",
                   seed=args.seed, block_size=min(64, n), sum_wait_s=300.0,
                   max_shed_retries=1_000_000)
        stats, models = run_loadgen_round(coord, cfg, args.close_timeout)
        assert stats["accepted"] == n, stats
        model_loadgen = models[coord.url]

        # ground truth: the exact weights the two driver shards forged
        sizes = lg_runner.shard_sizes(n, 2)
        weights = np.concatenate([
            np.random.default_rng(args.seed + s)
            .uniform(-1, 1, (sizes[s], args.model_len))
            .astype(np.float32)
            for s in range(2)
        ])

        driver = RoundDriver(coord.url, n)
        params, sums = driver.open_round()
        seed = params.seed.as_bytes()
        keys = [
            keys_for_task(seed, params.sum, params.update, "update",
                          start=i * 100_000)
            for i in range(n)
        ]

        async def control():
            client = HttpClient(coord.url)

            async def submit(blob: bytes) -> None:
                await client.send_message(blob)

            try:
                return await flood(
                    submit, params, sums, n,
                    build=lambda i: build_update_message(
                        params, keys[i], sums, weights[i],
                        Fraction(1, n), wire_planar=False),
                )
            finally:
                client.close()

        fstats = asyncio.run(control())
        assert fstats.accepted == n, fstats
        model_control = driver.close_round(timeout_s=args.close_timeout)
        if model_loadgen != model_control:
            raise RuntimeError(
                "identity leg FAILED: loadgen round is not byte-identical "
                "to the flood control round"
            )
        return {
            "participants": n,
            "model_len": args.model_len,
            "byte_identical": True,
            "model_bytes": len(model_loadgen),
        }
    finally:
        coord.stop()


def leg_legacy_control(tmp: str, args) -> dict:
    """The pre-v2 shape: legacy wire, host parse, unpacked uint32 staging.
    Pins the denominator of the bytes-moved comparison."""
    from xaynet_tpu.loadgen import runner as lg_runner

    n = args.legacy_control
    coord = Coordinator(tmp, args.port, update_n=n, model_len=args.model_len,
                        wire_format="legacy", wire_ingest=False,
                        packed_staging=False, phase_max=3600.0)
    try:
        cfg = lg_runner.default_cfg()
        cfg.update(url=coord.url, participants=n, drivers=1, wire="legacy",
                   seed=args.seed, block_size=min(128, n), sum_wait_s=300.0,
                   max_shed_retries=1_000_000)
        stats, _ = run_loadgen_round(coord, cfg, args.close_timeout)
        assert stats["accepted"] == n, stats
        staged = _staged_bytes(coord.url)
        return {
            "participants": n,
            "accepted_per_s": stats["accepted_per_s"],
            "bytes_staged": staged,
            "bytes_per_accepted": (
                round(staged.get("unpacked", 0.0) / n, 1) if n else None
            ),
        }
    finally:
        coord.stop()


def append_history(result: dict, args) -> None:
    records = []
    head = result["headline"]
    records.append({
        "ts": round(time.time(), 3),
        "source": "loadgen_soak",
        "metric": "ingress accepted updates",
        "value": head["accepted_per_s"],
        "unit": "updates/s",
        "platform": "cpu",
        "cpus": os.cpu_count(),
        "participants": head["participants"],
        "drivers": head["drivers"],
        "tenants": head["tenants"],
        "edges": head["edges"],
        "wire": head["wire"],
        "model_len": head["model_len"],
        "replay_wall_s": head["replay_wall_s"],
        "bytes_per_accepted": head["bytes_per_accepted"],
        "shed": head["shed"],
    })
    if result.get("legacy_control"):
        records.append({
            "ts": round(time.time(), 3),
            "source": "loadgen_soak",
            "metric": "ingress staging bytes per accepted update",
            "value": head["bytes_per_accepted"],
            "unit": "bytes/update",
            "platform": "cpu",
            "wire": head["wire"],
            "model_len": head["model_len"],
            "legacy_bytes_per_accepted":
                result["legacy_control"]["bytes_per_accepted"],
        })
    with open(HISTORY, "a") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--participants", type=int, default=2000)
    ap.add_argument("--drivers", type=int, default=2)
    ap.add_argument("--tenants", type=int, default=0)
    ap.add_argument("--edges", type=int, default=0)
    ap.add_argument("--model-len", type=int, default=256)
    ap.add_argument("--block-size", type=int, default=512)
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--wire", choices=("packed", "legacy"), default="packed")
    ap.add_argument("--seed", type=int, default=20260806)
    ap.add_argument("--port", type=int, default=18620)
    ap.add_argument("--identity", action="store_true")
    ap.add_argument("--identity-n", type=int, default=12)
    ap.add_argument("--legacy-control", type=int, default=0, metavar="N")
    ap.add_argument("--close-timeout", type=float, default=7200.0)
    ap.add_argument("--append-history", action="store_true")
    args = ap.parse_args()
    if args.tenants and args.edges:
        ap.error("--tenants and --edges are separate topologies")

    result = {}
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmp:
        if args.identity:
            result["identity"] = leg_identity(tmp, args)
            print(json.dumps({"identity": result["identity"]}), file=sys.stderr)
        result["headline"] = leg_headline(tmp, args)
        print(json.dumps({"headline": result["headline"]}), file=sys.stderr)
        if args.legacy_control:
            result["legacy_control"] = leg_legacy_control(tmp, args)
            packed_bpa = result["headline"]["bytes_per_accepted"]
            legacy_bpa = result["legacy_control"]["bytes_per_accepted"]
            if args.wire == "packed" and not (packed_bpa < legacy_bpa):
                raise RuntimeError(
                    f"packed path must move strictly fewer staging bytes per "
                    f"accepted update: packed={packed_bpa} legacy={legacy_bpa}"
                )
            result["packed_vs_legacy_bytes"] = {
                "packed": packed_bpa,
                "legacy": legacy_bpa,
                "strictly_fewer": packed_bpa < legacy_bpa,
            }
    result["wall_s"] = round(time.perf_counter() - t0, 2)
    if args.append_history:
        append_history(result, args)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
