"""Opportunistic TPU evidence capture (VERDICT r02, next-round item 1).

The axon tunnel to the real TPU chip has been dead at both end-of-round
bench captures so far.  Instead of betting the round on one end-of-round
moment, this watcher loops in the background:

  * every ``--interval`` seconds it probes the accelerator in a fresh
    subprocess (a wedged tunnel hangs the JAX backend init forever, so the
    probe must be externally timed out);
  * every attempt is appended to ``TPU_WATCH.log`` — if the tunnel never
    comes up all round, that log is the committed proof;
  * the moment a probe succeeds it runs the capture suite cheapest-first
    (``tools/tpu_fold_bench.py`` at 2.5M then 25M params, ``bench.py``
    headline + Pallas tile sweep, ``tools/bench_round.py`` round legs),
    appending platform-tagged JSON to ``BENCH_HISTORY.jsonl`` after every
    capture and short-circuiting when a re-probe says the tunnel died;
  * it exits 0 only once a **25M-param accelerator number** is on record
    (writing ``TPU_EVIDENCE_r03.md``); smaller partial captures are kept
    but the watch continues for the real headline.

Run:  python tools/tpu_watch.py [--interval 600] [--probe-timeout 150]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_WATCH.log")
HISTORY = os.path.join(REPO, "BENCH_HISTORY.jsonl")
EVIDENCE = os.path.join(REPO, "TPU_EVIDENCE_r05.md")

def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def log(line: str) -> None:
    stamped = f"{_now()} {line}"
    print(stamped, flush=True)
    with open(LOG, "a") as f:
        f.write(stamped + "\n")


if REPO not in sys.path:
    sys.path.insert(0, REPO)


def probe(timeout: float) -> bool:
    """One accelerator probe, sharing bench.py's detection contract."""
    import contextlib
    import io

    from bench import _device_probe_ok

    detail = io.StringIO()
    with contextlib.redirect_stderr(detail):
        ok = _device_probe_ok(timeout=timeout, attempts=1)
    log(("probe OK: " if ok else "probe FAIL: ") + " | ".join(detail.getvalue().split("\n"))[:500])
    return ok


def run_capture(name: str, cmd: list[str], timeout: float) -> dict:
    """Run one capture command; return a record for the history file."""
    log(f"capture [{name}] start: {' '.join(cmd)}")
    t0 = time.time()
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env={k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"},
        )
        rc, out, err = r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = (e.stdout or b"").decode(errors="replace") if isinstance(e.stdout, bytes) else (e.stdout or "")
        child_err = (e.stderr or b"").decode(errors="replace") if isinstance(e.stderr, bytes) else (e.stderr or "")
        err = f"TIMEOUT after {timeout}s\n{child_err}"
    dt = time.time() - t0
    # last JSON-looking line of stdout is the parsed result (bench.py contract)
    parsed = None
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    rec = {
        # float epoch `ts` is the machine-sortable key across every
        # BENCH_HISTORY.jsonl producer; `ts_iso` is for humans
        "ts": round(time.time(), 3),
        "ts_iso": _now(),
        "source": f"tpu_watch:{name}",
        "rc": rc,
        "seconds": round(dt, 1),
        "parsed": parsed,
        "stdout_tail": out[-3000:],
        "stderr_tail": err[-2000:],
    }
    log(f"capture [{name}] done rc={rc} in {dt:.0f}s parsed={json.dumps(parsed)}")
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0)
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--once", action="store_true", help="probe once and exit")
    args = ap.parse_args()

    # the probe/capture subprocesses must let the accelerator plugin claim
    # the backend — a forced-cpu JAX_PLATFORMS inherited from the operator's
    # shell would make every probe report 'cpu' forever
    os.environ.pop("JAX_PLATFORMS", None)

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        log(f"--- probe attempt {attempt} ---")
        if probe(args.probe_timeout):
            # cheapest-first: the round-2/3 tunnel windows lasted ~20 min and
            # died mid-capture, so grab a small committed number BEFORE the
            # expensive full-scale runs (each fold_bench stage appends its
            # own history line the moment it has a number)
            specs = [
                # seconds-scale first stage (VERDICT r04 item 1): ~200k params
                # is a ~5 MB/update transfer and a sub-second fold, so even a
                # 3-minute window banks the program's first platform:"tpu"
                # line; --auto-stage makes the first Mosaic compile and the
                # kernel=auto calibration branch happen under this cheapest
                # capture rather than a big one
                ("fold_micro",
                 [sys.executable, "tools/tpu_fold_bench.py",
                  "--model-len", "200000", "--k", "8", "--auto-stage"], 300),
                ("fold_2.5m",
                 [sys.executable, "tools/tpu_fold_bench.py",
                  "--model-len", "2500000", "--k", "8", "--auto-stage"], 600),
                ("fold_25m",
                 [sys.executable, "tools/tpu_fold_bench.py",
                  "--model-len", "25000000", "--k", "8"], 1200),
                ("bench_headline", [sys.executable, "bench.py"], 1800),
                ("bench_round_25m",
                 [sys.executable, "tools/bench_round.py", "--model-len", "25000000",
                  "--updates", "64", "--batch", "16"], 2400),
            ]
            records = []
            for name, cmd, cap_timeout in specs:
                rec = run_capture(name, cmd, cap_timeout)
                records.append(rec)
                with open(HISTORY, "a") as f:  # crash-safe: append as we go
                    f.write(json.dumps(rec) + "\n")
                # a failed capture usually means the tunnel died mid-window;
                # don't burn an hour timing out the remaining (bigger)
                # captures against a dead tunnel — re-probe to decide
                if rec["rc"] != 0 and not probe(args.probe_timeout):
                    log("tunnel gone mid-suite; abandoning remaining captures")
                    break
            good = [
                r for r in records
                if r["rc"] == 0 and r["parsed"] and r["parsed"].get("platform") not in (None, "cpu")
            ]
            if good:
                # one document header across however many windows contribute;
                # only successful captures get sections (failures are in
                # TPU_WATCH.log + BENCH_HISTORY.jsonl)
                with open(EVIDENCE, "a") as f:
                    if f.tell() == 0:
                        f.write("# TPU evidence — round 5 (captured by tools/tpu_watch.py)\n\n")
                    f.write(f"## window at {_now()} (probe attempt {attempt})\n\n")
                    for rec in good:
                        f.write(f"### {rec['source']} (rc={rec['rc']}, {rec['seconds']}s)\n\n")
                        f.write("```\n" + rec["stdout_tail"] + "\n```\n\n")
                        f.write("Parsed: `" + json.dumps(rec["parsed"]) + "`\n\n")
            # only a 25M-scale accelerator number ends the watch: exiting on
            # the small 2.5M capture alone would abandon later windows that
            # could yield the headline the round actually needs
            if any((r["parsed"] or {}).get("model_len") == 25_000_000 for r in good):
                log("TPU capture complete at 25M; exiting so the builder can commit")
                return 0
            if good:
                log("partial TPU evidence captured (sub-25M); continuing watch for the full headline")
            else:
                log("probe succeeded but no capture completed on the accelerator; continuing watch")
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if args.once:
            return 1
        time.sleep(args.interval)
    log("deadline reached without a live accelerator; TPU_WATCH.log is the evidence")
    return 1


if __name__ == "__main__":
    sys.exit(main())
