"""Opportunistic TPU evidence capture (VERDICT r02, next-round item 1).

The axon tunnel to the real TPU chip has been dead at both end-of-round
bench captures so far.  Instead of betting the round on one end-of-round
moment, this watcher loops in the background:

  * every ``--interval`` seconds it probes the accelerator in a fresh
    subprocess (a wedged tunnel hangs the JAX backend init forever, so the
    probe must be externally timed out);
  * every attempt is appended to ``TPU_WATCH.log`` — if the tunnel never
    comes up all round, that log is the committed proof;
  * the moment a probe succeeds it immediately runs the full capture
    suite (``bench.py`` headline + Pallas tile sweep, and
    ``tools/bench_round.py`` end-to-end round legs at 25M params), appends
    platform-tagged JSON to ``BENCH_HISTORY.jsonl``, writes
    ``TPU_EVIDENCE_r03.md``, and exits 0 so the builder can commit.

Run:  python tools/tpu_watch.py [--interval 600] [--probe-timeout 150]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_WATCH.log")
HISTORY = os.path.join(REPO, "BENCH_HISTORY.jsonl")
EVIDENCE = os.path.join(REPO, "TPU_EVIDENCE_r03.md")

def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def log(line: str) -> None:
    stamped = f"{_now()} {line}"
    print(stamped, flush=True)
    with open(LOG, "a") as f:
        f.write(stamped + "\n")


if REPO not in sys.path:
    sys.path.insert(0, REPO)


def probe(timeout: float) -> bool:
    """One accelerator probe, sharing bench.py's detection contract."""
    import contextlib
    import io

    from bench import _device_probe_ok

    detail = io.StringIO()
    with contextlib.redirect_stderr(detail):
        ok = _device_probe_ok(timeout=timeout, attempts=1)
    log(("probe OK: " if ok else "probe FAIL: ") + " | ".join(detail.getvalue().split("\n"))[:500])
    return ok


def run_capture(name: str, cmd: list[str], timeout: float) -> dict:
    """Run one capture command; return a record for the history file."""
    log(f"capture [{name}] start: {' '.join(cmd)}")
    t0 = time.time()
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env={k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"},
        )
        rc, out, err = r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = (e.stdout or b"").decode(errors="replace") if isinstance(e.stdout, bytes) else (e.stdout or "")
        child_err = (e.stderr or b"").decode(errors="replace") if isinstance(e.stderr, bytes) else (e.stderr or "")
        err = f"TIMEOUT after {timeout}s\n{child_err}"
    dt = time.time() - t0
    # last JSON-looking line of stdout is the parsed result (bench.py contract)
    parsed = None
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    rec = {
        "ts": _now(),
        "source": f"tpu_watch:{name}",
        "rc": rc,
        "seconds": round(dt, 1),
        "parsed": parsed,
        "stdout_tail": out[-3000:],
        "stderr_tail": err[-2000:],
    }
    log(f"capture [{name}] done rc={rc} in {dt:.0f}s parsed={json.dumps(parsed)}")
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0)
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--once", action="store_true", help="probe once and exit")
    args = ap.parse_args()

    # the probe/capture subprocesses must let the accelerator plugin claim
    # the backend — a forced-cpu JAX_PLATFORMS inherited from the operator's
    # shell would make every probe report 'cpu' forever
    os.environ.pop("JAX_PLATFORMS", None)

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        log(f"--- probe attempt {attempt} ---")
        if probe(args.probe_timeout):
            records = [
                run_capture("bench_headline", [sys.executable, "bench.py"], 1800),
                run_capture(
                    "bench_round_25m",
                    [sys.executable, "tools/bench_round.py", "--model-len", "25000000",
                     "--updates", "64", "--batch", "16"],
                    2400,
                ),
            ]
            with open(HISTORY, "a") as f:
                for rec in records:
                    f.write(json.dumps(rec) + "\n")
            # success = at least one capture actually completed on an
            # accelerator; a tunnel that died mid-bench must not end the watch
            good = [
                r for r in records
                if r["rc"] == 0 and r["parsed"] and r["parsed"].get("platform") not in (None, "cpu")
            ]
            if not good:
                log("probe succeeded but no capture completed on the accelerator; continuing watch")
                if args.once:
                    return 1
                time.sleep(args.interval)
                continue
            with open(EVIDENCE, "w") as f:
                f.write("# TPU evidence — round 3 (captured by tools/tpu_watch.py)\n\n")
                f.write(f"Captured {_now()} after {attempt} probe attempts.\n\n")
                for rec in records:
                    f.write(f"## {rec['source']} (rc={rec['rc']}, {rec['seconds']}s)\n\n")
                    f.write("```\n" + rec["stdout_tail"] + "\n```\n\n")
                    if rec["parsed"]:
                        f.write("Parsed: `" + json.dumps(rec["parsed"]) + "`\n\n")
            log("TPU capture complete; exiting so the builder can commit")
            return 0
        if args.once:
            return 1
        time.sleep(args.interval)
    log("deadline reached without a live accelerator; TPU_WATCH.log is the evidence")
    return 1


if __name__ == "__main__":
    sys.exit(main())
