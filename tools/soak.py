"""Long-haul soak: a real coordinator process under participant churn.

Runs the coordinator as a subprocess (the production entry point), then
cycles fresh participants through rounds over the REST socket — every round
gets NEW keypairs (churn), so dictionaries, multipart buffers and the model
archive are exercised continuously. Tracks the coordinator's RSS across
rounds; steady-state growth beyond the expected per-round model archive
indicates a leak.

Usage:
  python tools/soak.py --rounds 200 [--model-len 2000]
Prints one JSON line: rounds completed, wall, rounds/s, RSS start/end/slope.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


def _scrape_console(port: int, require_tenants: list[str] | None = None) -> dict:
    """GET /statusz + /alerts off the live coordinator (DESIGN §20 smoke).

    Runs while the coordinator is still up — asserts the operator console
    renders (200, HTML, every tenant id present) and the SLO alert payload
    parses, and folds both into the soak's result JSON so CI carries the
    evidence."""
    from urllib.request import urlopen

    with urlopen(f"http://127.0.0.1:{port}/statusz", timeout=10) as resp:
        page = resp.read().decode("utf-8", "replace")
        if resp.status != 200 or "<html" not in page:
            raise RuntimeError(f"/statusz not healthy: {resp.status}")
    missing = [tid for tid in (require_tenants or []) if tid not in page]
    if missing:
        raise RuntimeError(f"/statusz missing tenants: {missing}")
    with urlopen(f"http://127.0.0.1:{port}/alerts", timeout=10) as resp:
        if resp.status != 200:
            raise RuntimeError(f"/alerts not healthy: {resp.status}")
        alerts = json.loads(resp.read())
    # per-tenant SLO burn gauges off /metrics: the soak's evidence that the
    # engine tracks tenants independently, not one merged series
    with urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
        text = resp.read().decode("utf-8", "replace")
    burn_tenants = sorted(
        {
            line.split('tenant="', 1)[1].split('"', 1)[0]
            for line in text.splitlines()
            if line.startswith("xaynet_slo_burn_rate{")
        }
    )
    return {
        "statusz_bytes": len(page),
        "alerts_active": alerts.get("active", []),
        "alerts_recent": len(alerts.get("recent", [])),
        "slo_burn_tenants": burn_tenants,
    }


CONFIG = """
[api]
bind_address = "127.0.0.1:{port}"

[pet.sum]
prob = 0.5
[pet.sum.count]
min = 1
max = 1
[pet.sum.time]
min = 0.0
max = 20.0

[pet.update]
prob = 0.9
[pet.update.count]
min = {update_min}
max = {update_max}
{update_quorum_line}
[pet.update.time]
min = 0.0
max = 20.0

[liveness]
stall_grace_s = {stall_grace}

[pet.sum2.count]
min = 1
max = 1
[pet.sum2.time]
min = 0.0
max = 20.0

[model]
length = {model_len}

[aggregation]
device = {agg_device}
batch_size = {agg_batch}
kernel = "{agg_kernel}"
wire_ingest = {agg_wire_ingest}

[storage]
backend = "filesystem"
model_dir = "{model_dir}"

{edge_enabled_line}
[log]
# info: the soak artifact reads the aggregator's "kernel resolved" line
filter = "info"
"""

EDGE_CONFIG = """
[api]
bind_address = "127.0.0.1:{port}"

[edge]
upstream_url = "http://127.0.0.1:{upstream_port}"
edge_id = "{edge_id}"
max_members = {max_members}
linger_s = 0.2
poll_s = 0.1

[log]
filter = "info"
"""


N_CHAOS_UPDATERS = 6

# per-tenant mask-config/model-size diversity for --tenants N: tenant i
# gets MODEL_LENS[i % ...] params and GROUPS[i % ...] group arithmetic, so
# the multi-tenant smoke genuinely packs variable-length models with
# different group orders into one pool (docs/DESIGN.md §19)
TENANT_MODEL_LENS = (1500, 2200, 900, 3000)
TENANT_GROUPS = ("integer", "prime", "power2", "integer")


def _tenant_config(port: int, model_len: int, group: str, model_dir: str) -> str:
    """One tenant's FULL override settings file (loaded standalone by the
    multi-tenant runner; [api] is unused there — the process listener comes
    from the base config)."""
    base = CONFIG.format(
        port=port,
        model_len=model_len,
        model_dir=model_dir,
        agg_device="true",
        agg_wire_ingest="false",
        agg_batch=2,
        agg_kernel="auto",
        update_min=3,
        update_max=3,
        update_quorum_line="",
        stall_grace=1.0,
        edge_enabled_line="",
    )
    return base + f'\n[mask]\ngroup_type = "{group}"\n'


def _drive_tenant_rounds(
    url: str, rounds: int, model_len: int, expected: bytes | None, label: str,
    round_timeout_s: float = 120.0,
) -> bytes:
    """Drive ``rounds`` PET rounds against ``url`` (a bare or /t/<tenant>
    base) with DETERMINISTIC participant models; every completed round's
    global model must equal ``expected`` (byte-identity vs the
    single-tenant control) when given. Returns the last model bytes.

    Each round gets ``round_timeout_s`` of wall clock — a tick-count bound
    would burn out in seconds once every participant is awaiting, racing
    the coordinator's first-round unmask compile."""
    from fractions import Fraction

    import numpy as np

    from xaynet_tpu.sdk.client import HttpClient
    from xaynet_tpu.sdk.participant import Participant
    from xaynet_tpu.sdk.simulation import keys_for_task

    def fetch_params():
        return asyncio.run(HttpClient(url, keep_alive=False).get_round_params())

    def fetch_model() -> bytes:
        model = asyncio.run(HttpClient(url, keep_alive=False).get_model())
        return np.asarray(model, dtype=np.float64).tobytes()

    completed = 0
    last_seed = None
    model_bytes = b""
    while completed < rounds:
        params = fetch_params()
        if params.seed.as_bytes() == last_seed:
            time.sleep(0.01)
            continue
        last_seed = params.seed.as_bytes()
        seed = last_seed
        summer = keys_for_task(seed, params.sum, params.update, "sum")
        upd, start = [], 0
        while len(upd) < 3:
            k = keys_for_task(seed, params.sum, params.update, "update", start=start)
            start += 100000
            if all(k.public != u.public for u in upd) and k.public != summer.public:
                upd.append(k)
        parts = [Participant(url, keys=summer, scalar=Fraction(1, 3))]
        for i, k in enumerate(upd):
            p = Participant(url, keys=k, scalar=Fraction(1, 3))
            p.set_model(np.full(model_len, 0.25 * (i + 1), dtype=np.float32))
            parts.append(p)
        deadline = time.time() + round_timeout_s
        closed = False
        while time.time() < deadline:
            for p in parts:
                p.tick()
            if fetch_params().seed.as_bytes() != seed:
                closed = True
                break
        if not closed:
            raise RuntimeError(f"{label}: round {completed + 1} did not complete")
        model_bytes = fetch_model()
        if expected is not None and model_bytes != expected:
            raise RuntimeError(
                f"{label}: round {completed + 1} NOT byte-identical to the "
                "single-tenant control"
            )
        completed += 1
    return model_bytes


def run_multi_tenant_soak(args) -> None:
    """--tenants N: N tenants with distinct mask configs/model sizes in ONE
    coordinator process, each driven concurrently over /t/<tenant>/... and
    checked byte-identical to its single-tenant control run."""
    import socket
    import threading

    n = args.tenants
    tenants = [f"t{i}" for i in range(n)]
    spec = {
        tid: (
            TENANT_MODEL_LENS[i % len(TENANT_MODEL_LENS)],
            TENANT_GROUPS[i % len(TENANT_GROUPS)],
        )
        for i, tid in enumerate(tenants)
    }
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

    def wait_listening(port: int, proc) -> None:
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=1):
                    return
            except OSError:
                if proc.poll() is not None:
                    raise RuntimeError("coordinator exited during startup")
                time.sleep(0.25)
        raise RuntimeError("coordinator did not start listening in 90s")

    t0 = time.perf_counter()
    controls: dict[str, bytes] = {}
    with tempfile.TemporaryDirectory() as tmp:
        cfg_dir = os.path.join(tmp, "tenants")
        os.makedirs(cfg_dir)
        for tid, (mlen, group) in spec.items():
            with open(os.path.join(cfg_dir, f"{tid}.toml"), "w") as f:
                f.write(
                    _tenant_config(
                        args.port, mlen, group, os.path.join(tmp, f"models-{tid}")
                    )
                )
        # --- single-tenant control runs: one round each, alone ------------
        for tid, (mlen, group) in spec.items():
            log = open(os.path.join(tmp, f"control-{tid}.log"), "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "xaynet_tpu.server.runner",
                 "-c", os.path.join(cfg_dir, f"{tid}.toml")],
                env=env, stdout=log, stderr=subprocess.STDOUT,
            )
            try:
                wait_listening(args.port, proc)
                controls[tid] = _drive_tenant_rounds(
                    f"http://127.0.0.1:{args.port}", 1, mlen, None,
                    f"control {tid}",
                )
            finally:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5)
                log.close()
            print(f"control {tid}: model {len(controls[tid])} bytes", file=sys.stderr)
        # --- the multi-tenant run -----------------------------------------
        base_cfg = os.path.join(tmp, "multi.toml")
        with open(base_cfg, "w") as f:
            f.write(
                _tenant_config(
                    args.port,
                    spec[tenants[0]][0],
                    spec[tenants[0]][1],
                    os.path.join(tmp, "models-multi"),
                )
                + "\n[tenancy]\nenabled = true\n"
                + f'tenants = "{",".join(tenants)}"\n'
                + f'config_dir = "{cfg_dir}"\n'
            )
        log_path = os.path.join(tmp, "multi.log")
        log = open(log_path, "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "xaynet_tpu.server.runner", "-c", base_cfg],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        try:
            wait_listening(args.port, proc)
            errors: list[BaseException] = []

            def drive(tid: str) -> None:
                mlen, _ = spec[tid]
                try:
                    _drive_tenant_rounds(
                        f"http://127.0.0.1:{args.port}/t/{tid}",
                        args.rounds,
                        mlen,
                        controls[tid],
                        f"tenant {tid}",
                    )
                except BaseException as err:
                    errors.append(err)

            threads = [
                threading.Thread(target=drive, args=(tid,), daemon=True)
                for tid in tenants
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            if errors:
                raise errors[0]
            console = _scrape_console(args.port, require_tenants=tenants)
            rss = _rss_kb(proc.pid)
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
            log.close()
    print(
        json.dumps(
            {
                "tenants": {
                    tid: {"model_len": spec[tid][0], "group": spec[tid][1]}
                    for tid in tenants
                },
                "rounds_per_tenant": args.rounds,
                "byte_identical": True,
                "wall_s": round(time.perf_counter() - t0, 2),
                "rss_kb": rss,
                "console": console,
            }
        )
    )


def _http_status(url: str, method: str = "GET", body: bytes | None = None,
                 headers: dict | None = None, timeout: float = 60.0):
    """One HTTP call returning (status, body bytes) — 4xx/5xx included
    (urllib raises on those; the churn soak ASSERTS on 401/404/429)."""
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    req = Request(url, data=body, method=method)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except HTTPError as err:
        return err.code, err.read()


def _metric_value(port: int, family: str, labels: dict) -> float | None:
    """One sample off the live /metrics endpoint (Prometheus text)."""
    from urllib.request import urlopen

    with urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
        text = resp.read().decode("utf-8", "replace")
    for line in text.splitlines():
        if not line.startswith(family + "{") and line.split(" ")[0] != family:
            continue
        if all(f'{k}="{v}"' in line for k, v in labels.items()):
            try:
                return float(line.rsplit(" ", 1)[1])
            except ValueError:
                return None
    return None


def _metric_sum(port: int, family: str) -> float:
    """Sum of every sample of ``family`` across all label sets (e.g. the
    total leased pool pages over every arena x tenant)."""
    from urllib.request import urlopen

    with urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
        text = resp.read().decode("utf-8", "replace")
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(family + "{") and line.split(" ")[0] != family:
            continue
        try:
            total += float(line.rsplit(" ", 1)[1])
        except ValueError:
            continue
    return total


# --- SIGKILL-matrix chaos soak (docs/DESIGN.md §9) --------------------------

# the full matrix: one seeded kill coordinate per phase family plus the
# publish window. <site>:<n> dies on the n-th visit to the site — "update:2"
# is mid-window (2 of 3 updates journaled), "unmask:publish:1" lands AFTER
# the model save but BEFORE the journal retires (the idempotent-republish
# window, the nastiest restart point).
KILL_MATRIX = ("sum:1", "update:2", "sum2:1", "unmask:publish:1")
RECOVERY_METRIC = "restart recovery wall"
RECOVERY_UNIT = "s/recovery"


def _kill_config(port: int, model_len: int, state_dir: str) -> str:
    """A checkpoint-enabled coordinator config whose durable state (file
    coordinator + model archive + round journal) all lives under
    ``state_dir`` — the restart boots on the SAME tree the kill orphaned.

    ``checkpoint_every_batches = 1`` with ``batch_size = 1`` puts a journal
    write BEFORE every update acknowledgement, so any accepted message
    survives any kill point. Overlap is pinned off: the matrix measures the
    journal, not the journal x speculation interplay (tests cover that)."""
    base = CONFIG.format(
        port=port,
        model_len=model_len,
        model_dir=state_dir,
        agg_device="true",
        agg_wire_ingest="false",
        agg_batch=1,
        agg_kernel="auto",
        update_min=3,
        update_max=3,
        update_quorum_line="",
        stall_grace=5.0,
        edge_enabled_line="",
    )
    # the template's [storage] table already exists — inject the coordinator
    # backend into it (tomllib rejects a duplicate [storage] section)
    base = base.replace(
        'backend = "filesystem"', 'backend = "filesystem"\ncoordinator = "file"'
    )
    return base + (
        "\n[restore]\nenable = true\n"
        "\n[resilience]\n"
        "checkpoint_enabled = true\n"
        "checkpoint_every_batches = 1\n"
        "checkpoint_every_s = 1.0\n"
        "max_resume_attempts = 3\n"
        "\n[overlap]\nenabled = false\n"
    )


def _drive_crash_round(
    url: str, model_len: int, expected: bytes | None, label: str,
    timeout_s: float = 300.0,
) -> bytes:
    """Drive ONE deterministic PET round, tolerating a coordinator death
    and restart mid-round: every fetch retries through the dead-socket
    window, and ``Participant.tick`` already swallows transport errors into
    a PENDING transition (the resilient client bridges short gaps on its
    own). Returns the published global model bytes, byte-compared against
    ``expected`` when given."""
    from fractions import Fraction

    import numpy as np

    from xaynet_tpu.sdk.client import HttpClient
    from xaynet_tpu.sdk.participant import Participant
    from xaynet_tpu.sdk.simulation import keys_for_task

    def fetch_params():
        return asyncio.run(HttpClient(url, keep_alive=False).get_round_params())

    def fetch_model() -> bytes:
        model = asyncio.run(HttpClient(url, keep_alive=False).get_model())
        return np.asarray(model, dtype=np.float64).tobytes()

    deadline = time.time() + timeout_s
    params = None
    while params is None:
        if time.time() > deadline:
            raise RuntimeError(f"{label}: no round parameters before timeout")
        try:
            params = fetch_params()
        except Exception:
            time.sleep(0.2)
    seed = params.seed.as_bytes()
    summer = keys_for_task(seed, params.sum, params.update, "sum")
    upd, start = [], 0
    while len(upd) < 3:
        k = keys_for_task(seed, params.sum, params.update, "update", start=start)
        start += 100000
        if all(k.public != u.public for u in upd) and k.public != summer.public:
            upd.append(k)
    parts = [Participant(url, keys=summer, scalar=Fraction(1, 3))]
    for i, k in enumerate(upd):
        p = Participant(url, keys=k, scalar=Fraction(1, 3))
        p.set_model(np.full(model_len, 0.25 * (i + 1), dtype=np.float32))
        parts.append(p)
    try:
        closed = False
        while time.time() < deadline:
            for p in parts:
                p.tick()
            try:
                if fetch_params().seed.as_bytes() != seed:
                    closed = True
                    break
            except Exception:
                # coordinator dead or restarting: keep the participants'
                # resend state warm and poll again
                time.sleep(0.2)
        if not closed:
            raise RuntimeError(f"{label}: round did not complete")
        model_bytes = None
        while model_bytes is None:
            if time.time() > deadline + 30:
                raise RuntimeError(f"{label}: model not fetchable after round close")
            try:
                model_bytes = fetch_model()
            except Exception:
                time.sleep(0.2)
        if expected is not None and model_bytes != expected:
            raise RuntimeError(f"{label}: model NOT byte-identical to the unkilled control")
        return model_bytes
    finally:
        for p in parts:
            p.close()


def run_kill_matrix_soak(args) -> None:
    """--kill-matrix: SIGKILL the coordinator at seeded (phase, message)
    coordinates, restart it on the same durable tree, and drive the
    surviving participants to completion. Per coordinate the harness
    asserts (docs/DESIGN.md §9):

    - the restarted coordinator RESUMED the killed phase from the round
      journal (``xaynet_resume_total{phase,outcome="resumed"}`` >= 1);
    - the published global model is byte-identical to an unkilled control;
    - zero pool pages stay leased after the round (no leak across a kill);
    - the restart-to-serving wall (``xaynet_recovery_seconds``) is
      recorded — with ``--append-history`` it lands in BENCH_HISTORY.jsonl
      as the lower-is-better "restart recovery wall" bench-gate family.
    """
    import signal
    import socket
    import threading

    coords = [
        c.strip()
        for c in (args.kill_points or ",".join(KILL_MATRIX)).split(",")
        if c.strip()
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XAYNET_KILL_POINT", None)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

    def wait_listening(port: int, proc) -> None:
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=1):
                    return
            except OSError:
                if proc.poll() is not None:
                    raise RuntimeError("coordinator exited during startup")
                time.sleep(0.25)
        raise RuntimeError("coordinator did not start listening in 90s")

    def stop(proc) -> None:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)

    url = f"http://127.0.0.1:{args.port}"
    t0 = time.perf_counter()
    results = []
    with tempfile.TemporaryDirectory() as tmp:
        def boot(state_dir: str, tag: str, extra_env: dict | None = None):
            cfg = os.path.join(state_dir, "coordinator.toml")
            if not os.path.exists(cfg):
                with open(cfg, "w") as f:
                    f.write(_kill_config(args.port, args.model_len, state_dir))
            log = open(os.path.join(state_dir, f"{tag}.log"), "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "xaynet_tpu.server.runner", "-c", cfg],
                env=dict(env, **(extra_env or {})),
                stdout=log, stderr=subprocess.STDOUT,
            )
            return proc, log

        # --- unkilled control: the byte-identity reference ----------------
        control_dir = os.path.join(tmp, "control")
        os.makedirs(control_dir)
        proc, log = boot(control_dir, "control")
        try:
            wait_listening(args.port, proc)
            control = _drive_crash_round(url, args.model_len, None, "control")
        finally:
            stop(proc)
            log.close()
        print(f"control: model {len(control)} bytes", file=sys.stderr)

        # --- the matrix ---------------------------------------------------
        for coord in coords:
            phase = coord.split(":", 1)[0]
            state_dir = os.path.join(tmp, coord.replace(":", "_"))
            os.makedirs(state_dir)
            proc, log = boot(state_dir, "killed", {"XAYNET_KILL_POINT": coord})
            box: dict = {}

            def drive() -> None:
                try:
                    box["model"] = _drive_crash_round(
                        url, args.model_len, control, f"kill {coord}"
                    )
                except BaseException as err:
                    box["error"] = err

            th = threading.Thread(target=drive, daemon=True)
            try:
                wait_listening(args.port, proc)
                th.start()
                # the seeded kill MUST fire: anything else (clean exit,
                # crash-on-boot, survived round) fails the matrix
                rc = proc.wait(timeout=240)
                if rc != -signal.SIGKILL:
                    raise RuntimeError(f"{coord}: coordinator exited {rc}, expected SIGKILL")
            finally:
                log.close()
            print(f"{coord}: killed (pid {proc.pid})", file=sys.stderr)
            t_restart = time.perf_counter()
            proc, log = boot(state_dir, "restarted")
            try:
                wait_listening(args.port, proc)
                restart_wall = time.perf_counter() - t_restart
                th.join(timeout=300)
                if th.is_alive():
                    raise RuntimeError(f"{coord}: round did not complete after restart")
                if "error" in box:
                    raise box["error"]
                resumed = _metric_value(
                    args.port, "xaynet_resume_total",
                    {"phase": phase, "outcome": "resumed"},
                )
                if not resumed:
                    raise RuntimeError(
                        f"{coord}: no xaynet_resume_total{{phase={phase!r},"
                        f'outcome="resumed"}} sample after restart'
                    )
                recovery_s = _metric_value(args.port, "xaynet_recovery_seconds", {})
                leaked = _metric_sum(args.port, "xaynet_pool_pages")
                if leaked:
                    raise RuntimeError(f"{coord}: {leaked:g} pool pages leaked")
            finally:
                stop(proc)
                log.close()
            print(
                f"{coord}: resumed={resumed:g} recovery={recovery_s}s "
                f"restart_wall={restart_wall:.2f}s",
                file=sys.stderr,
            )
            results.append(
                {
                    "kill_point": coord,
                    "phase": phase,
                    "resumed": resumed,
                    "recovery_s": recovery_s,
                    "restart_to_serving_s": round(restart_wall, 3),
                    "byte_identical": True,
                    "pool_pages_leaked": leaked,
                }
            )
    if args.append_history:
        history = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BENCH_HISTORY.jsonl",
        )
        ts = time.time()
        with open(history, "a") as f:
            for rec in results:
                f.write(
                    json.dumps(
                        {
                            "ts": ts,
                            "cpus": os.cpu_count(),
                            "metric": f"{RECOVERY_METRIC} ({rec['kill_point']})",
                            "value": rec["recovery_s"],
                            "unit": RECOVERY_UNIT,
                            "restart_to_serving_s": rec["restart_to_serving_s"],
                            "model_len": args.model_len,
                        }
                    )
                    + "\n"
                )
    print(
        json.dumps(
            {
                "kill_matrix": results,
                "model_len": args.model_len,
                "byte_identical": True,
                "wall_s": round(time.perf_counter() - t0, 2),
            }
        )
    )


def run_tenant_churn_soak(args) -> None:
    """--tenant-churn: the elastic-lifecycle chaos soak (docs/DESIGN.md §23).

    One multi-tenant coordinator boots with t0+t1; t1's storage is
    fault-injected (``t:t1:...`` sites) so its rounds fail and trip the
    quarantine, while t0 drives rounds CONTINUOUSLY — every one
    byte-identical to its single-tenant control. Mid-run, t2 is onboarded
    over the authenticated /admin/tenants API, completes a
    control-identical round, and is drained back out; the soak then pins:
    quarantined t1 sheds with 429 and auto-readmits via the half-open
    probe round, admin auth rejects bad tokens, the drained tenant's
    routes 404, and its pool pages are ZERO after teardown."""
    import socket
    import threading

    # t2 stays on the integer group: its round is driven ONCE against a
    # wall-clock-bounded driver mid-churn, and the power2 group's slow
    # big-int unmask can outrun that budget on a loaded CI host
    spec = {
        "t0": (TENANT_MODEL_LENS[0], TENANT_GROUPS[0]),
        "t1": (TENANT_MODEL_LENS[1], TENANT_GROUPS[1]),
        "t2": (900, "integer"),
    }
    admin_token = "churn-soak-admin-token"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    # fault ONE tenant's storage: t1's Idle delete_dicts eats the whole
    # 4-attempt retry budget on rounds 1 AND 2 (max=8 faults), so exactly
    # two rounds fail — the lifecycle quarantine threshold below. The
    # budget is then SPENT: the half-open probe round's storage works and
    # t1 earns its way back in. is_ready is NOT faulted (readiness checks
    # stay truthful, and their recorded successes reset the storage
    # breaker between rounds — the STORAGE breaker never opens; only the
    # lifecycle quarantine does).
    env["XAYNET_FAULT_PLAN"] = (
        "seed=11;t:t1:storage.coordinator.delete_dicts:error,rate=1.0,max=8"
    )

    def wait_listening(port: int, proc) -> None:
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=1):
                    return
            except OSError:
                if proc.poll() is not None:
                    raise RuntimeError("coordinator exited during startup")
                time.sleep(0.25)
        raise RuntimeError("coordinator did not start listening in 90s")

    def stop(proc) -> None:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)

    t0_wall = time.perf_counter()
    controls: dict[str, bytes] = {}
    events: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        cfg_dir = os.path.join(tmp, "tenants")
        os.makedirs(cfg_dir)
        for tid, (mlen, group) in spec.items():
            with open(os.path.join(cfg_dir, f"{tid}.toml"), "w") as f:
                f.write(
                    _tenant_config(
                        args.port, mlen, group, os.path.join(tmp, f"models-{tid}")
                    )
                )
        # --- single-tenant control runs (fault plan OFF) -------------------
        control_env = {k: v for k, v in env.items() if k != "XAYNET_FAULT_PLAN"}
        for tid, (mlen, group) in spec.items():
            clog_path = os.path.join(tmp, f"control-{tid}.log")
            log = open(clog_path, "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "xaynet_tpu.server.runner",
                 "-c", os.path.join(cfg_dir, f"{tid}.toml")],
                env=control_env, stdout=log, stderr=subprocess.STDOUT,
            )
            try:
                wait_listening(args.port, proc)
                controls[tid] = _drive_tenant_rounds(
                    f"http://127.0.0.1:{args.port}", 1, mlen, None, f"control {tid}"
                )
            except BaseException:
                log.flush()
                with open(clog_path) as lf:
                    print("".join(lf.readlines()[-40:]), file=sys.stderr)
                raise
            finally:
                stop(proc)
                log.close()
            print(f"control {tid}: model {len(controls[tid])} bytes", file=sys.stderr)
        # --- the churn run: boot with t0 + t1, t2 arrives later ------------
        base_cfg = os.path.join(tmp, "multi.toml")
        with open(base_cfg, "w") as f:
            f.write(
                _tenant_config(
                    args.port, spec["t0"][0], spec["t0"][1],
                    os.path.join(tmp, "models-multi"),
                )
                + "\n[tenancy]\nenabled = true\n"
                + 'tenants = "t0,t1"\n'
                + f'config_dir = "{cfg_dir}"\n'
                + f'admin_token = "{admin_token}"\n'
                + "drain_timeout_s = 60.0\n"
                + "quarantine_failures = 2\n"
                + "quarantine_reset_s = 5.0\n"
            )
        log_path = os.path.join(tmp, "multi.log")
        log = open(log_path, "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "xaynet_tpu.server.runner", "-c", base_cfg],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        base = f"http://127.0.0.1:{args.port}"
        try:
            wait_listening(args.port, proc)
            # -- t0: continuous control-identical rounds, the whole time ----
            stop_t0 = threading.Event()
            t0_rounds = [0]
            t0_errors: list[BaseException] = []

            def drive_t0() -> None:
                try:
                    while not stop_t0.is_set():
                        _drive_tenant_rounds(
                            f"{base}/t/t0", 1, spec["t0"][0], controls["t0"],
                            "tenant t0",
                        )
                        t0_rounds[0] += 1
                except BaseException as err:
                    t0_errors.append(err)

            t0_thread = threading.Thread(target=drive_t0, daemon=True)
            t0_thread.start()

            # -- t1 trips the quarantine under storage faults ---------------
            deadline = time.time() + 120
            while time.time() < deadline:
                if _metric_value(args.port, "xaynet_tenant_state", {"tenant": "t1"}) == 3.0:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("t1 never reached the quarantined state")
            events.append("t1 quarantined")
            # quarantined ingress sheds with 429 + Retry-After
            status, _ = _http_status(
                f"{base}/t/t1/message", method="POST", body=b"probe", timeout=10
            )
            if status != 429:
                raise RuntimeError(f"quarantined POST expected 429, got {status}")
            events.append("t1 sheds 429")
            if _metric_value(args.port, "xaynet_tenant_quarantines_total",
                             {"tenant": "t1"}) != 1.0:
                raise RuntimeError("xaynet_tenant_quarantines_total{t1} != 1")

            # -- auto-readmission: the half-open probe round completes ------
            probe_deadline = time.time() + 180
            readmitted = False
            while time.time() < probe_deadline:
                try:
                    _drive_tenant_rounds(
                        f"{base}/t/t1", 1, spec["t1"][0], controls["t1"],
                        "tenant t1 probe", round_timeout_s=25.0,
                    )
                    readmitted = True
                    break
                except Exception:
                    time.sleep(0.5)
            if not readmitted:
                raise RuntimeError("t1 probe round never completed (no readmission)")
            state_deadline = time.time() + 30
            while time.time() < state_deadline:
                if _metric_value(args.port, "xaynet_tenant_state", {"tenant": "t1"}) == 2.0:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("t1 not back to serving after the probe round")
            events.append("t1 readmitted (control-identical probe round)")

            # -- admin auth: constant-time token, bad/missing -> 401 --------
            for hdrs in ({}, {"x-admin-token": "wrong"}):
                status, _ = _http_status(
                    f"{base}/admin/tenants", headers=hdrs, timeout=10
                )
                if status != 401:
                    raise RuntimeError(f"admin without valid token: got {status}")
            events.append("admin auth rejects bad tokens")

            # -- onboard t2 mid-run over the admin API ----------------------
            status, body = _http_status(
                f"{base}/admin/tenants", method="POST",
                body=json.dumps({"tenant": "t2"}).encode(),
                headers={"x-admin-token": admin_token,
                         "content-type": "application/json"},
                timeout=180,
            )
            if status != 200:
                raise RuntimeError(f"onboard t2 failed: {status} {body[:200]!r}")
            onboard_s = json.loads(body).get("onboard_s")
            _drive_tenant_rounds(
                f"{base}/t/t2", 1, spec["t2"][0], controls["t2"], "tenant t2"
            )
            events.append(f"t2 onboarded ({onboard_s}s) + control-identical round")

            # -- drain t2 back out; zero leaked pages, routes 404 -----------
            status, body = _http_status(
                f"{base}/admin/tenants/t2", method="DELETE",
                headers={"x-admin-token": admin_token}, timeout=120,
            )
            if status != 200:
                raise RuntimeError(f"offboard t2 failed: {status} {body[:200]!r}")
            outcome = json.loads(body).get("outcome")
            pages = _metric_value(
                args.port, "xaynet_pool_pages", {"arena": "host", "tenant": "t2"}
            )
            if pages not in (None, 0.0):
                raise RuntimeError(f"t2 leaked {pages} host pool pages after drain")
            status, _ = _http_status(f"{base}/t/t2/params", timeout=10)
            if status != 404:
                raise RuntimeError(f"drained t2 routes expected 404, got {status}")
            events.append(f"t2 drained ({outcome}); zero leaked pages; routes 404")

            # -- t0 survived the whole churn, byte-identical throughout -----
            stop_t0.set()
            t0_thread.join(timeout=300)
            if t0_errors:
                raise t0_errors[0]
            if t0_rounds[0] < 1:
                raise RuntimeError("t0 completed no rounds during the churn")
            console = _scrape_console(args.port, require_tenants=["t0", "t1"])
            rss = _rss_kb(proc.pid)
        except BaseException:
            log.flush()
            with open(log_path) as lf:
                tail = lf.readlines()[-60:]
            print("".join(tail), file=sys.stderr)
            raise
        finally:
            stop(proc)
            log.close()
    print(
        json.dumps(
            {
                "churn_events": events,
                "t0_rounds_byte_identical": t0_rounds[0],
                "wall_s": round(time.perf_counter() - t0_wall, 2),
                "rss_kb": rss,
                "console": console,
            }
        )
    )


def run_chaos_soak_sync(
    port: int, rounds: int, model_len: int, dropout: float, stragglers: int
) -> dict:
    """Churn soak: the sum leg runs a real Participant; the update leg is
    driven by ``flood`` with the dropout/straggler knobs, so every round
    exercises the quorum-completion (degraded close) path end to end over
    the REST socket. Returns per-run churn totals alongside the round
    count."""
    from fractions import Fraction

    import numpy as np

    from xaynet_tpu.sdk.client import HttpClient, ResilientClient
    from xaynet_tpu.sdk.participant import Participant
    from xaynet_tpu.sdk.simulation import flood, keys_for_task

    url = f"http://127.0.0.1:{port}"

    def _client(round_seed: bytes | None = None):
        # a multi-hundred-round soak must survive the transient blips it
        # exists to exercise: one connection reset on a bare HttpClient
        # would abort the whole run (the sum leg already retries — the
        # Participant wraps its client in ResilientClient by default)
        # one-shot per-poll client: its event loop dies with asyncio.run,
        # so a pooled keep-alive socket would just leak until GC
        client = ResilientClient(HttpClient(url, keep_alive=False))
        # pin the round's trace id: chaos uploads stitch into the
        # coordinator's round trace, so a failed round's flight dump can
        # be joined to the soak's own logs
        client.set_round_trace(round_seed)
        return client

    def fetch_params():
        return asyncio.run(_client().get_round_params())

    completed = 0
    dropped_total = straggled_total = accepted_total = 0
    last_seed = None
    t0 = time.perf_counter()
    while completed < rounds:
        params = fetch_params()
        if params.seed.as_bytes() == last_seed:
            time.sleep(0.01)
            continue
        last_seed = params.seed.as_bytes()
        seed = last_seed
        summer = Participant(
            url,
            keys=keys_for_task(seed, params.sum, params.update, "sum"),
            scalar=Fraction(1, N_CHAOS_UPDATERS),
        )
        # drive the summer through Sum so the sum dictionary exists
        for _ in range(200):
            summer.tick()
            sum_dict = asyncio.run(_client().get_sums())
            if sum_dict:
                break
            time.sleep(0.05)
        else:
            raise RuntimeError(f"round {completed + 1}: sum dictionary never appeared")

        async def flood_updates():
            client = _client(round_seed=seed)

            async def submit(blob: bytes) -> None:
                await client.send_message(blob)

            rng = np.random.default_rng(completed + 1)
            return await flood(
                submit,
                params,
                sum_dict,
                N_CHAOS_UPDATERS,
                models=[
                    rng.uniform(-1, 1, model_len).astype(np.float32)
                    for _ in range(N_CHAOS_UPDATERS)
                ],
                scalar=Fraction(1, N_CHAOS_UPDATERS),
                key_spacing=100_000,
                dropout_rate=dropout,
                stragglers=stragglers,
                straggle_delay_s=0.3,
                churn_seed=completed + 1,
            )

        stats = asyncio.run(flood_updates())
        dropped_total += stats.dropped
        straggled_total += stats.straggled
        accepted_total += stats.accepted
        # the summer finishes sum2 and the round closes (degraded when the
        # dropouts left the window below count.min)
        try:
            for _ in range(400):
                summer.tick()
                if fetch_params().seed.as_bytes() != seed:
                    break
                time.sleep(0.05)
            else:
                raise RuntimeError(f"round {completed + 1} did not complete")
        finally:
            summer.close()
        completed += 1
    return {
        "rounds": completed,
        "wall_s": round(time.perf_counter() - t0, 2),
        "updates_accepted": accepted_total,
        "updates_dropped": dropped_total,
        "updates_straggled": straggled_total,
    }


def run_two_tier_soak_sync(
    port: int, edge_ports: list, rounds: int, model_len: int, updaters: int
) -> dict:
    """Two-tier soak: the sum leg talks to the coordinator directly; every
    update upload goes to an EDGE (round-robin across ``edge_ports``),
    which folds windows locally and ships partial-aggregate envelopes
    upstream. The round completes exactly like the flat topology — the
    coordinator just sees envelopes instead of per-participant updates."""
    import itertools

    from fractions import Fraction

    import numpy as np

    from xaynet_tpu.sdk.client import HttpClient, ResilientClient
    from xaynet_tpu.sdk.participant import Participant
    from xaynet_tpu.sdk.simulation import flood, keys_for_task

    url = f"http://127.0.0.1:{port}"
    edge_urls = [f"http://127.0.0.1:{p}" for p in edge_ports]

    def fetch_params():
        # one-shot per-poll clients (here and below): the loop dies with
        # asyncio.run, so a pooled keep-alive socket would leak until GC
        return asyncio.run(
            ResilientClient(HttpClient(url, keep_alive=False)).get_round_params()
        )

    completed = 0
    accepted_total = 0
    last_seed = None
    t0 = time.perf_counter()
    while completed < rounds:
        params = fetch_params()
        if params.seed.as_bytes() == last_seed:
            time.sleep(0.01)
            continue
        last_seed = params.seed.as_bytes()
        seed = last_seed
        summer = Participant(
            url,
            keys=keys_for_task(seed, params.sum, params.update, "sum"),
            scalar=Fraction(1, updaters),
        )
        try:
            for _ in range(200):
                summer.tick()
                sum_dict = asyncio.run(
                    ResilientClient(HttpClient(url, keep_alive=False)).get_sums()
                )
                if sum_dict:
                    break
                time.sleep(0.05)
            else:
                raise RuntimeError(f"round {completed + 1}: sum dictionary never appeared")

            async def flood_edges():
                clients = [ResilientClient(HttpClient(u)) for u in edge_urls]
                for c in clients:
                    # two-tier uploads carry the round trace id too: the
                    # edge adopts it, so edge + coordinator + soak stitch
                    c.set_round_trace(seed)
                rr = itertools.count()

                async def submit(blob: bytes) -> None:
                    await clients[next(rr) % len(clients)].send_message(blob)

                rng = np.random.default_rng(completed + 1)
                try:
                    return await flood(
                        submit,
                        params,
                        sum_dict,
                        updaters,
                        models=[
                            rng.uniform(-1, 1, model_len).astype(np.float32)
                            for _ in range(updaters)
                        ],
                        scalar=Fraction(1, updaters),
                        key_spacing=100_000,
                    )
                finally:
                    for c in clients:
                        c.close()

            stats = asyncio.run(flood_edges())
            accepted_total += stats.accepted
            for _ in range(600):
                summer.tick()
                if fetch_params().seed.as_bytes() != seed:
                    break
                time.sleep(0.05)
            else:
                raise RuntimeError(f"round {completed + 1} did not complete")
        finally:
            summer.close()
        completed += 1
    return {
        "rounds": completed,
        "wall_s": round(time.perf_counter() - t0, 2),
        "updates_accepted": accepted_total,
        "edges": len(edge_urls),
        "updaters_per_round": updaters,
    }


def run_soak_sync(port: int, rounds: int, model_len: int) -> dict:
    # synchronous driver: Participant.tick() owns its own event loop, so
    # the soak loop must NOT run inside asyncio itself
    from fractions import Fraction

    import numpy as np

    from xaynet_tpu.sdk.client import HttpClient
    from xaynet_tpu.sdk.participant import Participant
    from xaynet_tpu.sdk.simulation import keys_for_task

    url = f"http://127.0.0.1:{port}"

    def fetch_params():
        return asyncio.run(HttpClient(url, keep_alive=False).get_round_params())

    completed = 0
    last_seed = None
    t0 = time.perf_counter()
    while completed < rounds:
        params = fetch_params()
        if params.seed.as_bytes() == last_seed:
            time.sleep(0.01)
            continue
        last_seed = params.seed.as_bytes()
        seed = last_seed
        # churn: brand-new participants every round
        summer = keys_for_task(seed, params.sum, params.update, "sum")
        upd, start = [], 0
        while len(upd) < 3:
            k = keys_for_task(seed, params.sum, params.update, "update", start=start)
            start += 100000
            if all(k.public != u.public for u in upd) and k.public != summer.public:
                upd.append(k)

        parts = [Participant(url, keys=summer, scalar=Fraction(1, 3))]
        for i, k in enumerate(upd):
            p = Participant(url, keys=k, scalar=Fraction(1, 3))
            p.set_model(np.full(model_len, 0.25 * (i + 1), dtype=np.float32))
            parts.append(p)
        for _ in range(400):
            for p in parts:
                p.tick()
            if fetch_params().seed.as_bytes() != seed:
                break  # round completed, coordinator moved on
        else:
            raise RuntimeError(f"round {completed + 1} did not complete")
        completed += 1
    return {"rounds": completed, "wall_s": round(time.perf_counter() - t0, 2)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=200)
    ap.add_argument("--model-len", type=int, default=2000)
    ap.add_argument("--port", type=int, default=18439)
    ap.add_argument(
        "--device-kernel",
        default=None,
        # no bare "pallas": the soak pins the coordinator to the CPU backend,
        # where explicit Mosaic compilation cannot succeed (auto falls back)
        choices=["auto", "xla", "pallas-interpret"],
        help="run the coordinator with device aggregation on the virtual mesh using this fold kernel",
    )
    ap.add_argument(
        "--wire-ingest",
        action="store_true",
        help="with --device-kernel: lazy Update parse + device unpack/validity "
        "(aggregation.wire_ingest=true) — leak-checks the production "
        "device-ingest mode over many rounds",
    )
    ap.add_argument(
        "--dropout",
        type=float,
        default=None,
        metavar="RATE",
        help="churn soak: drive updates through flood() with this dropout "
        "fraction; the coordinator runs with a quorum'd update window and "
        "closes those rounds DEGRADED instead of timing out",
    )
    ap.add_argument(
        "--stragglers",
        type=int,
        default=None,
        metavar="N",
        help="churn soak: delay N of the surviving update uploads per round "
        "(they still land inside the stall grace window)",
    )
    ap.add_argument(
        "--edges",
        type=int,
        default=None,
        metavar="N",
        help="two-tier soak: spawn N edge aggregator processes; all update "
        "uploads go through the edges (round-robin) and reach the "
        "coordinator as partial-aggregate envelopes",
    )
    ap.add_argument(
        "--edge-updaters",
        type=int,
        default=10,
        metavar="M",
        help="with --edges: update participants PER EDGE per round "
        "(default 10; --edges 4 therefore drives 40 participants)",
    )
    ap.add_argument(
        "--tenants",
        type=int,
        default=None,
        metavar="N",
        help="multi-tenant soak: N tenants with distinct mask configs and "
        "model sizes in ONE coordinator process (device aggregation over "
        "the shared paged pool), each driven concurrently over "
        "/t/<tenant>/... and checked byte-identical to its single-tenant "
        "control run (docs/DESIGN.md §19)",
    )
    ap.add_argument(
        "--tenant-churn",
        action="store_true",
        help="elastic-lifecycle chaos soak: onboard/drain tenants mid-run "
        "over the authenticated /admin/tenants API while one tenant's "
        "storage is fault-injected into quarantine and back; surviving "
        "tenants stay byte-identical to their single-tenant controls and "
        "the drained tenant leaks zero pool pages (docs/DESIGN.md §23)",
    )
    ap.add_argument(
        "--kill-matrix",
        action="store_true",
        help="SIGKILL-matrix chaos soak: kill the coordinator at seeded "
        "(phase, message-index) coordinates, restart it on the same durable "
        "tree and drive the surviving participants to completion — the "
        "global model must be byte-identical to an unkilled control, the "
        "killed phase must RESUME from the round journal, and zero pool "
        "pages may leak (docs/DESIGN.md §9)",
    )
    ap.add_argument(
        "--kill-points",
        default=None,
        metavar="SITE:N,...",
        help="with --kill-matrix: comma-separated kill coordinates "
        "(default: the full matrix sum:1,update:2,sum2:1,unmask:publish:1); "
        "CI smoke runs a one-per-phase-family subset",
    )
    ap.add_argument(
        "--append-history",
        action="store_true",
        help="with --kill-matrix: append one 'restart recovery wall' record "
        "per kill coordinate to BENCH_HISTORY.jsonl (the lower-is-better "
        "bench-gate family)",
    )
    ap.add_argument(
        "--faults",
        type=int,
        default=None,
        metavar="SEED",
        help="chaos soak: replay a seeded FaultPlan against the live "
        "coordinator (transient storage errors + latency across all "
        "components); rounds must still complete because the resilience "
        "layer retries them in place",
    )
    ap.add_argument(
        "--fault-spec",
        default=None,
        help="override the generated plan ('seed=' is prepended from --faults); "
        "see xaynet_tpu.resilience.faults for the grammar",
    )
    args = ap.parse_args()
    if args.wire_ingest and not args.device_kernel:
        ap.error("--wire-ingest requires --device-kernel")
    if args.kill_matrix:
        if (
            args.tenants is not None
            or args.tenant_churn
            or args.edges is not None
            or args.dropout is not None
            or args.stragglers is not None
            or args.faults is not None
        ):
            ap.error("--kill-matrix is a separate soak (it owns its own "
                     "process lifecycle and durable tree)")
        run_kill_matrix_soak(args)
        return
    if args.kill_points or args.append_history:
        ap.error("--kill-points/--append-history require --kill-matrix")
    if args.tenant_churn:
        if (
            args.tenants is not None
            or args.edges is not None
            or args.dropout is not None
            or args.stragglers is not None
            or args.faults is not None
        ):
            ap.error("--tenant-churn is a separate soak (it owns its own "
                     "tenant set and fault plan)")
        run_tenant_churn_soak(args)
        return
    if args.tenants is not None:
        if args.tenants < 2:
            ap.error("--tenants must be >= 2 (one tenant is the ordinary soak)")
        if args.edges or args.dropout is not None or args.stragglers is not None:
            ap.error("--tenants is a separate soak from --edges/--dropout")
        run_multi_tenant_soak(args)
        return
    chaos = args.dropout is not None or args.stragglers is not None
    dropout = args.dropout or 0.0
    stragglers = args.stragglers or 0
    if args.edges is not None:
        if args.edges < 1:
            ap.error("--edges must be >= 1")
        if chaos:
            ap.error("--edges and --dropout/--stragglers are separate soaks")
        if args.edge_updaters < 1:
            ap.error("--edge-updaters must be >= 1")
    two_tier_updaters = (args.edges or 0) * args.edge_updaters
    if chaos:
        if not (0.0 <= dropout < 1.0):
            ap.error("--dropout must be in [0, 1)")
        survivors = N_CHAOS_UPDATERS - int(round(N_CHAOS_UPDATERS * dropout))
        if survivors < 3:  # UPDATE_COUNT_MIN: below this no quorum can help
            ap.error(
                f"--dropout {dropout} leaves {survivors} of {N_CHAOS_UPDATERS} "
                "updaters; the PET update floor is 3"
            )
        if stragglers < 0 or stragglers > survivors:
            ap.error("--stragglers must be in [0, survivors]")
    if args.fault_spec is not None and args.faults is None:
        ap.error("--fault-spec requires --faults")
    if args.fault_spec is not None and "seed=" in args.fault_spec:
        # FaultPlan.parse lets a later seed= clause win, which would
        # silently override --faults and defeat a seed sweep
        ap.error("--fault-spec must not contain 'seed=' (use --faults)")

    fault_plan = None
    if args.faults is not None:
        spec = args.fault_spec or (
            # steady trickle of transient faults + latency over every
            # storage component; bounded so the tail of the soak runs clean
            "storage.coordinator.*:error,rate=0.02,max=50;"
            "storage.models.*:error,rate=0.02,max=20;"
            "storage.*:latency,rate=0.02,delay=0.02,max=100"
        )
        fault_plan = f"seed={args.faults};{spec}"
        # fail fast on a bad spec before booting a coordinator around it
        from xaynet_tpu.resilience.faults import FaultPlan

        FaultPlan.parse(fault_plan)

    with tempfile.TemporaryDirectory() as tmp:
        cfg_path = os.path.join(tmp, "config.toml")
        with open(cfg_path, "w") as f:
            f.write(
                CONFIG.format(
                    port=args.port,
                    model_len=args.model_len,
                    model_dir=os.path.join(tmp, "models"),
                    agg_device="true" if args.device_kernel else "false",
                    agg_wire_ingest="true" if args.wire_ingest else "false",
                    # keep the host-path default (64) so plain-soak numbers
                    # stay comparable across rounds; small batches only for
                    # the device path so every round actually flushes
                    agg_batch=2 if args.device_kernel else 64,
                    agg_kernel=args.device_kernel or "auto",
                    # churn soak: full updater fan-in as the window, quorum
                    # at the floor so dropped-out rounds close degraded;
                    # two-tier soak: the window is the full edge fan-in
                    update_min=(
                        two_tier_updaters
                        if args.edges
                        else (N_CHAOS_UPDATERS if chaos else 3)
                    ),
                    update_max=(
                        two_tier_updaters
                        if args.edges
                        else (N_CHAOS_UPDATERS if chaos else 3)
                    ),
                    update_quorum_line="quorum = 3" if chaos else "",
                    # stragglers delay 0.3s: inside the grace, so they count
                    stall_grace=1.0,
                    edge_enabled_line="[edge]\nenabled = true" if args.edges else "",
                )
            )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # flight-recorder dumps must SURVIVE the soak's tempdir: a failed
        # chaos round's forensics are the whole point of keeping them
        # (mkdtemp outside `tmp`; the path is printed in the result JSON
        # and on any failure)
        flight_dir = tempfile.mkdtemp(prefix="xaynet-soak-flight-")
        env["XAYNET_FLIGHT_DIR"] = flight_dir
        os.environ["XAYNET_FLIGHT_DIR"] = flight_dir  # SDK-side triggers too
        if fault_plan is not None:
            env["XAYNET_FAULT_PLAN"] = fault_plan
        if args.device_kernel:
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
        coord_log_path = os.path.join(tmp, "coordinator.log")
        coord_log = open(coord_log_path, "w")
        edge_procs, edge_ports, edge_logs = [], [], []
        proc = subprocess.Popen(
            [sys.executable, "-m", "xaynet_tpu.server.runner", "-c", cfg_path],
            env=env,
            stdout=coord_log,
            stderr=subprocess.STDOUT,
        )
        try:
            # wait until the coordinator actually listens (loaded CI hosts
            # can take longer than any fixed sleep)
            import socket

            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    with socket.create_connection(("127.0.0.1", args.port), timeout=1):
                        break
                except OSError:
                    if proc.poll() is not None:
                        raise RuntimeError("coordinator exited during startup")
                    time.sleep(0.25)
            else:
                raise RuntimeError("coordinator did not start listening in 60s")
            if args.edges:
                for i in range(args.edges):
                    edge_port = args.port + 1 + i
                    edge_cfg = os.path.join(tmp, f"edge{i}.toml")
                    with open(edge_cfg, "w") as f:
                        f.write(
                            EDGE_CONFIG.format(
                                port=edge_port,
                                upstream_port=args.port,
                                edge_id=f"edge-{i}",
                                max_members=args.edge_updaters,
                            )
                        )
                    edge_log = open(os.path.join(tmp, f"edge{i}.log"), "w")
                    edge_logs.append(edge_log)
                    edge_procs.append(
                        subprocess.Popen(
                            [sys.executable, "-m", "xaynet_tpu.edge.runner",
                             "-c", edge_cfg],
                            env=env,
                            stdout=edge_log,
                            stderr=subprocess.STDOUT,
                        )
                    )
                    edge_ports.append(edge_port)
                deadline = time.time() + 60
                pending_ports = list(edge_ports)
                while pending_ports and time.time() < deadline:
                    try:
                        with socket.create_connection(
                            ("127.0.0.1", pending_ports[0]), timeout=1
                        ):
                            pending_ports.pop(0)
                    except OSError:
                        time.sleep(0.25)
                if pending_ports:
                    raise RuntimeError("edge processes did not start listening in 60s")
            rss_start = _rss_kb(proc.pid)
            # warmup block first: the first rounds pay one-time costs (JIT
            # compiles, XLA buffer pools, import side-effects) that are not
            # per-round growth; the steady-state rate is what a leak looks
            # like (same split the bench_round RSS gate uses)
            warmup_rounds = min(20, max(1, args.rounds // 10))

            def run_block(n_rounds: int) -> dict:
                if args.edges:
                    return run_two_tier_soak_sync(
                        args.port, edge_ports, n_rounds, args.model_len,
                        two_tier_updaters,
                    )
                if chaos:
                    return run_chaos_soak_sync(
                        args.port, n_rounds, args.model_len, dropout, stragglers
                    )
                return run_soak_sync(args.port, n_rounds, args.model_len)

            def _flight_dumps() -> list:
                try:
                    return sorted(
                        os.path.join(flight_dir, f)
                        for f in os.listdir(flight_dir)
                        if f.startswith("flight_")
                    )
                except OSError:
                    return []

            try:
                run_block(warmup_rounds)
                rss_warm = _rss_kb(proc.pid)
                result = run_block(args.rounds)
            except Exception as err:
                # a failed/non-identical round stops being
                # reproduce-from-scratch: name the forensic bundles the
                # coordinator/edges dumped on the way down
                dumps = _flight_dumps()
                print(
                    json.dumps(
                        {
                            "soak_failed": str(err),
                            "flight_dir": flight_dir,
                            "flight_dumps": dumps,
                        }
                    ),
                    file=sys.stderr,
                )
                raise
            rss_end = _rss_kb(proc.pid)
            resolved = None
            if args.device_kernel:
                # the aggregator logs its per-round kernel resolution; the
                # LAST line is the steady-state answer (VERDICT r05 item 7:
                # the soak artifact must name the resolved kernel)
                coord_log.flush()
                with open(coord_log_path) as lf:
                    for line in lf:
                        if "aggregation kernel resolved:" in line:
                            resolved = line.rsplit("resolved:", 1)[1].strip()
            result.update(
                {
                    "rounds_per_s": round(result["rounds"] / result["wall_s"], 2),
                    "warmup_rounds": warmup_rounds,
                    "rss_start_kb": rss_start,
                    "rss_warm_kb": rss_warm,
                    "rss_end_kb": rss_end,
                    "rss_steady_kb_per_round": round(
                        (rss_end - rss_warm) / max(result["rounds"], 1), 1
                    ),
                    "kernel_requested": args.device_kernel,
                    "kernel_resolved": resolved,
                    "edges": args.edges,
                    "fault_plan": fault_plan,
                    "dropout": dropout if chaos else None,
                    "stragglers": stragglers if chaos else None,
                    "flight_dir": flight_dir,
                    "flight_dumps": _flight_dumps(),
                    "console": _scrape_console(args.port),
                }
            )
            print(json.dumps(result))
        finally:
            for ep in edge_procs:
                ep.terminate()
            proc.terminate()
            for ep in edge_procs:
                try:
                    ep.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    ep.kill()
                    ep.wait(timeout=5)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
            coord_log.close()
            for el in edge_logs:
                el.close()


if __name__ == "__main__":
    main()
