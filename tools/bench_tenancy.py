"""Tenant lifecycle bench: onboard-to-first-round latency, cold vs warm.

The elastic-lifecycle headline (docs/DESIGN.md §23): how long from the
authenticated ``POST /admin/tenants`` until the new tenant's FIRST round
completes. Two legs against real coordinator processes:

- **cold vs warm** — two successive processes share one
  ``XAYNET_CALIB_CACHE`` file. The first onboard races the fold-kernel
  calibration inside its first round and persists the verdict; the second
  process loads it during the onboard warm step, so its first round
  resolves the kernel from the cache instead of probing. The warm latency
  must come in measurably below cold — that delta IS the PR-18 cache
  earning its keep on the onboarding path.
- **density** — inside the warm process, additional tenants are onboarded
  while the earlier ones keep serving; the LAST onboard's latency is the
  headline at density N. This is the number an operator actually waits on
  when adding a tenant to a busy pool.

``--append-history`` appends one record per leg to BENCH_HISTORY.jsonl;
``tools/bench_gate.py`` gates the family LOWER-IS-BETTER (unit
``s/onboard``).

Usage:
  JAX_PLATFORMS=cpu python tools/bench_tenancy.py [--density 3]
      [--port 18457] [--append-history]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from soak import (  # noqa: E402
    TENANT_GROUPS,
    TENANT_MODEL_LENS,
    _drive_tenant_rounds,
    _http_status,
    _tenant_config,
)

HISTORY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BENCH_HISTORY.jsonl"
)
ADMIN_TOKEN = "bench-tenancy-admin-token"


def _wait_listening(port: int, proc) -> None:
    deadline = time.time() + 90
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError("coordinator exited during startup")
            time.sleep(0.25)
    raise RuntimeError("coordinator did not start listening in 90s")


def _stop(proc) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=5)


def _onboard_to_first_round(port: int, tenant: str, model_len: int) -> dict:
    """POST the onboard, then drive the tenant's first round; the headline
    latency is admin-POST-to-round-close, the number the operator waits on."""
    t0 = time.perf_counter()
    status, body = _http_status(
        f"http://127.0.0.1:{port}/admin/tenants",
        method="POST",
        body=json.dumps({"tenant": tenant}).encode(),
        headers={"x-admin-token": ADMIN_TOKEN, "content-type": "application/json"},
        timeout=300,
    )
    if status != 200:
        raise RuntimeError(f"onboard {tenant} failed: {status} {body[:200]!r}")
    _drive_tenant_rounds(
        f"http://127.0.0.1:{port}/t/{tenant}", 1, model_len, None, f"bench {tenant}"
    )
    total_s = time.perf_counter() - t0
    return {"total_s": total_s, "onboard_s": float(json.loads(body)["onboard_s"])}


def run(args) -> list[dict]:
    # t2+ deliberately reuse the integer group: the bench measures the
    # LIFECYCLE path (build + calib warm + admit + first round), and the
    # power2 group's slow big-int unmask would drown that signal
    spec = {"t0": (TENANT_MODEL_LENS[0], TENANT_GROUPS[0])}
    for i in range(1, args.density + 1):
        spec[f"t{i}"] = (600 + 120 * i, "integer")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

    results: list[dict] = []
    with tempfile.TemporaryDirectory() as tmp:
        env["XAYNET_CALIB_CACHE"] = os.path.join(tmp, "calib.json")
        cfg_dir = os.path.join(tmp, "tenants")
        os.makedirs(cfg_dir)
        for tid, (mlen, group) in spec.items():
            with open(os.path.join(cfg_dir, f"{tid}.toml"), "w") as f:
                f.write(
                    _tenant_config(
                        args.port, mlen, group, os.path.join(tmp, f"models-{tid}")
                    )
                )
        base_cfg = os.path.join(tmp, "multi.toml")
        with open(base_cfg, "w") as f:
            f.write(
                _tenant_config(
                    args.port, spec["t0"][0], spec["t0"][1],
                    os.path.join(tmp, "models-multi"),
                )
                + "\n[tenancy]\nenabled = true\n"
                + 'tenants = "t0"\n'
                + f'config_dir = "{cfg_dir}"\n'
                + f'admin_token = "{ADMIN_TOKEN}"\n'
            )

        def boot(log_name: str):
            log = open(os.path.join(tmp, log_name), "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "xaynet_tpu.server.runner", "-c", base_cfg],
                env=env, stdout=log, stderr=subprocess.STDOUT,
            )
            _wait_listening(args.port, proc)
            return proc, log

        # --- leg 1: cold onboard (no calibration cache on disk yet) --------
        proc, log = boot("cold.log")
        try:
            cold = _onboard_to_first_round(args.port, "t1", spec["t1"][0])
        finally:
            _stop(proc)
            log.close()
        results.append(
            {
                "metric": "tenant onboard-to-first-round latency (cold)",
                "value": round(cold["total_s"], 4),
                "unit": "s/onboard",
                "onboard_s": round(cold["onboard_s"], 4),
                "tenants": 1,
            }
        )
        # --- leg 2: warm onboard (fresh process, persisted verdicts) -------
        if not os.path.exists(env["XAYNET_CALIB_CACHE"]):
            raise RuntimeError(
                "cold run persisted no calibration verdicts; the warm leg "
                "would silently re-measure cold"
            )
        proc, log = boot("warm.log")
        try:
            warm = _onboard_to_first_round(args.port, "t1", spec["t1"][0])
            results.append(
                {
                    "metric": "tenant onboard-to-first-round latency (warm)",
                    "value": round(warm["total_s"], 4),
                    "unit": "s/onboard",
                    "onboard_s": round(warm["onboard_s"], 4),
                    "tenants": 1,
                }
            )
            # --- leg 3: density — the Nth onboard joins a busy pool --------
            last = None
            for i in range(2, args.density + 1):
                last = _onboard_to_first_round(args.port, f"t{i}", spec[f"t{i}"][0])
            if last is not None:
                results.append(
                    {
                        "metric": (
                            "tenant onboard-to-first-round latency "
                            f"(warm @density {args.density})"
                        ),
                        "value": round(last["total_s"], 4),
                        "unit": "s/onboard",
                        "onboard_s": round(last["onboard_s"], 4),
                        "tenants": args.density,
                    }
                )
        finally:
            _stop(proc)
            log.close()
    return results


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--port", type=int, default=18457)
    ap.add_argument(
        "--density",
        type=int,
        default=3,
        help="tenants serving when the last onboard is measured (default 3)",
    )
    ap.add_argument(
        "--append-history",
        action="store_true",
        help=f"append one record per leg to {os.path.basename(HISTORY)}",
    )
    args = ap.parse_args()
    if args.density < 1:
        ap.error("--density must be >= 1")
    results = run(args)
    cold = results[0]["value"]
    warm = results[1]["value"]
    print(
        json.dumps(
            {
                "legs": results,
                "warm_speedup": round(cold / warm, 3) if warm else None,
                "cpus": os.cpu_count(),
            }
        )
    )
    if args.append_history:
        ts = time.time()
        with open(HISTORY, "a") as f:
            for rec in results:
                f.write(json.dumps({"ts": ts, "cpus": os.cpu_count(), **rec}) + "\n")


if __name__ == "__main__":
    main()
