"""Accounting-invariant pass: sanctioned mutators of ``nb_models`` and the
per-edge seed watermark.

The unmask linchpin (docs/DESIGN.md §9–§11) is ``nb_models ==
seed-watermark``: the update count credited into the aggregate must equal
the seed-dictionary watermark the Sum2/unmask legs reconstruct against.
Every code path that mutates either side is therefore load-bearing — a
new ``agg.nb_models += k`` dropped into a convenient spot is how the
invariant silently drifts (double credit near the cap, undercount after a
degraded retry, replayed edge envelopes counted twice).

This pass whitelists the *sanctioned mutation sites* by (file, function
qualname) with a recorded rationale, and flags every other attribute
store/aug-store of ``nb_models`` and every mutation of the per-edge
watermark map (``edge_watermarks``) under ``xaynet_tpu/``. Adding a
legitimate site means extending the whitelist here — with a rationale —
in the same diff, which is exactly the review nudge the invariant needs;
a one-off experiment can carry ``# lint: invariant-ok: <why>`` instead.
"""

from __future__ import annotations

import ast

from .callgraph import CallGraph, iter_owned_nodes
from .core import Finding, suppressed, suppression_pending_rationale

# (file, function qualname) -> rationale. Qualnames are exact; a rename or
# move is a (deliberate) finding until the whitelist follows it.
NB_MODELS_SITES: dict[tuple[str, str], str] = {
    # the protocol-level aggregator: the reference implementation's own
    # accounting (one credit per aggregate()d mask object / batch member)
    ("xaynet_tpu/core/mask/masking.py", "Aggregation.__init__"): "fresh aggregation starts at zero",
    ("xaynet_tpu/core/mask/masking.py", "Aggregation.aggregate"): "per-object credit",
    ("xaynet_tpu/core/mask/masking.py", "Aggregation.aggregate_batch"): "per-batch credit",
    ("xaynet_tpu/core/mask/masking.py", "Aggregation.aggregate_partial"):
        "edge partial-aggregate credit (members - 1 on top of the object credit)",
    # the device aggregator: same contract, device accumulator
    ("xaynet_tpu/parallel/aggregator.py", "ShardedAggregator.__init__"): "fresh accumulator",
    ("xaynet_tpu/parallel/aggregator.py", "ShardedAggregator.add_batch"): "pre-validated batch credit",
    ("xaynet_tpu/parallel/aggregator.py", "ShardedAggregator.add_planar_batch"):
        "pre-validated planar batch credit",
    ("xaynet_tpu/parallel/aggregator.py", "ShardedAggregator._ingest_staged_bytes"):
        "wire batch credit from the synced acceptance vector",
    ("xaynet_tpu/parallel/aggregator.py", "ShardedAggregator.restore"):
        "checkpoint resume restores the persisted count",
    ("xaynet_tpu/parallel/aggregator.py", "ShardedAggregator.restore_shards"):
        "journal resume restores the persisted count (per-shard planes path)",
    ("xaynet_tpu/parallel/aggregator.py", "ShardedAggregator.reset"): "round reset",
    # the streaming pipeline: every credit sits under the pipeline lock,
    # paired with the in-flight decrement (counted_models() atomicity)
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator.fold_planar_rows_now"):
        "caller-thread fold credit",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator.fold_packed_rows_now"):
        "caller-thread fold credit (pre-packed byte-planar rows, §21 wire ingest)",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator.fold_planar_stack_now"):
        "caller-thread fold credit (stacked device batch, fused mask pipeline)",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._fold_pinned_stack"):
        "the ONE shared caller-thread shard fan-out credit (stacked + row-chunked paths)",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._credit"):
        "worker fold credit + in-flight handoff under one lock",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._fold_payload"):
        "degraded-path wire credit from the synced acceptance vector",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._drain_inner"):
        "the ONE deferred wire credit at the drain barrier (drain()'s body; "
        "the public method only wraps it in the stream.drain trace span)",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._dispatch_sharded"):
        "degraded shard-parallel batch credit",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._dispatch_sharded_wire"):
        "degraded shard-parallel wire credit",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._shard_job_done"):
        "cross-shard commit barrier: last shard credits the batch",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._fold_planar_rows_now_sharded"):
        "caller-thread shard-parallel fold credit",
    ("xaynet_tpu/parallel/streaming.py", "StreamingAggregator._drain_sharded"):
        "deferred wire credit at the cross-shard barrier",
    # the server-side aggregation facade
    ("xaynet_tpu/server/aggregation.py", "StagedAggregator.fold_partial"):
        "edge envelope: members - 1 on top of the per-object device credit",
    ("xaynet_tpu/server/aggregation.py", "StagedAggregator.restore_state"):
        "checkpoint resume restores the persisted count",
    ("xaynet_tpu/server/aggregation.py", "StagedAggregator.finalize"):
        "host handoff copies the device count verbatim",
    ("xaynet_tpu/server/aggregation.py", "DeviceAggregation.__init__"):
        "in-place unmask view copies the device count verbatim",
    # participant-side local mask aggregation (SDK): not the coordinator
    # invariant, but the same field name on the shared Aggregation type
    ("xaynet_tpu/sdk/state_machine.py", "StateMachine._aggregate_masks"):
        "participant-local sum-mask reconstruction bookkeeping",
}

WATERMARK_SITES: dict[tuple[str, str], str] = {
    ("xaynet_tpu/server/phases/update.py", "UpdatePhase.handle_partial"):
        "the one commit site: watermark advances with the folded envelope",
    ("xaynet_tpu/server/phases/idle.py", "Idle.process"):
        "round-scoped reset (window sequences restart per round)",
}

_WATERMARK_ATTR = "edge_watermarks"
_MUTATING_MAP_METHODS = frozenset({"clear", "pop", "popitem", "update", "setdefault"})


def _qualname_chain(qualname: str) -> list[str]:
    """Every enclosing qualname ("A.b.c" -> ["A.b.c", "A.b", "A"]) — a
    whitelisted function covers its nested helpers/lambdas."""
    parts = qualname.split(".")
    return [".".join(parts[:i]) for i in range(len(parts), 0, -1)]


def run(graph: CallGraph) -> list[Finding]:
    findings: list[Finding] = []
    for fi in graph.symbols.functions:
        rel = fi.file.rel
        if not rel.startswith("xaynet_tpu/"):
            continue
        allowed_nb = any(
            (rel, q) in NB_MODELS_SITES for q in _qualname_chain(fi.qualname)
        )
        allowed_wm = any(
            (rel, q) in WATERMARK_SITES for q in _qualname_chain(fi.qualname)
        )
        for node in iter_owned_nodes(fi.node):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "nb_models" and not allowed_nb:
                    line = fi.file.line(t.lineno)
                    if suppressed("invariant", line):
                        continue
                    msg = (
                        f"mutation of nb_models outside the sanctioned "
                        f"accounting sites (in '{fi.qualname}') — nb_models "
                        "must stay equal to the seed watermark at unmask "
                        "(DESIGN §9–§11); add the site to "
                        "tools/analysis/invariants.py with a rationale, or "
                        "annotate '# lint: invariant-ok: <rationale>'"
                    )
                    if suppression_pending_rationale("invariant", line):
                        msg += " [suppression present but missing its rationale]"
                    findings.append(Finding("invariant", rel, t.lineno, msg))
                # shared.edge_watermarks[edge] = seq  (subscript store)
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Attribute)
                    and t.value.attr == _WATERMARK_ATTR
                    and not allowed_wm
                ):
                    line = fi.file.line(t.lineno)
                    if suppressed("invariant", line):
                        continue
                    findings.append(
                        Finding(
                            "invariant",
                            rel,
                            t.lineno,
                            f"mutation of the per-edge seed watermark outside "
                            f"its sanctioned sites (in '{fi.qualname}') — the "
                            "watermark is the replay fence for the nb_models "
                            "invariant; whitelist the site in "
                            "tools/analysis/invariants.py or annotate "
                            "'# lint: invariant-ok: <rationale>'",
                        )
                    )
            # shared.edge_watermarks.clear() / .pop(...) / .update(...)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_MAP_METHODS
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == _WATERMARK_ATTR
                and not allowed_wm
            ):
                line = fi.file.line(node.lineno)
                if suppressed("invariant", line):
                    continue
                findings.append(
                    Finding(
                        "invariant",
                        rel,
                        node.lineno,
                        f"mutation of the per-edge seed watermark outside its "
                        f"sanctioned sites (in '{fi.qualname}', "
                        f".{node.func.attr}()) — whitelist the site in "
                        "tools/analysis/invariants.py or annotate "
                        "'# lint: invariant-ok: <rationale>'",
                    )
                )
    return findings
