"""Pass-based, cross-file static analysis for the xaynet-tpu tree.

Replaces the flat rule list that used to live in ``tools/lint.py``
(ISSUE 9): a shared per-file AST/symbol-table cache (:mod:`cache`), a
project-wide call-graph builder (:mod:`callgraph`), a rule registry with
per-rule suppression and a checked-in baseline (:mod:`core`), the ported
per-file rules (:mod:`filerules`) and four deep passes:

- :mod:`locks` — `# guarded-by:` lock-discipline race lint;
- :mod:`purity` — call-graph host-sync/purity (sim programs and fold
  workers), closing the name-prefix heuristics' false negatives;
- :mod:`invariants` — sanctioned mutation sites of ``nb_models`` and the
  per-edge seed watermark;
- :mod:`metricscheck` — code <-> docs/DESIGN.md metric-table parity;
- :mod:`spans` — span discipline + docs/DESIGN.md §16 span-table parity;
- :mod:`taint` — interprocedural secret-flow analysis: key material never
  reaches logs, span attrs, metric labels, JSON dumps, flight-recorder
  payloads or raised exception messages (docs/DESIGN.md §18).

``tools/lint.py`` remains the CLI (tier-1/CI invocation unchanged);
docs/DESIGN.md §14 documents conventions and how to add a rule.
"""

from .cache import FileInfo, ResultCache, SourceCache
from .callgraph import CallGraph, SymbolTable, thread_entry_points
from .core import RULES, Baseline, Finding, Rule, suppressed
from .driver import DEFAULT_TARGETS, Analyzer, main, run
from .filerules import check_file_info

__all__ = [
    "Analyzer",
    "Baseline",
    "CallGraph",
    "DEFAULT_TARGETS",
    "FileInfo",
    "Finding",
    "ResultCache",
    "RULES",
    "Rule",
    "SourceCache",
    "SymbolTable",
    "check_file_info",
    "main",
    "run",
    "suppressed",
    "thread_entry_points",
]
