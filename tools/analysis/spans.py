"""Span-discipline pass: tracing spans <-> docs/DESIGN.md §16 parity.

The tracing layer's correctness contract (docs/DESIGN.md §16) has three
machine-checkable legs, mirrored here as rule ``span``:

1. **context-manager enforcement** — every ``<tracer>.span(...)`` call
   must be a ``with``-item: the context manager is the ONLY construct that
   guarantees a span exit on every exception path. A bare call leaks an
   unfinished span (and, worse, never resets the ambient context).
   ``record_span`` (retroactive spans) is exempt by design — it records a
   finished span atomically.
2. **declare-once** — every span name is registered via
   ``declare_span("literal")`` exactly once across the tree (the runtime
   registry enforces this per process; the pass makes it a compile-time
   finding), and declarations must be string LITERALS so the table check
   below can see them.
3. **DESIGN-table parity** — the declared name set matches the §16 span
   table between ``<!-- span-table:begin -->`` / ``<!-- span-table:end -->``
   markers, both directions (the metrics-table cross-check idiom).

The pass is lexical + single-module-resolution only: a span-name argument
may be a literal (checked against the declared set) or a reference to a
module-level ``declare_span`` binding / table (trusted — the runtime check
in ``Tracer.span`` hard-fails an undeclared name either way).
"""

from __future__ import annotations

import ast
import re

from .cache import FileInfo
from .core import Finding, suppressed

_BEGIN = "<!-- span-table:begin -->"
_END = "<!-- span-table:end -->"
_TOKEN_RE = re.compile(r"`([a-z0-9_.{},]+)`")


def _expand(token: str) -> list[str]:
    """``phase.{sum,update}`` -> concrete names (metricscheck's shorthand)."""
    m = re.search(r"\{([^{}]*)\}", token)
    if m is None:
        return [token]
    before, group, after = token[: m.start()], m.group(1), token[m.end():]
    return [name for part in group.split(",") for name in _expand(before + part + after)]


def documented(design_text: str) -> dict[str, int]:
    """span name -> first documenting line, from marked table rows."""
    out: dict[str, int] = {}
    active = False
    for i, line in enumerate(design_text.splitlines(), 1):
        if _BEGIN in line:
            active = True
            continue
        if _END in line:
            active = False
            continue
        if not active or not line.lstrip().startswith("|"):
            continue
        for token in _TOKEN_RE.findall(line):
            for name in _expand(token):
                if "." in name or name == "round":  # span names, not prose
                    out.setdefault(name, i)
    return out


def _is_declare_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "declare_span"
    return isinstance(func, ast.Attribute) and func.attr == "declare_span"


def _is_get_tracer(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "get_tracer"
    return isinstance(func, ast.Attribute) and func.attr == "get_tracer"


class _ModuleScan(ast.NodeVisitor):
    """One module's declare sites, tracer span calls, and with-items."""

    def __init__(self):
        self.declares: list[tuple[str | None, int]] = []  # (literal name | None, line)
        self.span_calls: list[ast.Call] = []
        self.with_items: set[int] = set()  # id() of context expressions
        self._tracer_names: set[str] = set()

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_get_tracer(node.value):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self._tracer_names.add(target.id)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            self.with_items.add(id(item.context_expr))
        self.generic_visit(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        for item in node.items:
            self.with_items.add(id(item.context_expr))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _is_declare_call(node):
            name = None
            if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
                node.args[0].value, str
            ):
                name = node.args[0].value
            self.declares.append((name, node.lineno))
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "span":
            value = func.value
            if _is_get_tracer(value) or (
                isinstance(value, ast.Name) and value.id in self._tracer_names
            ):
                self.span_calls.append(node)
        self.generic_visit(node)


def run(files: list[FileInfo], design_path) -> list[Finding]:
    findings: list[Finding] = []
    declares: dict[str, list[tuple[str, int]]] = {}  # name -> [(rel, line)]
    scans: list[tuple[FileInfo, _ModuleScan]] = []
    for info in files:
        if info.tree is None or not info.rel.startswith("xaynet_tpu/"):
            continue
        scan = _ModuleScan()
        scan.visit(info.tree)
        scans.append((info, scan))
        for name, line in scan.declares:
            if name is None:
                if not suppressed("span", info.line(line)):
                    findings.append(
                        Finding(
                            "span",
                            info.rel,
                            line,
                            "declare_span argument must be a string literal "
                            "(the DESIGN §16 table check reads it statically)",
                        )
                    )
                continue
            declares.setdefault(name, []).append((info.rel, line))

    for name, sites in sorted(declares.items()):
        for rel, line in sites[1:]:
            findings.append(
                Finding(
                    "span",
                    rel,
                    line,
                    f"span name '{name}' is declared more than once (first in "
                    f"{sites[0][0]}) — one module owns a span name; import "
                    "its constant instead",
                )
            )

    for info, scan in scans:
        for call in scan.span_calls:
            if id(call) not in scan.with_items:
                if suppressed("span", info.line(call.lineno)):
                    continue
                findings.append(
                    Finding(
                        "span",
                        info.rel,
                        call.lineno,
                        "tracer span() must be used as a `with` item — the "
                        "context manager is what guarantees the exit on "
                        "every exception path (DESIGN §16)",
                    )
                )
                continue
            if call.args and isinstance(call.args[0], ast.Constant):
                name = call.args[0].value
                if isinstance(name, str) and name not in declares:
                    if not suppressed("span", info.line(call.lineno)):
                        findings.append(
                            Finding(
                                "span",
                                info.rel,
                                call.lineno,
                                f"span name '{name}' is used but never "
                                "declared via declare_span",
                            )
                        )

    try:
        design_text = design_path.read_text()
    except OSError:
        findings.append(Finding("span", "docs/DESIGN.md", 1, "docs/DESIGN.md is unreadable"))
        return findings
    docs = documented(design_text)
    if not docs:
        findings.append(
            Finding(
                "span",
                "docs/DESIGN.md",
                1,
                "no marked span table found (expected "
                f"'{_BEGIN}' ... '{_END}' around the §16 span table)",
            )
        )
        return findings
    for name, sites in sorted(declares.items()):
        if name not in docs:
            rel, line = sites[0]
            findings.append(
                Finding(
                    "span",
                    rel,
                    line,
                    f"span '{name}' is not in the DESIGN.md §16 span table "
                    "(add a row inside the span-table markers)",
                )
            )
    for name, line in sorted(docs.items()):
        if name not in declares:
            findings.append(
                Finding(
                    "span",
                    "docs/DESIGN.md",
                    line,
                    f"documented span '{name}' is not declared anywhere "
                    "under xaynet_tpu/ (stale table row?)",
                )
            )
    return findings
