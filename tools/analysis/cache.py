"""Shared per-file AST/source cache and the persistent result cache.

:class:`FileInfo` is the one parse of a file every pass shares: source
text, split lines, the AST, the module's dotted name and its import
table. :class:`SourceCache` memoizes them per run so the per-file rules,
the call-graph builder and the deep passes never re-parse.

:class:`ResultCache` persists *findings* between runs, keyed by content
hash and invalidated by a digest of the analyzer's own sources — so the
full-tree gate after a no-op edit costs one stat+hash sweep, not a
re-analysis (ISSUE 9's "full-tree gate stays under a few seconds").
The cache file lives at ``<repo>/.lint-cache.json`` and is gitignored.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path

from .core import Finding


def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


class FileInfo:
    """One parsed file: source, lines, AST, module identity, imports.

    Reading + hashing is eager (the result cache keys on it); decoding,
    parsing and the import table are lazy, so a cache hit never pays for
    ``ast.parse``.
    """

    def __init__(self, repo: Path, path: Path):
        self.repo = Path(repo)
        self.path = Path(path)
        self.rel = self.path.relative_to(self.repo).as_posix()
        self._raw = self.path.read_bytes()
        self.content_key = _sha1(self._raw)
        self._loaded = False
        self._problems: list[Finding] = []  # load/parse failures
        self._text: str | None = None
        self._lines: list[str] = []
        self._tree: ast.Module | None = None
        self._imports: dict[str, str] | None = None
        # dotted module name ("xaynet_tpu.parallel.streaming"); packages
        # drop the trailing __init__
        parts = list(Path(self.rel).with_suffix("").parts)
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        self.module = ".".join(parts)

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        try:
            self._text = self._raw.decode("utf-8")
        except UnicodeDecodeError as e:
            self._problems.append(
                Finding("encoding", self.rel, 1, f"not valid UTF-8: {e}")
            )
            return
        self._lines = self._text.splitlines()
        try:
            self._tree = ast.parse(self._text, filename=self.rel)
        except SyntaxError as e:
            self._problems.append(
                Finding("syntax", self.rel, e.lineno or 1, f"syntax error: {e.msg}")
            )

    @property
    def problems(self) -> list[Finding]:
        self._load()
        return self._problems

    @property
    def text(self) -> str | None:
        self._load()
        return self._text

    @property
    def lines(self) -> list[str]:
        self._load()
        return self._lines

    @property
    def tree(self) -> ast.Module | None:
        self._load()
        return self._tree

    @property
    def imports(self) -> dict[str, str]:
        if self._imports is None:
            self._imports = self._import_table()
        return self._imports

    def line(self, lineno: int) -> str:
        self._load()
        return self._lines[lineno - 1] if 0 < lineno <= len(self._lines) else ""

    def _import_table(self) -> dict[str, str]:
        """local name -> dotted target ("np" -> "numpy", "limbs_jax" ->
        "xaynet_tpu.ops.limbs_jax", "mod_add" -> "x.ops.limbs_jax.mod_add").
        Relative imports resolve against this file's package."""
        table: dict[str, str] = {}
        if self.tree is None:
            return table
        pkg_parts = self.module.split(".") if self.module else []
        if not self.rel.endswith("__init__.py"):
            pkg_parts = pkg_parts[:-1]
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    table[local] = alias.name if alias.asname else local
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    prefix = ".".join(base + ([node.module] if node.module else []))
                else:
                    prefix = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    table[local] = f"{prefix}.{alias.name}" if prefix else alias.name
        return table


class SourceCache:
    """Per-run FileInfo memo (the shared AST/symbol-table cache)."""

    def __init__(self, repo: Path):
        self.repo = Path(repo)
        self._files: dict[str, FileInfo] = {}

    def get(self, path: Path) -> FileInfo:
        key = str(path)
        info = self._files.get(key)
        if info is None:
            info = self._files[key] = FileInfo(self.repo, path)
        return info


def tool_digest() -> str:
    """Digest of the analyzer's own sources — any change to a rule or a
    pass invalidates every cached result."""
    here = Path(__file__).resolve().parent
    h = hashlib.sha1()
    for p in sorted(here.glob("*.py")) + [here.parent / "lint.py"]:
        if p.exists():
            h.update(p.name.encode())
            h.update(p.read_bytes())
    return h.hexdigest()


class ResultCache:
    """mtime/hash-keyed persistent findings cache.

    ``files``: rel -> {"key": content sha1, "findings": [...]} for the
    per-file rules. ``project``: one entry keyed by the digest of every
    analyzed file (plus docs/DESIGN.md) for the cross-file passes.
    """

    VERSION = 1

    def __init__(self, path: Path, enabled: bool = True):
        self.path = Path(path)
        self.enabled = enabled
        self.digest = tool_digest()
        self._dirty = False
        self._data = {"version": self.VERSION, "tool": self.digest, "files": {}, "project": {}}
        if enabled and self.path.exists():
            try:
                data = json.loads(self.path.read_text())
            except (OSError, json.JSONDecodeError):
                data = None
            if (
                isinstance(data, dict)
                and data.get("version") == self.VERSION
                and data.get("tool") == self.digest
            ):
                self._data = data

    # -- per-file findings -------------------------------------------------

    def get_file(self, rel: str, content_key: str) -> list[Finding] | None:
        if not self.enabled:
            return None
        entry = self._data["files"].get(rel)
        if not entry or entry.get("key") != content_key:
            return None
        return [Finding.from_json(obj) for obj in entry["findings"]]

    def put_file(self, rel: str, content_key: str, findings: list[Finding]) -> None:
        if not self.enabled:
            return
        self._data["files"][rel] = {
            "key": content_key,
            "findings": [f.to_json() for f in findings],
        }
        self._dirty = True

    # -- whole-tree pass results -------------------------------------------

    def get_project(self, tree_key: str) -> list[Finding] | None:
        if not self.enabled:
            return None
        entry = self._data["project"]
        if entry.get("key") != tree_key:
            return None
        return [Finding.from_json(obj) for obj in entry["findings"]]

    def put_project(self, tree_key: str, findings: list[Finding]) -> None:
        if not self.enabled:
            return
        self._data["project"] = {
            "key": tree_key,
            "findings": [f.to_json() for f in findings],
        }
        self._dirty = True

    def save(self) -> None:
        if not (self.enabled and self._dirty):
            return
        try:
            self.path.write_text(json.dumps(self._data))
        except OSError:
            pass  # a read-only checkout just loses the speedup
