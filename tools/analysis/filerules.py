"""The per-file rules — the pre-framework ``tools/lint.py`` checks, ported.

Each rule keeps its original message text (CI logs, the older tests and
muscle memory all grep for it) and its original suppression annotation;
the framework only adds the shared parse (:class:`cache.FileInfo`), rule
names for the baseline, and JSON output.

The two name-prefix host-sync heuristics (``_WORKER_SYNC_PREFIXES`` under
``xaynet_tpu/parallel`` and ``_prog*`` under ``xaynet_tpu/sim``) stay here
as fast lexical checks; their known false negative — helpers defined
*outside* the prefixed function but called from it — is closed by the
call-graph pass in :mod:`purity`, which shares the ``sync`` rule and the
``# lint: sync-ok`` annotation.
"""

from __future__ import annotations

import ast

from .cache import FileInfo
from .core import Finding, suppressed

MAX_LINE = 120


class _ImportVisitor(ast.NodeVisitor):
    """Collects module-scope imports and every name used anywhere."""

    def __init__(self):
        self.imports: dict[str, tuple[int, str]] = {}  # local name -> (line, display)
        self.used: set[str] = set()
        self.star_imports: list[int] = []

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            if alias.asname == alias.name:
                continue  # `import x as x` is an explicit re-export
            self.imports[local] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for alias in node.names:
            if alias.name == "*":
                self.star_imports.append(node.lineno)
                continue
            if alias.asname == alias.name:
                continue  # explicit re-export idiom
            local = alias.asname or alias.name
            self.imports[local] = (node.lineno, alias.name)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # record the root name of attribute chains (module.attr)
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)


def _used_in_annotations(tree: ast.AST) -> set[str]:
    """Names referenced inside *string* type annotations (``x: "Foo"``).

    Only annotation positions count — a module name mentioned in a docstring
    or assert message must NOT exempt a dead import.
    """
    out: set[str] = set()

    def collect(ann) -> None:
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                expr = ast.parse(ann.value, mode="eval")
            except SyntaxError:
                return
            for n in ast.walk(expr):
                if isinstance(n, ast.Name):
                    out.add(n.id)

    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            collect(node.annotation)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            collect(node.returns)
            for arg in (
                node.args.args + node.args.posonlyargs + node.args.kwonlyargs
                + ([node.args.vararg] if node.args.vararg else [])
                + ([node.args.kwarg] if node.args.kwarg else [])
            ):
                collect(arg.annotation)
    return out


def _is_unbounded_queue(node: ast.Call) -> bool:
    """True for ``asyncio.Queue()`` / ``Queue()`` constructed without a size,
    or with a literal non-positive one (asyncio treats ``maxsize <= 0`` as
    unbounded). Non-constant sizes are trusted — the rule is syntactic."""
    func = node.func
    if isinstance(func, ast.Attribute):
        is_queue = func.attr == "Queue" and (
            isinstance(func.value, ast.Name) and func.value.id == "asyncio"
        )
    elif isinstance(func, ast.Name):
        is_queue = func.id == "Queue"
    else:
        is_queue = False
    if not is_queue:
        return False
    size = node.args[0] if node.args else None
    if size is None:
        for kw in node.keywords:
            if kw.arg == "maxsize":
                size = kw.value
                break
    if size is None:
        return True
    if isinstance(size, ast.Constant) and isinstance(size.value, (int, float)):
        return size.value <= 0
    if isinstance(size, ast.UnaryOp) and isinstance(size.op, ast.USub):
        return isinstance(size.operand, ast.Constant)
    return False


def _is_silent_broad_swallow(node: ast.ExceptHandler) -> bool:
    """True for a handler that (a) catches Exception/BaseException —
    directly or inside a tuple — and (b) whose body does nothing but
    ``pass``/``...``/``continue``. Narrow handlers and handlers that log,
    meter, assign or re-raise are fine."""

    def names(t) -> list:
        if t is None:
            return []
        if isinstance(t, ast.Tuple):
            return [n for elt in t.elts for n in names(elt)]
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, ast.Attribute):
            return [t.attr]
        return []

    if not any(n in ("Exception", "BaseException") for n in names(node.type)):
        return False
    for stmt in node.body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / Ellipsis
        return False
    return True


# transport entry points that bypass the resilient client wrapper when
# called directly from SDK code
_RAW_HTTP_CALLEES = frozenset(
    {"urlopen", "urlretrieve", "open_connection", "create_connection", "socket"}
)


def _is_raw_http_call(node: ast.Call) -> bool:
    """True for direct transport constructions (urllib/socket/asyncio
    streams) — syntactic, like the queue rule: any spelling that resolves
    to one of the raw entry points counts."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _RAW_HTTP_CALLEES
    return isinstance(func, ast.Name) and func.id in _RAW_HTTP_CALLEES


# fold entry points that bypass the EdgeAggregator accounting path when
# called directly from edge code: a modular add without the matching
# member/seed-dict accounting ships an envelope whose nb_models disagrees
# with its content and breaks the coordinator's nb_models == seed-watermark
# unmask invariant (docs/DESIGN.md §11)
_FOLD_CALLEES = frozenset(
    {
        "aggregate",
        "aggregate_batch",
        "aggregate_partial",
        "fold_partial",
        "mod_add",
        "batch_mod_sum",
        "fold_wire_batch_host",
        "fold_planar_batch_host",
        "masked_add",
    }
)


def _is_fold_call(node: ast.Call) -> bool:
    """True for any spelling that resolves to a masked-add/fold entry point
    (syntactic, like the queue rule)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _FOLD_CALLEES
    return isinstance(func, ast.Name) and func.id in _FOLD_CALLEES


# fold-worker call-graph function-name prefixes under xaynet_tpu/parallel:
# the producers (submit_*), the per-batch/per-shard fold paths (_fold*,
# fold*, _credit, _dispatch*, _retry*, _shard*), and the worker loops
# (_process*, _worker*). drain()/_drain* are the sanctioned sync points and
# deliberately NOT listed. (Lexical fast path; the reachability closure
# lives in tools/analysis/purity.py.)
_WORKER_SYNC_PREFIXES = (
    "_process",
    "_fold",
    "fold",
    "_dispatch",
    "_credit",
    "_retry",
    "_shard",
    "_worker",
    "submit",
    "_submit",
)

# host-blocking entry points: np.asarray materializes a device value on the
# host; block_until_ready is an explicit device barrier
_SYNC_CALLEES = frozenset({"asarray", "block_until_ready"})

# simulation program bodies: functions with these name prefixes under
# xaynet_tpu/sim are jitted whole-round program code — pure traced JAX
_SIM_PROGRAM_PREFIXES = ("_prog",)

# Python-int limb math: pulls group elements out of the graph one integer
# at a time (the pattern the in-graph simulation exists to eliminate)
_HOST_INT_CALLEES = frozenset(
    {"limbs_to_int", "limbs_to_ints", "int_to_limbs", "ints_to_limbs", "item", "tolist", "int"}
)


def _is_host_roundtrip(node: ast.Call) -> bool:
    """True for host syncs AND Python-int limb math (syntactic, any
    spelling that resolves to one of the entry points)."""
    if _is_blocking_sync(node):
        return True
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _HOST_INT_CALLEES
    return isinstance(func, ast.Name) and func.id in _HOST_INT_CALLEES


def _is_blocking_sync(node: ast.Call) -> bool:
    """True for any spelling of ``np.asarray(...)`` /
    ``jax.block_until_ready(...)`` / ``x.block_until_ready()`` (syntactic,
    like the other rules)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _SYNC_CALLEES
    return isinstance(func, ast.Name) and func.id in _SYNC_CALLEES


def _is_width_expr(node: ast.BinOp) -> bool:
    """True for the hand-computed width idioms ``(x + 7) // 8`` (bits/bytes
    -> bytes) and ``(x + 3) // 4`` (bytes -> uint32 limbs) — the two
    expressions the codec module (``ops/limbs.py``) owns. Purely
    syntactic, commutative in the addition."""
    if not isinstance(node.op, ast.FloorDiv):
        return False
    if not (isinstance(node.right, ast.Constant) and node.right.value in (4, 8)):
        return False
    want = 7 if node.right.value == 8 else 3
    left = node.left
    if not (isinstance(left, ast.BinOp) and isinstance(left.op, ast.Add)):
        return False
    return (
        isinstance(left.right, ast.Constant) and left.right.value == want
    ) or (isinstance(left.left, ast.Constant) and left.left.value == want)


def _is_device_put(node: ast.Call) -> bool:
    """True for ``jax.device_put(...)`` / ``device_put(...)`` calls (the
    rule is syntactic, like the queue rule: any spelling that resolves to
    the jax transfer entry point counts)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr == "device_put"
    return isinstance(func, ast.Name) and func.id == "device_put"


_WIRECOPY_PAYLOAD_NAMES = frozenset(
    {"body", "payload", "raw", "blob", "buf", "wire", "msg", "message"}
)


def _wire_copy_kind(node: ast.AST) -> str | None:
    """Classify whole-body copy idioms on the ingress path: ``bytes()`` /
    ``bytearray()`` materializations, ``.tobytes()`` exports, and
    slice-copies of payload-named buffers (slicing ``bytes`` copies; the
    zero-copy spelling slices a ``memoryview``, which doesn't)."""
    if isinstance(node, ast.Call):
        func = node.func
        if (
            isinstance(func, ast.Name)
            and func.id in ("bytes", "bytearray")
            and node.args
        ):
            return f"{func.id}() materialization"
        if isinstance(func, ast.Attribute) and func.attr == "tobytes":
            return ".tobytes() export"
    if isinstance(node, ast.Subscript) and isinstance(node.slice, ast.Slice):
        target = node.value
        name = (
            target.id
            if isinstance(target, ast.Name)
            else target.attr if isinstance(target, ast.Attribute) else ""
        )
        low = name.lower()
        if low in _WIRECOPY_PAYLOAD_NAMES or any(
            low.endswith("_" + n) for n in _WIRECOPY_PAYLOAD_NAMES
        ):
            return f"slice-copy of payload buffer '{name}'"
    return None


def check_file_info(info: FileInfo) -> list[Finding]:
    """Run every per-file rule over one parsed file."""
    problems: list[Finding] = list(info.problems)
    rel = info.rel
    if info.text is None:
        return problems
    text = info.text

    def add(rule: str, line: int, message: str) -> None:
        problems.append(Finding(rule, rel, line, message))

    # --- format-level checks ----------------------------------------------
    generated = "generated by" in text[:200]
    if text and not text.endswith("\n"):
        add("fmt", text.count(chr(10)) + 1, "missing final newline")
    for i, line in enumerate(text.splitlines(), 1):
        stripped = line.rstrip("\n")
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            add("fmt", i, "tab in indentation")
        if stripped != stripped.rstrip():
            add("fmt", i, "trailing whitespace")
        if len(stripped) > MAX_LINE and "http" not in stripped and not generated:
            add("fmt", i, f"line longer than {MAX_LINE} chars ({len(stripped)})")

    # --- AST checks --------------------------------------------------------
    tree = info.tree
    if tree is None:
        return problems

    visitor = _ImportVisitor()
    visitor.visit(tree)

    for line in visitor.star_imports:
        add("star-import", line, "star import")

    if info.path.name != "__init__.py":  # __init__ files are re-export indexes
        exported = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        if isinstance(node.value, (ast.List, ast.Tuple)):
                            for elt in node.value.elts:
                                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                                    exported.add(elt.value)
        string_refs = _used_in_annotations(tree)
        for name, (line, display) in sorted(visitor.imports.items()):
            if name in visitor.used or name in exported or name in string_refs:
                continue
            add("unused-import", line, f"unused import '{display}'")

    # hot-path trees: raw perf_counter timing bypasses the telemetry layer
    hot_path = rel.startswith(("xaynet_tpu/parallel", "xaynet_tpu/server"))
    # coordinator queue trees: unbounded queues defeat admission control
    bounded_tree = rel.startswith(
        ("xaynet_tpu/server", "xaynet_tpu/ingest", "xaynet_tpu/edge")
    )
    # edge tree: every fold must flow through the EdgeAggregator accounting
    # path (admit/seal), never a direct masked_add
    edge_tree = rel.startswith("xaynet_tpu/edge")
    # coordinator/storage trees: silent broad swallows hide infrastructure
    # failures from the resilience layer and the operator
    no_swallow_tree = rel.startswith(("xaynet_tpu/server", "xaynet_tpu/storage"))
    # SDK tree: raw transports bypass the resilient client wrapper
    sdk_tree = rel.startswith("xaynet_tpu/sdk")
    # width rule: every wire/pack width must come from the codec module
    # (ops/limbs.py — wire_width_for / draw_width_for / n_limbs_for_bytes);
    # a hand-computed copy drifting from the codec is exactly how a packed
    # plane and its unpack disagree by one byte
    width_tree = (
        rel.startswith("xaynet_tpu/") and rel != "xaynet_tpu/ops/limbs.py"
    )
    # ingress path: request bodies must stay zero-copy memoryview views
    # from socket read to staging — a stray bytes()/tobytes()/slice copy
    # doubles the per-update byte traffic the packed wire exists to cut
    wirecopy_tree = (
        rel.startswith("xaynet_tpu/ingest/") or rel == "xaynet_tpu/server/rest.py"
    )

    line_of = info.line

    # sim tree: host round-trips inside jitted program bodies reintroduce
    # the per-phase host syncs the in-graph round exists to eliminate
    if rel.startswith("xaynet_tpu/sim"):
        flagged_sim: set[int] = set()
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not fn.name.startswith(_SIM_PROGRAM_PREFIXES):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and _is_host_roundtrip(node)
                    and node.lineno not in flagged_sim
                ):
                    flagged_sim.add(node.lineno)
                    if not suppressed("sync", line_of(node.lineno)):
                        add(
                            "sync",
                            node.lineno,
                            f"host round-trip in sim program "
                            f"body '{fn.name}' (np.asarray/block_until_ready/"
                            "Python-int limb math must stay outside jitted round "
                            "programs; move it to the host boundary or annotate a "
                            "deliberate materialization with '# lint: sync-ok')",
                        )

    # parallel tree: blocking host syncs inside fold-worker code paths
    # serialize the pipeline overlap; drain() is the sanctioned sync point
    if rel.startswith("xaynet_tpu/parallel"):
        flagged: set[int] = set()
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not fn.name.startswith(_WORKER_SYNC_PREFIXES):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and _is_blocking_sync(node)
                    and node.lineno not in flagged
                ):
                    flagged.add(node.lineno)
                    if not suppressed("sync", line_of(node.lineno)):
                        add(
                            "sync",
                            node.lineno,
                            f"blocking host sync in fold-worker "
                            f"code path '{fn.name}' (synchronize in drain(), or "
                            "annotate a deliberate transfer barrier / host-kernel "
                            "materialization with '# lint: sync-ok')",
                        )

    for node in ast.walk(tree):
        if hot_path and isinstance(node, ast.Call):
            func = node.func
            callee = (
                func.attr
                if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            if callee == "perf_counter":
                if not suppressed("telemetry", line_of(node.lineno)):
                    add(
                        "telemetry",
                        node.lineno,
                        "raw perf_counter timing bypasses the "
                        "telemetry registry (use xaynet_tpu.telemetry.profiling or a "
                        "registry histogram timer)",
                    )
        if bounded_tree and isinstance(node, ast.Call) and _is_unbounded_queue(node):
            if not suppressed("unbounded", line_of(node.lineno)):
                add(
                    "unbounded",
                    node.lineno,
                    "unbounded asyncio.Queue() in the "
                    "coordinator tree (pass a maxsize, or annotate a deliberate "
                    "sentinel/upstream-bounded channel with '# lint: unbounded-ok')",
                )
        if sdk_tree and isinstance(node, ast.Call) and _is_raw_http_call(node):
            if not suppressed("raw-http", line_of(node.lineno)):
                add(
                    "raw-http",
                    node.lineno,
                    "raw HTTP/socket call in the SDK tree "
                    "bypasses the resilient client wrapper (route coordinator "
                    "traffic through sdk.client.HttpClient/ResilientClient, or "
                    "annotate the transport itself with '# lint: raw-http-ok')",
                )
        if edge_tree and isinstance(node, ast.Call) and _is_fold_call(node):
            if not suppressed("fold", line_of(node.lineno)):
                add(
                    "fold",
                    node.lineno,
                    "direct masked_add/fold call in the edge "
                    "tree bypasses the partial-aggregate accounting path (fold "
                    "through EdgeAggregator.admit/seal, or annotate the accounting "
                    "path's own fold site with '# lint: fold-ok')",
                )
        if width_tree and isinstance(node, ast.BinOp) and _is_width_expr(node):
            if not suppressed("width", line_of(node.lineno)):
                add(
                    "width",
                    node.lineno,
                    "hand-computed wire/pack width expression "
                    "(use ops.limbs.wire_width_for / draw_width_for / "
                    "n_limbs_for_bytes — the codec module is the single "
                    "source of truth — or annotate a non-wire byte-length "
                    "computation with '# lint: width-ok')",
                )
        if wirecopy_tree:
            kind = _wire_copy_kind(node)
            if kind is not None and not suppressed("wirecopy", line_of(node.lineno)):
                add(
                    "wirecopy",
                    node.lineno,
                    f"whole-body copy on the ingress path ({kind}) — "
                    "request payloads must stay zero-copy memoryview views "
                    "end to end; annotate a deliberate boundary "
                    "materialization with '# lint: wirecopy-ok'",
                )
        if bounded_tree and isinstance(node, ast.Call) and _is_device_put(node):
            if not suppressed("device-put", line_of(node.lineno)):
                add(
                    "device-put",
                    node.lineno,
                    "direct jax.device_put in the coordinator "
                    "tree (stage update batches through the streaming pipeline's "
                    "buffer ring — parallel.streaming — or annotate a deliberate "
                    "non-update-tensor upload with '# lint: device-put-ok')",
                )
        if (
            no_swallow_tree
            and isinstance(node, ast.ExceptHandler)
            and _is_silent_broad_swallow(node)
        ):
            if not suppressed("swallow", line_of(node.lineno)):
                add(
                    "swallow",
                    node.lineno,
                    "silent broad-exception swallow in the "
                    "coordinator/storage tree (log, meter, retry or re-raise — or "
                    "annotate a deliberate best-effort cleanup with "
                    "'# lint: swallow-ok')",
                )
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    add(
                        "mutable-default",
                        default.lineno,
                        f"mutable default argument in '{node.name}'",
                    )
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            add("bare-except", node.lineno, "bare 'except:'")
        elif isinstance(node, ast.Dict):
            seen: set[object] = set()
            for key in node.keys:
                if isinstance(key, ast.Constant):
                    marker = (type(key.value).__name__, key.value)
                    if marker in seen:
                        add("dup-key", key.lineno, f"duplicate dict key {key.value!r}")
                    seen.add(marker)
    return problems
