"""Metrics cross-check: code <-> docs/DESIGN.md metric-table parity.

Registration sites are ``<registry>.counter/gauge/histogram("xaynet_...",
...)`` calls under ``xaynet_tpu/`` (lookups — ``get``/``sample_value`` —
don't count). The documentation side is every markdown table row between
``<!-- metrics-table:begin -->`` / ``<!-- metrics-table:end -->`` markers
in docs/DESIGN.md; inside those rows, backticked metric tokens support
two shorthands::

    `xaynet_streaming_{staging_depth,inflight_folds}`   brace expansion
    `xaynet_messages_total{phase,outcome}`              trailing label set

Checks (rule ``metrics``):
  1. every ``xaynet_*`` family is registered exactly once (the registry is
     idempotent at runtime, but two independent registration sites with
     the same name mean two modules think they own the family);
  2. every registered family appears in the DESIGN metric tables;
  3. every documented family is actually registered (no stale doc rows).
"""

from __future__ import annotations

import ast
import re

from .cache import FileInfo
from .core import Finding, suppressed

_REG_METHODS = frozenset({"counter", "gauge", "histogram"})
_BEGIN = "<!-- metrics-table:begin -->"
_END = "<!-- metrics-table:end -->"
_TOKEN_RE = re.compile(r"`(xaynet_[a-z0-9_{},]+)`")


def registrations(files: list[FileInfo]) -> dict[str, list[tuple[str, int]]]:
    """metric name -> [(rel, line)] registration sites under xaynet_tpu/."""
    out: dict[str, list[tuple[str, int]]] = {}
    for info in files:
        if not info.rel.startswith("xaynet_tpu/") or info.tree is None:
            continue
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr not in _REG_METHODS or not node.args:
                continue
            first = node.args[0]
            if (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("xaynet_")
            ):
                out.setdefault(first.value, []).append((info.rel, node.lineno))
    return out


def _expand(token: str) -> list[str]:
    """Brace shorthand -> concrete family names. A trailing ``{...}`` after
    a complete name is a label set (stripped); a ``{a,b}`` group mid-token
    — or right after a trailing ``_`` — expands."""
    m = re.search(r"\{([^{}]*)\}", token)
    if m is None:
        return [token]
    before, group, after = token[: m.start()], m.group(1), token[m.end():]
    if not after and not before.endswith("_"):  # trailing -> label set
        return [before]
    return [name for part in group.split(",") for name in _expand(before + part + after)]


def documented(design_text: str) -> dict[str, int]:
    """metric name -> first documenting line, from marked table rows."""
    out: dict[str, int] = {}
    active = False
    for i, line in enumerate(design_text.splitlines(), 1):
        if _BEGIN in line:
            active = True
            continue
        if _END in line:
            active = False
            continue
        if not active or not line.lstrip().startswith("|"):
            continue
        for token in _TOKEN_RE.findall(line):
            for name in _expand(token):
                out.setdefault(name, i)
    return out


def run(files: list[FileInfo], design_path) -> list[Finding]:
    findings: list[Finding] = []
    regs = registrations(files)
    try:
        design_text = design_path.read_text()
    except OSError:
        return [
            Finding("metrics", "docs/DESIGN.md", 1, "docs/DESIGN.md is unreadable")
        ]
    docs = documented(design_text)
    if not docs:
        return [
            Finding(
                "metrics",
                "docs/DESIGN.md",
                1,
                "no marked metric tables found (expected "
                f"'{_BEGIN}' ... '{_END}' around the §6 series table)",
            )
        ]
    by_rel: dict[str, FileInfo] = {f.rel: f for f in files}
    for name, sites in sorted(regs.items()):
        if len(sites) > 1:
            for rel, line in sites[1:]:
                info = by_rel.get(rel)
                if info and suppressed("metrics", info.line(line)):
                    continue
                # no line number in the message: baseline keys must stay
                # stable when unrelated edits shift the first site
                findings.append(
                    Finding(
                        "metrics",
                        rel,
                        line,
                        f"metric '{name}' is registered more than once "
                        f"(first in {sites[0][0]}) — one module owns a "
                        "family; import its symbol instead",
                    )
                )
        if name not in docs:
            rel, line = sites[0]
            info = by_rel.get(rel)
            if info and suppressed("metrics", info.line(line)):
                continue
            findings.append(
                Finding(
                    "metrics",
                    rel,
                    line,
                    f"metric '{name}' is not in the DESIGN.md metric tables "
                    "(add a row inside the metrics-table markers, §6)",
                )
            )
    for name, line in sorted(docs.items()):
        if name not in regs:
            findings.append(
                Finding(
                    "metrics",
                    "docs/DESIGN.md",
                    line,
                    f"documented metric '{name}' is not registered anywhere "
                    "under xaynet_tpu/ (stale table row?)",
                )
            )
    return findings
