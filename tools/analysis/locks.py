"""Lock-discipline race lint (`# guarded-by:` annotations).

Convention (docs/DESIGN.md §14): a shared mutable attribute of a threaded
class is annotated where it is initialized::

    self._pending = []  # guarded-by: _lock
    self.max_occupancy = 0  # guarded-by: event-loop

``_lock`` names a lock attribute; the special guard ``event-loop`` marks
asyncio-confined state that NO thread may touch. The pass then flags any
read or write of a guarded attribute from a function *reachable from a
worker-thread entry point* (``Thread(target=...)``, executor
``submit``/``map`` — see :func:`callgraph.thread_entry_points`) that is
not lexically inside a ``with <lock>:`` block for the matching lock.
This is exactly the access pattern behind the PR-7 torn-shard-slice race
(concurrent donating jit calls on per-shard accumulators), turned into a
compile-time finding.

Scope and honesty limits (deliberate, documented):

- accesses are matched on ``self.<attr>`` plus ``<var>.<attr>`` where the
  receiver's class is known from the type sketch (parameter annotations,
  ``v = ClassName(...)``); untyped receivers are not matched;
- lock matching is lexical and name-based: any ``with`` whose context
  expression *ends in* the guard name counts (``with self._lock:``,
  ``with plan._device_dispatch_lock:``). ``.acquire()``/``.release()``
  pairs do NOT count — convert them or suppress with a rationale;
- suppression requires a rationale: ``# lint: guarded-ok: <why>``.
"""

from __future__ import annotations

import ast
import re

from .callgraph import CallGraph, _is_self, iter_owned_nodes, thread_entry_points
from .core import Finding, suppressed, suppression_pending_rationale

GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w\-]*)")
EVENT_LOOP_GUARDS = ("event-loop", "asyncio-loop")


def collect_guarded(graph: CallGraph) -> dict[tuple[str, str], dict[str, str]]:
    """(rel, class) -> {attr: guard} from ``# guarded-by:`` annotations on
    ``self.<attr> = ...`` initialization lines."""
    out: dict[tuple[str, str], dict[str, str]] = {}
    for (rel, cls), methods in graph.symbols.class_methods.items():
        gmap: dict[str, str] = {}
        for fi in methods.values():
            info = fi.file
            for node in iter_owned_nodes(fi.node):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and _is_self(t.value):
                        m = GUARDED_RE.search(info.line(t.lineno))
                        if m:
                            gmap[t.attr] = m.group(1)
        if gmap:
            out[(rel, cls)] = gmap
    return out


def _held_locks(fn_node) -> dict[int, frozenset]:
    """node id -> set of lock names lexically held at that node (terminal
    names of ``with`` context expressions)."""
    held_at: dict[int, frozenset] = {}

    def terminal_name(expr) -> str | None:
        if isinstance(expr, ast.Attribute):
            return expr.attr
        if isinstance(expr, ast.Name):
            return expr.id
        return None

    def walk(node, held: frozenset):
        for child in ast.iter_child_nodes(node):
            child_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                names = set()
                for item in child.items:
                    n = terminal_name(item.context_expr)
                    if n:
                        names.add(n)
                child_held = held | frozenset(names)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # separate FuncInfo, analyzed on its own
            held_at[id(child)] = child_held
            walk(child, child_held)

    held_at[id(fn_node)] = frozenset()
    walk(fn_node, frozenset())
    return held_at


def run(graph: CallGraph) -> list[Finding]:
    symbols = graph.symbols
    guarded = collect_guarded(graph)
    if not guarded:
        return []
    # guard lookup by class simple name (for typed non-self receivers)
    by_class_name: dict[str, list[tuple[tuple[str, str], dict[str, str]]]] = {}
    for key, gmap in guarded.items():
        by_class_name.setdefault(key[1], []).append((key, gmap))

    entries = thread_entry_points(graph)
    reach = graph.reachable(entries)
    # event-loop confinement stops at coroutine boundaries: a thread that
    # RUNS an asyncio loop (the SDK's in-process federation, run_until_
    # complete) executes its coroutines ON the loop — only a sync-only
    # chain from a thread entry to the access is a foreign-thread touch
    reach_sync = graph.reachable(entries, through_async=False)
    findings: list[Finding] = []

    for fi in symbols.functions:
        if fi.uid not in reach:
            continue
        own_guards = guarded.get((fi.file.rel, fi.cls or ""), {})
        types = graph._local_types(fi)
        held_at = _held_locks(fi.node)
        flagged: set[tuple[int, str]] = set()
        for node in iter_owned_nodes(fi.node):
            if not (isinstance(node, ast.Attribute) and isinstance(node.ctx, (ast.Load, ast.Store, ast.Del))):
                continue
            attr = node.attr
            guard = None
            cls_label = fi.cls
            if _is_self(node.value) and attr in own_guards:
                if fi.name == "__init__":
                    continue  # construction happens-before thread start
                guard = own_guards[attr]
            elif isinstance(node.value, ast.Name):
                cname = types.get(node.value.id)
                if cname:
                    for (rel_cls, gmap) in by_class_name.get(cname, []):
                        if attr in gmap:
                            guard = gmap[attr]
                            cls_label = cname
                            break
            if guard is None:
                continue
            held = held_at.get(id(node), frozenset())
            is_loop_guard = guard in EVENT_LOOP_GUARDS
            if is_loop_guard and fi.uid not in reach_sync:
                continue  # only reachable through a coroutine: loop context
            if not is_loop_guard and guard in held:
                continue
            key = (node.lineno, attr)
            if key in flagged:
                continue
            flagged.add(key)
            line = fi.file.line(node.lineno)
            if suppressed("guarded", line):
                continue
            pending = suppression_pending_rationale("guarded", line)
            if is_loop_guard:
                msg = (
                    f"'{cls_label}.{attr}' is event-loop-confined (guarded-by: "
                    f"{guard}) but '{fi.qualname}' is reachable from a "
                    "worker-thread entry point — marshal through "
                    "call_soon_threadsafe or move the access onto the loop"
                )
            else:
                msg = (
                    f"unguarded access to '{cls_label}.{attr}' (guarded-by: "
                    f"{guard}) in worker-thread-reachable '{fi.qualname}' — "
                    f"hold 'with {guard}:' around the access or annotate "
                    "'# lint: guarded-ok: <rationale>'"
                )
            if pending:
                msg += " [suppression present but missing its rationale]"
            findings.append(Finding("guarded", fi.file.rel, node.lineno, msg))
    return findings
