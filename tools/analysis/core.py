"""Findings, rules, suppression and baseline — the analysis data model.

Every check in the framework is a registered :class:`Rule` with a stable
name. A rule's findings can be silenced three ways, in order of intent:

- **fix the code** (the default expectation);
- **per-line suppression** — ``# lint: <token>-ok`` on the offending
  line, where ``<token>`` is the rule's suppression token. Rules marked
  ``rationale_required`` additionally demand a human-readable reason on
  the same line (``# lint: guarded-ok: single-owner shard buffer``) —
  a bare token does NOT suppress them;
- **baseline** — a checked-in JSON file of known findings
  (``tools/analysis/baseline.json``) for gradual adoption: baselined
  findings are reported as *masked* and don't fail the gate, new ones do.

Baseline keys deliberately exclude line numbers so unrelated edits above
a known finding don't churn the file.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path


@dataclass(frozen=True)
class Rule:
    """One registered check."""

    name: str
    token: str  # per-line suppression token: `# lint: <token>-ok`
    doc: str
    rationale_required: bool = False
    legacy_tokens: tuple[str, ...] = ()  # pre-framework spellings


RULES: dict[str, Rule] = {}


def rule(
    name: str,
    doc: str,
    token: str | None = None,
    rationale_required: bool = False,
    legacy_tokens: tuple[str, ...] = (),
) -> Rule:
    r = Rule(name, token or name, doc, rationale_required, tuple(legacy_tokens))
    RULES[name] = r
    return r


# --- the rule inventory (docs/DESIGN.md §14 mirrors this) -------------------

rule("encoding", "file is not valid UTF-8")
rule("syntax", "file does not parse")
rule("fmt", "tabs in indentation / trailing whitespace / missing final newline / long lines")
rule("star-import", "`from x import *`")
rule("unused-import", "module-scope import never referenced")
rule("mutable-default", "list/dict/set literal as a default argument")
rule("bare-except", "`except:` without an exception type")
rule("dup-key", "duplicate literal key in a dict display")
rule(
    "telemetry",
    "raw time.perf_counter() in the hot-path trees (must flow through "
    "xaynet_tpu.telemetry)",
    legacy_tokens=("telemetry-exempt",),
)
rule("unbounded", "bare unbounded asyncio.Queue() in the coordinator trees")
rule("device-put", "direct jax.device_put in the coordinator trees")
rule("swallow", "silent broad-exception swallow in the coordinator/storage trees")
rule("raw-http", "raw HTTP/socket transport call in the SDK tree")
rule("fold", "direct masked_add/fold call in the edge tree")
rule(
    "sync",
    "blocking host sync / host round-trip in fold-worker or jitted sim "
    "program code (lexical prefix rule AND the call-graph purity pass)",
)
rule(
    "guarded",
    "read/write of a `# guarded-by:` attribute from worker-thread-reachable "
    "code outside its lock",
    rationale_required=True,
)
rule(
    "invariant",
    "mutation of nb_models / the per-edge seed watermark outside the "
    "sanctioned accounting sites (the nb_models == seed-watermark unmask "
    "linchpin, docs/DESIGN.md §9–§11)",
    rationale_required=True,
)
rule(
    "metrics",
    "xaynet_* metric registered more than once, or code <-> DESIGN.md "
    "metric-table drift",
)
rule(
    "width",
    "hand-computed wire/pack width expression ((x + 7) // 8 or (x + 3) // 4) "
    "outside the codec module (ops/limbs.py is the single source of truth: "
    "wire_width_for / draw_width_for / n_limbs_for_bytes)",
)
rule(
    "wirecopy",
    "whole-body copy of a request payload on the ingress path (bytes()/"
    "bytearray() materialization, .tobytes() export, or a slice-copy of a "
    "payload buffer in ingest/ + server/rest.py — bodies must stay "
    "zero-copy memoryview views end to end, docs/DESIGN.md §21)",
)
rule(
    "span",
    "tracing span() not used as a context manager, span name declared "
    "twice / undeclared, or code <-> DESIGN.md §16 span-table drift",
)
rule(
    "tenant",
    "tenant-scope: code under server/ + parallel/ reading tenant-scoped "
    "state (Shared round fields, pool pages/leases, edge watermarks) with "
    "no tenant key in scope, or a pool lease/release call site outside the "
    "sanctioned whitelist (the leases == releases round invariant, "
    "docs/DESIGN.md §19)",
    rationale_required=True,
)
rule(
    "taint",
    "secret-flow: key material (mask seeds, keypair secret halves, ChaCha "
    "keystreams, the edge token) reaching an observability or persistence "
    "sink (logs, span attrs, metric labels, JSON dumps/reports/checkpoints, "
    "flight-recorder payloads, exception messages) without passing a "
    "declassifier (seal/encrypt, sha256, len/type, telemetry.redact) — "
    "docs/DESIGN.md §18",
    rationale_required=True,
)


def suppressed(rule_name: str, line: str) -> bool:
    """True when ``line`` carries a valid suppression for ``rule_name``.

    For ``rationale_required`` rules the ``# lint: <token>-ok`` marker must
    be followed by a non-empty rationale (after ``:``/``—``/``-``/spaces);
    a bare marker does not count.
    """
    r = RULES[rule_name]
    marker = f"lint: {r.token}-ok"
    if marker in line:
        if not r.rationale_required:
            return True
        rest = line[line.index(marker) + len(marker):]
        return bool(rest.strip(" \t:—–-.,()"))
    return any(tok in line for tok in r.legacy_tokens)


def suppression_pending_rationale(rule_name: str, line: str) -> bool:
    """True when the line carries the rule's marker but no rationale (only
    meaningful for rationale-required rules — used to improve messages)."""
    r = RULES[rule_name]
    marker = f"lint: {r.token}-ok"
    return r.rationale_required and marker in line and not suppressed(rule_name, line)


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str  # repo-relative posix path
    line: int
    message: str

    def legacy(self) -> str:
        """The pre-framework one-line format (what CI logs and the older
        tests grep)."""
        return f"{self.file}:{self.line}: {self.message}"

    def key(self) -> str:
        """Baseline identity: rule + file + message, no line number."""
        return f"{self.rule}|{self.file}|{self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "message": self.message,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "Finding":
        return cls(obj["rule"], obj["file"], int(obj["line"]), obj["message"])


class Baseline:
    """Checked-in known findings; keys are :meth:`Finding.key` with counts
    (several identical findings in one file consume several slots)."""

    def __init__(self, counts: dict[str, int]):
        self.counts = dict(counts)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls({})
        data = json.loads(path.read_text())
        return cls({str(k): int(v) for k, v in (data.get("findings") or {}).items()})

    @staticmethod
    def write(path: Path, findings: list[Finding]) -> None:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.key()] = counts.get(f.key(), 0) + 1
        path.write_text(
            json.dumps(
                {"version": 1, "findings": dict(sorted(counts.items()))}, indent=2
            )
            + "\n"
        )

    def split(self, findings: list[Finding]) -> tuple[list[Finding], list[Finding]]:
        """(new, masked): masked findings consume baseline slots per key."""
        budget = dict(self.counts)
        new: list[Finding] = []
        masked: list[Finding] = []
        for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule)):
            k = f.key()
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                masked.append(f)
            else:
                new.append(f)
        return new, masked
