"""Interprocedural secret-flow taint pass (rule ``taint``).

Xaynet's value proposition is that the coordinator never *sees* an
individual model or mask seed — yet the observability surface (structured
logs, span attributes, flight-recorder dumps, per-round JSON reports,
durable checkpoints, exception messages) grew for three PRs with no tool
auditing it for secret leakage. This pass makes the invariant
machine-checked (docs/DESIGN.md §18):

- a **source registry** marks the secret producers: ``MaskSeed``
  construction/generation, the ``.secret`` half of
  ``EncryptKeyPair``/``SigningKeyPair``, ``SecretEncryptKey``, ChaCha
  keys/keystreams (``keystream_blocks``/``ChaChaStream``), seeded
  samplers (``StreamSampler``), ``PetSettings.mask_seed`` (the
  ``mask_seed`` attribute), key-derivation seeds (``generate_seed``) and
  the ``[edge]`` shared ``token``;
- taint propagates through assignments, containers, f-strings/format
  arithmetic, comprehensions and **function boundaries**: every function
  gets a summary (which params reach which sinks, what the return value
  carries) computed to a fixed point over the PR-9 call graph, with
  attr-level tracking for secret-bearing containers (``self.seeds[pk] =
  ...`` taints the attribute for the whole class, across methods);
- a **declassifier set** terminates flows: sealing (``encrypt``),
  hashing (``sha256``), signatures, length/type-only projections
  (``len``/``type``/``bool``), comparisons, and ``telemetry.redact()``
  (``scrub_attrs`` is deliberately NOT one — it only redacts deny-listed
  keys, so taint under other keys must keep flowing);
- a **sink registry** turns surviving flows into findings: logging
  calls, span attributes (``span(..., k=v)`` / ``handle.set(k=v)`` /
  ``record_span``), metric label values (``.labels(...)``), flight
  recorder payloads (``flight_dump``), serialized JSON dumps
  (``json.dump``/``dumps`` — round reports, checkpoint headers, durable
  state blobs), and exception messages raised under
  ``xaynet_tpu/{server,sdk,edge}/``.

Suppression is ``# lint: taint-ok: <rationale>`` (a bare marker does NOT
suppress). It works at two points: on the **sink** line (silences that
finding) and on the **source** line — a suppressed source is a sanctioned
declassification boundary, so the value's onward flow stops being tracked
(e.g. the coordinator's durable-state blob legitimately carries the round
secret key; suppressing the ``.secret`` read there keeps every downstream
store write clean instead of demanding a cascade of suppressions).

The source/declassifier/sink registries are cross-checked against the
marker-delimited tables in docs/DESIGN.md §18, both directions — the
metrics-table parity idiom applied to the taint model.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .callgraph import CallGraph, FuncInfo, _is_self, iter_owned_nodes
from .core import Finding, suppressed, suppression_pending_rationale

# --- registries (docs/DESIGN.md §18 mirrors these, machine-checked) ---------

# callee simple name (or CapWord receiver of a classmethod call) -> token
SOURCE_CALLS: dict[str, str] = {
    "MaskSeed": "mask-seed",
    "SecretEncryptKey": "secret-encrypt-key",
    "StreamSampler": "seeded-sampler",
    "ChaChaStream": "chacha-keystream",
    "keystream_blocks": "chacha-keystream",
    "generate_seed": "key-seed",
}

# attribute name read anywhere -> token (the secret halves / injected seeds)
SOURCE_ATTRS: dict[str, str] = {
    "secret": "keypair-secret-half",
    "mask_seed": "mask-seed-setting",
    "token": "edge-token",
}

# callee simple names that TERMINATE a flow (seal, hash, sign, project)
DECLASSIFIERS = frozenset(
    {
        "encrypt",          # sealed-box seal: ciphertext is publishable
        "sha256",           # digests don't reveal key material
        "sign",             # Ed25519 signatures are published by protocol
        "sign_detached",
        "is_eligible",
        "compare_digest",   # constant-time comparison -> bool
        "public_key",       # secret -> public half
        "x25519_public",
        "ed25519_public",
        "round_trace_id",   # sha256-derived public correlation id
        "len",              # length/type-only projections
        "type",
        "bool",
        "redact",           # telemetry.redact(): the sanctioned projection
        # NOT scrub_attrs: it only redacts deny-listed KEYS, so a tainted
        # value under a non-denied key passes through verbatim — modeling
        # it as a declassifier would declare that leak clean
    }
)

SINK_TOKENS = (
    "log-call",
    "span-attr",
    "metric-label",
    "flight-dump",
    "serialized-dump",
    "exception-message",
    "statusz-page",
    "alerts-payload",
)

_SRC_DESC = {
    "mask-seed": "mask seed material",
    "mask-seed-setting": "the injected mask_seed setting",
    "keypair-secret-half": "a keypair's secret half",
    "secret-encrypt-key": "a secret encryption key",
    "seeded-sampler": "seeded keystream-sampler output",
    "chacha-keystream": "raw ChaCha keystream",
    "key-seed": "key-derivation seed bytes",
    "edge-token": "the [edge] shared token",
}

_SINK_DESC = {
    "log-call": "a logging call",
    "span-attr": "a tracing span attribute",
    "metric-label": "a metric label value",
    "flight-dump": "a flight-recorder dump payload",
    "serialized-dump": "a serialized JSON dump (report/checkpoint/state blob)",
    "exception-message": "an exception message",
    "statusz-page": "the /statusz operator console page",
    "alerts-payload": "the /alerts SLO payload",
}

_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "error", "exception", "critical", "log"}
)
_LOG_RECEIVERS = frozenset({"logger", "logging", "log"})

# exception messages are a sink only where an attacker/operator-facing
# surface raises them (ISSUE 14): the coordinator, the SDK and the edge
_RAISE_SINK_TREES = ("xaynet_tpu/server/", "xaynet_tpu/sdk/", "xaynet_tpu/edge/")

_MAX_LABELS = 12   # per-expression cap: beyond this the signal is "everything"
_MAX_HOPS = 6      # reported path depth cap
_MAX_ITERS = 10    # global fixed-point safety bound

# --- labels ------------------------------------------------------------------
# Src label:   ("src", token, rel)  — rel names the file the secret came from.
# Param label: ("param", func_uid, index)
#
# Labels deliberately carry NO path: the taint lattice must be finite for
# the fixed point to converge (path-carrying labels mint a fresh label per
# distinct call chain and never saturate on cyclic graphs). Call-chain hops
# are recorded as the FIRST-SEEN value on sink-flow entries instead — they
# decorate the finding message without participating in set identity.


def _src(token: str, rel: str) -> tuple:
    return ("src", token, rel)


class Summary:
    """Per-function taint summary, grown monotonically to a fixed point."""

    __slots__ = ("ret", "sinks", "attr_writes")

    def __init__(self):
        self.ret: set[tuple] = set()
        # param index -> {(sink_token, sink_rel): first-seen hop chain}
        self.sinks: dict[int, dict[tuple[str, str], tuple]] = {}
        # param index -> {(class_name, attr)} — caller taint lands on an attr
        self.attr_writes: dict[int, set[tuple[str, str]]] = {}

    def size(self) -> tuple[int, int, int]:
        return (
            len(self.ret),
            sum(len(v) for v in self.sinks.values()),
            sum(len(v) for v in self.attr_writes.values()),
        )


def _callee_parts(node: ast.Call) -> tuple[Optional[str], Optional[ast.expr]]:
    """(simple callee name, receiver expr or None)."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id, None
    if isinstance(func, ast.Attribute):
        return func.attr, func.value
    return None, None


def _param_names(fn_node) -> list[str]:
    args = getattr(fn_node, "args", None)
    if args is None:
        return []
    names = [a.arg for a in args.posonlyargs + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names.extend(a.arg for a in args.kwonlyargs)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


class TaintPass:
    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.symbols = graph.symbols
        self.summaries: dict[str, Summary] = {
            fi.uid: Summary() for fi in self.symbols.functions
        }
        # (class simple name, attr) -> set of Src labels
        self.attr_taint: dict[tuple[str, str], set[tuple]] = {}
        # (class simple name, attr) -> uids that read it (worklist deps)
        self._attr_readers: dict[tuple[str, str], set[str]] = {}
        self.findings: dict[tuple, Finding] = {}
        self._changed = False
        self._grew_attrs: set[tuple[str, str]] = set()

    # -- suppression helpers ----------------------------------------------

    def _line_suppressed(self, fi: FuncInfo, lineno: int) -> bool:
        return suppressed("taint", fi.file.line(lineno))

    def _note_pending_rationale(self, fi: FuncInfo, lineno: int) -> None:
        if suppression_pending_rationale("taint", fi.file.line(lineno)):
            key = (fi.file.rel, lineno, "pending")
            self.findings.setdefault(
                key,
                Finding(
                    "taint",
                    fi.file.rel,
                    lineno,
                    "taint suppression present but missing its rationale — "
                    "'# lint: taint-ok: <why this flow is sanctioned>'",
                ),
            )

    # -- findings ----------------------------------------------------------

    def _report(
        self,
        fi: FuncInfo,
        lineno: int,
        label: tuple,
        sink_token: str,
        sink_rel: str,
        extra_hops: tuple = (),
    ) -> None:
        if self._line_suppressed(fi, lineno):
            return
        self._note_pending_rationale(fi, lineno)
        hops = extra_hops[:_MAX_HOPS]
        path = f" via {' -> '.join(hops)}" if hops else ""
        where = "" if sink_rel == fi.file.rel else f" in {sink_rel}"
        msg = (
            f"secret flow: {_SRC_DESC.get(label[1], label[1])} "
            f"(source: {label[2]}) reaches {_SINK_DESC.get(sink_token, sink_token)}"
            f"{where} from '{fi.qualname}'{path} — seal/hash the value, keep a "
            "length/type-only projection, route it through telemetry.redact(), "
            "or annotate '# lint: taint-ok: <rationale>'"
        )
        key = (fi.file.rel, lineno, label[1], sink_token, hops)
        if key not in self.findings:
            self.findings[key] = Finding("taint", fi.file.rel, lineno, msg)

    # -- per-function analysis --------------------------------------------

    def analyze(self, fi: FuncInfo) -> tuple[bool, set[tuple[str, str]]]:
        """One (re-)analysis of ``fi``; returns (summary grew, attr keys
        whose global taint grew) so the worklist can requeue dependents."""
        summary = self.summaries[fi.uid]
        before = summary.size()
        self._grew_attrs = set()
        params = _param_names(fi.node)
        env: dict[str, set[tuple]] = {
            name: {("param", fi.uid, i)} for i, name in enumerate(params)
        }
        self._fi = fi
        self._env = env
        self._summary = summary

        # two binding sweeps: flow-insensitive, but later-defined helpers /
        # out-of-order reads stabilize on the second sweep
        for _ in range(2):
            for node in iter_owned_nodes(fi.node):
                self._bind(node, record=False)
        # final sweep records attr stores, sinks, returns
        for node in iter_owned_nodes(fi.node):
            self._bind(node, record=True)
            if isinstance(node, ast.Return) and node.value is not None:
                summary.ret |= self._cap(self.eval(node.value))
            elif isinstance(node, ast.Raise) and node.exc is not None:
                if fi.file.rel.startswith(_RAISE_SINK_TREES):
                    exc = node.exc
                    taint: set[tuple] = set()
                    if isinstance(exc, ast.Call):
                        # the message args, directly: the CapWord
                        # constructor rule would drop positional taint
                        for a in exc.args:
                            taint |= self.eval(a)
                        for kw in exc.keywords:
                            taint |= self.eval(kw.value)
                    else:
                        taint = self.eval(exc)
                    self._sink_value(taint, "exception-message", node.lineno)
            elif isinstance(node, ast.Call):
                self.eval(node)  # standalone/nested calls: sink detection

        grew = summary.size() != before
        if grew:
            self._changed = True
        return grew, self._grew_attrs

    def _cap(self, labels: set[tuple]) -> set[tuple]:
        if len(labels) <= _MAX_LABELS:
            return labels
        return set(sorted(labels)[:_MAX_LABELS])

    def _bind(self, node, record: bool) -> None:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            targets, value = [node.target], node.iter
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    t = self.eval(item.context_expr)
                    self._assign(item.optional_vars, t, record)
            return
        elif isinstance(node, ast.Call) and record:
            # container mutation: self.X.append(secret) / env var likewise
            name, recv = _callee_parts(node)
            if name in ("append", "add", "update", "setdefault", "extend") and recv is not None:
                arg_taint: set[tuple] = set()
                for a in node.args:
                    arg_taint |= self.eval(a)
                for kw in node.keywords:
                    arg_taint |= self.eval(kw.value)
                if arg_taint:
                    self._store_into(recv, arg_taint, node.lineno)
            return
        else:
            return
        if value is None:
            return
        taint = self.eval(value)
        if isinstance(node, ast.AugAssign):
            for t in targets:
                if isinstance(t, ast.Name):
                    taint = taint | self._env.get(t.id, set())
        for t in targets:
            self._assign(t, taint, record, lineno=node.lineno)

    def _assign(self, target, taint: set[tuple], record: bool, lineno: int = 0) -> None:
        if isinstance(target, ast.Name):
            self._env[target.id] = self._cap(taint | (
                self._env.get(target.id, set()) if record else set()
            ))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign(elt, taint, record, lineno)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, taint, record, lineno)
        elif isinstance(target, ast.Subscript):
            # d[k] = secret taints the container
            self._store_into(target.value, taint, lineno)
        elif isinstance(target, ast.Attribute) and record and taint:
            self._store_attr(target, taint, lineno)

    def _store_into(self, container, taint: set[tuple], lineno: int) -> None:
        if not taint:
            return
        if isinstance(container, ast.Name):
            self._env[container.id] = self._cap(
                self._env.get(container.id, set()) | taint
            )
        elif isinstance(container, ast.Attribute):
            self._store_attr(container, taint, lineno)

    def _store_attr(self, target: ast.Attribute, taint: set[tuple], lineno: int) -> None:
        """``self.X = secret`` / ``obj.X[k] = secret``: attr-level tracking.

        Src labels land in the global (class, attr) map; Param labels are
        recorded on the summary so caller-side taint reaches the attr at
        the call site (the fixed point ripples both onward).
        """
        cls = self._recv_class(target.value)
        if cls is None:
            return
        if lineno and self._line_suppressed(self._fi, lineno):
            return  # sanctioned boundary: the store is declassified
        key = (cls, target.attr)
        for label in taint:
            if label[0] == "src":
                bucket = self.attr_taint.setdefault(key, set())
                if label not in bucket:
                    bucket.add(label)
                    self._changed = True
                    self._grew_attrs.add(key)
            elif label[0] == "param" and label[1] == self._fi.uid:
                writes = self._summary.attr_writes.setdefault(label[2], set())
                if key not in writes:
                    writes.add(key)
                    self._changed = True

    def _recv_class(self, recv) -> Optional[str]:
        """Class simple name of an attribute receiver, when known."""
        if _is_self(recv):
            return self._fi.cls
        if isinstance(recv, ast.Name):
            return self.graph._local_types(self._fi).get(recv.id)
        if isinstance(recv, ast.Attribute) and _is_self(recv.value) and self._fi.cls:
            return self.symbols.attr_types.get(
                (self._fi.file.rel, self._fi.cls), {}
            ).get(recv.attr)
        return None

    # -- expression evaluation --------------------------------------------

    def eval(self, node) -> set[tuple]:
        if node is None or isinstance(node, (ast.Constant, ast.Compare)):
            return set()  # comparisons are boolean projections
        if isinstance(node, ast.Name):
            return self._env.get(node.id, set())
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.Await):
            return self.eval(node.value)
        if isinstance(node, (ast.JoinedStr, ast.BoolOp)):
            out: set[tuple] = set()
            for v in node.values:
                out |= self.eval(v)
            return self._cap(out)
        if isinstance(node, ast.FormattedValue):
            return self.eval(node.value)
        if isinstance(node, ast.BinOp):
            return self._cap(self.eval(node.left) | self.eval(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.IfExp):
            return self._cap(self.eval(node.body) | self.eval(node.orelse))
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            out = set()
            for elt in node.elts:
                out |= self.eval(elt)
            return self._cap(out)
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                if k is not None:
                    out |= self.eval(k)
            for v in node.values:
                out |= self.eval(v)
            return self._cap(out)
        if isinstance(node, ast.Subscript):
            return self.eval(node.value)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            return self._eval_comp(node)
        if isinstance(node, ast.Slice):
            return set()
        if isinstance(node, ast.NamedExpr):
            t = self.eval(node.value)
            self._assign(node.target, t, record=False)
            return t
        return set()

    def _eval_comp(self, node) -> set[tuple]:
        saved = dict(self._env)
        try:
            for gen in node.generators:
                t = self.eval(gen.iter)
                self._assign(gen.target, t, record=False)
            if isinstance(node, ast.DictComp):
                return self._cap(self.eval(node.key) | self.eval(node.value))
            return self.eval(node.elt)
        finally:
            self._env = saved

    def _eval_attr(self, node: ast.Attribute) -> set[tuple]:
        recv_taint = self.eval(node.value)
        out = set(recv_taint)
        token = SOURCE_ATTRS.get(node.attr)
        if token is not None and isinstance(node.ctx, ast.Load):
            if not self._line_suppressed(self._fi, node.lineno):
                out.add(_src(token, self._fi.file.rel))
            else:
                self._note_pending_rationale(self._fi, node.lineno)
        cls = self._recv_class(node.value)
        if cls is not None:
            key = (cls, node.attr)
            self._attr_readers.setdefault(key, set()).add(self._fi.uid)
            out |= self.attr_taint.get(key, set())
        return self._cap(out)

    def _eval_call(self, node: ast.Call) -> set[tuple]:
        name, recv = _callee_parts(node)

        # 1) explicit sinks (short-circuit: the API boundary is the sink)
        if self._explicit_sink(node, name, recv):
            return set()

        # 2) declassifiers terminate the flow
        if name in DECLASSIFIERS:
            return set()

        # 3) sources
        if name in SOURCE_CALLS or (
            isinstance(recv, ast.Name) and recv.id in SOURCE_CALLS
        ):
            token = SOURCE_CALLS.get(name) or SOURCE_CALLS[recv.id]
            if self._line_suppressed(self._fi, node.lineno):
                self._note_pending_rationale(self._fi, node.lineno)
                return set()
            return {_src(token, self._fi.file.rel)}

        # 4) resolved project callees: apply summaries
        callees = self._resolve(node, name, recv)
        if callees:
            return self._apply_summaries(node, callees, recv)

        # 5) CapWord constructor of an unresolved class: attr-level only —
        # whole-object taint through constructors drowns the signal, but a
        # kwarg like Masker(seed=...) taints that attribute for the class
        if name and name[:1].isupper():
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                taint = self.eval(kw.value)
                for label in taint:
                    if label[0] != "src":
                        continue
                    bucket = self.attr_taint.setdefault((name, kw.arg), set())
                    if label not in bucket:
                        bucket.add(label)
                        self._changed = True
                        self._grew_attrs.add((name, kw.arg))
            return set()

        # 6) unknown call: conservative union (str(), b"".join, .hex(), ...)
        out: set[tuple] = set()
        if recv is not None:
            out |= self.eval(recv)
        for a in node.args:
            out |= self.eval(a)
        for kw in node.keywords:
            out |= self.eval(kw.value)
        return self._cap(out)

    # -- sinks -------------------------------------------------------------

    def _sink_value(self, taint: set[tuple], token: str, lineno: int,
                    sink_rel: str | None = None, hops: tuple = ()) -> None:
        for label in taint:
            if label[0] == "src":
                self._report(
                    self._fi, lineno, label, token,
                    sink_rel or self._fi.file.rel, hops,
                )
            elif label[0] == "param" and label[1] == self._fi.uid:
                flows = self._summary.sinks.setdefault(label[2], {})
                key = (token, sink_rel or self._fi.file.rel)
                if key not in flows:
                    flows[key] = hops
                    self._changed = True

    @staticmethod
    def _is_logger_recv(recv) -> bool:
        """Every logger spelling the tree uses: a bound module-level name
        (``logger.warning``), the chained form
        (``logging.getLogger(...).warning``), and a logger attribute
        (``self.logger.warning``)."""
        if isinstance(recv, ast.Name):
            return recv.id in _LOG_RECEIVERS
        if isinstance(recv, ast.Attribute):
            return recv.attr in _LOG_RECEIVERS
        if isinstance(recv, ast.Call):
            return _callee_parts(recv)[0] == "getLogger"
        return False

    def _explicit_sink(self, node: ast.Call, name, recv) -> bool:
        if name in _LOG_METHODS and self._is_logger_recv(recv):
            taint: set[tuple] = set()
            for a in node.args:
                taint |= self.eval(a)
            for kw in node.keywords:
                taint |= self.eval(kw.value)
            self._sink_value(taint, "log-call", node.lineno)
            return True
        if name in ("span", "record_span") and recv is not None:
            for kw in node.keywords:
                if kw.arg in ("ctx", "link"):
                    continue
                self._sink_value(self.eval(kw.value), "span-attr", node.lineno)
            return False  # positional args (the name) still evaluate normally
        if name == "set" and recv is not None and node.keywords:
            # span-handle attrs (gauges/events use positional .set(value))
            for kw in node.keywords:
                self._sink_value(self.eval(kw.value), "span-attr", node.lineno)
            return True
        if name == "labels" and recv is not None:
            taint = set()
            for a in node.args:
                taint |= self.eval(a)
            for kw in node.keywords:
                taint |= self.eval(kw.value)
            self._sink_value(taint, "metric-label", node.lineno)
            return True
        if name == "flight_dump":
            taint = set()
            for a in node.args:
                taint |= self.eval(a)
            for kw in node.keywords:
                taint |= self.eval(kw.value)
            self._sink_value(taint, "flight-dump", node.lineno)
            return True
        if name == "render_statusz":
            # the operator console (ISSUE 16): everything flowing into the
            # page builder lands in browser-served HTML
            taint = set()
            for a in node.args:
                taint |= self.eval(a)
            for kw in node.keywords:
                taint |= self.eval(kw.value)
            self._sink_value(taint, "statusz-page", node.lineno)
            return True
        if name == "alerts_payload" and recv is not None:
            # the /alerts JSON body: the engine receiver's state IS the
            # export surface (the builder takes no data args)
            taint = self.eval(recv)
            for a in node.args:
                taint |= self.eval(a)
            self._sink_value(taint, "alerts-payload", node.lineno)
            return True
        if name in ("dump", "dumps") and isinstance(recv, ast.Name):
            dotted = self._fi.file.imports.get(recv.id, recv.id)
            if dotted == "json":
                if node.args:
                    self._sink_value(
                        self.eval(node.args[0]), "serialized-dump", node.lineno
                    )
                return True
        return False

    # -- interprocedural application ---------------------------------------

    def _resolve(self, node: ast.Call, name, recv) -> list[FuncInfo]:
        func = node.func
        if isinstance(func, ast.Name):
            return self.graph._resolve_name(func.id, self._fi)
        if isinstance(func, ast.Attribute):
            return self.graph._resolve_attr_call(
                func, self._fi, self.graph._local_types(self._fi)
            )
        return []

    def _apply_summaries(self, node: ast.Call, callees: list[FuncInfo], recv) -> set[tuple]:
        out: set[tuple] = set()
        recv_taint = self.eval(recv) if recv is not None else set()
        for callee in callees:
            summary = self.summaries.get(callee.uid)
            if summary is None:
                continue
            bound = self._bind_args(node, callee, recv, recv_taint)
            # returns: Src labels hop through the callee; Param labels map
            # back to the bound argument taint
            for label in list(summary.ret):
                if label[0] == "src":
                    out.add(label)
                elif label[0] == "param" and label[1] == callee.uid:
                    out |= bound.get(label[2], set())
            # param -> sink flows: a tainted argument here IS the leak
            for idx, flows in list(summary.sinks.items()):
                arg_taint = bound.get(idx)
                if not arg_taint:
                    continue
                for (token, sink_rel), hops in list(flows.items()):
                    chained = (callee.qualname,) + hops
                    self._sink_value(
                        arg_taint, token, node.lineno, sink_rel, chained[:_MAX_HOPS]
                    )
            # param -> attr writes: caller taint lands on the class attr
            for idx, keys in list(summary.attr_writes.items()):
                arg_taint = bound.get(idx)
                if not arg_taint:
                    continue
                for key in list(keys):
                    for label in list(arg_taint):
                        if label[0] == "src":
                            bucket = self.attr_taint.setdefault(key, set())
                            if label not in bucket:
                                bucket.add(label)
                                self._changed = True
                                self._grew_attrs.add(key)
                        elif label[0] == "param" and label[1] == self._fi.uid:
                            writes = self._summary.attr_writes.setdefault(
                                label[2], set()
                            )
                            if key not in writes:
                                writes.add(key)
                                self._changed = True
        return self._cap(out)

    def _bind_args(
        self, node: ast.Call, callee: FuncInfo, recv, recv_taint: set[tuple]
    ) -> dict[int, set[tuple]]:
        """Call-site taint per callee param index (receiver = param 0 for
        method calls on instances)."""
        args_node = getattr(callee.node, "args", None)
        if args_node is None:
            return {}
        pos_names = [a.arg for a in args_node.posonlyargs + args_node.args]
        names = _param_names(callee.node)
        index_of = {n: i for i, n in enumerate(names)}
        vararg_idx = index_of.get(args_node.vararg.arg) if args_node.vararg else None
        kwarg_idx = index_of.get(args_node.kwarg.arg) if args_node.kwarg else None
        bound: dict[int, set[tuple]] = {}

        def put(idx: Optional[int], taint: set[tuple]) -> None:
            if idx is None or not taint:
                return
            bound[idx] = bound.get(idx, set()) | taint

        offset = 0
        is_method_call = (
            callee.cls is not None
            and isinstance(node.func, ast.Attribute)
            and not (isinstance(recv, ast.Name) and recv.id[:1].isupper())
        )
        if is_method_call:
            put(0, recv_taint)
            offset = 1
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred):
                put(vararg_idx, self.eval(a.value))
                continue
            pos = i + offset
            if pos < len(pos_names):
                put(index_of[pos_names[pos]], self.eval(a))
            else:
                put(vararg_idx, self.eval(a))
        for kw in node.keywords:
            taint = self.eval(kw.value)
            if kw.arg is None:  # **spread
                put(kwarg_idx, taint)
            elif kw.arg in index_of and kw.arg not in (
                args_node.vararg.arg if args_node.vararg else None,
            ):
                put(index_of[kw.arg], taint)
            else:
                put(kwarg_idx, taint)
        return bound

    # -- driver ------------------------------------------------------------

    def run(self) -> list[Finding]:
        """Worklist fixed point: a function re-analyzes only when a callee
        summary or an attribute it reads grew. Taint is monotone over a
        finite lattice, so the queue drains; findings accumulate (a flow
        once found stays found)."""
        from collections import deque

        callers: dict[str, set[str]] = {}
        for uid, outs in self.graph.edges.items():
            for out in outs:
                callers.setdefault(out, set()).add(uid)
        order = [fi.uid for fi in self.symbols.functions]
        queue = deque(order)
        queued = set(order)
        budget = len(order) * _MAX_ITERS * 4  # safety valve, never hit in practice
        while queue and budget > 0:
            budget -= 1
            uid = queue.popleft()
            queued.discard(uid)
            fi = self.symbols.by_uid.get(uid)
            if fi is None:
                continue
            grew, grew_attrs = self.analyze(fi)
            dependents: set[str] = set()
            if grew:
                dependents |= callers.get(uid, set())
            for key in grew_attrs:
                dependents |= self._attr_readers.get(key, set())
            for dep in dependents:
                if dep not in queued:
                    queued.add(dep)
                    queue.append(dep)
        return sorted(
            self.findings.values(), key=lambda f: (f.file, f.line, f.message)
        )


# --- DESIGN.md §18 parity ----------------------------------------------------

_TABLES = (
    ("taint-source-table", "source"),
    ("taint-declassifier-table", "declassifier"),
    ("taint-sink-table", "sink"),
)
_TOKEN_RE = re.compile(r"`([A-Za-z0-9_.\-]+)`")


def _registry_tokens() -> dict[str, set[str]]:
    return {
        "source": set(SOURCE_CALLS.values()) | set(SOURCE_ATTRS.values()),
        "declassifier": set(DECLASSIFIERS),
        "sink": set(SINK_TOKENS),
    }


def documented_tokens(design_text: str) -> dict[str, dict[str, int]]:
    """kind -> {token: first documenting line} from the marked §18 tables.

    Only the FIRST cell of each row carries registry identity; later cells
    are prose (and freely backtick code that is not a registry token).
    """
    out: dict[str, dict[str, int]] = {kind: {} for _, kind in _TABLES}
    active: Optional[str] = None
    for i, line in enumerate(design_text.splitlines(), 1):
        for marker, kind in _TABLES:
            if f"<!-- {marker}:begin -->" in line:
                active = kind
            elif f"<!-- {marker}:end -->" in line:
                active = None
        if active is None or not line.lstrip().startswith("|"):
            continue
        first_cell = line.lstrip().lstrip("|").split("|", 1)[0]
        for token in _TOKEN_RE.findall(first_cell):
            out[active].setdefault(token, i)
    return out


def _parity_findings(design_path) -> list[Finding]:
    findings: list[Finding] = []
    try:
        design_text = design_path.read_text()
    except OSError:
        return [Finding("taint", "docs/DESIGN.md", 1, "docs/DESIGN.md is unreadable")]
    docs = documented_tokens(design_text)
    if not any(docs.values()):
        return [
            Finding(
                "taint",
                "docs/DESIGN.md",
                1,
                "no marked taint tables found (expected "
                "'<!-- taint-source-table:begin -->' ... markers around the "
                "§18 source/declassifier/sink tables)",
            )
        ]
    registry = _registry_tokens()
    for kind in registry:
        for token in sorted(registry[kind] - set(docs[kind])):
            findings.append(
                Finding(
                    "taint",
                    "docs/DESIGN.md",
                    1,
                    f"taint {kind} '{token}' (tools/analysis/taint.py) is not "
                    f"in the DESIGN.md §18 {kind} table (add a row inside the "
                    f"taint-{kind}-table markers)",
                )
            )
        for token, line in sorted(docs[kind].items()):
            if token not in registry[kind]:
                findings.append(
                    Finding(
                        "taint",
                        "docs/DESIGN.md",
                        line,
                        f"documented taint {kind} '{token}' is not in the "
                        "tools/analysis/taint.py registry (stale table row?)",
                    )
                )
    return findings


def run(graph: CallGraph, design_path=None) -> list[Finding]:
    findings = TaintPass(graph).run()
    if design_path is not None:
        findings.extend(_parity_findings(design_path))
    return findings
