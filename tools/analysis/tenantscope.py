"""Tenant-scope pass: tenant-keyed access to round state + sanctioned
page-lease sites.

Multi-tenancy (docs/DESIGN.md §19) turns formerly process-global round
state into per-tenant state: ``Shared``'s round fields (the per-edge seed
watermarks, the resume budget), the accumulator pool's pages, and the
scheduler's fold slots are all keyed by tenant id. A helper that reads
one of these without a tenant in scope is exactly how cross-tenant bleed
starts — an edge watermark checked against the wrong tenant's map, a
page-table probe that aggregates across tenants, a reclaim that frees a
neighbour's pages.

Two legs:

1. **tenant-key-in-scope** — functions under ``xaynet_tpu/server/`` and
   ``xaynet_tpu/parallel/`` that touch tenant-scoped state (the
   ``Shared`` round fields ``edge_watermarks``/``resume_attempts``, or
   the pool's tenant-keyed surface ``page_table``/``balanced``/
   ``reclaim``) must have a tenant key in scope: a parameter named
   ``tenant``, or a ``tenant`` attribute/name read anywhere in the
   function (``self.tenant``, ``shared.tenant``). Sites where the scoping
   is structural (the object itself is per-tenant and no key exists to
   thread) carry ``# lint: tenant-ok: <rationale>`` — the rationale is
   the review record.

2. **sanctioned lease sites** — every ``lease_host``/``lease_device``
   call outside ``xaynet_tpu/tenancy/`` must appear in
   :data:`LEASE_SITES` with a rationale naming its paired release. This
   is the static half of the *leases == releases at round end* invariant:
   the whitelist below is the closed set of places pages enter
   circulation, each reviewed to give them back (unmask release, ring
   close, GC-finalizer backstop, Idle reclaim).
"""

from __future__ import annotations

import ast

from .callgraph import CallGraph, iter_owned_nodes
from .core import Finding, suppressed, suppression_pending_rationale

# Shared round fields + pool surface reads that are tenant-keyed
_SCOPED_ATTRS = frozenset({"edge_watermarks", "resume_attempts"})
_SCOPED_POOL_CALLS = frozenset({"page_table", "balanced", "reclaim"})

_LEASE_CALLS = frozenset({"lease_host", "lease_device"})

# (file, function qualname) -> rationale naming the paired release.
LEASE_SITES: dict[tuple[str, str], str] = {
    ("xaynet_tpu/parallel/streaming.py", "_StagingRing.__init__"):
        "staging ring buffers; released by ring.close() from the "
        "pipeline's close(), GC finalizer as the crash backstop",
    ("xaynet_tpu/parallel/shards.py", "ShardPlan._alloc"):
        "per-shard accumulator/spare buffers; released by "
        "release_pages() from the round's unmask tail, GC finalizer + "
        "Idle reclaim as crash backstops",
    ("xaynet_tpu/parallel/shards.py", "ShardPlan.__init__"):
        "device-ledger lease for the plan's HBM footprint; released with "
        "release_pages() exactly like the host buffers",
}

_PREFIXES = ("xaynet_tpu/server/", "xaynet_tpu/parallel/")


def _qualname_chain(qualname: str) -> list[str]:
    parts = qualname.split(".")
    return [".".join(parts[:i]) for i in range(len(parts), 0, -1)]


def _has_tenant_key(fi) -> bool:
    """A tenant key in scope: a param named ``tenant``, or any read of a
    ``tenant`` name/attribute inside the function body."""
    args = fi.node.args
    for a in (
        *args.posonlyargs, *args.args, *args.kwonlyargs,
        *( [args.vararg] if args.vararg else [] ),
        *( [args.kwarg] if args.kwarg else [] ),
    ):
        if a.arg == "tenant":
            return True
    for node in iter_owned_nodes(fi.node):
        if isinstance(node, ast.Attribute) and node.attr == "tenant":
            return True
        if isinstance(node, ast.Name) and node.id == "tenant":
            return True
    return False


def run(graph: CallGraph) -> list[Finding]:
    findings: list[Finding] = []
    for fi in graph.symbols.functions:
        rel = fi.file.rel
        if rel.startswith("xaynet_tpu/tenancy/"):
            continue  # the pool/scheduler themselves
        in_scope_tree = rel.startswith(_PREFIXES)
        lease_allowed = any(
            (rel, q) in LEASE_SITES for q in _qualname_chain(fi.qualname)
        )
        tenant_keyed: bool | None = None  # computed lazily per function
        for node in iter_owned_nodes(fi.node):
            # -- leg 2: sanctioned lease sites (whole xaynet_tpu tree) ----
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LEASE_CALLS
                and not lease_allowed
            ):
                line = fi.file.line(node.lineno)
                if suppressed("tenant", line):
                    continue
                msg = (
                    f"page lease ({node.func.attr}) outside the sanctioned "
                    f"sites (in '{fi.qualname}') — every lease site must "
                    "pair with a release for the leases == releases round "
                    "invariant (DESIGN §19); add the site to "
                    "tools/analysis/tenantscope.py LEASE_SITES with its "
                    "paired release, or annotate "
                    "'# lint: tenant-ok: <rationale>'"
                )
                if suppression_pending_rationale("tenant", line):
                    msg += " [suppression present but missing its rationale]"
                findings.append(Finding("tenant", rel, node.lineno, msg))
                continue
            if not in_scope_tree:
                continue
            # -- leg 1: tenant key in scope ------------------------------
            scoped = None
            if isinstance(node, ast.Attribute) and node.attr in _SCOPED_ATTRS:
                # skip the dataclass field DEFINITIONS (AnnAssign targets
                # at class scope are not owned by any function, so they
                # never reach here anyway)
                scoped = node.attr
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SCOPED_POOL_CALLS
            ):
                scoped = f"{node.func.attr}()"
            if scoped is None:
                continue
            if tenant_keyed is None:
                tenant_keyed = _has_tenant_key(fi)
            if tenant_keyed:
                continue
            line = fi.file.line(node.lineno)
            if suppressed("tenant", line):
                continue
            msg = (
                f"tenant-scoped state ({scoped}) read in '{fi.qualname}' "
                "with no tenant key in scope — thread the tenant id (or "
                "read it: self.tenant / shared.tenant) so the access is "
                "visibly scoped, or annotate "
                "'# lint: tenant-ok: <rationale>' (DESIGN §19)"
            )
            if suppression_pending_rationale("tenant", line):
                msg += " [suppression present but missing its rationale]"
            findings.append(Finding("tenant", rel, node.lineno, msg))
    return findings
