"""Tenant-scope pass: tenant-keyed access to round state + sanctioned
page-lease sites.

Multi-tenancy (docs/DESIGN.md §19) turns formerly process-global round
state into per-tenant state: ``Shared``'s round fields (the per-edge seed
watermarks, the resume budget), the accumulator pool's pages, and the
scheduler's fold slots are all keyed by tenant id. A helper that reads
one of these without a tenant in scope is exactly how cross-tenant bleed
starts — an edge watermark checked against the wrong tenant's map, a
page-table probe that aggregates across tenants, a reclaim that frees a
neighbour's pages.

Two legs:

1. **tenant-key-in-scope** — functions under ``xaynet_tpu/server/`` and
   ``xaynet_tpu/parallel/`` that touch tenant-scoped state (the
   ``Shared`` round fields ``edge_watermarks``/``resume_attempts``, or
   the pool's tenant-keyed surface ``page_table``/``balanced``/
   ``reclaim``) must have a tenant key in scope: a parameter named
   ``tenant``, or a ``tenant`` attribute/name read anywhere in the
   function (``self.tenant``, ``shared.tenant``). Sites where the scoping
   is structural (the object itself is per-tenant and no key exists to
   thread) carry ``# lint: tenant-ok: <rationale>`` — the rationale is
   the review record.

2. **sanctioned lease sites** — every ``lease_host``/``lease_device``
   call outside ``xaynet_tpu/tenancy/`` must appear in
   :data:`LEASE_SITES` with a rationale naming its paired release. This
   is the static half of the *leases == releases at round end* invariant:
   the whitelist below is the closed set of places pages enter
   circulation, each reviewed to give them back (unmask release, ring
   close, GC-finalizer backstop, Idle reclaim).

3. **admin-path lock discipline** — the elastic lifecycle manager
   (``tenancy/lifecycle.py``, §23) mutates the registry, the live routing
   dict, the scheduler's weight/tier/demotion maps and the pool from the
   admin REST path *while rounds are running*. Every such mutation must
   be lexically inside a ``with``/``async with`` on a lock-named
   attribute (``*_lock`` / ``*_cond``), or carry a ``# guarded-by:
   <lock>`` annotation recording which lock the callee takes internally.
   Functions named ``*_locked`` are exempt (the caller holds the lock —
   the repo-wide convention).

4. **sanctioned migration sites** — compaction moves a page run and
   swaps ``lease.array`` under the pool lock, so every place *outside*
   ``xaynet_tpu/tenancy/`` that registers or clears a lease's
   ``migrator`` (``set_migrator`` calls, ``.migrator`` stores) must
   appear in :data:`MIGRATION_SITES` with a rationale proving the buffer
   is quiescent when movable and pinned before any access.
"""

from __future__ import annotations

import ast
import re

from .callgraph import CallGraph, iter_owned_nodes
from .core import Finding, suppressed, suppression_pending_rationale

# Shared round fields + pool surface reads that are tenant-keyed
_SCOPED_ATTRS = frozenset({"edge_watermarks", "resume_attempts"})
_SCOPED_POOL_CALLS = frozenset({"page_table", "balanced", "reclaim"})

_LEASE_CALLS = frozenset({"lease_host", "lease_device"})

# (file, function qualname) -> rationale naming the paired release.
LEASE_SITES: dict[tuple[str, str], str] = {
    ("xaynet_tpu/parallel/streaming.py", "_StagingRing.__init__"):
        "staging ring buffers; released by ring.close() from the "
        "pipeline's close(), GC finalizer as the crash backstop",
    ("xaynet_tpu/parallel/shards.py", "ShardPlan._alloc"):
        "per-shard accumulator/spare buffers; released by "
        "release_pages() from the round's unmask tail, GC finalizer + "
        "Idle reclaim as crash backstops",
    ("xaynet_tpu/parallel/shards.py", "ShardPlan.__init__"):
        "device-ledger lease for the plan's HBM footprint; released with "
        "release_pages() exactly like the host buffers",
}

_PREFIXES = ("xaynet_tpu/server/", "xaynet_tpu/parallel/")

# -- leg 3: admin-path lock discipline ----------------------------------------

_ADMIN_FILE = "xaynet_tpu/tenancy/lifecycle.py"
# attribute calls that mutate shared registry/routes/scheduler/pool/budget
# state from the admin path
_ADMIN_MUTATORS = frozenset({
    "add", "remove", "pop", "set_weight", "set_tier", "set_demoted",
    "forget_tenant", "reclaim", "compact", "discharge",
})
# accepts dotted guards ("pool._lock") unlike the locks pass — here the
# annotation is a review record of which lock the CALLEE takes internally
_GUARDED_ANNOT_RE = re.compile(r"#\s*guarded-by:\s*([\w.\-]+)")
_LOCK_NAME_RE = re.compile(r"(_lock|_cond)$")

# -- leg 4: sanctioned migration sites ----------------------------------------

# (file, function qualname) -> rationale proving the quiescence protocol.
MIGRATION_SITES: dict[tuple[str, str], str] = {
    ("xaynet_tpu/parallel/streaming.py", "_StagingRing.__init__"):
        "free ring buffers opt in at construction, before any is handed "
        "out; acquire() pins before the first access",
    ("xaynet_tpu/parallel/streaming.py", "_StagingRing.acquire"):
        "clears the migrator THROUGH the pool lock before reading "
        "lease.array — an in-flight buffer is an immovable barrier",
    ("xaynet_tpu/parallel/streaming.py", "_StagingRing.release"):
        "re-registers the migrator as the buffer re-enters the free "
        "queue (quiescent again)",
}


def _lockish_with_held(fn_node) -> dict[int, bool]:
    """node id -> whether the node sits lexically inside a ``with`` /
    ``async with`` whose context expression's terminal name looks like a
    lock (``*_lock`` / ``*_cond``)."""
    held_at: dict[int, bool] = {}

    def terminal_name(expr):
        if isinstance(expr, ast.Attribute):
            return expr.attr
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Call):
            return terminal_name(expr.func)
        return None

    def walk(node, held: bool):
        for child in ast.iter_child_nodes(node):
            child_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    name = terminal_name(item.context_expr)
                    if name and _LOCK_NAME_RE.search(name):
                        child_held = True
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # separate FuncInfo, analyzed on its own
            held_at[id(child)] = child_held
            walk(child, child_held)

    held_at[id(fn_node)] = False
    walk(fn_node, False)
    return held_at


def _qualname_chain(qualname: str) -> list[str]:
    parts = qualname.split(".")
    return [".".join(parts[:i]) for i in range(len(parts), 0, -1)]


def _has_tenant_key(fi) -> bool:
    """A tenant key in scope: a param named ``tenant``, or any read of a
    ``tenant`` name/attribute inside the function body."""
    args = fi.node.args
    for a in (
        *args.posonlyargs, *args.args, *args.kwonlyargs,
        *( [args.vararg] if args.vararg else [] ),
        *( [args.kwarg] if args.kwarg else [] ),
    ):
        if a.arg == "tenant":
            return True
    for node in iter_owned_nodes(fi.node):
        if isinstance(node, ast.Attribute) and node.attr == "tenant":
            return True
        if isinstance(node, ast.Name) and node.id == "tenant":
            return True
    return False


def _admin_lock_findings(fi) -> list[Finding]:
    """Leg 3: every admin-path mutation in the lifecycle manager must be
    under a lock-named ``with`` or carry a ``# guarded-by:`` record."""
    if fi.name == "__init__" or fi.name.endswith("_locked"):
        return []
    findings: list[Finding] = []
    held_at = _lockish_with_held(fi.node)
    for node in iter_owned_nodes(fi.node):
        mutator = None
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _ADMIN_MUTATORS
        ):
            mutator = f"{node.func.attr}()"
        elif (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, (ast.Store, ast.Del))
            and isinstance(node.value, ast.Attribute)
        ):
            mutator = f"{node.value.attr}[...]"
        if mutator is None:
            continue
        if held_at.get(id(node), False):
            continue
        line = fi.file.line(node.lineno)
        if _GUARDED_ANNOT_RE.search(line):
            continue
        if suppressed("tenant", line):
            continue
        msg = (
            f"admin-path mutation ({mutator}) in '{fi.qualname}' outside "
            "any lock-named 'with' block — the lifecycle mutates live "
            "routing/registry/scheduler/pool state while rounds run "
            "(DESIGN §23); hold the lock, or annotate the line "
            "'# guarded-by: <lock>' naming the lock the callee takes, or "
            "'# lint: tenant-ok: <rationale>'"
        )
        if suppression_pending_rationale("tenant", line):
            msg += " [suppression present but missing its rationale]"
        findings.append(Finding("tenant", fi.file.rel, node.lineno, msg))
    return findings


def run(graph: CallGraph) -> list[Finding]:
    findings: list[Finding] = []
    for fi in graph.symbols.functions:
        rel = fi.file.rel
        if rel.startswith("xaynet_tpu/tenancy/"):
            if rel == _ADMIN_FILE:
                findings.extend(_admin_lock_findings(fi))
            continue  # the pool/scheduler themselves
        in_scope_tree = rel.startswith(_PREFIXES)
        lease_allowed = any(
            (rel, q) in LEASE_SITES for q in _qualname_chain(fi.qualname)
        )
        migration_allowed = any(
            (rel, q) in MIGRATION_SITES for q in _qualname_chain(fi.qualname)
        )
        tenant_keyed: bool | None = None  # computed lazily per function
        for node in iter_owned_nodes(fi.node):
            # -- leg 2: sanctioned lease sites (whole xaynet_tpu tree) ----
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LEASE_CALLS
                and not lease_allowed
            ):
                line = fi.file.line(node.lineno)
                if suppressed("tenant", line):
                    continue
                msg = (
                    f"page lease ({node.func.attr}) outside the sanctioned "
                    f"sites (in '{fi.qualname}') — every lease site must "
                    "pair with a release for the leases == releases round "
                    "invariant (DESIGN §19); add the site to "
                    "tools/analysis/tenantscope.py LEASE_SITES with its "
                    "paired release, or annotate "
                    "'# lint: tenant-ok: <rationale>'"
                )
                if suppression_pending_rationale("tenant", line):
                    msg += " [suppression present but missing its rationale]"
                findings.append(Finding("tenant", rel, node.lineno, msg))
                continue
            # -- leg 4: sanctioned migration sites (whole xaynet_tpu tree)
            migration = None
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "set_migrator"
            ):
                migration = "set_migrator()"
            elif (
                isinstance(node, ast.Attribute)
                and node.attr == "migrator"
                and isinstance(node.ctx, (ast.Store, ast.Del))
            ):
                migration = ".migrator ="
            if migration is not None and not migration_allowed:
                line = fi.file.line(node.lineno)
                if suppressed("tenant", line):
                    continue
                msg = (
                    f"compaction migrator toggled ({migration}) outside the "
                    f"sanctioned sites (in '{fi.qualname}') — a migrator "
                    "marks a page run MOVABLE, so the site must prove the "
                    "buffer is quiescent while registered and pinned before "
                    "any access (DESIGN §23); add the site to "
                    "tools/analysis/tenantscope.py MIGRATION_SITES with its "
                    "quiescence rationale, or annotate "
                    "'# lint: tenant-ok: <rationale>'"
                )
                if suppression_pending_rationale("tenant", line):
                    msg += " [suppression present but missing its rationale]"
                findings.append(Finding("tenant", rel, node.lineno, msg))
                continue
            if not in_scope_tree:
                continue
            # -- leg 1: tenant key in scope ------------------------------
            scoped = None
            if isinstance(node, ast.Attribute) and node.attr in _SCOPED_ATTRS:
                # skip the dataclass field DEFINITIONS (AnnAssign targets
                # at class scope are not owned by any function, so they
                # never reach here anyway)
                scoped = node.attr
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SCOPED_POOL_CALLS
            ):
                scoped = f"{node.func.attr}()"
            if scoped is None:
                continue
            if tenant_keyed is None:
                tenant_keyed = _has_tenant_key(fi)
            if tenant_keyed:
                continue
            line = fi.file.line(node.lineno)
            if suppressed("tenant", line):
                continue
            msg = (
                f"tenant-scoped state ({scoped}) read in '{fi.qualname}' "
                "with no tenant key in scope — thread the tenant id (or "
                "read it: self.tenant / shared.tenant) so the access is "
                "visibly scoped, or annotate "
                "'# lint: tenant-ok: <rationale>' (DESIGN §19)"
            )
            if suppression_pending_rationale("tenant", line):
                msg += " [suppression present but missing its rationale]"
            findings.append(Finding("tenant", rel, node.lineno, msg))
    return findings
