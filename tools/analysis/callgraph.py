"""Project-wide symbol table and call graph.

Conservative, name-based resolution over the shared per-file ASTs:

- every ``def``/``async def``/``lambda`` becomes a :class:`FuncInfo` with
  a qualname and (when lexically inside a class) its class;
- call *and bare reference* edges — passing ``self._worker`` to
  ``Thread(target=...)`` or ``_prog_derive`` to ``jax.vmap`` is an edge,
  which is what lets reachability see through higher-order wrappers
  (``vmap``/``scan``/``shard_map``/executor ``submit``/``map``);
- attribute calls resolve through a small flow-insensitive type sketch:
  ``self.x`` types recorded from ``self.x = ClassName(...)`` assignments
  and annotations, parameter/return annotations, and local
  ``v = ClassName(...)`` / ``v = self.x`` assignments. Receivers typed to
  an *external* module (numpy, jax, stdlib) produce no edge;
- unresolvable attribute calls fall back to a global method-name match,
  dropped entirely when more than :data:`AMBIGUITY_CUTOFF` definitions
  share the name (a ``.get``/``.close`` edge to thirty classes would make
  reachability meaningless). This trades a sliver of soundness for a
  usable signal; docs/DESIGN.md §14 records the limitation.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .cache import FileInfo

AMBIGUITY_CUTOFF = 6

# Ubiquitous object-lifecycle/container verbs are excluded from the
# *untyped-receiver* fallback: `t.start()` on a stdlib Thread must not
# edge into every project class with a `start`. Typed receivers (the
# attr/local sketch) still resolve these precisely.
_FALLBACK_STOPLIST = frozenset(
    {
        "start", "stop", "run", "close", "join", "get", "put", "append",
        "clear", "update", "pop", "read", "write", "send", "recv",
        "acquire", "release", "set", "inc", "dec", "labels", "observe",
        "items", "values", "keys", "encode", "decode", "copy", "add",
    }
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class FuncInfo:
    """One function/method/lambda definition."""

    __slots__ = ("node", "name", "qualname", "cls", "file", "uid", "returns")

    def __init__(self, node, name: str, qualname: str, cls: Optional[str], file: FileInfo):
        self.node = node
        self.name = name
        self.qualname = qualname
        self.cls = cls  # nearest lexically-enclosing class, or None
        self.file = file
        self.uid = f"{file.rel}::{qualname}"
        self.returns = None  # simple return-annotation class name, or None
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.returns = _ann_name(node.returns)

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<FuncInfo {self.uid}>"


def _ann_name(ann) -> Optional[str]:
    """Best-effort class name out of an annotation node (``Foo``,
    ``"Foo"``, ``mod.Foo``, ``Optional[Foo]`` -> ``Foo``)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Subscript):  # Optional[Foo] / weakref.ref[Foo]
        base = _ann_name(ann.value)
        if base in ("Optional", "ref"):
            return _ann_name(ann.slice)
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):  # Foo | None
        return _ann_name(ann.left) or _ann_name(ann.right)
    return None


def iter_owned_nodes(fn_node):
    """Walk a function's body, NOT descending into nested defs/lambdas
    (those are their own FuncInfos)."""
    stack = list(ast.iter_child_nodes(fn_node))
    if isinstance(fn_node, ast.Lambda):
        stack = [fn_node.body]
    for default in getattr(getattr(fn_node, "args", None), "defaults", []) or []:
        stack.append(default)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


class SymbolTable:
    """Indexes over every FuncInfo and class in the analyzed tree."""

    def __init__(self, files: list[FileInfo]):
        self.files = [f for f in files if f.tree is not None and f.rel.endswith(".py")]
        self.functions: list[FuncInfo] = []
        self.by_uid: dict[str, FuncInfo] = {}
        self.by_name: dict[str, list[FuncInfo]] = {}
        # (rel, class) -> {method name -> FuncInfo}
        self.class_methods: dict[tuple[str, str], dict[str, FuncInfo]] = {}
        # module -> {top-level function name -> FuncInfo}
        self.module_funcs: dict[str, dict[str, FuncInfo]] = {}
        # class simple name -> [(rel, class)]
        self.classes: dict[str, list[tuple[str, str]]] = {}
        # (rel, class) -> {self attr -> class simple name}
        self.attr_types: dict[tuple[str, str], dict[str, str]] = {}
        # FuncInfo containing each ast function node (for parent lookups)
        self.node_owner: dict[int, FuncInfo] = {}
        for f in self.files:
            self._index_file(f)

    # -- construction ------------------------------------------------------

    def _index_file(self, f: FileInfo) -> None:
        def visit(node, qual: list[str], cls: Optional[str], depth: int):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self.classes.setdefault(child.name, []).append((f.rel, child.name))
                    self.class_methods.setdefault((f.rel, child.name), {})
                    self.attr_types.setdefault((f.rel, child.name), {})
                    visit(child, qual + [child.name], child.name, depth)
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = ".".join(qual + [child.name])
                    fi = FuncInfo(child, child.name, qn, cls, f)
                    self._add(fi, depth, qual)
                    visit(child, qual + [child.name], cls, depth + 1)
                elif isinstance(child, ast.Lambda):
                    # lambdas hide anywhere (args to Thread/vmap/map, defaults)
                    name = f"<lambda:{child.lineno}>"
                    fi = FuncInfo(child, name, ".".join(qual + [name]), cls, f)
                    self._add(fi, depth + 1, qual)
                    visit(child, qual, cls, depth + 1)
                else:
                    visit(child, qual, cls, depth)

        visit(f.tree, [], None, 0)
        # self-attribute type sketch: self.x = ClassName(...) / self.x: T
        for (rel, cls), methods in self.class_methods.items():
            if rel != f.rel:
                continue
            sketch = self.attr_types[(rel, cls)]
            for fi in methods.values():
                args = getattr(fi.node, "args", None)
                param_types: dict[str, str] = {}
                if args is not None:
                    for a in args.args + args.posonlyargs + args.kwonlyargs:
                        ann = _ann_name(a.annotation)
                        if ann:
                            param_types[a.arg] = ann
                for node in iter_owned_nodes(fi.node):
                    target = None
                    value = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        target, value = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign):
                        target, value = node.target, node.value
                        if isinstance(target, ast.Attribute) and _is_self(target.value):
                            ann = _ann_name(node.annotation)
                            if ann:
                                sketch.setdefault(target.attr, ann)
                    if not (isinstance(target, ast.Attribute) and _is_self(target.value)):
                        continue
                    if isinstance(value, ast.Call):
                        cname = _call_class_name(value)
                        if cname:
                            sketch.setdefault(target.attr, cname)
                    elif isinstance(value, ast.Name) and value.id in param_types:
                        # self.plan = plan, with `plan: Plan` in the signature
                        sketch.setdefault(target.attr, param_types[value.id])

    def _add(self, fi: FuncInfo, depth: int, qual: list[str]) -> None:
        self.functions.append(fi)
        self.by_uid[fi.uid] = fi
        self.by_name.setdefault(fi.name, []).append(fi)
        self.node_owner[id(fi.node)] = fi
        if fi.cls is not None and qual and qual[-1] == fi.cls:
            self.class_methods.setdefault((fi.file.rel, fi.cls), {})[fi.name] = fi
        elif depth == 0 and not qual:
            self.module_funcs.setdefault(fi.file.module, {})[fi.name] = fi

    # -- queries -----------------------------------------------------------

    def method(self, rel: str, cls: str, name: str) -> Optional[FuncInfo]:
        return self.class_methods.get((rel, cls), {}).get(name)

    def methods_named(self, name: str) -> list[FuncInfo]:
        return [fi for fi in self.by_name.get(name, []) if fi.cls is not None]

    def class_named(self, name: str) -> list[tuple[str, str]]:
        return self.classes.get(name, [])


def _is_self(node) -> bool:
    return isinstance(node, ast.Name) and node.id == "self"


def _is_none(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _call_class_name(call: ast.Call) -> Optional[str]:
    """``ClassName(...)`` / ``mod.ClassName(...)`` -> "ClassName" when it
    looks like a class construction (CapWord heuristic)."""
    func = call.func
    name = None
    if isinstance(func, ast.Name):
        name = func.id
    elif isinstance(func, ast.Attribute):
        name = func.attr
    if name and name[:1].isupper():
        return name
    return None


class CallGraph:
    """Edges (including bare references) between FuncInfos."""

    def __init__(self, symbols: SymbolTable):
        self.symbols = symbols
        self._types_memo: dict[str, dict[str, str]] = {}
        self.edges: dict[str, set[str]] = {}
        for fi in symbols.functions:
            self.edges[fi.uid] = self._edges_of(fi)

    # -- per-function local type sketch ------------------------------------

    def _local_types(self, fi: FuncInfo) -> dict[str, str]:
        """variable -> class simple name, from annotations and trivial
        assignments (flow-insensitive: last writer wins is fine here)."""
        memo = self._types_memo.get(fi.uid)
        if memo is not None:
            return memo
        types: dict[str, str] = {}
        self._types_memo[fi.uid] = types
        node = fi.node
        args = getattr(node, "args", None)
        if args is not None:
            for a in args.args + args.posonlyargs + args.kwonlyargs:
                ann = _ann_name(a.annotation)
                if ann:
                    types[a.arg] = ann
        cls_sketch = (
            self.symbols.attr_types.get((fi.file.rel, fi.cls), {}) if fi.cls else {}
        )
        for sub in iter_owned_nodes(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t, v = sub.targets[0], sub.value
                if not isinstance(t, ast.Name):
                    continue
                if isinstance(v, ast.IfExp):  # X(...) if cond else None
                    v = v.body if not _is_none(v.body) else v.orelse
                if isinstance(v, ast.Call):
                    cname = _call_class_name(v)
                    if cname:
                        types[t.id] = cname
                        continue
                    callee = v.func
                    # self = ref() — the weakref-deref worker idiom: calling
                    # a ref[T]-typed name yields a T
                    if isinstance(callee, ast.Name) and callee.id in types:
                        types[t.id] = types[callee.id]
                        continue
                    # v = self.meth(...) with a return annotation
                    if (
                        isinstance(callee, ast.Attribute)
                        and _is_self(callee.value)
                        and fi.cls
                    ):
                        m = self.symbols.method(fi.file.rel, fi.cls, callee.attr)
                        if m and m.returns:
                            types[t.id] = m.returns
                elif isinstance(v, ast.Attribute) and _is_self(v.value):
                    cname = cls_sketch.get(v.attr)
                    if cname:
                        types[t.id] = cname
        return types

    # -- edge construction -------------------------------------------------

    def _resolve_class_method(self, cname: str, meth: str, near: FileInfo) -> list[FuncInfo]:
        """Methods named ``meth`` on classes named ``cname`` (same file
        preferred, then anywhere)."""
        hits = []
        for rel, cls in self.symbols.class_named(cname):
            m = self.symbols.method(rel, cls, meth)
            if m is not None:
                hits.append(m)
        same = [m for m in hits if m.file.rel == near.rel]
        return same or hits

    def _resolve_name(self, name: str, fi: FuncInfo) -> list[FuncInfo]:
        """A bare ``Name`` in fi's body: closure-visible nested defs,
        module functions, then the import table."""
        # closure scoping: a bare name binds to a def whose PARENT is one
        # of fi's enclosing *function* scopes (dot-boundary match — a bald
        # startswith would let `Cls.other.helper` shadow a module-level
        # `helper` called from `Cls.body`; class scopes don't leak into
        # methods, so the parent must itself be a FuncInfo)
        parts = fi.qualname.split(".")
        scopes = {".".join(parts[:i]) for i in range(1, len(parts) + 1)}
        for cand in self.symbols.by_name.get(name, []):
            if cand.file.rel != fi.file.rel or cand.uid == fi.uid:
                continue
            parent = cand.qualname.rsplit(".", 1)[0] if "." in cand.qualname else ""
            if (
                parent
                and parent in scopes
                and f"{cand.file.rel}::{parent}" in self.symbols.by_uid
            ):
                return [cand]
        mod = self.symbols.module_funcs.get(fi.file.module, {})
        if name in mod:
            return [mod[name]]
        dotted = fi.file.imports.get(name)
        if dotted:
            mod_name, _, attr = dotted.rpartition(".")
            target = self.symbols.module_funcs.get(mod_name, {}).get(attr)
            if target is not None:
                return [target]
            return []  # external import — no project edge
        return []

    def _resolve_attr_call(self, node: ast.Attribute, fi: FuncInfo, types: dict) -> list[FuncInfo]:
        meth = node.attr
        recv = node.value
        # self.meth()
        if _is_self(recv) and fi.cls:
            m = self.symbols.method(fi.file.rel, fi.cls, meth)
            if m is not None:
                return [m]
            return self._fallback(meth)
        # NAME.meth() — typed local, imported module, or fallback
        if isinstance(recv, ast.Name):
            cname = types.get(recv.id)
            if cname:
                hits = self._resolve_class_method(cname, meth, fi.file)
                if hits:
                    return hits
                return []  # typed to a class without that method: no edge
            dotted = fi.file.imports.get(recv.id)
            if dotted is not None:
                target = self.symbols.module_funcs.get(dotted, {}).get(meth)
                return [target] if target is not None else []
            return self._fallback(meth)
        # self.attr.meth() — via the class attr sketch
        if (
            isinstance(recv, ast.Attribute)
            and _is_self(recv.value)
            and fi.cls is not None
        ):
            cname = self.symbols.attr_types.get((fi.file.rel, fi.cls), {}).get(recv.attr)
            if cname:
                hits = self._resolve_class_method(cname, meth, fi.file)
                if hits:
                    return hits
                return []
        return self._fallback(meth)

    def _fallback(self, meth: str) -> list[FuncInfo]:
        if meth in _FALLBACK_STOPLIST:
            return []
        cands = self.symbols.methods_named(meth)
        if 0 < len(cands) <= AMBIGUITY_CUTOFF:
            return cands
        return []

    def _edges_of(self, fi: FuncInfo) -> set[str]:
        out: set[str] = set()
        types = self._local_types(fi)
        for node in iter_owned_nodes(fi.node):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Name):
                    for t in self._resolve_name(func.id, fi):
                        out.add(t.uid)
                elif isinstance(func, ast.Attribute):
                    for t in self._resolve_attr_call(func, fi, types):
                        out.add(t.uid)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                # bare reference: f passed to vmap/scan/Thread/submit/...
                for t in self._resolve_name(node.id, fi):
                    out.add(t.uid)
            elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
                # self.meth / obj.meth referenced without a call
                parent_is_call = False  # handled above when it IS the callee
                if not parent_is_call and _is_self(node.value) and fi.cls:
                    m = self.symbols.method(fi.file.rel, fi.cls, node.attr)
                    if m is not None:
                        out.add(m.uid)
            elif isinstance(node, _FUNC_NODES):
                # owning a nested def/lambda counts as referencing it
                owner = self.symbols.node_owner.get(id(node))
                if owner is not None:
                    out.add(owner.uid)
        return out

    # -- reachability ------------------------------------------------------

    def reachable(self, roots: Iterable[FuncInfo], through_async: bool = True) -> set[str]:
        """Transitive closure over call/reference edges.

        ``through_async=False`` stops at coroutine boundaries: entering an
        ``async def`` means execution moved onto an event loop (whatever
        thread hosts it), so event-loop-confinement checks must not follow
        the edge. Lock-discipline checks DO follow it — coroutine code
        races against worker threads on lock-guarded state just fine.
        """
        seen: set[str] = set()
        stack = [r.uid for r in roots]
        while stack:
            uid = stack.pop()
            if uid in seen:
                continue
            seen.add(uid)
            for nxt in self.edges.get(uid, ()):
                if not through_async:
                    fi = self.symbols.by_uid.get(nxt)
                    if fi is not None and isinstance(fi.node, ast.AsyncFunctionDef):
                        continue
                stack.append(nxt)
        return seen


def thread_entry_points(graph: CallGraph) -> list[FuncInfo]:
    """Worker-thread entry points, project-wide:

    - ``threading.Thread(target=X)`` (any spelling of ``Thread``);
    - ``<executor>.submit(X, ...)`` / ``<executor>.map(X, ...)``;
    - ``loop.run_in_executor(pool, X, ...)``.

    ``X`` resolves like any reference (names, ``self.meth``, lambdas);
    lambdas become entries themselves so their bodies are analyzed.
    Memoized per graph (several passes ask).
    """
    memo = getattr(graph, "_entries_memo", None)
    if memo is not None:
        return memo
    symbols = graph.symbols
    entries: list[FuncInfo] = []

    def resolve_target(expr, fi: FuncInfo) -> list[FuncInfo]:
        if isinstance(expr, ast.Lambda):
            owner = symbols.node_owner.get(id(expr))
            return [owner] if owner is not None else []
        if isinstance(expr, ast.Name):
            return graph._resolve_name(expr.id, fi)
        if isinstance(expr, ast.Attribute):
            return graph._resolve_attr_call(expr, fi, graph._local_types(fi))
        return []

    for fi in symbols.functions:
        for node in iter_owned_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            callee = (
                func.attr
                if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            targets: list = []
            if callee == "Thread":
                targets = [kw.value for kw in node.keywords if kw.arg == "target"]
            elif callee in ("submit", "run_in_executor") and node.args:
                idx = 1 if callee == "run_in_executor" and len(node.args) > 1 else 0
                targets = [node.args[idx]]
            elif callee == "map" and isinstance(func, ast.Attribute) and node.args:
                # executor .map only — builtin map(fn, ...) runs inline
                targets = [node.args[0]]
            for t_expr in targets:
                entries.extend(resolve_target(t_expr, fi))
    # dedupe, stable order
    seen: set[str] = set()
    out = []
    for e in entries:
        if e.uid not in seen:
            seen.add(e.uid)
            out.append(e)
    graph._entries_memo = out
    return out
