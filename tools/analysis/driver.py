"""Analysis driver: discovery, parallel per-file stage, deep passes,
baseline gating, ``--changed`` mode, JSON/human output.

``tools/lint.py`` is the CLI entry point (the tier-1/CI invocation is
unchanged); it delegates here. Flow:

1. discover files (the classic lint targets);
2. per-file rules, in parallel, through the persistent result cache
   (content-hash keyed, invalidated by the analyzer's own digest);
3. when the run covers the default full tree: the four deep passes
   (lock discipline, call-graph purity, accounting invariants, metrics
   cross-check), memoized as one unit keyed by the whole-tree digest;
4. baseline split: baselined findings report as *masked* and don't fail
   the gate; everything else does.

``--changed`` restricts *reporting and per-file work* to files differing
from the merge-base with the upstream (or the working-tree diff when
there is no upstream); the deep passes still see the whole tree — they
are cross-file by definition — but their findings are filtered the same
way, and the caches keep the whole thing fast.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import hashlib
import json
import os
import subprocess
import sys
from pathlib import Path

from . import filerules, invariants, locks, metricscheck, purity, spans, taint, tenantscope
from .cache import ResultCache, SourceCache
from .callgraph import CallGraph, SymbolTable
from .core import Baseline, Finding

DEFAULT_TARGETS = [
    "xaynet_tpu",
    "tests",
    "tools",
    "examples",
    "bench.py",
    "__graft_entry__.py",
    "conftest.py",
]

CACHE_NAME = ".lint-cache.json"
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"


def discover(repo: Path, targets: list[str] | None) -> list[Path]:
    files: list[Path] = []
    for t in targets or DEFAULT_TARGETS:
        p = (repo / t) if not Path(t).is_absolute() else Path(t)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.exists():
            files.append(p)
    return files


def changed_files(repo: Path) -> set[str] | None:
    """Repo-relative paths differing from the upstream merge-base, plus
    working-tree modifications; None when git is unavailable (treat
    everything as changed)."""
    def git(*args: str) -> str | None:
        try:
            res = subprocess.run(
                ["git", *args], cwd=repo, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        return res.stdout if res.returncode == 0 else None

    base = None
    for upstream in ("@{upstream}", "origin/main", "origin/master"):
        out = git("merge-base", "HEAD", upstream)
        if out:
            base = out.strip()
            break
    changed: set[str] = set()
    diff = git("diff", "--name-only", base) if base else git("diff", "--name-only", "HEAD")
    if diff is None:
        return None
    changed.update(line.strip() for line in diff.splitlines() if line.strip())
    status = git("status", "--porcelain")
    if status:
        for line in status.splitlines():
            parts = line[3:].split(" -> ")
            changed.add(parts[-1].strip().strip('"'))
    return changed


def _file_worker(args: tuple[str, str]) -> list[dict]:
    """Process-pool leg of the per-file stage: parse + run the per-file
    rules for one path, returning JSON-able findings (module-level so it
    pickles; each worker re-reads the file, which is what makes the stage
    embarrassingly parallel)."""
    repo, path = args
    from .cache import FileInfo  # local import: cheap in forked workers

    info = FileInfo(Path(repo), Path(path))
    return [f.to_json() for f in filerules.check_file_info(info)]


class Analyzer:
    def __init__(self, repo: Path, use_cache: bool = True, jobs: int = 0):
        self.repo = Path(repo)
        self.sources = SourceCache(self.repo)
        self.results = ResultCache(self.repo / CACHE_NAME, enabled=use_cache)
        self.jobs = jobs or min(8, os.cpu_count() or 1)

    # -- per-file stage ----------------------------------------------------

    def file_findings(self, paths: list[Path]) -> list[Finding]:
        """Per-file rules through the result cache; cache misses fan out to
        a PROCESS pool (ast.parse + AST walks are GIL-bound, so threads buy
        nothing). Cache reads/writes stay on this process. Any pool failure
        falls back to the serial loop."""
        out: list[Finding] = []
        misses: list[Path] = []
        for path in paths:
            info = self.sources.get(path)
            cached = self.results.get_file(info.rel, info.content_key)
            if cached is not None:
                out.extend(cached)
            else:
                misses.append(path)

        def serial(path: Path) -> list[Finding]:
            info = self.sources.get(path)
            found = filerules.check_file_info(info)
            self.results.put_file(info.rel, info.content_key, found)
            return found

        if self.jobs > 1 and len(misses) > 8:
            results: list[list[dict]] | None = None
            try:
                with concurrent.futures.ProcessPoolExecutor(self.jobs) as pool:
                    results = list(
                        pool.map(
                            _file_worker,
                            [(str(self.repo), str(p)) for p in misses],
                            chunksize=8,
                        )
                    )
            except (OSError, concurrent.futures.process.BrokenProcessPool):
                results = None  # sandboxed/fork-less environments: go serial
            if results is not None:
                for path, objs in zip(misses, results):
                    found = [Finding.from_json(o) for o in objs]
                    info = self.sources.get(path)
                    self.results.put_file(info.rel, info.content_key, found)
                    out.extend(found)
                return out
        for path in misses:
            out.extend(serial(path))
        return out

    # -- deep passes -------------------------------------------------------

    def project_findings(self, paths: list[Path]) -> list[Finding]:
        design = self.repo / "docs" / "DESIGN.md"
        h = hashlib.sha1()
        infos = []
        for path in paths:
            info = self.sources.get(path)
            # the deep passes reason about the production tree; tests and
            # tooling would double the graph for zero rule surface
            if info.rel.startswith("xaynet_tpu/"):
                infos.append(info)
                h.update(info.rel.encode())
                h.update(info.content_key.encode())
        if design.exists():
            h.update(design.read_bytes())
        tree_key = h.hexdigest()
        cached = self.results.get_project(tree_key)
        if cached is not None:
            return cached
        symbols = SymbolTable(infos)
        graph = CallGraph(symbols)
        findings = []
        findings.extend(locks.run(graph))
        findings.extend(purity.run(graph))
        findings.extend(invariants.run(graph))
        findings.extend(tenantscope.run(graph))
        findings.extend(taint.run(graph, design))
        findings.extend(metricscheck.run(infos, design))
        findings.extend(spans.run(infos, design))
        self.results.put_project(tree_key, findings)
        return findings


def run(
    repo: Path,
    targets: list[str] | None = None,
    *,
    strict: bool = False,
    changed: bool = False,
    jobs: int = 0,
    use_cache: bool = True,
    json_out: bool = False,
    update_baseline: bool = False,
    deep: bool | None = None,
    baseline_path: Path | None = None,
) -> int:
    baseline_file = Path(baseline_path) if baseline_path else BASELINE_PATH
    if update_baseline and changed:
        # a baseline recorded from a filtered view would silently DROP
        # every entry outside the diff; the next --strict run then fails
        # on findings that were deliberately baselined
        print(
            "--update-baseline records what this invocation analyzed; "
            "combine it with the full tree, not --changed",
            file=sys.stderr,
        )
        return 2
    analyzer = Analyzer(repo, use_cache=use_cache, jobs=jobs)
    full_tree = not targets
    paths = discover(repo, list(targets) if targets else None)
    all_paths = paths if full_tree else None  # one tree walk, reused below

    report_set: set[str] | None = None
    if changed and not strict:
        rels = changed_files(repo)
        if rels is not None:
            report_set = rels
            # per-file work shrinks to the diff; the deep passes below
            # still see the whole tree (they are cross-file by definition)
            paths = [
                p for p in paths if p.relative_to(repo).as_posix() in report_set
            ]

    findings = analyzer.file_findings(paths)
    # the deep passes are cross-file: they run on full-tree invocations
    # (CI, the bare default) and are skipped when linting an explicit
    # subset, where a partial view would fabricate drift findings
    if deep if deep is not None else full_tree:
        findings.extend(
            analyzer.project_findings(
                all_paths if all_paths is not None else discover(repo, None)
            )
        )
    analyzer.results.save()

    if report_set is not None:
        findings = [
            f for f in findings if f.file in report_set or f.file == "docs/DESIGN.md"
        ]

    if update_baseline:
        Baseline.write(baseline_file, findings)
        print(
            f"baseline: recorded {len(findings)} finding(s) to {baseline_file}",
            file=sys.stderr,
        )
        return 0

    baseline = Baseline.load(baseline_file)
    new, masked = baseline.split(findings)

    if json_out:
        print(
            json.dumps(
                {
                    "files": len(paths),
                    "findings": [f.to_json() for f in new],
                    "masked": [f.to_json() for f in masked],
                    "strict": strict,
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.legacy())
    summary = f"lint: {len(paths)} files, {len(new)} problems"
    if masked:
        summary += f" ({len(masked)} baselined)"
    print(summary, file=sys.stderr)
    return 1 if new else 0


def main(argv: list[str], repo: Path) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/lint.py",
        description=(
            "pass-based static analysis gate (tools/analysis/): per-file "
            "hygiene rules plus the cross-file deep passes — lock "
            "discipline, host-sync purity, accounting invariants, "
            "metrics/span DESIGN parity, and the secret-flow taint pass "
            "(rule 'taint': key material must not reach logs, span attrs, "
            "metric labels, JSON dumps, flight-recorder payloads or raised "
            "exception messages; suppress with '# lint: taint-ok: "
            "<rationale>' — docs/DESIGN.md §18)"
        ),
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: the repo tree)")
    ap.add_argument(
        "--strict",
        action="store_true",
        help="the CI gate: always the full tree + all passes (--changed and "
        "path filtering ignored); the baseline applies in every mode",
    )
    ap.add_argument(
        "--changed",
        action="store_true",
        help="report only files differing from the upstream merge-base",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--jobs", type=int, default=0, help="parallel file analysis width")
    ap.add_argument("--no-cache", action="store_true", help="ignore and don't write the result cache")
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="record current findings as the accepted baseline",
    )
    args = ap.parse_args(argv)
    if args.update_baseline and (args.paths or args.changed):
        ap.error(
            "--update-baseline records the FULL tree; drop --changed/paths "
            "(a baseline written from a filtered view would discard every "
            "entry outside it)"
        )
    targets = args.paths or None
    if args.strict:
        targets = None  # the gate always sees the whole tree
    return run(
        repo,
        targets,
        strict=args.strict,
        changed=args.changed,
        jobs=args.jobs,
        use_cache=not args.no_cache,
        json_out=args.json,
        update_baseline=args.update_baseline,
    )
