"""Call-graph host-sync/purity pass.

Replaces the reach of the two name-prefix heuristics in
:mod:`filerules` with real reachability (shared rule name ``sync``,
shared ``# lint: sync-ok`` annotation):

- **sim leg** — roots are the jitted program bodies (functions named
  ``_prog*`` under ``xaynet_tpu/sim``); anything transitively reachable
  from them, *in any file*, may not host-sync (``np.asarray`` — numpy's,
  not ``jnp.asarray``'s trace-safe cousin — ``block_until_ready``,
  ``.item()``, ``.tolist()``) or do Python-int limb math
  (``limbs_to_int``/``int_to_limbs``/...). Bare ``int()`` stays a
  lexical-only check in :mod:`filerules`: trace-time ``int(shape)`` is
  legitimate in shared ops code, so flagging it across the closure would
  drown the signal.
- **fold-worker leg** — roots are the worker-thread entry points whose
  target lives under ``xaynet_tpu/parallel``; reachable functions *in
  that tree* may not ``asarray``/``block_until_ready`` outside
  ``drain()``/``_drain*`` (the sanctioned sync points).
- **pallas-kernel leg** — roots are the Pallas kernel bodies (functions
  named ``*_kernel`` under ``xaynet_tpu/ops``, the shapes
  ``pl.pallas_call`` executes); anything transitively reachable from them,
  in any file, may not host-sync or do Python-int limb math — a host
  round-trip inside a kernel body fails at Mosaic lowering time on real
  hardware, but the interpret route would silently run it, so the CPU CI
  must catch it statically (``# lint: sync-ok`` allowlist honored).

Sites already covered lexically by the per-file prefix rules are skipped
here (one finding per site, not two); everything the old heuristic missed
— a helper defined outside the ``_prog*`` body, a worker-reachable method
whose name matches no prefix — now surfaces.
"""

from __future__ import annotations

import ast

from .callgraph import CallGraph, FuncInfo, iter_owned_nodes, thread_entry_points
from .core import Finding, suppressed
from .filerules import _SIM_PROGRAM_PREFIXES, _WORKER_SYNC_PREFIXES

_HOST_LIMB_CALLEES = frozenset(
    {"limbs_to_int", "limbs_to_ints", "int_to_limbs", "ints_to_limbs", "item", "tolist"}
)


def _callee_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _receiver_module(node: ast.Call, fi: FuncInfo) -> str | None:
    """Dotted module of an attribute call's receiver, via the file's import
    table (``np.asarray`` -> "numpy", ``jnp.asarray`` -> "jax.numpy");
    None when the receiver is not a plain imported-module name."""
    func = node.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return fi.file.imports.get(func.value.id)
    if isinstance(func, ast.Name):
        dotted = fi.file.imports.get(func.id)
        if dotted and "." in dotted:
            return dotted.rsplit(".", 1)[0]
    return None


def _is_numpy_asarray(node: ast.Call, fi: FuncInfo) -> bool:
    """``asarray`` spellings that resolve to numpy (the host sync), not
    ``jax.numpy`` (trace-safe). Unknown receivers count as numpy — a bare
    ``x.asarray()`` in reachable code deserves a look, not a pass."""
    if _callee_name(node) != "asarray":
        return False
    mod = _receiver_module(node, fi)
    if mod is None:
        return True
    return not mod.startswith("jax")


def _lexically_covered_sim(fi: FuncInfo) -> bool:
    """Already checked by the per-file ``_prog*`` rule (which walks nested
    defs too): the site's enclosing-def chain hits a ``_prog*`` function in
    a sim file."""
    if not fi.file.rel.startswith("xaynet_tpu/sim"):
        return False
    return any(
        part.startswith(_SIM_PROGRAM_PREFIXES) for part in fi.qualname.split(".")
    )


def _lexically_covered_worker(fi: FuncInfo) -> bool:
    if not fi.file.rel.startswith("xaynet_tpu/parallel"):
        return False
    return fi.name.startswith(_WORKER_SYNC_PREFIXES)


def run(graph: CallGraph) -> list[Finding]:
    symbols = graph.symbols
    findings: list[Finding] = []

    # --- sim leg ----------------------------------------------------------
    sim_roots = [
        fi
        for fi in symbols.functions
        if fi.file.rel.startswith("xaynet_tpu/sim")
        and fi.name.startswith(_SIM_PROGRAM_PREFIXES)
    ]
    sim_reach = graph.reachable(sim_roots)
    root_names = {fi.uid: fi for fi in sim_roots}

    for fi in symbols.functions:
        if fi.uid not in sim_reach or _lexically_covered_sim(fi):
            continue
        flagged: set[int] = set()
        for node in iter_owned_nodes(fi.node):
            if not isinstance(node, ast.Call) or node.lineno in flagged:
                continue
            callee = _callee_name(node)
            bad = (
                callee == "block_until_ready"
                or callee in _HOST_LIMB_CALLEES
                or _is_numpy_asarray(node, fi)
            )
            if not bad:
                continue
            flagged.add(node.lineno)
            if suppressed("sync", fi.file.line(node.lineno)):
                continue
            root_hint = "a sim program body" if fi.uid not in root_names else f"'{fi.name}'"
            findings.append(
                Finding(
                    "sync",
                    fi.file.rel,
                    node.lineno,
                    f"host round-trip in '{fi.qualname}', which is reachable "
                    f"from {root_hint} (jitted sim round programs must stay "
                    "pure all the way down the call graph — the name-prefix "
                    "rule only sees the `_prog*` body itself; move the "
                    f"'{callee}' to the host boundary or annotate "
                    "'# lint: sync-ok')",
                )
            )

    # --- pallas-kernel leg ------------------------------------------------
    # roots: ``*_kernel`` defs in ops files that import Pallas — the name
    # alone would also catch selector helpers like ``_resolve_mask_kernel``
    # (whose closure is the whole pipeline, not a kernel body)
    kernel_roots = [
        fi
        for fi in symbols.functions
        if fi.file.rel.startswith("xaynet_tpu/ops/")
        and fi.name.endswith("_kernel")
        and any(
            mod.startswith("jax.experimental.pallas")
            for mod in fi.file.imports.values()
        )
    ]
    kernel_reach = graph.reachable(kernel_roots)
    kernel_root_uids = {fi.uid for fi in kernel_roots}

    for fi in symbols.functions:
        if fi.uid not in kernel_reach or fi.uid in sim_reach:
            # functions shared with the sim closure were already walked
            # above — one finding per site, not two
            continue
        flagged = set()
        for node in iter_owned_nodes(fi.node):
            if not isinstance(node, ast.Call) or node.lineno in flagged:
                continue
            callee = _callee_name(node)
            bad = (
                callee == "block_until_ready"
                or callee in _HOST_LIMB_CALLEES
                or _is_numpy_asarray(node, fi)
            )
            if not bad:
                continue
            flagged.add(node.lineno)
            if suppressed("sync", fi.file.line(node.lineno)):
                continue
            root_hint = (
                f"'{fi.name}'" if fi.uid in kernel_root_uids else "a Pallas kernel body"
            )
            findings.append(
                Finding(
                    "sync",
                    fi.file.rel,
                    node.lineno,
                    f"host round-trip in '{fi.qualname}', which is reachable "
                    f"from {root_hint} (Pallas kernel bodies must stay pure "
                    "traced code — a sync lowers nowhere on real hardware "
                    "and the interpret route would silently run it; move the "
                    f"'{callee}' to the host boundary or annotate "
                    "'# lint: sync-ok')",
                )
            )

    # --- fold-worker leg --------------------------------------------------
    worker_roots = [
        fi
        for fi in thread_entry_points(graph)
        if fi.file.rel.startswith("xaynet_tpu/parallel")
    ]
    worker_reach = graph.reachable(worker_roots)

    for fi in symbols.functions:
        if (
            fi.uid not in worker_reach
            or not fi.file.rel.startswith("xaynet_tpu/parallel")
            or _lexically_covered_worker(fi)
            or fi.name.startswith(("drain", "_drain"))
        ):
            continue
        flagged = set()
        for node in iter_owned_nodes(fi.node):
            if not isinstance(node, ast.Call) or node.lineno in flagged:
                continue
            callee = _callee_name(node)
            if callee not in ("asarray", "block_until_ready"):
                continue
            flagged.add(node.lineno)
            if suppressed("sync", fi.file.line(node.lineno)):
                continue
            findings.append(
                Finding(
                    "sync",
                    fi.file.rel,
                    node.lineno,
                    f"blocking host sync in '{fi.qualname}', which is "
                    "reachable from a fold-worker entry point despite "
                    "matching no worker name prefix (synchronize in drain(), "
                    "or annotate a deliberate barrier with '# lint: sync-ok')",
                )
            )
    return findings
