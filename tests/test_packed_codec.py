"""Packed-limb reduction (docs/DESIGN.md §17): the byte-planar codec, the
packed staging pipeline, the reduce-scatter accumulator, and pre-mask
quantization.

The properties everything rests on:

- the packed planar codec is a LOSSLESS re-representation for validated
  group elements (``element < order <= 2^(8*bpn)``) across every group
  family, including non-byte-aligned and non-limb-aligned orders;
- a packed-staging round is **byte-identical** to the unpacked control
  across mesh={1,2,8} × kernel={xla, native-u64, auto} — the fold is the
  same exact modular sum, only the staged representation changes;
- the reduce-scatter plan persists across drain windows and the per-shard
  unmask produces the exact gathered-subtract result;
- quantized configs derive protocol-consistent orders (the catalogue's
  own construction at the coarser scale), serialize wire-compatibly, and
  keep the fixed-point error inside the analytic ``nb_models/exp_shift``
  bound — the accuracy gate's foundation.
"""

from fractions import Fraction

import numpy as np
import pytest

import jax

from xaynet_tpu.core.mask.config import (
    BoundType,
    DataType,
    GroupType,
    InvalidMaskConfigError,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.core.mask.masking import Aggregation, Masker
from xaynet_tpu.core.mask.model import Scalar
from xaynet_tpu.ops import limbs as host_limbs
from xaynet_tpu.parallel.aggregator import ShardedAggregator
from xaynet_tpu.parallel.mesh import make_mesh
from xaynet_tpu.parallel.streaming import BYTES_STAGED, StreamingAggregator

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)

# one config per group family, deliberately covering non-limb-aligned
# (bpn=7: M6) and byte-boundary (Power2) widths, plus quantized orders
# for the odd widths (bpn=5, 4, 3) no catalogue entry produces
FAMILY_CONFIGS = [
    MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6),  # bpn 7
    MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3),  # bpn 6
    MaskConfig(GroupType.POWER2, DataType.F32, BoundType.B0, ModelType.M3),  # bpn 6
    MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3, 2),  # bpn 5
    MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3, 7),  # bpn 3
]


def _rand_limbs(rng, order, k, n):
    """uint32[k, L, n] planar elements uniform in [0, order)."""
    n_limb = host_limbs.n_limbs_for_order(order)
    if order <= 2**63:
        vals = rng.integers(0, order, size=k * n, dtype=np.uint64)
        wire = np.zeros((k * n, n_limb), dtype=np.uint32)
        wire[:, 0] = (vals & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        if n_limb > 1:
            wire[:, 1] = (vals >> np.uint64(32)).astype(np.uint32)
    else:  # wide synthetic orders: python ints (small test sizes only)
        vals = [int.from_bytes(rng.bytes(2 * n_limb * 4), "little") % order
                for _ in range(k * n)]
        wire = host_limbs.ints_to_limbs(vals, n_limb)
    wire = wire.reshape(k, n, n_limb)
    return np.ascontiguousarray(wire.transpose(0, 2, 1)), wire


# --- codec roundtrip property tests ----------------------------------------


@pytest.mark.parametrize("cfg", FAMILY_CONFIGS, ids=lambda c: f"{c.group_type.name}-q{c.quant}")
def test_pack_roundtrip_property(cfg):
    order = cfg.order
    bpn = host_limbs.wire_width_for(order)
    assert bpn == cfg.bytes_per_number
    n_limb = host_limbs.n_limbs_for_order(order)
    rng = np.random.default_rng(order % (2**32))
    for trial in range(3):
        k, n = int(rng.integers(1, 6)), int(rng.integers(1, 400))
        planar, wire = _rand_limbs(rng, order, k, n)
        packed = host_limbs.pack_planar(planar, bpn)
        assert packed.shape == (k, bpn, n)
        assert np.array_equal(host_limbs.unpack_planar(packed, n_limb), planar)
        # the wire pack is the same bytes
        assert np.array_equal(host_limbs.pack_wire(wire, bpn), packed)
        # strided (non-contiguous) input packs identically
        assert np.array_equal(
            host_limbs.pack_planar(wire.transpose(0, 2, 1), bpn), packed
        )


def test_pack_roundtrip_synthetic_widths():
    """Every pack width 1..12 bytes (beyond what the catalogue produces),
    including widths that don't align to limbs or bytes-of-order."""
    rng = np.random.default_rng(7)
    for bpn in range(1, 13):
        order = (1 << (8 * bpn)) - int(rng.integers(1, 250))
        n_limb = host_limbs.n_limbs_for_order(order)
        assert host_limbs.wire_width_for(order) == bpn
        planar, _ = _rand_limbs(rng, order, 3, 61)
        packed = host_limbs.pack_planar(planar, bpn)
        assert np.array_equal(host_limbs.unpack_planar(packed, n_limb), planar)


@pytest.mark.parametrize("cfg", FAMILY_CONFIGS, ids=lambda c: f"{c.group_type.name}-q{c.quant}")
def test_packed_host_fold_matches_planar(cfg):
    order = cfg.order
    ol = host_limbs.order_limbs_for(order)
    bpn = host_limbs.wire_width_for(order)
    n_limb = host_limbs.n_limbs_for_order(order)
    rng = np.random.default_rng(3)
    k, n = 6, 1031
    planar, _ = _rand_limbs(rng, order, k, n)
    acc0 = np.zeros((n_limb, n), dtype=np.uint32)
    ref = host_limbs.fold_planar_batch_host(acc0.copy(), planar, ol)
    packed = host_limbs.pack_planar(planar, bpn)
    out = host_limbs.fold_packed_batch_host(acc0.copy(), packed, ol)
    assert np.array_equal(out, ref)


def test_packed_device_fold_matches_planar():
    from xaynet_tpu.ops.fold_jax import fold_packed_batch, fold_planar_batch

    order = CFG.order
    bpn = host_limbs.wire_width_for(order)
    n_limb = host_limbs.n_limbs_for_order(order)
    rng = np.random.default_rng(5)
    planar, _ = _rand_limbs(rng, order, 4, 515)
    packed = host_limbs.pack_planar(planar, bpn)
    acc = np.zeros((n_limb, 515), dtype=np.uint32)
    ref = np.asarray(fold_planar_batch(acc.copy(), planar, order))
    out = np.asarray(fold_packed_batch(acc.copy(), packed, n_limb, order))
    assert np.array_equal(out, ref)


def test_packed_slice_fold_matches_full():
    order = CFG.order
    ol = host_limbs.order_limbs_for(order)
    bpn = host_limbs.wire_width_for(order)
    n_limb = host_limbs.n_limbs_for_order(order)
    rng = np.random.default_rng(11)
    k, n = 4, 2048
    planar, _ = _rand_limbs(rng, order, k, n)
    packed = host_limbs.pack_planar(planar, bpn)
    ref = host_limbs.fold_planar_batch_host(
        np.zeros((n_limb, n), np.uint32), planar, ol
    )
    # per-shard contiguous accumulator addressing (acc_cols), mid-batch slice
    lo, hi = 512, 1536
    acc = np.zeros((n_limb, hi - lo), np.uint32)
    spare = np.empty_like(acc)
    if host_limbs.fold_packed_slice_host(
        acc, packed, spare, lo, hi, ol, acc_cols=hi - lo
    ):
        assert np.array_equal(spare, ref[:, lo:hi])
    else:
        pytest.skip("native packed kernel unavailable")


# --- packed staging byte-identity across mesh x kernel ---------------------


def _mesh(n):
    return make_mesh(jax.devices()[:n])


def _wire_updates(cfg, n, k, seed):
    rng = np.random.default_rng(seed)
    wire, _ = _rand_limbs(rng, cfg.order, k, n)
    return np.ascontiguousarray(wire.transpose(0, 2, 1))  # [K, n, L]


@pytest.mark.parametrize("mesh_n", (1, 2, 8))
@pytest.mark.parametrize("kernel", ("xla", "native-u64", "auto"))
def test_packed_round_byte_identical_to_unpacked_control(mesh_n, kernel):
    n, k, batches = 515, 4, 2
    stack = _wire_updates(CFG, n, k, seed=mesh_n * 31 + len(kernel))

    def run(packed):
        agg = ShardedAggregator(CFG, n, mesh=_mesh(mesh_n), kernel=kernel)
        st = StreamingAggregator(
            agg, staging_buffers=2, dispatch_ahead=2, max_batch=k, packed=packed
        )
        for _ in range(batches):
            st.submit_batch(stack)
        st.drain()
        snap, nm = agg.snapshot(), agg.nb_models
        st.close()
        return snap, nm

    ref, nm_ref = run(packed=False)
    out, nm = run(packed=True)
    assert nm == nm_ref == k * batches
    assert np.array_equal(out, ref)


def test_packed_staging_counts_fewer_bytes():
    n, k = 2048, 4
    stack = _wire_updates(CFG, n, k, seed=9)
    moved = {}
    for packed in (False, True):
        label = "packed" if packed else "unpacked"
        before = BYTES_STAGED.labels(layout=label).value
        agg = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
        st = StreamingAggregator(agg, max_batch=k, packed=packed)
        st.submit_batch(stack)
        st.drain()
        st.close()
        moved[label] = BYTES_STAGED.labels(layout=label).value - before
    bpn = host_limbs.wire_width_for(CFG.order)
    n_limb = host_limbs.n_limbs_for_order(CFG.order)
    assert moved["packed"] > 0
    assert moved["packed"] / moved["unpacked"] == pytest.approx(bpn / (4 * n_limb))


def test_packed_staging_auto_skips_boundary_orders():
    """At order == 2^(32L) (bpn == 4L) packing is a no-op and auto-disables."""
    cfg = None
    for g, d, b, m in [
        (GroupType.POWER2, DataType.F32, BoundType.B4, ModelType.M12),
        (GroupType.POWER2, DataType.F64, BoundType.B0, ModelType.M9),
    ]:
        c = MaskConfig(g, d, b, m)
        if c.order == 1 << (32 * host_limbs.n_limbs_for_order(c.order)):
            cfg = c
            break
    if cfg is None:
        pytest.skip("no 2^(32L)-boundary order in the probed configs")
    agg = ShardedAggregator(cfg, 64, kernel="xla")
    assert not agg.packed_staging_usable()
    st = StreamingAggregator(agg, max_batch=2, packed=True)
    assert not st._packed  # forced on but not usable -> unpacked layout
    st.close()


# --- reduce-scatter accumulator --------------------------------------------


@pytest.mark.parametrize("kernel", ("xla", "native-u64"))
def test_plan_persists_across_drain_windows(kernel):
    n, k = 1031, 3
    stack = _wire_updates(CFG, n, k, seed=17)
    agg = ShardedAggregator(CFG, n, mesh=make_mesh(), kernel=kernel)
    st = StreamingAggregator(agg, max_batch=k)
    st.submit_batch(stack)
    st.drain()
    plan1 = agg._live_plan
    assert plan1 is not None  # adopted, not reassembled away
    st.submit_batch(stack)
    st.drain()
    assert agg._live_plan is plan1  # the SAME plan served both windows
    # acc reads reassemble on demand and match the sequential oracle
    seq = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    seq.add_batch(stack)
    seq.add_batch(stack)
    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == 2 * k
    st.close()
    # the adopted plan still serves reads after close (finalize path)
    assert np.array_equal(agg.snapshot(), seq.snapshot())


def test_plan_unmask_matches_gathered_subtract():
    n, k = 1031, 3
    stack = _wire_updates(CFG, n, k, seed=19)
    ol = host_limbs.order_limbs_for(CFG.order)
    rng = np.random.default_rng(23)
    _, mask_wire = _rand_limbs(rng, CFG.order, 1, n)
    mask = mask_wire[0]
    for kernel in ("xla", "native-u64"):
        agg = ShardedAggregator(CFG, n, mesh=make_mesh(), kernel=kernel)
        st = StreamingAggregator(agg, max_batch=k)
        st.submit_batch(stack)
        st.drain()
        assert agg._live_plan is not None
        got = agg.unmask_limbs(mask)
        ref = host_limbs.mod_sub(host_limbs.batch_mod_sum(stack, ol), mask, ol)
        assert np.array_equal(got, ref)
        st.close()


def test_acc_write_supersedes_plan():
    n, k = 515, 2
    stack = _wire_updates(CFG, n, k, seed=29)
    agg = ShardedAggregator(CFG, n, mesh=make_mesh(), kernel="xla")
    st = StreamingAggregator(agg, max_batch=k)
    st.submit_batch(stack)
    st.drain()
    assert agg._live_plan is not None
    agg.reset()
    assert agg._live_plan is None
    assert not np.asarray(agg.acc).any()
    # the pipeline rebuilds a fresh plan instead of folding into the stale one
    st.submit_batch(stack)
    st.drain()
    seq = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    seq.add_batch(stack)
    assert np.array_equal(agg.snapshot(), seq.snapshot())
    st.close()


def test_mid_round_snapshot_then_more_folds():
    """A checkpoint read (snapshot) between drain windows must not corrupt
    later folds (device plans donate their buffers per fold)."""
    n, k = 1031, 3
    stack = _wire_updates(CFG, n, k, seed=31)
    agg = ShardedAggregator(CFG, n, mesh=make_mesh(), kernel="xla")
    st = StreamingAggregator(agg, max_batch=k)
    st.submit_batch(stack)
    st.drain()
    snap1 = agg.snapshot()
    st.submit_batch(stack)
    st.drain()
    seq = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    seq.add_batch(stack)
    assert np.array_equal(snap1, seq.snapshot())
    seq.add_batch(stack)
    assert np.array_equal(agg.snapshot(), seq.snapshot())
    st.close()


# --- pre-mask quantization -------------------------------------------------


def test_quantized_order_construction():
    for g in (GroupType.INTEGER, GroupType.PRIME, GroupType.POWER2):
        for q in (0, 1, 4, 7, 10):
            c = MaskConfig(g, DataType.F32, BoundType.B0, ModelType.M3, q)
            base = 2 * int(c.add_shift) * c.exp_shift * c.max_nb_models + 1
            assert c.order >= base
            assert c.exp_shift == 10 ** (10 - q)
            if g is GroupType.INTEGER:
                assert c.order == base
            elif g is GroupType.POWER2:
                assert c.order == 1 << (base - 1).bit_length()
            else:
                assert c.order & 1  # odd
                # every quantized prime is a strong probable prime
                from xaynet_tpu.core.mask.config import _is_probable_prime

                assert _is_probable_prime(c.order)
    # quant=0 must be the exact catalogue entry
    assert (
        MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3, 0).order
        == MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3).order
    )


def test_quantized_config_wire_roundtrip_and_backward_compat():
    for q in (0, 3, 10):
        c = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M12, q)
        assert MaskConfig.from_bytes(c.to_bytes()) == c
    # quant=0 serializes byte-identically to the reference format
    assert MaskConfig(
        GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3
    ).to_bytes() == bytes([1, 0, 0, 3])
    # old readers' bytes parse to quant=0 configs
    assert MaskConfig.from_bytes(bytes([0, 0, 0, 6])).quant == 0


def test_quant_ceiling_validated():
    with pytest.raises(InvalidMaskConfigError):
        MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3, 11)
    with pytest.raises(InvalidMaskConfigError):
        MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3, -1)
    # BMAX f32 allows deeper levels (exp_shift 10^45) up to the wire
    # nibble ceiling — 16..45 would pass the scale check but have no wire
    # encoding, so construction (and thus Settings.validate()) rejects
    # them instead of letting the round-params serialization blow up
    # mid-round
    MaskConfig(GroupType.PRIME, DataType.F32, BoundType.BMAX, ModelType.M3, 15)
    with pytest.raises(InvalidMaskConfigError):
        MaskConfig(GroupType.PRIME, DataType.F32, BoundType.BMAX, ModelType.M3, 16)


@pytest.mark.parametrize("quant", (0, 4, 7))
def test_quantized_round_accuracy_bound(quant):
    """The accuracy gate's analytic core: a full mask -> aggregate ->
    unmask round at quant level q recovers the true weighted mean within
    nb_models / exp_shift_q per weight."""
    cfg = MaskConfig(
        GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3, quant
    ).pair()
    rng = np.random.default_rng(41)
    nb, n = 4, 257
    weights = [rng.uniform(-1, 1, n).astype(np.float32) for _ in range(nb)]
    agg, magg = Aggregation(cfg, n), Aggregation(cfg, n)
    for w in weights:
        seed, obj = Masker(cfg).mask(Scalar(Fraction(1, nb)), w)
        agg.aggregate(obj)
        magg.aggregate(seed.derive_mask(n, cfg))
    out = agg.unmask_array(magg.object)
    true = sum(w.astype(np.float64) for w in weights) / nb
    assert np.abs(out - true).max() <= nb / cfg.vect.exp_shift + 1e-12


def test_quantized_round_through_device_pipeline():
    """A quantized config (1-limb order, bpn=4) runs the packed streaming
    pipeline byte-identically to its own sequential fold."""
    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3, 4)
    assert host_limbs.n_limbs_for_order(cfg.order) == 1
    n, k = 1031, 4
    stack = _wire_updates(cfg, n, k, seed=43)
    agg = ShardedAggregator(cfg, n, mesh=make_mesh(), kernel="auto")
    st = StreamingAggregator(agg, max_batch=k)
    st.submit_batch(stack)
    st.drain()
    seq = ShardedAggregator(cfg, n, mesh=_mesh(1), kernel="xla")
    seq.add_batch(stack)
    assert np.array_equal(agg.snapshot(), seq.snapshot())
    st.close()


def test_settings_quant_load_and_validation():
    from xaynet_tpu.server.settings import Settings, SettingsError

    s = Settings.load(env={"XAYNET__MASK__QUANT": "4"})
    assert s.mask.quant == 4
    assert s.mask.to_config().quant == 4
    with pytest.raises(SettingsError):
        Settings.load(env={"XAYNET__MASK__QUANT": "11"})
    # packed staging knob
    s2 = Settings.load(env={"XAYNET__AGGREGATION__PACKED_STAGING": "false"})
    assert s2.aggregation.packed_staging is False
    assert Settings.default().aggregation.packed_staging is True


def test_round_report_bytes_section_carries_deltas():
    """The per-round report's `bytes` section reports THIS round's staged/
    reduced byte deltas, not process totals."""
    from xaynet_tpu.telemetry.report import RoundReporter

    rep = RoundReporter(path=None)
    rep.begin_round(1)
    n, k = 515, 2
    stack = _wire_updates(CFG, n, k, seed=47)
    agg = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    st = StreamingAggregator(agg, max_batch=k, packed=True)
    st.submit_batch(stack)
    st.drain()
    st.close()
    rep.flush()
    first = rep.last_report
    assert first["bytes"]["staged"]["packed"] > 0
    # a round that moves nothing reports no bytes section (deltas, not totals)
    rep.begin_round(2)
    rep.flush()
    assert "bytes" not in rep.last_report or not rep.last_report["bytes"].get("staged")
