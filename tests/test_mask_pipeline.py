"""The fused mask pipeline (ISSUE 11 / DESIGN §15).

Golden-vector acceptance: every production ``sum_masks`` route — the
in-graph batched derive streamed through the shard pipeline, the fused
Pallas keystream→reject→fold kernel (interpret), the threaded native
sampler, and the legacy host-chunked path — is BYTE-identical to folding
the scalar ``MaskSeed.derive_mask`` reference per seed, across all three
finite-group families, including deliberately tiny chunk budgets that
force the multi-trip rejection ``while_loop`` and the count-th-accept
byte-cursor handoff. Plus the coordinator side: ``finalize_inplace``'s
``DeviceAggregation`` unmasks per-shard slices in place, byte-identical
to the gathered host path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from xaynet_tpu.core.crypto.prng import StreamSampler
from xaynet_tpu.core.mask.config import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.core.mask.masking import Aggregation, Masker
from xaynet_tpu.core.mask.model import Scalar
from xaynet_tpu.core.mask.seed import MaskSeed
from xaynet_tpu.ops import fold_pallas, limbs as host_limbs, masking_jax
from xaynet_tpu.ops.fold_jax import planar_to_wire

CONFIGS = [
    MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3),
    MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3),
    MaskConfig(GroupType.POWER2, DataType.F32, BoundType.B0, ModelType.M3),
]


def _reference_sum(seeds: list[bytes], n: int, pair) -> Aggregation:
    agg = Aggregation(pair, n)
    for s in seeds:
        agg.aggregate(MaskSeed(s).derive_mask(n, pair))
    return agg


def _seed_words_offsets(seeds: list[bytes], pair):
    kws, offs = [], []
    for s in seeds:
        sampler = StreamSampler(s)
        sampler.draw_limbs(1, pair.unit.order)
        offs.append(sampler.consumed_bytes)
        kws.append(np.frombuffer(s, dtype="<u4"))
    return np.stack(kws), np.asarray(offs, np.int32)


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: c.group_type.name)
def test_fused_kernel_golden_vs_scalar_derive(cfg):
    """mask_fold_planar_pallas == sum of MaskSeed.derive_mask vects, and
    the end cursors equal the scalar sampler's consumed-bytes handoff."""
    pair = cfg.pair()
    n = 53
    seeds = [bytes([i, i ^ 0x3C]) * 16 for i in range(1, 6)]
    ref = _reference_sum(seeds, n, pair)

    kws, offs = _seed_words_offsets(seeds, pair)
    L = host_limbs.n_limbs_for_order(pair.vect.order)
    acc = jnp.zeros((L, n), jnp.uint32)
    acc, ends = fold_pallas.mask_fold_planar_pallas(
        acc, jnp.asarray(kws), offs, n, pair.vect.order, interpret=True
    )
    assert np.array_equal(planar_to_wire(acc), ref.object.vect.data)

    # count-th-accept cursor handoff: the kernel's end cursor must equal
    # the scalar sampler's cursor after the SAME unit + n-vector draws
    for seed, end in zip(seeds, np.asarray(ends)):
        sampler = StreamSampler(seed)
        sampler.draw_limbs(1, pair.unit.order)
        sampler.draw_limbs(n, pair.vect.order)
        assert sampler.consumed_bytes == int(end)


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: c.group_type.name)
def test_fused_kernel_multi_trip_tiny_chunks(cfg):
    """A chunk budget far below the element count forces the multi-trip
    rejection while_loop INSIDE the kernel; result and cursors must not
    depend on the chunking."""
    pair = cfg.pair()
    n = 41
    seeds = [bytes([9, i]) * 16 for i in range(1, 4)]
    ref = _reference_sum(seeds, n, pair)
    kws, offs = _seed_words_offsets(seeds, pair)
    L = host_limbs.n_limbs_for_order(pair.vect.order)

    acc_big = jnp.zeros((L, n), jnp.uint32)
    acc_big, ends_big = fold_pallas.mask_fold_planar_pallas(
        acc_big, jnp.asarray(kws), offs, n, pair.vect.order, interpret=True
    )
    acc_tiny = jnp.zeros((L, n), jnp.uint32)
    acc_tiny, ends_tiny = fold_pallas.mask_fold_planar_pallas(
        acc_tiny,
        jnp.asarray(kws),
        offs,
        n,
        pair.vect.order,
        chunk_candidates=7,
        interpret=True,
    )
    assert np.array_equal(planar_to_wire(acc_tiny), ref.object.vect.data)
    assert np.array_equal(np.asarray(ends_big), np.asarray(ends_tiny))


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: c.group_type.name)
# host-chunked (the legacy path) is pinned by the slow-marked
# tests/test_jax_kernels.py sum_masks tests — re-running it here would pay
# its ~25s-per-shape unrolled-keystream XLA compile three more times
@pytest.mark.parametrize("kernel", ["batch", "fused-pallas-interpret", "host-threaded"])
def test_sum_masks_routes_byte_identical(cfg, kernel):
    """Every production route of the promoted entry point returns the
    exact (unit, vect) the scalar reference computes."""
    pair = cfg.pair()
    n = 37
    seeds = [bytes([i, i ^ 0x5A]) * 16 for i in range(1, 11)]
    ref = _reference_sum(seeds, n, pair)
    unit, vect = masking_jax.sum_masks(seeds, n, pair, seed_batch=4, kernel=kernel)
    assert np.array_equal(unit, ref.object.unit.data)
    assert np.array_equal(np.asarray(vect), ref.object.vect.data)
    assert masking_jax.resolved_mask_kernel() == kernel


def test_sum_masks_fused_tiny_chunks_multi_trip():
    """The fused ROUTE (not just the kernel) with a tiny chunk budget:
    multi-trip derivation composed with the group loop stays exact."""
    pair = CONFIGS[0].pair()
    n = 29
    seeds = [bytes([i, 0x77]) * 16 for i in range(1, 8)]
    ref = _reference_sum(seeds, n, pair)
    unit, vect = masking_jax._sum_masks_fused(
        seeds, n, pair, seed_batch=3, interpret=True, chunk_candidates=5
    )
    assert np.array_equal(unit, ref.object.unit.data)
    assert np.array_equal(np.asarray(vect), ref.object.vect.data)


def test_sum_masks_batch_on_mesh_matches_reference():
    """The batch route streaming mask planes through the PR-7 shard
    pipeline on the full device mesh (mesh=8 under the CI virtual-device
    flags; degenerates to mesh=1 on a single device)."""
    from xaynet_tpu.parallel.mesh import make_mesh

    pair = CONFIGS[0].pair()
    n = 43  # deliberately not divisible by the mesh size
    seeds = [bytes([i, 0x11]) * 16 for i in range(1, 10)]
    ref = _reference_sum(seeds, n, pair)
    unit, vect = masking_jax.sum_masks(
        seeds, n, pair, seed_batch=4, kernel="batch", mesh=make_mesh()
    )
    assert np.array_equal(unit, ref.object.unit.data)
    assert np.array_equal(np.asarray(vect), ref.object.vect.data)


def test_auto_calibration_memoizes_and_reports():
    """auto resolves once per (backend, shape) and the verdict is reused;
    the resolved route is observable for the bench."""
    pair = CONFIGS[0].pair()
    n = 31
    seeds = [bytes([i, 0x42]) * 16 for i in range(1, 6)]
    first = masking_jax.calibrate_mask_kernel(seeds, n, pair, seed_batch=4)
    assert first in ("batch", "fused-pallas-interpret", "fused-pallas", "host-threaded")
    unit, vect = masking_jax.sum_masks(seeds, n, pair, seed_batch=4, kernel="auto")
    assert masking_jax.resolved_mask_kernel() == first
    ref = _reference_sum(seeds, n, pair)
    assert np.array_equal(np.asarray(vect), ref.object.vect.data)


def test_compile_cache_gauge_bounded_and_published():
    from xaynet_tpu.telemetry.registry import get_registry

    pair = CONFIGS[0].pair()
    seeds = [bytes([i, 0x21]) * 16 for i in range(1, 4)]
    masking_jax.sum_masks(seeds, 19, pair, kernel="batch")
    reg = get_registry()
    value = reg.sample_value("xaynet_mask_derive_compile_cache")
    assert value is not None and 1 <= value <= 3 * masking_jax._COMPILE_CACHE_MAX
    # the lru caches are bounded: maxsize is the declared constant
    assert masking_jax._mask_batch_fn.cache_info().maxsize == masking_jax._COMPILE_CACHE_MAX
    assert masking_jax._unit_offsets_fn.cache_info().maxsize == masking_jax._COMPILE_CACHE_MAX


def test_pinned_mask_kernel_engages_promoted_path(monkeypatch):
    """PetSettings.mask_kernel's contract: a pinned route ENGAGES the
    routed pipeline at any model size; only an explicit device_sum2=False
    overrides the pin back to the legacy host path."""
    import xaynet_tpu.ops.masking_jax as mj
    from xaynet_tpu.sdk.state_machine import StateMachine

    sm = StateMachine.__new__(StateMachine)
    sm.device_sum2 = None
    sm.device_sum2_strict = True
    sm.mask_kernel = "host-threaded"
    seeds = [MaskSeed(bytes([i]) * 32) for i in range(1, 4)]
    calls = []
    real = mj.sum_masks

    def spy(s, n, c, **kw):
        calls.append(kw.get("kernel"))
        return real(s, n, c, **kw)

    monkeypatch.setattr(mj, "sum_masks", spy)
    pair = CONFIGS[0].pair()
    obj = StateMachine._aggregate_masks(sm, seeds, 16, pair)
    assert calls == ["host-threaded"]
    sm.device_sum2 = False  # explicit False wins over the pin
    calls.clear()
    host_obj = StateMachine._aggregate_masks(sm, seeds, 16, pair)
    assert not calls
    assert obj == host_obj  # both paths byte-identical either way


def test_finalize_inplace_device_view_unmasks_per_shard():
    """DeviceAggregation: validation without gathering, per-shard in-place
    subtract byte-identical to the gathered host finalize()."""
    from xaynet_tpu.core.mask.masking import UnmaskingError
    from xaynet_tpu.server.aggregation import DeviceAggregation, StagedAggregator

    cfg = CONFIGS[0]
    n, k = 103, 6  # not divisible by the 8-device mesh
    rng = np.random.default_rng(7)
    host = StagedAggregator(cfg.pair(), n, device=False)
    dev = StagedAggregator(cfg.pair(), n, device=True, batch_size=4)
    mask_agg = Aggregation(cfg.pair(), n)
    for _ in range(k):
        w = rng.uniform(-1, 1, n).astype(np.float32)
        seed, masked = Masker(cfg.pair()).mask(Scalar(1, k), w)
        mask_agg.aggregate(MaskSeed(seed.as_bytes()).derive_mask(n, cfg.pair()))
        for a in (host, dev):
            a.validate_aggregation(masked)
            a.aggregate(masked)
    host_agg = host.finalize_inplace()
    dev_view = dev.finalize_inplace()
    assert isinstance(dev_view, DeviceAggregation)
    assert dev_view.nb_models == host_agg.nb_models == k
    assert len(dev_view) == n and dev_view.config == cfg.pair()

    mask = mask_agg.object
    dev_view.validate_unmasking(mask)
    got = dev_view.unmask_array(mask)
    want = host_agg.unmask_array(mask)
    assert got.tobytes() == want.tobytes()
    # the gathered-object escape hatch still works (checkpoints/tests)
    assert np.array_equal(dev_view.object.vect.data, host_agg.object.vect.data)
    # validation failures surface without touching the accumulator
    empty = StagedAggregator(cfg.pair(), n, device=True).finalize_inplace()
    with pytest.raises(UnmaskingError, match="NoModel"):
        empty.validate_unmasking(mask)


def test_unmask_phase_uses_inplace_view_without_double_timing(monkeypatch):
    """Sum2Phase hands Unmask the in-place view, and the phase does not
    wrap the view's unmask in a second `unmask` kernel timer."""
    import asyncio

    from xaynet_tpu.server.aggregation import StagedAggregator
    from xaynet_tpu.server.phases.sum2 import Sum2Phase

    cfg = CONFIGS[0]
    n, k = 24, 3
    dev = StagedAggregator(cfg.pair(), n, device=True, batch_size=2)
    mask_agg = Aggregation(cfg.pair(), n)
    rng = np.random.default_rng(3)
    for _ in range(k):
        w = rng.uniform(-1, 1, n).astype(np.float32)
        seed, masked = Masker(cfg.pair()).mask(Scalar(1, k), w)
        mask_agg.aggregate(MaskSeed(seed.as_bytes()).derive_mask(n, cfg.pair()))
        dev.aggregate(masked)

    phase = Sum2Phase.__new__(Sum2Phase)
    phase.aggregator = dev
    phase._base = None  # no round journal: next() must skip the unmask entry
    phase._votes = []

    class _Shared:
        pass

    phase.shared = _Shared()
    # next() consults [overlap]: pin the serial path — this test asserts
    # the drain-time in-place view contract, not the §22 eager engine
    from xaynet_tpu.server.settings import OverlapSettings

    class _SettingsStub:
        overlap = OverlapSettings(enabled=False)

    phase.shared.settings = _SettingsStub()

    async def drive():
        from xaynet_tpu.server.aggregation import DeviceAggregation

        nxt = await Sum2Phase.next(phase)
        assert isinstance(nxt.model_agg, DeviceAggregation)
        return nxt.model_agg

    view = asyncio.run(drive())
    got = view.unmask_array(mask_agg.object)
    assert got.shape == (n,)
