"""Runtime secret redaction (ISSUE 14, docs/DESIGN.md §18).

The static taint pass proves no key material FLOWS into telemetry at lint
time; this file covers the runtime complement: ``telemetry.redact()``
(the sanctioned length/type-only projection), the ``scrub_attrs``
deny-list filter, its wiring into flight-recorder dumps and Chrome-trace
exports (defense-in-depth for values that become secret only
dynamically), the ``xaynet_redactions_total`` metric, and regression
pins for the sanctioned durable-state flows the pass suppresses.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from xaynet_tpu.telemetry import recorder as recorder_mod  # noqa: E402
from xaynet_tpu.telemetry import tracing  # noqa: E402
from xaynet_tpu.telemetry.redact import redact, scrub_attrs  # noqa: E402
from xaynet_tpu.telemetry.registry import get_registry  # noqa: E402

S_REDACT = tracing.declare_span("test.redact")


def _redactions(site: str) -> float:
    return get_registry().sample_value(
        "xaynet_redactions_total", labels={"site": site}
    ) or 0.0


# --- redact() ---------------------------------------------------------------


def test_redact_is_length_type_digest_only():
    secret = os.urandom(32)
    out = redact(secret)
    assert secret.hex() not in out
    assert "bytes:32" in out
    # the sha256 prefix correlates two mentions of the same secret
    assert out == redact(secret)
    assert out != redact(os.urandom(32))


def test_redact_handles_strings_and_counts():
    before = _redactions("redact")
    out = redact("super-secret-token")
    assert "super-secret-token" not in out
    assert "str:18" in out
    assert _redactions("redact") == before + 1


# --- scrub_attrs ------------------------------------------------------------


def test_scrub_attrs_denies_secret_keys_and_keeps_the_rest():
    seed = os.urandom(32).hex()
    attrs = {
        "mask_seed": seed,
        "round_seed": seed,
        "secret_key": seed,
        "edge_token": "hunter2",
        "keystream_bytes": seed,
        "private_half": seed,
        "sk": seed,
        "key_bytes": seed,
        "batch": 42,
        "outcome": "folded",
        "edge_id": "edge-7",
    }
    out = scrub_attrs(attrs, "flight")
    blob = json.dumps(out)
    assert seed not in blob and "hunter2" not in blob
    # shape preserved, non-denied values untouched
    assert out["batch"] == 42
    assert out["outcome"] == "folded"
    assert out["edge_id"] == "edge-7"
    assert set(out) == set(attrs)


def test_scrub_attrs_recurses_into_nested_containers():
    seed = os.urandom(16).hex()
    attrs = {"ring": [{"attrs": {"seed": seed, "n": 1}}], "meta": {"token": seed}}
    out = scrub_attrs(attrs, "trace")
    blob = json.dumps(out)
    assert seed not in blob
    assert out["ring"][0]["attrs"]["n"] == 1


# --- flight-recorder dumps are scrubbed before disk -------------------------


def test_flight_dump_scrubs_secret_keyed_attrs(tmp_path, monkeypatch):
    monkeypatch.setattr(recorder_mod, "_recorder", None)
    monkeypatch.setenv("XAYNET_FLIGHT_DIR", str(tmp_path))
    rec = recorder_mod.get_recorder()
    tracer = tracing.get_tracer()
    tracer.begin_round(7, tracing.new_id())
    seed = os.urandom(32).hex()
    # a ring span carrying a secret-keyed attr (what static analysis
    # cannot see when the value arrived off the wire)
    with tracer.span(S_REDACT, mask_seed=seed, batch=3):
        pass
    before = _redactions("flight")
    path = rec.dump("pipeline-poison", "batch 3 poisoned", round_seed=seed, batch=3)
    assert path is not None
    raw = Path(path).read_text()
    assert seed not in raw, "secret bytes reached the flight dump"
    bundle = json.loads(raw)
    assert bundle["attrs"]["batch"] == 3
    assert bundle["attrs"]["round_seed"].startswith("<redacted ")
    ring = [s for s in bundle["ring"] if s["name"] == "test.redact"]
    assert ring and ring[0]["attrs"]["mask_seed"].startswith("<redacted ")
    assert ring[0]["attrs"]["batch"] == 3
    assert _redactions("flight") > before
    tracer.end_round()


# --- Chrome-trace exports are scrubbed before disk --------------------------


def test_chrome_trace_export_scrubs_span_attrs():
    span = tracing.Span("test.redact", "t" * 16, "s" * 16, None, 0.0, {})
    seed = os.urandom(32).hex()
    span.attrs = {"secret_key": seed, "members": 5}
    before = _redactions("trace")
    doc = tracing.to_chrome_trace([span])
    blob = json.dumps(doc)
    assert seed not in blob
    event = next(e for e in doc["traceEvents"] if e.get("name") == "test.redact")
    assert event["args"]["members"] == 5
    assert event["args"]["secret_key"].startswith("<redacted ")
    # identity args (trace/span ids) are not key material and survive
    assert event["args"]["trace"] == "t" * 16
    assert _redactions("trace") > before


# --- the sanctioned durable-state flows stay functional ---------------------


def test_coordinator_state_blob_still_carries_the_round_key():
    """Regression for the `# lint: taint-ok` on CoordinatorState.to_bytes:
    the suppression documents a SANCTIONED flow — a restarted coordinator
    must recover the round's secret key from its own durable store, so the
    blob must keep carrying it (redacting there would brick restore)."""
    from xaynet_tpu.core.common import RoundParameters, RoundSeed
    from xaynet_tpu.core.crypto.encrypt import EncryptKeyPair
    from xaynet_tpu.core.mask.config import (
        BoundType, DataType, GroupType, MaskConfig, ModelType,
    )
    from xaynet_tpu.server.coordinator import CoordinatorState

    keys = EncryptKeyPair.generate()
    config = MaskConfig(
        GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3
    ).pair()
    state = CoordinatorState(
        keys=keys,
        round_id=3,
        round_params=RoundParameters(
            pk=keys.public.as_bytes(),
            sum=0.5,
            update=0.5,
            seed=RoundSeed.generate(),
            mask_config=config,
            model_length=4,
        ),
    )
    restored = CoordinatorState.from_bytes(state.to_bytes())
    assert restored.keys.secret.as_bytes() == keys.secret.as_bytes()
    assert restored.round_params == state.round_params
