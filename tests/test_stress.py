"""Large-scale stress paths (opt-in: XAYNET_STRESS=1).

Exercises the 25M-parameter shapes of baseline config #4 end-to-end on the
host kernels: native mask expansion, staged aggregation, unmask + decode.
Excluded from the default suite for runtime; run with

    XAYNET_STRESS=1 python -m pytest tests/test_stress.py -q
"""

import os
import time

import numpy as np
import pytest

from xaynet_tpu.core.crypto.prng import StreamSampler
from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    MaskObject,
    MaskUnit,
    MaskVect,
    ModelType,
)
from xaynet_tpu.ops import limbs as limb_ops

pytestmark = pytest.mark.skipif(
    not os.environ.get("XAYNET_STRESS"), reason="set XAYNET_STRESS=1 to run"
)

N = 25_000_000
CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)


def test_25m_mask_aggregate_unmask():
    """3 masked 25M-element updates -> aggregate -> unmask == exact sum."""
    order = CFG.order
    n_limb = limb_ops.n_limbs_for_order(order)
    t_all = time.time()

    # "masked updates": uniform group elements straight from the sampler
    stacks, units = [], []
    for i in range(3):
        t0 = time.time()
        sampler = StreamSampler(bytes([i + 1]) * 32)
        unit = sampler.draw_limbs(1, order)[0]
        vect = sampler.draw_limbs(N, order)
        print(f"update {i}: sampled in {time.time() - t0:.1f}s")
        stacks.append(vect)
        units.append(unit)

    agg = Aggregation(CFG.pair(), N)
    t0 = time.time()
    agg.aggregate_batch(np.stack(stacks), np.stack(units))
    t_agg = time.time() - t0
    print(f"aggregate_batch(3 x 25M): {t_agg:.1f}s")

    # spot-check 1000 random positions against python big-int arithmetic
    idx = np.random.default_rng(0).integers(0, N, 1000)
    got = limb_ops.limbs_to_ints(agg.object.vect.data[idx])
    for j, i_ in enumerate(idx):
        want = sum(limb_ops.limbs_to_ints(s[i_ : i_ + 1])[0] for s in stacks) % order
        assert got[j] == want

    # unmask with one of the updates as the "mask" (mechanically identical)
    mask = MaskObject(MaskVect(CFG, stacks[0]), MaskUnit(CFG, units[0]))
    t0 = time.time()
    unmasked_limbs, _ = agg._unmasked_limbs(mask)
    t_unmask = time.time() - t0
    print(f"unmask subtract (25M): {t_unmask:.1f}s; total {time.time() - t_all:.1f}s")
    assert unmasked_limbs.shape == (N, n_limb)


def test_1m_param_full_round_wall_clock():
    """Full PET round at 1M parameters through the REST stack (stress)."""
    import time

    from xaynet_tpu.sdk.api import ParticipantABC
    from xaynet_tpu.sdk.federation import LocalFederation

    MLEN = 1_000_000

    class Const(ParticipantABC):
        def __init__(self, v):
            self.v = v

        def train_round(self, training_input):
            return np.full(MLEN, self.v, dtype=np.float32)

    fed = LocalFederation(model_length=MLEN, n_sum=1, n_update=3)
    trainers = [Const(0.0), Const(-0.6), Const(0.0), Const(0.6)]
    try:
        t0 = time.time()
        (result,) = list(fed.rounds(trainers, n_rounds=1, round_timeout=300))
        print(f"1M-param round wall-clock: {time.time() - t0:.1f}s")
    finally:
        fed.stop()
    np.testing.assert_allclose(result.global_model, np.zeros(MLEN), atol=1e-9)


def test_25m_param_full_round_wall_clock():
    """Baseline config #4 shape: a complete PET round at 25M parameters
    (ResNet-50 scale) through the full protocol stack, host kernels only."""
    import asyncio
    import time
    from fractions import Fraction

    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.simulation import keys_for_task
    from xaynet_tpu.sdk.state_machine import PetSettings as SdkPet, StateMachine as P
    from xaynet_tpu.sdk.traits import ModelStore
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.settings import (
        CountSettings,
        PhaseSettings,
        PetSettings,
        Settings,
        Sum2Settings,
        TimeSettings,
    )
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store

    MLEN = 25_000_000

    class MS(ModelStore):
        def __init__(self, m):
            self.m = m

        async def load_model(self):
            return self.m

    async def run():
        st = Settings(
            pet=PetSettings(
                sum=PhaseSettings(prob=0.4, count=CountSettings(1, 1), time=TimeSettings(0, 600)),
                update=PhaseSettings(prob=0.5, count=CountSettings(3, 3), time=TimeSettings(0, 600)),
                sum2=Sum2Settings(count=CountSettings(1, 1), time=TimeSettings(0, 600)),
            )
        )
        st.model.length = MLEN
        st.mask.model_type = st.mask.model_type.__class__.M6
        store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
        machine, tx, events = await StateMachineInitializer(st, store).init()
        handler = PetMessageHandler(events, tx)
        fetcher = Fetcher(events)
        mt = asyncio.create_task(machine.run())
        while fetcher.phase().value != "sum":
            await asyncio.sleep(0.01)
        seed = fetcher.round_params().seed.as_bytes()
        rng = np.random.default_rng(0)
        parts = [
            P(
                SdkPet(keys=keys_for_task(seed, 0.4, 0.5, "sum", start=0), max_message_size=None),
                InProcessClient(fetcher, handler),
                MS(None),
            )
        ]
        expected_mean = 0.0
        for i in range(3):
            k = keys_for_task(seed, 0.4, 0.5, "update", start=(10 + i) * 1000)
            local = rng.uniform(-1, 1, MLEN).astype(np.float32)
            expected_mean += float(local.astype(np.float64).mean()) / 3
            parts.append(
                P(
                    SdkPet(keys=k, scalar=Fraction(1, 3), max_message_size=None),
                    InProcessClient(fetcher, handler),
                    MS(local),
                )
            )
        t0 = time.time()

        async def drive(sm):
            for _ in range(600):
                try:
                    await sm.transition()
                except Exception:
                    pass
                if fetcher.model() is not None and sm.phase.value == "awaiting":
                    return
                await asyncio.sleep(0.05)

        await asyncio.gather(*(drive(p) for p in parts))
        while fetcher.model() is None:
            await asyncio.sleep(0.05)
        wall = time.time() - t0
        model = np.asarray(fetcher.model())
        print(f"25M-param full PET round wall-clock: {wall:.1f}s")
        assert model.shape == (MLEN,)
        assert abs(float(model.mean()) - expected_mean) < 1e-6
        mt.cancel()
        return wall

    asyncio.run(asyncio.wait_for(run(), 900))


def test_1m_device_mesh_aggregation():
    """Sharded device aggregation at 1M params on the 8-device mesh."""
    from xaynet_tpu.parallel.aggregator import ShardedAggregator

    n, k = 1_000_000, 8
    order = CFG.order
    n_limb = limb_ops.n_limbs_for_order(order)
    rng = np.random.default_rng(1)
    stack = rng.integers(0, 2**32, size=(k, n, n_limb), dtype=np.uint32)
    stack[..., n_limb - 1] &= (1 << 20) - 1  # keep elements < order

    dev = ShardedAggregator(CFG, n)
    t0 = time.time()
    dev.add_batch(stack)
    got = dev.snapshot()
    print(f"device mesh fold 8 x 1M: {time.time() - t0:.2f}s")

    acc = np.zeros((n, n_limb), dtype=np.uint32)
    want = limb_ops.batch_mod_sum(stack.copy(), limb_ops.order_limbs_for(order))
    assert np.array_equal(got, want)


def test_256mb_multipart_streaming_reassembly_bounded_rss():
    """A >=256MB multipart payload round-trips through chunked reassembly
    with the parse's transient memory bounded: the streaming parser must
    never materialize a second contiguous copy of the payload (VERDICT
    round-1 item 8 'done' bar). tracemalloc measures the parse itself
    (peak minus retained output), not the process high-water mark."""
    import tracemalloc

    from xaynet_tpu.core.mask.object import MaskUnit, MaskVect
    from xaynet_tpu.core.message import Sum2, Tag
    from xaynet_tpu.core.message.encoder import MessageBuilder
    from xaynet_tpu.core.message.payloads import Chunk, parse_payload_stream

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    n = 45_000_000  # x6 bytes/elem = 270 MB of wire payload
    rng = np.random.default_rng(1)
    top = int(cfg.order >> 32)
    limbs = rng.integers(0, 1 << 32, size=(n, 2), dtype=np.uint32)
    limbs[:, 1] = rng.integers(0, top, size=n, dtype=np.uint32)
    sample_first, sample_last = limbs[0].copy(), limbs[-1].copy()
    unit = limbs[0].copy()
    payload = Sum2(
        sum_signature=b"\x0d" * 64,
        model_mask=MaskObject(MaskVect(cfg, limbs), MaskUnit(cfg, unit)),
    )
    raw = payload.to_bytes()
    wire = len(raw)
    assert wire >= 256 * 1024 * 1024, wire

    budget = 1 << 20  # 1MB chunks
    builder = MessageBuilder()
    n_chunks = -(-wire // budget)
    for i in range(n_chunks):
        builder.add(
            Chunk(
                id=i + 1,
                message_id=3,
                last=(i == n_chunks - 1),
                data=raw[i * budget : (i + 1) * budget],
            )
        )
    del raw, limbs, payload

    tracemalloc.start()
    parsed = parse_payload_stream(Tag.SUM2, builder.take_reader())
    current, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()

    vect = parsed.model_mask.vect
    assert len(vect) == n
    # content survives chunk boundaries (an offset bug would shift bytes)
    assert np.array_equal(vect.data[0], sample_first)
    assert np.array_equal(vect.data[-1], sample_last)
    # transient overhead above the retained limb tensor must stay under one
    # wire copy — a concat-then-parse allocates the full joined payload
    # (1x wire) plus a full-size conversion buffer on top
    assert peak - current < wire, (peak, current, wire)


def _protocol_scale_round(n_sum, n_update, mlen, model_for, timeout=600, wire_ingest=False):
    """ONE round with ``n_update`` update + ``n_sum`` sum participants through
    the real coordinator pipeline (state machine + services + in-process
    transport), asserting the seed-dict fan-out (#sum x #update entries),
    the window counters, and the exact aggregate. Returns the wall-clock.

    Reference behavior: the coordinator accepts exactly count.max update
    messages and every accepted update inserts its local seed dict
    atomically (phases/update.rs:119-152); each sum participant must then
    see one encrypted seed per accepted update (GET /seeds).

    ``model_for(i, rng)`` supplies participant i's local model (float32,
    length ``mlen``).
    """
    import asyncio
    import logging
    import time
    from fractions import Fraction

    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.simulation import keys_for_task
    from xaynet_tpu.sdk.state_machine import PetSettings as SdkPet, StateMachine as P
    from xaynet_tpu.sdk.traits import ModelStore
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.settings import (
        CountSettings,
        PhaseSettings,
        PetSettings,
        Settings,
        Sum2Settings,
        TimeSettings,
    )
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store

    N_SUM, N_UPDATE, MLEN = n_sum, n_update, mlen
    SUM_PROB, UPDATE_PROB = 0.3, 0.9

    class MS(ModelStore):
        def __init__(self, m):
            self.m = m

        async def load_model(self):
            return self.m

    counter_lines: list[str] = []

    class _Capture(logging.Handler):
        def emit(self, record):
            msg = record.getMessage()
            if "accepted" in msg:
                counter_lines.append(msg)

    async def run():
        st = Settings(
            pet=PetSettings(
                sum=PhaseSettings(
                    prob=SUM_PROB,
                    count=CountSettings(N_SUM, N_SUM),
                    time=TimeSettings(0, 600),
                ),
                update=PhaseSettings(
                    prob=UPDATE_PROB,
                    count=CountSettings(N_UPDATE, N_UPDATE),
                    time=TimeSettings(0, 600),
                ),
                sum2=Sum2Settings(count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 600)),
            )
        )
        st.model.length = MLEN
        if wire_ingest:
            st.aggregation.device = True
            st.aggregation.wire_ingest = True
            st.aggregation.kernel = "xla"
            st.aggregation.batch_size = 16
        store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
        machine, tx, events = await StateMachineInitializer(st, store).init()
        handler = PetMessageHandler(events, tx, wire_ingest=wire_ingest)
        fetcher = Fetcher(events)
        cap = _Capture()
        coord_logger = logging.getLogger("xaynet.coordinator")
        prev_level = coord_logger.level
        coord_logger.setLevel(logging.INFO)  # counter lines log at INFO
        coord_logger.addHandler(cap)
        mt = asyncio.create_task(machine.run())
        try:
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            seed = fetcher.round_params().seed.as_bytes()

            sum_parts = []
            for i in range(N_SUM):
                keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 10_000)
                sum_parts.append(P(SdkPet(keys=keys), InProcessClient(fetcher, handler), MS(None)))
            upd_parts = []
            expected = np.zeros(MLEN)
            rng = np.random.default_rng(7)
            t_keys = time.time()
            for i in range(N_UPDATE):
                keys = keys_for_task(
                    seed, SUM_PROB, UPDATE_PROB, "update", start=1_000_000 + i * 10_000
                )
                local = model_for(i, rng)
                expected += local.astype(np.float64) / N_UPDATE
                upd_parts.append(
                    P(
                        SdkPet(keys=keys, scalar=Fraction(1, N_UPDATE)),
                        InProcessClient(fetcher, handler),
                        MS(local),
                    )
                )
            print(
                f"[scale {N_UPDATE}x{MLEN}] built {N_UPDATE} participants "
                f"in {time.time() - t_keys:.1f}s"
            )

            t0 = time.time()

            async def drive(sm):
                consecutive_errors = 0
                for _ in range(3000):
                    try:
                        await sm.transition()
                        consecutive_errors = 0
                    except Exception:
                        # transient races are expected at this concurrency,
                        # but a persistent failure must surface, not become
                        # an opaque 600s timeout
                        consecutive_errors += 1
                        if consecutive_errors >= 50:
                            raise
                    if fetcher.model() is not None and sm.phase.value == "awaiting":
                        return
                    await asyncio.sleep(0.005)

            captured = {}

            async def capture_seed_dict():
                # the broadcast happens at the update->sum2 transition and is
                # superseded when the next round starts; grab it in-flight
                for _ in range(120_000):
                    sd = fetcher.seed_dict()
                    if sd:
                        captured["sd"] = sd
                        return
                    await asyncio.sleep(0.005)

            await asyncio.gather(
                capture_seed_dict(), *(drive(p) for p in sum_parts + upd_parts)
            )
            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            wall = time.time() - t0
            print(
                f"[scale {N_UPDATE}x{MLEN}] round wall-clock: {wall:.1f}s "
                f"({N_UPDATE} updates, {N_SUM} sum)"
            )

            # seed-dict fan-out: one encrypted seed per accepted update for
            # EVERY sum participant
            seed_dict = captured.get("sd")
            assert seed_dict is not None and len(seed_dict) == N_SUM
            for sp in sum_parts:
                mine = seed_dict.get(sp.keys.public)
                assert mine is not None and len(mine) == N_UPDATE

            # window counters: the coordinator accepted exactly the window
            assert any(
                f"update: {N_UPDATE} accepted (min {N_UPDATE}, max {N_UPDATE})" in ln
                for ln in counter_lines
            ), counter_lines[-5:]
            assert any(
                f"sum: {N_SUM} accepted (min {N_SUM}, max {N_SUM})" in ln
                for ln in counter_lines
            ), counter_lines[:5]

            model = np.asarray(fetcher.model())
            np.testing.assert_allclose(model, expected, atol=1e-6)
            return wall
        finally:
            coord_logger.removeHandler(cap)
            coord_logger.setLevel(prev_level)
            mt.cancel()
            try:
                await mt
            except (asyncio.CancelledError, Exception):
                pass

    return asyncio.run(asyncio.wait_for(run(), timeout))


def test_1000_update_participants_one_round():
    """Protocol scale (BASELINE config #3 shape): 1,000 update + 2 sum
    participants, tiny model."""
    wall = _protocol_scale_round(
        n_sum=2,
        n_update=1000,
        mlen=8,
        model_for=lambda i, rng: np.full(8, rng.uniform(-1, 1), dtype=np.float32),
    )
    assert wall < 300, f"1k-participant round took {wall:.0f}s"


def test_100_update_participants_1m_params_one_round():
    """Protocol scale COUPLED to data scale (VERDICT r04 item 6): 100 update
    + 3 sum participants at 1M params through the same real pipeline, where
    seed-dict fan-out (3 x 100 entries) and staging pressure interact —
    bridging the 1,000 x 8 and 3 x 25M extremes."""
    wall = _protocol_scale_round(
        n_sum=3,
        n_update=100,
        mlen=1_000_000,
        model_for=lambda i, rng: rng.uniform(-1, 1, size=1_000_000).astype(np.float32),
        timeout=1200,
    )
    assert wall < 900, f"100x1M round took {wall:.0f}s"


def test_100_update_participants_1m_params_wire_ingest_round():
    """The SAME coupled-scale round through the coordinator-integrated
    device wire ingest (lazy multipart parse -> per-update device validity
    before seed insert -> device-resident flush on the 8-device mesh):
    sustained production-path evidence at protocol x data scale."""
    wall = _protocol_scale_round(
        n_sum=3,
        n_update=100,
        mlen=1_000_000,
        model_for=lambda i, rng: rng.uniform(-1, 1, size=1_000_000).astype(np.float32),
        timeout=1200,
        wire_ingest=True,
    )
    assert wall < 900, f"100x1M wire-ingest round took {wall:.0f}s"
