"""Whole-round durable journal (docs/DESIGN.md §9).

Pins the crash-anywhere contracts layered on top of the PR-4 update-only
checkpoint:

1. **XNCKPT2 wire format** — round dictionaries, mask votes and packed
   per-shard planes roundtrip byte-exact; XNCKPT1 blobs still read (and
   stay update-only);
2. **reseed replay** — boot-time validation replays the journaled
   dictionaries into an empty store and prunes accepted-but-unjournaled
   orphans, so cross-process resume works on volatile backends;
3. **fail-soft journal writes** — a write that exhausts the storage retry
   policy is skipped and metered, never raised into the phase;
4. **resume budget & phase guards** — Failure burns ``resume_attempts``
   then restarts at Idle (``xaynet_resume_total{outcome=
   "budget_exhausted"}``); a journal entry for another phase restarts
   instead of resuming;
5. **lifecycle interplay** — a journal resume is NOT a round boundary:
   quarantine/probe accounting only moves on true round outcomes;
6. **multi-phase boot restore** — a coordinator killed mid-sum2 re-enters
   Sum2 with the aggregate and votes restored and finishes the round with
   the correct model.
"""

import asyncio
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.resilience import FaultPlan, ResilientStore, RetryPolicy, clear_plan, install_plan
from xaynet_tpu.resilience import checkpoint as ckpt_mod
from xaynet_tpu.server.coordinator import CoordinatorState
from xaynet_tpu.server.events import EventPublisher, PhaseName
from xaynet_tpu.server.phases.base import Shared, reduce_count_window
from xaynet_tpu.server.phases.failure import Failure
from xaynet_tpu.server.phases.idle import Idle
from xaynet_tpu.server.phases.update import UpdatePhase
from xaynet_tpu.server.requests import RequestReceiver
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store


@pytest.fixture(autouse=True)
def _no_leftover_fault_plan():
    clear_plan()
    yield
    clear_plan()


def _mem_store() -> Store:
    return Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())


def _settings(n_sum=2, n_update=3, model_len=13) -> Settings:
    s = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=0.4,
                count=CountSettings(min=n_sum, max=n_sum),
                time=TimeSettings(min=0.0, max=30.0),
            ),
            update=PhaseSettings(
                prob=0.5,
                count=CountSettings(min=n_update, max=n_update),
                time=TimeSettings(min=0.0, max=30.0),
            ),
            sum2=Sum2Settings(
                count=CountSettings(min=n_sum, max=n_sum),
                time=TimeSettings(min=0.0, max=30.0),
            ),
        )
    )
    s.model.length = model_len
    s.resilience.retry_base_ms = 1.0
    s.resilience.retry_max_ms = 20.0
    return s


def _pk(i: int) -> bytes:
    return bytes([i]) * 32


def _seed(i: int) -> bytes:
    return bytes([i]) * 80  # ENCRYPTED_MASK_SEED_LENGTH


def _ckpt(**kw) -> ckpt_mod.RoundCheckpoint:
    rng = np.random.default_rng(3)
    base = dict(
        round_id=4,
        phase="update",
        round_seed=b"\x11" * 32,
        mask_config=[["PRIME", "F32", "B0", "M3"], ["PRIME", "F32", "B0", "M3"]],
        model_length=7,
        nb_models=2,
        seed_watermark=2,
        vect=rng.integers(0, 2**32, size=(7, 6), dtype=np.uint32),
        unit=rng.integers(0, 2**32, size=(6,), dtype=np.uint32),
    )
    base.update(kw)
    return ckpt_mod.RoundCheckpoint(**base)


# --------------------------------------------------------------------------
# Wire format
# --------------------------------------------------------------------------


def test_v2_roundtrip_dicts_votes_and_planes():
    rng = np.random.default_rng(9)
    planes = [
        (0, 4, rng.integers(0, 2**32, size=(6, 4), dtype=np.uint32)),
        (4, 8, rng.integers(0, 2**32, size=(6, 4), dtype=np.uint32)),
    ]
    ck = _ckpt(
        phase="sum2",
        sum_dict={_pk(1): b"e" * 32},
        seed_dicts={_pk(10): {_pk(1): _seed(10)}, _pk(11): {_pk(1): _seed(11)}},
        mask_votes=[(_pk(1), b"\x05" * 21)],
        vect=np.zeros((0, 0), dtype=np.uint32),
        planes=planes,
    )
    again = ckpt_mod.RoundCheckpoint.from_bytes(ck.to_bytes())
    assert again.version == 2 and again.phase == "sum2"
    assert again.sum_dict == {_pk(1): b"e" * 32}
    assert again.seed_dicts == {
        _pk(10): {_pk(1): _seed(10)},
        _pk(11): {_pk(1): _seed(11)},
    }
    assert again.mask_votes == [(_pk(1), b"\x05" * 21)]
    assert len(again.planes) == 2
    for (lo, hi, plane), (lo2, hi2, plane2) in zip(planes, again.planes):
        assert (lo, hi) == (lo2, hi2)
        assert np.array_equal(plane, plane2)
    # the planes ARE the aggregate: wire reassembly honors model_length
    wire = again.wire_vect()
    assert wire.shape == (7, 6)
    full = np.concatenate([planes[0][2], planes[1][2]], axis=1)
    assert np.array_equal(wire, full[:, :7].T)


def test_sum_entry_roundtrips_with_empty_aggregate():
    ck = _ckpt(
        phase="sum",
        nb_models=0,
        seed_watermark=0,
        vect=np.zeros((0, 0), dtype=np.uint32),
        unit=np.zeros((0,), dtype=np.uint32),
        sum_dict={_pk(1): b"e" * 32, _pk(2): b"f" * 32},
    )
    again = ckpt_mod.RoundCheckpoint.from_bytes(ck.to_bytes())
    assert again.phase == "sum" and again.nb_models == 0
    assert again.sum_dict == {_pk(1): b"e" * 32, _pk(2): b"f" * 32}
    assert again.vect.size == 0 and again.unit.size == 0


def test_v1_blob_reads_as_update_only():
    ck = _ckpt(version=1)
    blob = ck.to_bytes()
    assert blob.startswith(ckpt_mod.MAGIC)
    again = ckpt_mod.RoundCheckpoint.from_bytes(blob)
    assert again.version == 1
    assert again.sum_dict == {} and again.seed_dicts == {} and again.mask_votes == []
    assert np.array_equal(again.vect, ck.vect)


# --------------------------------------------------------------------------
# Reseed replay (boot restore on volatile backends)
# --------------------------------------------------------------------------


def _round_identity(settings):
    state = CoordinatorState.from_settings(settings)
    state.round_id = 4
    return (
        state,
        ckpt_mod.mask_config_names(state.round_params.mask_config),
        state.round_params.seed.as_bytes(),
    )


def test_validate_reseed_replays_journal_into_empty_store():
    settings = _settings(model_len=7)
    state, names, seed = _round_identity(settings)
    store = _mem_store()
    ck = _ckpt(
        round_seed=seed,
        mask_config=names,
        sum_dict={_pk(1): b"e" * 32},
        seed_dicts={_pk(10): {_pk(1): _seed(10)}, _pk(11): {_pk(1): _seed(11)}},
    )

    async def run():
        # the store is EMPTY (process died, memory backend): without the
        # replay the watermark check would reject; with it the journal
        # repopulates the dictionaries through the protocol primitives
        assert await ckpt_mod.validate(ck, state, store) is not None
        assert await ckpt_mod.validate(ck, state, store, reseed=True) is None
        seed_dict = await store.coordinator.seed_dict()
        assert ckpt_mod.seed_dict_watermark(seed_dict) == 2
        assert (await store.coordinator.sum_dict()) == {_pk(1): b"e" * 32}
        # idempotent: a second reseed validation still passes
        assert await ckpt_mod.validate(ck, state, store, reseed=True) is None

    asyncio.run(run())


def test_validate_reseed_prunes_orphan_update_participants():
    settings = _settings(model_len=7)
    state, names, seed = _round_identity(settings)
    store = _mem_store()
    ck = _ckpt(
        round_seed=seed,
        mask_config=names,
        sum_dict={_pk(1): b"e" * 32},
        seed_dicts={_pk(10): {_pk(1): _seed(10)}, _pk(11): {_pk(1): _seed(11)}},
    )

    async def run():
        from xaynet_tpu.core.mask.seed import EncryptedMaskSeed

        # the store holds one MORE update than the journal: accepted after
        # the last journal write, its masked model died with the process —
        # the prune drops it so its un-acked client can resend
        await store.coordinator.add_sum_participant(_pk(1), b"e" * 32)
        for upk in (_pk(10), _pk(11), _pk(12)):
            await store.coordinator.add_local_seed_dict(
                upk, {_pk(1): EncryptedMaskSeed(_seed(9))}
            )
        assert await ckpt_mod.validate(ck, state, store, reseed=True) is None
        seed_dict = await store.coordinator.seed_dict()
        pks = {pk for inner in seed_dict.values() for pk in inner}
        assert pks == {_pk(10), _pk(11)}  # the orphan is gone

    asyncio.run(run())


def test_reduce_count_window_clamps_at_zero():
    params = PhaseSettings(
        prob=0.5,
        count=CountSettings(min=2, max=4),
        time=TimeSettings(min=0.0, max=30.0),
    )
    reduced = reduce_count_window(params, 3)
    assert reduced.count.min == 0 and reduced.count.max == 1
    assert reduce_count_window(params, 0) is params


# --------------------------------------------------------------------------
# Per-shard planes: device snapshot/restore roundtrip
# --------------------------------------------------------------------------


def test_sharded_aggregator_snapshot_restore_shards_roundtrip():
    from xaynet_tpu.core.mask import BoundType, DataType, GroupType, MaskConfig, ModelType
    from xaynet_tpu.ops import limbs as host_limbs
    from xaynet_tpu.parallel.aggregator import ShardedAggregator

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    n = 103
    L = host_limbs.n_limbs_for_order(cfg.order)
    rng = np.random.default_rng(5)
    batch = rng.integers(0, 2**32, size=(4, n, L), dtype=np.uint32)
    batch[:, :, -1] = 0  # keep every element below the group order

    agg = ShardedAggregator(cfg, n)
    agg.add_batch(batch)
    planes = agg.snapshot_shards()
    assert planes is not None and planes

    fresh = ShardedAggregator(cfg, n)
    fresh.restore_shards(planes, agg.nb_models)
    assert fresh.nb_models == 4
    assert np.array_equal(fresh.snapshot(), agg.snapshot())


# --------------------------------------------------------------------------
# Fail-soft journal writes (satellite: save through the retry policy)
# --------------------------------------------------------------------------


def test_journal_write_exhausting_retries_skips_not_raises():
    class _SharedStub:
        pass

    install_plan(FaultPlan.parse("seed=1;storage.coordinator.set_round_checkpoint:error"))
    store = ResilientStore(
        _mem_store(),
        policy=RetryPolicy(max_attempts=2, base_delay_s=0.001, max_delay_s=0.002),
    )
    shared = _SharedStub()
    shared.store = store
    shared.round_id = 7

    before_skip = ckpt_mod.SAVE_FAILURES.value
    before_fail = ckpt_mod.CHECKPOINTS.labels(outcome="failed").value
    ok = asyncio.run(ckpt_mod.write_entry(shared, _ckpt()))
    assert ok is False  # skipped — the phase it protects never sees a raise
    assert ckpt_mod.SAVE_FAILURES.value == before_skip + 1
    assert ckpt_mod.CHECKPOINTS.labels(outcome="failed").value == before_fail + 1

    clear_plan()
    assert asyncio.run(ckpt_mod.write_entry(shared, _ckpt())) is True
    assert asyncio.run(store.coordinator.round_checkpoint()) is not None


# --------------------------------------------------------------------------
# Failure-phase resume guards
# --------------------------------------------------------------------------


def _failure_shared(settings, store, resume_attempts=0, tenant="default") -> Shared:
    state = CoordinatorState.from_settings(settings)
    state.round_id = 4
    shared = Shared(
        state=state,
        request_rx=RequestReceiver(),
        events=EventPublisher(4, None, None, PhaseName.UPDATE),
        store=store,
        settings=settings,
        tenant=tenant,
    )
    shared.resume_attempts = resume_attempts
    return shared


def test_failure_burns_resume_budget_then_restarts_at_idle():
    settings = _settings(model_len=7)
    settings.resilience.checkpoint_enabled = True
    settings.resilience.max_resume_attempts = 2
    store = _mem_store()
    shared = _failure_shared(settings, store, resume_attempts=2)

    before = ckpt_mod.RESUME_TOTAL.labels(phase="update", outcome="budget_exhausted").value
    failure = Failure(shared, RuntimeError("boom"), failed_phase=PhaseName.UPDATE)
    nxt = asyncio.run(asyncio.wait_for(failure.run_phase(), timeout=30))
    assert isinstance(nxt, Idle)
    after = ckpt_mod.RESUME_TOTAL.labels(phase="update", outcome="budget_exhausted").value
    assert after == before + 1


def test_failure_journal_phase_mismatch_restarts_round():
    settings = _settings(model_len=7)
    settings.resilience.checkpoint_enabled = True
    store = _mem_store()
    shared = _failure_shared(settings, store)
    names = ckpt_mod.mask_config_names(shared.state.round_params.mask_config)
    seed = shared.state.round_params.seed.as_bytes()
    ck = _ckpt(round_seed=seed, mask_config=names, nb_models=0, seed_watermark=0)
    asyncio.run(store.coordinator.set_round_checkpoint(ck.to_bytes()))

    before = ckpt_mod.RESUME_TOTAL.labels(phase="update", outcome="invalid").value
    # sum2 failed but the journal still says "update": sum2 participants
    # would never resend into a re-entered update window — restart instead
    failure = Failure(shared, RuntimeError("boom"), failed_phase=PhaseName.SUM2)
    resumed = asyncio.run(failure._try_resume())
    assert resumed is None
    assert (
        ckpt_mod.RESUME_TOTAL.labels(phase="update", outcome="invalid").value
        == before + 1
    )


def test_failure_resume_reenters_update_with_budget_spent():
    settings = _settings(model_len=7)
    settings.resilience.checkpoint_enabled = True
    settings.resilience.max_resume_attempts = 2
    store = _mem_store()
    shared = _failure_shared(settings, store)
    names = ckpt_mod.mask_config_names(shared.state.round_params.mask_config)
    seed = shared.state.round_params.seed.as_bytes()
    ck = _ckpt(round_seed=seed, mask_config=names, nb_models=0, seed_watermark=0)
    asyncio.run(store.coordinator.set_round_checkpoint(ck.to_bytes()))

    failure = Failure(shared, RuntimeError("boom"), failed_phase=PhaseName.UPDATE)
    resumed = asyncio.run(failure._try_resume())
    assert isinstance(resumed, UpdatePhase)
    assert shared.resume_attempts == 1


# --------------------------------------------------------------------------
# Lifecycle interplay: a resume is not a round boundary
# --------------------------------------------------------------------------


def test_journal_resume_does_not_move_quarantine_accounting():
    from xaynet_tpu.server.settings import TenancySettings
    from xaynet_tpu.tenancy import lifecycle as lc_mod
    from xaynet_tpu.tenancy.lifecycle import QUARANTINED, TenantLifecycle
    from xaynet_tpu.tenancy.registry import TenantRegistry

    lc = TenantLifecycle(
        TenancySettings(
            enabled=True,
            admin_token="test-admin-token",
            quarantine_failures=1,
            quarantine_reset_s=60.0,
        ),
        TenantRegistry(),
        {},
    )
    lc.mark_serving("acme")
    lc.note_round_failed("acme")  # threshold 1: straight to quarantine
    assert lc.state("acme") == QUARANTINED
    boundaries_at_quarantine = lc._boundaries.get("acme", 0)

    settings = _settings(model_len=7)
    settings.resilience.checkpoint_enabled = True
    settings.resilience.max_resume_attempts = 2
    store = _mem_store()
    shared = _failure_shared(settings, store, tenant="acme")
    names = ckpt_mod.mask_config_names(shared.state.round_params.mask_config)
    seed = shared.state.round_params.seed.as_bytes()
    ck = _ckpt(round_seed=seed, mask_config=names, nb_models=0, seed_watermark=0)
    asyncio.run(store.coordinator.set_round_checkpoint(ck.to_bytes()))

    lc_mod.install_manager(lc)
    try:
        # resume path: the round is still ALIVE — neither a breaker strike
        # nor a round boundary; quarantine probe accounting must not move
        failure = Failure(shared, RuntimeError("boom"), failed_phase=PhaseName.UPDATE)
        nxt = asyncio.run(asyncio.wait_for(failure.run_phase(), timeout=30))
        assert isinstance(nxt, UpdatePhase)
        assert lc.state("acme") == QUARANTINED
        assert lc._boundaries.get("acme", 0) == boundaries_at_quarantine

        # restart path (budget exhausted): a true round failure — the
        # boundary counts, and the open breaker keeps the quarantine held
        shared.resume_attempts = settings.resilience.max_resume_attempts
        failure = Failure(shared, RuntimeError("boom"), failed_phase=PhaseName.UPDATE)
        nxt = asyncio.run(asyncio.wait_for(failure.run_phase(), timeout=30))
        assert isinstance(nxt, Idle)
        assert lc._boundaries.get("acme", 0) == boundaries_at_quarantine + 1
        assert lc.state("acme") == QUARANTINED
    finally:
        lc_mod.install_manager(None)


# --------------------------------------------------------------------------
# Boot restore into Sum2 (in-process; the subprocess SIGKILL matrix lives
# in tools/soak.py --kill-matrix)
# --------------------------------------------------------------------------


def test_boot_restore_resumes_sum2_phase_and_finishes_round():
    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.simulation import keys_for_task
    from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
    from xaynet_tpu.sdk.traits import ModelStore
    from xaynet_tpu.server.phases.sum2 import Sum2Phase
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler

    class ArrayModelStore(ModelStore):
        def __init__(self, model):
            self.model = model

        async def load_model(self):
            return self.model

    n_sum, n_update = 2, 3
    settings = _settings(n_sum=n_sum, n_update=n_update)
    settings.restore.enable = True
    settings.resilience.checkpoint_enabled = True
    settings.resilience.checkpoint_every_batches = 1
    settings.aggregation.batch_size = 1
    model_len = settings.model.length
    store = _mem_store()
    rng = np.random.default_rng(21)
    locals_ = [rng.uniform(-1, 1, model_len).astype(np.float32) for _ in range(n_update)]
    expected = sum(w.astype(np.float64) / n_update for w in locals_)

    async def drive_until(sm, fetcher, stop, steps=400):
        for _ in range(steps):
            try:
                await sm.transition()
            except Exception:
                pass
            if await stop():
                return True
            await asyncio.sleep(0.01)
        return False

    async def phase_one():
        """Sum + update + ONE of two sum2 votes, then kill the machine."""
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        machine_task = asyncio.create_task(machine.run())
        try:
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            params = fetcher.round_params()
            seed = params.seed.as_bytes()
            summers = []
            for i in range(n_sum):
                sm = ParticipantSM(
                    PetSettings(
                        keys=keys_for_task(seed, params.sum, params.update, "sum", start=i * 1000)
                    ),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(None),
                )
                summers.append(sm)
                assert await drive_until(
                    sm, fetcher, lambda sm=sm: _ret(sm.phase.value == "sum2")
                )
            summer_blobs = [sm.save() for sm in summers]
            while fetcher.phase().value != "update":
                await asyncio.sleep(0.01)
            for i in range(n_update):
                sm = ParticipantSM(
                    PetSettings(
                        keys=keys_for_task(
                            seed, params.sum, params.update, "update", start=(10 + i) * 1000
                        ),
                        scalar=Fraction(1, n_update),
                    ),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(locals_[i]),
                )
                assert await drive_until(
                    sm, fetcher, lambda sm=sm: _ret(sm.phase.value == "awaiting")
                )
            while fetcher.phase().value != "sum2":
                await asyncio.sleep(0.01)
            # exactly ONE summer votes (window needs 2 → the phase stalls),
            # then wait for its vote to be journal-durable
            restored = ParticipantSM.restore(
                summer_blobs[0], InProcessClient(fetcher, handler), ArrayModelStore(None)
            )

            async def vote_journaled():
                blob = await store.coordinator.round_checkpoint()
                if blob is None:
                    return False
                ck = ckpt_mod.RoundCheckpoint.from_bytes(blob)
                return ck.phase == "sum2" and len(ck.mask_votes) >= 1

            assert await drive_until(restored, fetcher, vote_journaled)
            return seed, summer_blobs[1]
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    async def _ret(v):
        return v

    async def phase_two(seed, summer_blob):
        before = ckpt_mod.RESUME_TOTAL.labels(phase="sum2", outcome="resumed").value
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        # the machine restarts INSIDE sum2, one vote already restored
        phase = machine.phase
        assert isinstance(phase, Sum2Phase)
        assert len(phase._votes) == 1
        assert (
            ckpt_mod.RESUME_TOTAL.labels(phase="sum2", outcome="resumed").value
            == before + 1
        )
        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        assert fetcher.round_params().seed.as_bytes() == seed  # same round
        machine_task = asyncio.create_task(machine.run())
        try:
            second = ParticipantSM.restore(
                summer_blob, InProcessClient(fetcher, handler), ArrayModelStore(None)
            )

            async def model_published():
                return fetcher.model() is not None

            assert await drive_until(second, fetcher, model_published, steps=800)
            # the journal retires once the model is published
            for _ in range(200):
                if await store.coordinator.round_checkpoint() is None:
                    break
                await asyncio.sleep(0.01)
            assert await store.coordinator.round_checkpoint() is None
            return np.asarray(fetcher.model())
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    async def run():
        seed, summer_blob = await phase_one()
        return await phase_two(seed, summer_blob)

    model = asyncio.run(asyncio.wait_for(run(), timeout=120))
    # all three updates survived the kill inside the restored aggregate
    np.testing.assert_allclose(model, expected, atol=1e-9)
