"""Masking conformance grid — the protocol's numerical contract.

Ports the reference's macro-generated round-trip test grids
(rust/xaynet-core/src/mask/masking.rs:444-518, 718-763, 852-942):
mask -> derive mask from seed -> unmask must recover the model within
``1/exp_shift`` (or ``n/exp_shift`` after aggregating n models), across the
full GroupType x DataType x BoundType grid.
"""

import random
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    Masker,
    MaskConfig,
    MaskSeed,
    Model,
    ModelType,
    Scalar,
)

GROUPS = [GroupType.INTEGER, GroupType.PRIME, GroupType.POWER2]
DTYPES = [DataType.F32, DataType.F64, DataType.I32, DataType.I64]
BOUNDS = [BoundType.B0, BoundType.B2, BoundType.B4, BoundType.B6, BoundType.BMAX]

_BOUND_VALUES = {BoundType.B0: 1, BoundType.B2: 100, BoundType.B4: 10_000, BoundType.B6: 1_000_000}


def _rand_weights(rng, data_type, bound_type, n):
    if bound_type is BoundType.BMAX:
        if data_type is DataType.F32:
            bound = float(np.finfo(np.float32).max) / 2.1
        elif data_type is DataType.F64:
            bound = float(np.finfo(np.float64).max) / 2.1
        elif data_type is DataType.I32:
            bound = int(2**31 // 2.1)
        else:
            bound = int(2**63 // 2.1)
    else:
        bound = _BOUND_VALUES[bound_type]
    if data_type in (DataType.I32, DataType.I64):
        return [rng.randint(-int(bound), int(bound)) for _ in range(n)]
    ws = [rng.uniform(-bound, bound) for _ in range(n)]
    if data_type is DataType.F32:
        ws = [float(np.float32(w)) for w in ws]
    return ws


def _config(group, dtype, bound):
    return MaskConfig(group, dtype, bound, ModelType.M3)


@pytest.mark.parametrize("group", GROUPS)
@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("bound", BOUNDS)
def test_masking_roundtrip(group, dtype, bound):
    config = _config(group, dtype, bound)
    rng = random.Random(hash((group, dtype, bound)) & 0xFFFF)
    n = 10
    weights = _rand_weights(rng, dtype, bound, n)
    model = Model.from_primitives(weights, dtype)

    seed, masked = Masker(config.pair(), MaskSeed(bytes([rng.randrange(256) for _ in range(32)]))).mask(
        Scalar.unit(), model
    )
    assert len(masked.vect) == n
    assert masked.is_valid()

    mask = seed.derive_mask(n, config.pair())
    agg = Aggregation.from_object(masked)
    agg.validate_unmasking(mask)
    unmasked = agg.unmask(mask)

    tol = Fraction(1, config.exp_shift)
    for w, u in zip(model, unmasked):
        assert abs(w - u) <= tol, (float(w), float(u), group, dtype, bound)


@pytest.mark.parametrize("group", GROUPS)
@pytest.mark.parametrize("dtype", [DataType.F32, DataType.F64])
@pytest.mark.parametrize("bound", BOUNDS)
def test_masking_scalar_roundtrip(group, dtype, bound):
    """Scaled all-ones model must unmask back to ones (scalar correction)."""
    config = _config(group, dtype, bound)
    rng = random.Random(hash((group, dtype, bound, "s")) & 0xFFFF)
    n = 10
    if bound is BoundType.BMAX:
        hi = float(np.finfo(np.float32 if dtype is DataType.F32 else np.float64).max) / 2.1
    else:
        hi = float(_BOUND_VALUES[bound])
    scalar = Scalar.from_float(rng.uniform(1e-6, hi))
    model = Model.from_primitives([1] * n, DataType.I32)

    seed, masked = Masker(config.pair()).mask(scalar, model)
    assert masked.is_valid()
    mask = seed.derive_mask(n, config.pair())
    unmasked = Aggregation.from_object(masked).unmask(mask)

    tol = Fraction(1, config.exp_shift)
    for u in unmasked:
        assert abs(u - 1) <= tol, (float(u), group, dtype, bound)


@pytest.mark.parametrize("group", GROUPS)
@pytest.mark.parametrize("dtype", DTYPES)
def test_masking_and_aggregation(group, dtype):
    """Aggregate 5 masked models + 5 masks; unmask = weighted average."""
    bound = BoundType.B2
    config = _config(group, dtype, bound)
    rng = random.Random(hash((group, dtype)) & 0xFFFF)
    n, count = 10, 5
    scalar = Scalar(1, count)

    agg_model = Aggregation(config.pair(), n)
    agg_mask = Aggregation(config.pair(), n)
    averaged = [Fraction(0)] * n
    for _ in range(count):
        weights = _rand_weights(rng, dtype, bound, n)
        model = Model.from_primitives(weights, dtype)
        for i, w in enumerate(model):
            averaged[i] += scalar.value * w

        seed, masked = Masker(config.pair()).mask(scalar, model)
        mask = seed.derive_mask(n, config.pair())
        agg_model.validate_aggregation(masked)
        agg_model.aggregate(masked)
        agg_mask.validate_aggregation(mask)
        agg_mask.aggregate(mask)

    mask_final = agg_mask.object
    agg_model.validate_unmasking(mask_final)
    unmasked = agg_model.unmask(mask_final)

    tol = Fraction(count, config.exp_shift)
    for a, u in zip(averaged, unmasked):
        assert abs(a - u) <= tol, (float(a), float(u), group, dtype)


@pytest.mark.parametrize("group", GROUPS)
def test_aggregation_validity(group):
    """Random masked models stay inside the group through aggregation."""
    config = _config(group, DataType.F32, BoundType.B0)
    rng = random.Random(3)
    from xaynet_tpu.core.crypto.prng import uniform_ints
    from xaynet_tpu.core.mask import MaskObject

    n = 10
    agg = Aggregation(config.pair(), n)
    for k in range(1, 6):
        seed = bytes([rng.randrange(256) for _ in range(32)])
        ints = uniform_ints(seed, n + 1, config.order)
        obj = MaskObject.new(config.pair(), ints[1:], ints[0])
        agg.validate_aggregation(obj)
        agg.aggregate(obj)
        assert agg.nb_models == k
        assert agg.object.is_valid()


def test_fast_path_matches_exact():
    """numpy-f32 fast encode must agree with the exact rational path."""
    config = _config(GroupType.INTEGER, DataType.F32, BoundType.B0)
    rng = np.random.default_rng(0)
    weights32 = rng.uniform(-1, 1, size=256).astype(np.float32)
    model = Model.from_primitives(weights32.tolist(), DataType.F32)
    seed = MaskSeed(b"\x11" * 32)

    _, masked_fast = Masker(config.pair(), seed).mask(Scalar.unit(), weights32)
    _, masked_exact = Masker(config.pair(), seed).mask(Scalar.unit(), model)
    assert masked_fast == masked_exact


def test_batch_aggregation_matches_sequential():
    config = _config(GroupType.PRIME, DataType.F32, BoundType.B2)
    rng = np.random.default_rng(1)
    n, k = 32, 7
    objs = []
    for _ in range(k):
        w = rng.uniform(-100, 100, size=n).astype(np.float32)
        _, masked = Masker(config.pair()).mask(Scalar(1, k), w)
        objs.append(masked)

    seq = Aggregation(config.pair(), n)
    for o in objs:
        seq.aggregate(o)

    bat = Aggregation(config.pair(), n)
    stack = np.stack([o.vect.data for o in objs])
    units = np.stack([o.unit.data for o in objs])
    bat.aggregate_batch(stack, units)

    assert seq.nb_models == bat.nb_models == k
    assert seq.object == bat.object


@pytest.mark.parametrize("model_type", [ModelType.M6, ModelType.M9, ModelType.M12])
@pytest.mark.parametrize("group", GROUPS)
def test_masking_roundtrip_larger_capacities(group, model_type):
    """The M6/M9/M12 capacity tiers round-trip like M3 (bigger orders/limbs)."""
    config = MaskConfig(group, DataType.F32, BoundType.B2, model_type)
    rng = random.Random(hash((group, model_type)) & 0xFFFF)
    n = 8
    weights = [rng.uniform(-100, 100) for _ in range(n)]
    model = Model.from_primitives([float(np.float32(w)) for w in weights], DataType.F32)
    seed, masked = Masker(config.pair()).mask(Scalar.unit(), model)
    assert masked.is_valid()
    mask = seed.derive_mask(n, config.pair())
    unmasked = Aggregation.from_object(masked).unmask(mask)
    tol = Fraction(1, config.exp_shift)
    for w, u in zip(model, unmasked):
        assert abs(w - u) <= tol


def test_aggregation_capacity_bound():
    """validate_aggregation/unmasking enforce max_nb_models (M3 -> 1000)."""
    from xaynet_tpu.core.mask import AggregationError, UnmaskingError
    from xaynet_tpu.core.crypto.prng import uniform_ints
    from xaynet_tpu.core.mask import MaskObject

    config = _config(GroupType.PRIME, DataType.F32, BoundType.B0)
    ints = uniform_ints(b"\x01" * 32, 4, config.order)
    obj = MaskObject.new(config.pair(), ints[1:], ints[0])
    agg = Aggregation(config.pair(), 3)
    agg.aggregate(obj)
    agg.nb_models = config.max_nb_models  # at capacity
    with pytest.raises(AggregationError) as e:
        agg.validate_aggregation(obj)
    assert e.value.kind == "TooManyModels"
    agg.nb_models = config.max_nb_models + 1
    with pytest.raises(UnmaskingError) as e2:
        agg.validate_unmasking(obj)
    assert e2.value.kind == "TooManyModels"


def test_fast_path_matches_exact_with_scalar():
    """Non-unit scalars: dd fast encode == exact rational encode."""
    config = _config(GroupType.PRIME, DataType.F32, BoundType.B4)
    rng = np.random.default_rng(5)
    weights32 = rng.uniform(-10_000, 10_000, size=512).astype(np.float32)
    model = Model.from_primitives(weights32.tolist(), DataType.F32)
    seed = MaskSeed(b"\x2f" * 32)
    scalar = Scalar(3, 7)  # awkward rational

    _, fast = Masker(config.pair(), seed).mask(scalar, weights32)
    _, exact = Masker(config.pair(), seed).mask(scalar, model)
    assert fast == exact


def test_fast_path_clamping_matches_exact():
    """Weights beyond the bound clamp identically on both paths."""
    config = _config(GroupType.INTEGER, DataType.F32, BoundType.B0)
    weights32 = np.asarray([-5.0, -1.0, -0.5, 0.0, 0.5, 1.0, 5.0], dtype=np.float32)
    model = Model.from_primitives(weights32.tolist(), DataType.F32)
    seed = MaskSeed(b"\x3c" * 32)
    _, fast = Masker(config.pair(), seed).mask(Scalar.unit(), weights32)
    _, exact = Masker(config.pair(), seed).mask(Scalar.unit(), model)
    assert fast == exact
