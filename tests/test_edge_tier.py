"""Edge pre-aggregation tier (docs/DESIGN.md §11).

Covers the tentpole end to end:

- envelope wire-format round-trip + corruption detection;
- the partition-merge property: folding K random partitions of one update
  set through edge partials is BYTE-IDENTICAL to the flat fold, and the
  merged seed dicts are independent of merge order;
- a two-tier in-process round (coordinator + real EdgeService processes on
  the event loop): global model byte-identical to the flat single-tier run
  with the same inputs, coordinator envelope count reduced by ~the edge
  batch factor, per-edge watermark rejecting a replayed envelope whole;
- an edge crash mid-window: participants fall back to uploading upstream
  directly and the round still completes with the nb_models ==
  seed-watermark invariant intact.
"""

from __future__ import annotations

import asyncio
from fractions import Fraction

import numpy as np
import pytest

pytest.importorskip("jax")

from xaynet_tpu.core.crypto.encrypt import PublicEncryptKey
from xaynet_tpu.core.crypto.sign import SigningKeyPair
from xaynet_tpu.core.mask.masking import Aggregation, Masker
from xaynet_tpu.core.mask.model import Scalar
from xaynet_tpu.edge import (
    EdgeAdmitError,
    EdgeAggregator,
    EdgeCoordinatorApi,
    EdgeService,
    EnvelopeError,
    PartialAggregateEnvelope,
)
from xaynet_tpu.edge.rest import EdgeRestServer
from xaynet_tpu.sdk.client import HttpClient, ResilientClient
from xaynet_tpu.sdk.simulation import build_update_message, keys_for_task
from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
from xaynet_tpu.sdk.traits import ModelStore
from xaynet_tpu.server.requests import UpdateRequest
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    EdgeSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store
from xaynet_tpu.telemetry.registry import get_registry

SUM_PROB, UPDATE_PROB = 0.4, 0.5
MODEL_LEN = 7


def _mask_config():
    from xaynet_tpu.server.settings import MaskSettings

    return MaskSettings().to_config().pair()


def _settings(n_update: int, phase_max: float = 30.0) -> Settings:
    settings = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=SUM_PROB, count=CountSettings(1, 1), time=TimeSettings(0, phase_max)
            ),
            update=PhaseSettings(
                prob=UPDATE_PROB,
                count=CountSettings(n_update, n_update),
                time=TimeSettings(0, phase_max),
            ),
            sum2=Sum2Settings(
                count=CountSettings(1, 1), time=TimeSettings(0, phase_max)
            ),
        )
    )
    settings.model.length = MODEL_LEN
    settings.edge.enabled = True
    return settings


class _ArrayModelStore(ModelStore):
    def __init__(self, model=None):
        self.model = model

    async def load_model(self):
        return self.model


class _Coordinator:
    """In-process coordinator + REST server with the edge API enabled."""

    def __init__(self, settings: Settings):
        self.settings = settings

    async def __aenter__(self):
        self.store = Store(
            InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor()
        )
        machine, request_tx, events = await StateMachineInitializer(
            self.settings, self.store
        ).init()
        self.machine = machine
        self.handler = PetMessageHandler(events, request_tx)
        self.fetcher = Fetcher(events)
        self.events = events
        self.request_tx = request_tx
        self.edge_api = EdgeCoordinatorApi(events, request_tx)
        self.rest = RestServer(self.fetcher, self.handler, edge_api=self.edge_api)
        self.host, self.port = await self.rest.start("127.0.0.1", 0)
        self.machine_task = asyncio.create_task(machine.run())
        return self

    async def __aexit__(self, *exc):
        self.machine_task.cancel()
        await self.rest.stop()
        try:
            await self.machine_task
        except (asyncio.CancelledError, Exception):
            pass

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def wait_phase(self, name: str) -> None:
        while self.fetcher.phase().value != name:
            await asyncio.sleep(0.01)


class _Edge:
    """One in-process edge (EdgeService + participant-facing REST)."""

    def __init__(self, upstream_url: str, edge_id: str, max_members: int = 64,
                 linger_s: float = 0.05):
        settings = Settings.default()
        settings.edge = EdgeSettings(
            upstream_url=upstream_url,
            edge_id=edge_id,
            max_members=max_members,
            linger_s=linger_s,
            poll_s=0.02,
        )
        self.service = EdgeService(settings)
        self.rest = EdgeRestServer(self.service)

    async def __aenter__(self):
        self.host, self.port = await self.rest.start("127.0.0.1", 0)
        await self.service.start()
        return self

    async def __aexit__(self, *exc):
        await self.rest.stop()
        await self.service.stop()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def wait_update_phase(self) -> None:
        while not self.service.accepting_updates:
            await asyncio.sleep(0.01)


def _build_update_requests(params, sum_dict, models, scalar, key_start=5000):
    """Protocol-level UpdateRequests (no message layer): distinct pks, one
    masked model + local seed dict each — the edge fold path's input."""
    out = []
    for i, model in enumerate(models):
        keys = SigningKeyPair.derive_from_seed(
            (key_start + i).to_bytes(32, "little")
        )
        masker = Masker(params.mask_config)
        seed, masked = masker.mask(Scalar.from_fraction(scalar), np.asarray(model))
        out.append(
            UpdateRequest(
                participant_pk=keys.public,
                local_seed_dict={
                    sum_pk: seed.encrypt(PublicEncryptKey(ephm_pk))
                    for sum_pk, ephm_pk in sum_dict.items()
                },
                masked_model=masked,
            )
        )
    return out


async def _drive_round(
    coord: _Coordinator, models, update_targets, before_updates=None
) -> np.ndarray:
    """One full PET round over REST; update uploads go to ``update_targets``
    (HttpClients, round-robin) — the coordinator itself for the flat run,
    edges for the two-tier run. ``before_updates`` (async) runs once the
    update phase is open and the sum dictionary exists, before any upload —
    the two-tier test waits for the edges to sync the phase there."""
    probe = HttpClient(coord.url)
    await coord.wait_phase("sum")
    params = await probe.get_round_params()
    seed = params.seed.as_bytes()
    n = len(models)
    for target in update_targets:
        # resilient targets pin the round's trace id so their uploads
        # stitch into the coordinator's round trace (DESIGN §16)
        set_round_trace = getattr(target, "set_round_trace", None)
        if set_round_trace is not None:
            set_round_trace(seed)

    sum_keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=0)
    summer = ParticipantSM(
        PetSettings(keys=sum_keys),
        ResilientClient(HttpClient(coord.url)),
        _ArrayModelStore(None),
    )

    async def drive_summer():
        for _ in range(4000):
            try:
                await summer.transition()
            except Exception:
                pass
            model = await probe.get_model()
            if model is not None and summer.phase.value == "awaiting":
                return
            await asyncio.sleep(0.01)

    summer_task = asyncio.create_task(drive_summer())
    try:
        await coord.wait_phase("update")
        sum_dict = None
        while not sum_dict:
            sum_dict = await probe.get_sums()
            await asyncio.sleep(0.01)
        if before_updates is not None:
            await before_updates()
        sealed = [
            build_update_message(
                params,
                keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(20 + i) * 1000),
                sum_dict,
                models[i],
                Fraction(1, n),
            )
            for i in range(n)
        ]
        await asyncio.gather(
            *(
                update_targets[i % len(update_targets)].send_message(blob)
                for i, blob in enumerate(sealed)
            )
        )
        await asyncio.wait_for(summer_task, timeout=90)
    finally:
        if not summer_task.done():
            summer_task.cancel()
    model = await probe.get_model()
    assert model is not None
    return np.asarray(model)


# --- envelope wire format ----------------------------------------------------


def test_envelope_roundtrip_and_corruption():
    config = _mask_config()
    rng = np.random.default_rng(3)
    models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(3)]
    sum_dict = {b"\x01" * 32: b"\x02" * 32}
    params = _FakeParams(config)
    reqs = _build_update_requests(params, sum_dict, models, Fraction(1, 3))

    agg = EdgeAggregator(config, MODEL_LEN, max_members=8)
    for req in reqs:
        agg.admit(req)
    envelope = agg.seal("edge-a", b"\x07" * 32)
    blob = envelope.to_bytes()
    back = PartialAggregateEnvelope.from_bytes(blob)
    assert back.edge_id == "edge-a"
    assert back.window_seq == 0
    assert back.round_seed == b"\x07" * 32
    assert back.members == envelope.members
    assert back.masked == envelope.masked
    assert set(back.seed_dicts) == set(envelope.members)
    for pk in back.members:
        assert {
            k: v.as_bytes() for k, v in back.seed_dicts[pk].items()
        } == {k: v.as_bytes() for k, v in envelope.seed_dicts[pk].items()}

    # window sequence advances; dedup: resubmitting a shipped member fails
    with pytest.raises(EdgeAdmitError):
        agg.admit(reqs[0])

    # corruption: a flipped payload byte fails the digest, truncation fails
    corrupt = bytearray(blob)
    corrupt[-1] ^= 0x01
    with pytest.raises(EnvelopeError):
        PartialAggregateEnvelope.from_bytes(bytes(corrupt))
    with pytest.raises(EnvelopeError):
        PartialAggregateEnvelope.from_bytes(blob[: len(blob) // 2])
    with pytest.raises(EnvelopeError):
        PartialAggregateEnvelope.from_bytes(b"NOTMAGIC" + blob)


class _FakeParams:
    """Just enough RoundParameters surface for request building."""

    def __init__(self, config):
        self.mask_config = config
        self.model_length = MODEL_LEN


# --- partition-merge property ------------------------------------------------


def test_partition_merge_byte_identical_to_flat_fold():
    """Merging K random partitions of one update set through edge partials
    is byte-identical to the flat fold, for several random partitions, and
    the merged seed dict is independent of the merge order."""
    config = _mask_config()
    rng = np.random.default_rng(11)
    n = 12
    models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(n)]
    sum_dict = {b"\x01" * 32: b"\x02" * 32, b"\x03" * 32: b"\x04" * 32}
    params = _FakeParams(config)
    reqs = _build_update_requests(params, sum_dict, models, Fraction(1, n))

    # flat fold: every update aggregated centrally, in order
    flat = Aggregation(config, MODEL_LEN)
    flat_seed_dict: dict = {}
    for req in reqs:
        flat.aggregate(req.masked_model)
        for sum_pk, enc in req.local_seed_dict.items():
            flat_seed_dict.setdefault(sum_pk, {})[req.participant_pk] = enc.as_bytes()

    for trial in range(4):
        prng = np.random.default_rng(100 + trial)
        k = int(prng.integers(1, 5))
        assignment = prng.integers(0, k, size=n)
        order = list(prng.permutation(k))

        merged = Aggregation(config, MODEL_LEN)
        merged_seed_dict: dict = {}
        total = 0
        for part in order:
            member_ids = [i for i in range(n) if assignment[i] == part]
            if not member_ids:
                continue
            edge = EdgeAggregator(config, MODEL_LEN, max_members=n)
            for i in member_ids:
                edge.admit(reqs[i])
            envelope = edge.seal(f"edge-{part}", b"\x07" * 32)
            envelope = PartialAggregateEnvelope.from_bytes(envelope.to_bytes())
            merged.aggregate_partial(envelope.masked, len(envelope))
            total += len(envelope)
            # seed-dict merge order independence: dict merge is keyed by
            # (sum_pk, update_pk) — disjoint per member, any order works
            for pk in envelope.members:
                for sum_pk, enc in envelope.seed_dicts[pk].items():
                    merged_seed_dict.setdefault(sum_pk, {})[pk] = enc.as_bytes()

        assert total == n
        assert merged.nb_models == flat.nb_models == n
        assert (
            merged.object.vect.data.tobytes() == flat.object.vect.data.tobytes()
        ), f"trial {trial}: partitioned fold diverged from flat fold"
        assert merged.object.unit.data.tobytes() == flat.object.unit.data.tobytes()
        assert merged_seed_dict == flat_seed_dict


# --- two-tier round ----------------------------------------------------------


def test_two_tier_round_byte_identical_with_batched_ingress():
    """Acceptance: a 2-edge x 8-participant round produces a global model
    byte-identical to the flat run on the same inputs, with the
    coordinator folding ~N/edge-batch envelopes instead of N updates, and
    a replayed envelope rejected by the per-edge watermark."""
    registry = get_registry()

    def sample(name, labels=None):
        return registry.sample_value(name, labels) or 0.0

    async def run():
        n = 8
        rng = np.random.default_rng(5)
        models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(n)]
        expected = sum(m.astype(np.float64) for m in models) / n

        folded0 = sample("xaynet_edge_members_folded_total")
        accepted0 = sample("xaynet_edge_envelopes_total", {"outcome": "accepted"})

        async with _Coordinator(_settings(n)) as coord:
            async with _Edge(coord.url, "edge-a", max_members=4) as ea, _Edge(
                coord.url, "edge-b", max_members=4
            ) as eb:
                await coord.wait_phase("sum")
                targets = [HttpClient(ea.url), HttpClient(eb.url)]

                async def edges_see_update_phase():
                    # lock-step: edges must SEE the update phase before the
                    # flood, or early uploads would be relayed upstream and
                    # dilute the batching assertion
                    await ea.wait_update_phase()
                    await eb.wait_update_phase()

                got_tiered = await asyncio.wait_for(
                    _drive_round(
                        coord, models, targets, before_updates=edges_see_update_phase
                    ),
                    120,
                )
                # every update was folded via envelopes, none directly
                assert sample("xaynet_edge_members_folded_total") - folded0 == n
                envelopes = (
                    sample("xaynet_edge_envelopes_total", {"outcome": "accepted"})
                    - accepted0
                )
                # coordinator ingress shrank by ~the edge batch factor:
                # 8 updates over 2 edges with max_members=4 -> 2..4
                # envelopes (linger may split a window)
                assert 1 <= envelopes <= n / 2, envelopes

        np.testing.assert_allclose(got_tiered, expected, atol=1e-9)

        # flat control run: same models, updates straight to the coordinator
        async with _Coordinator(_settings(n)) as coord:
            got_flat = await asyncio.wait_for(
                _drive_round(coord, models, [HttpClient(coord.url)]), 120
            )
        np.testing.assert_allclose(got_flat, expected, atol=1e-9)
        assert got_tiered.tobytes() == got_flat.tobytes()

    asyncio.run(run())


# --- watermark + atomicity ---------------------------------------------------


def test_envelope_watermark_and_atomicity():
    """Direct protocol-level checks on the coordinator: a replayed envelope
    is rejected as stale, an envelope overlapping an already-seeded member
    is rejected WHOLE (the fresh member is not folded either), and the
    nb_models == seed-watermark invariant holds throughout."""

    async def run():
        n_min = 6
        config = _mask_config()
        rng = np.random.default_rng(9)
        models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(8)]
        # the members that end up folded below: edge-a windows [0,1,2] and
        # [6], edge-b window [3,4]
        expected = sum(models[i].astype(np.float64) for i in (0, 1, 2, 3, 4, 6)) / 6

        async with _Coordinator(_settings(n_min)) as coord:
            probe = HttpClient(coord.url)
            await coord.wait_phase("sum")
            params = await probe.get_round_params()
            seed = params.seed.as_bytes()
            summer = ParticipantSM(
                PetSettings(keys=keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum")),
                HttpClient(coord.url),
                _ArrayModelStore(None),
            )

            async def drive_summer():
                for _ in range(4000):
                    try:
                        await summer.transition()
                    except Exception:
                        pass
                    model = await probe.get_model()
                    if model is not None and summer.phase.value == "awaiting":
                        return
                    await asyncio.sleep(0.01)

            summer_task = asyncio.create_task(drive_summer())
            try:
                await coord.wait_phase("update")
                sum_dict = None
                while not sum_dict:
                    sum_dict = await probe.get_sums()
                    await asyncio.sleep(0.01)

                reqs = _build_update_requests(
                    params, sum_dict, models, Fraction(1, 6), key_start=7000
                )

                aggs: dict[str, EdgeAggregator] = {}

                def seal(member_reqs, edge_id):
                    edge = aggs.setdefault(
                        edge_id, EdgeAggregator(config, MODEL_LEN, max_members=8)
                    )
                    for r in member_reqs:
                        edge.admit(r)
                    return edge.seal(edge_id, seed)

                api = coord.edge_api
                env_a = seal(reqs[0:3], "edge-a")
                ok, _ = await api.submit_envelope(env_a.to_bytes())
                assert ok

                # replay of the envelope AT the watermark (lost ack, the
                # edge retried): acked idempotently as success — but NOT
                # folded again (the final model below proves nb_models and
                # the count window did not double-advance)
                ok, detail = await api.submit_envelope(env_a.to_bytes())
                assert ok, detail

                # overlap: reqs[2] already seeded + a FRESH member -> the
                # whole envelope bounces; the fresh member is NOT seeded
                env_overlap = seal([reqs[2], reqs[6]], "edge-b")
                ok, detail = await api.submit_envelope(env_overlap.to_bytes())
                assert not ok and "already seeded" in detail
                seed_dict_now = await coord.store.coordinator.seed_dict() or {}
                seeded_pks = {pk for inner in seed_dict_now.values() for pk in inner}
                assert reqs[6].participant_pk not in seeded_pks

                # ...so the bounced fresh member reaches the round through
                # another window (here: edge-a's next one, seq 1)
                env_a2 = seal([reqs[6]], "edge-a")
                ok, _ = await api.submit_envelope(env_a2.to_bytes())
                assert ok

                # an envelope strictly BELOW the watermark (an older
                # window, not the lost-ack replay) is rejected stale
                ok, detail = await api.submit_envelope(env_a.to_bytes())
                assert not ok and "stale" in detail

                # wrong round seed -> rejected
                env_wrong = seal([reqs[7]], "edge-c")
                env_wrong.round_seed = b"\x00" * 32
                ok, detail = await api.submit_envelope(env_wrong.to_bytes())
                assert not ok and "another round" in detail

                # a garbled envelope is a 400-class EnvelopeError
                with pytest.raises(EnvelopeError):
                    await api.submit_envelope(b"XNEDGE1garbage")

                # edge-b's next window completes the count window (3+1+2)
                env_b = seal(reqs[3:5], "edge-b")
                ok, _ = await api.submit_envelope(env_b.to_bytes())
                assert ok

                await asyncio.wait_for(summer_task, timeout=60)
            finally:
                if not summer_task.done():
                    summer_task.cancel()

            # the round unmasked exactly the 6 folded members: nb_models
            # agreed with the seed watermark, or unmask would have failed
            model = await probe.get_model()
            np.testing.assert_allclose(np.asarray(model), expected, atol=1e-9)

    asyncio.run(run())


# --- window straddling + restart sequences -----------------------------------


def test_aggregator_start_seq_continues_past_a_crashed_incarnation():
    """A restarted edge process must ship sequences PAST its predecessor's
    (the coordinator's per-edge watermark is strictly monotonic within a
    round): ``start_seq`` seeds the window sequence, and seals increment
    from there."""
    config = _mask_config()
    edge = EdgeAggregator(config, MODEL_LEN, max_members=4, start_seq=1_000)
    params_seed = b"\x05" * 32
    reqs = _build_update_requests(
        _FakeParams(config), {b"s" * 32: b"e" * 32}, [np.ones(MODEL_LEN)], Fraction(1, 1),
        key_start=9_500,
    )
    edge.admit(reqs[0])
    assert edge.seal("edge-r", params_seed).window_seq == 1_000
    edge2 = EdgeAggregator(config, MODEL_LEN, max_members=4, start_seq=1_000)
    edge2.admit(
        _build_update_requests(
            _FakeParams(config), {b"s" * 32: b"e" * 32}, [np.ones(MODEL_LEN)],
            Fraction(1, 1), key_start=9_600,
        )[0]
    )
    assert edge2.seal("edge-r", params_seed).window_seq == 1_000  # same base
    edge2.admit(
        _build_update_requests(
            _FakeParams(config), {b"s" * 32: b"e" * 32}, [np.ones(MODEL_LEN)],
            Fraction(1, 1), key_start=9_700,
        )[0]
    )
    assert edge2.seal("edge-r", params_seed).window_seq == 1_001  # increments


class _FakeParams:
    """Just enough RoundParameters surface for _build_update_requests."""

    def __init__(self, config):
        self.mask_config = config


def test_coalesced_batch_straddling_window_boundary_seals_mid_batch():
    """A coalesced ingest batch larger than the window's remaining space
    must seal the full window MID-BATCH and fold the tail into a fresh one
    — never bounce tail members with 'window-full' (a rejection the PR-5
    participant FSM treats as a permanent upload failure)."""

    async def run():
        n = 5
        rng = np.random.default_rng(23)
        models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(n)]
        expected = sum(m.astype(np.float64) for m in models) / n

        async with _Coordinator(_settings(n)) as coord:
            probe = HttpClient(coord.url)
            async with _Edge(
                coord.url, "edge-straddle", max_members=2, linger_s=0.05
            ) as edge:

                async def inject_batch():
                    await edge.wait_update_phase()
                    params = await probe.get_round_params()
                    sum_dict = await probe.get_sums()
                    reqs = _build_update_requests(
                        params, sum_dict, models, Fraction(1, n), key_start=11_000
                    )
                    loop = asyncio.get_running_loop()
                    futures = [loop.create_future() for _ in reqs]
                    from xaynet_tpu.server.requests import CoalescedUpdates

                    # one batch of 5 against max_members=2: straddles two
                    # window boundaries (2 + 2 + 1)
                    await edge.service.request_tx.request(
                        CoalescedUpdates(members=reqs, responses=futures)
                    )
                    results = await asyncio.gather(*futures, return_exceptions=True)
                    rejected = [r for r in results if isinstance(r, Exception)]
                    assert not rejected, f"tail members bounced: {rejected}"

                model = await _drive_round(
                    coord,
                    [],  # updates injected below, not uploaded over REST
                    [HttpClient(coord.url)],
                    before_updates=inject_batch,
                )
                np.testing.assert_allclose(model, expected, atol=1e-9)
                # the batch became >= 3 envelopes (2+2+1), not one bounce
                assert edge.service.shipped >= 3

    asyncio.run(run())


# --- edge crash mid-window ---------------------------------------------------


def test_edge_crash_mid_window_participants_fall_back_upstream():
    """An edge that dies before shipping its window loses nothing durable:
    the participants (whose uploads it absorbed) retry upstream directly,
    the round completes, and the invariant holds (the unmasked model is
    exactly the final member set)."""

    async def run():
        n = 4
        rng = np.random.default_rng(17)
        models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(n)]
        expected = sum(m.astype(np.float64) for m in models) / n

        async with _Coordinator(_settings(n)) as coord:
            probe = HttpClient(coord.url)
            # an edge with a long linger: it will absorb uploads and sit on
            # them, simulating a crash before any envelope ships
            async with _Edge(
                coord.url, "edge-crash", max_members=64, linger_s=30.0
            ) as edge:
                await coord.wait_phase("sum")
                params = await probe.get_round_params()
                seed = params.seed.as_bytes()
                summer = ParticipantSM(
                    PetSettings(keys=keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum")),
                    HttpClient(coord.url),
                    _ArrayModelStore(None),
                )

                async def drive_summer():
                    for _ in range(4000):
                        try:
                            await summer.transition()
                        except Exception:
                            pass
                        model = await probe.get_model()
                        if model is not None and summer.phase.value == "awaiting":
                            return
                        await asyncio.sleep(0.01)

                summer_task = asyncio.create_task(drive_summer())
                try:
                    await coord.wait_phase("update")
                    sum_dict = None
                    while not sum_dict:
                        sum_dict = await probe.get_sums()
                        await asyncio.sleep(0.01)
                    await edge.wait_update_phase()
                    sealed = [
                        build_update_message(
                            params,
                            keys_for_task(
                                seed, SUM_PROB, UPDATE_PROB, "update", start=(40 + i) * 1000
                            ),
                            sum_dict,
                            models[i],
                            Fraction(1, n),
                        )
                        for i in range(n)
                    ]
                    # half the participants upload via the edge...
                    edge_client = HttpClient(edge.url)
                    for blob in sealed[: n // 2]:
                        await edge_client.send_message(blob)
                    # ...whose window absorbed them (nothing shipped yet)
                    while edge.service.aggregator.pending < n // 2:
                        await asyncio.sleep(0.01)
                    assert edge.service.shipped == 0
                    # CRASH: the edge dies mid-window
                    await edge.service.stop()

                    # the participants' resilient clients notice the dead
                    # edge and fall back to the coordinator directly —
                    # modeled here by re-uploading ALL updates upstream
                    # (the edge-absorbed ones were never seeded upstream,
                    # so their retries are fresh, not duplicates)
                    direct = HttpClient(coord.url)
                    for blob in sealed:
                        await direct.send_message(blob)

                    await asyncio.wait_for(summer_task, timeout=60)
                finally:
                    if not summer_task.done():
                        summer_task.cancel()

                model = await probe.get_model()
                np.testing.assert_allclose(np.asarray(model), expected, atol=1e-9)

    asyncio.run(run())


# --- distributed round tracing (docs/DESIGN.md §16) --------------------------


def test_two_tier_round_single_stitched_trace(tmp_path):
    """Acceptance: a two-tier round (edge -> coordinator shard pipeline,
    SDK summer) produces ONE Chrome trace that passes the CI validator and
    carries spans from all five subsystems under ONE trace id — the id
    every tier derived independently from the round seed."""
    import sys as _sys
    from pathlib import Path as _Path

    repo = _Path(__file__).resolve().parent.parent
    if str(repo) not in _sys.path:
        _sys.path.insert(0, str(repo))
    from tools import trace_report
    from xaynet_tpu.telemetry import tracing

    tracer = tracing.get_tracer()
    old_mode, old_dir = tracer.mode, tracer.trace_dir
    tracer.configure(mode="on", trace_dir=str(tmp_path))

    async def run():
        n = 4
        rng = np.random.default_rng(9)
        models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(n)]
        settings = _settings(n)
        # the device path (shard-parallel on a multi-device mesh, the
        # single-worker streaming pipeline otherwise) — the `stream.*`
        # spans come from here
        settings.aggregation.device = True
        settings.aggregation.batch_size = 2
        async with _Coordinator(settings) as coord:
            async with _Edge(coord.url, "edge-tr", max_members=2) as edge:
                await coord.wait_phase("sum")
                targets = [ResilientClient(HttpClient(edge.url))]

                async def edge_ready():
                    await edge.wait_update_phase()

                try:
                    await asyncio.wait_for(
                        _drive_round(coord, models, targets, before_updates=edge_ready),
                        120,
                    )
                    # the round's export flushes when the NEXT round's Idle
                    # opens its window — wait for the file inside the
                    # coordinator's lifetime
                    for _ in range(400):
                        if list(tmp_path.glob("round_*.trace.json")):
                            break
                        await asyncio.sleep(0.05)
                finally:
                    for t in targets:
                        t.close()

    try:
        asyncio.run(run())
        files = sorted(tmp_path.glob("round_*.trace.json"))
        assert files, "no per-round trace exported"
        events = trace_report.load_events(str(files[0]))
        assert trace_report.validate(events) == []
        (round_event,) = [e for e in events if e["name"] == "round"]
        trace_id = round_event["args"]["trace"]
        stitched = [e for e in events if e["args"].get("trace") == trace_id]
        subsystems = {e["cat"] for e in stitched}
        # the five concurrent subsystems + the SDK, one trace id
        assert {"rest", "ingest", "stream", "phase", "edge", "sdk"} <= subsystems, (
            subsystems
        )
        # the envelope hop stitched: the coordinator's fold span links the
        # edge's seal span
        folds = [e for e in stitched if e["name"] == "edge.upstream_fold"]
        seals = {e["args"]["span"] for e in stitched if e["name"] == "edge.seal"}
        assert folds and any(e["args"].get("link") in seals for e in folds)
    finally:
        tracer.configure(mode=old_mode, trace_dir=old_dir)
        tracer.end_round()
