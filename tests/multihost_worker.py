"""Worker process for the multi-process multi-host aggregation tests.

Each worker is one "host": it joins the JAX distributed runtime, owns
``devs_per_proc`` virtual CPU devices of the 8-device global mesh,
parses/stages ONLY its slice of the model axis, and verifies its slice of
the unmasked result against the host oracle. Run by
tests/test_multihost.py (2-process default, 4-process under
XAYNET_STRESS=1), never directly by pytest.

argv: port process_id n_procs devs_per_proc
"""

import os
import sys

_DEVS = sys.argv[4] if len(sys.argv) > 4 else "4"
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_DEVS}"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xaynet_tpu.core.mask.config import (  # noqa: E402
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.ops import limbs as host_limbs  # noqa: E402
from xaynet_tpu.parallel.multihost import MultiHostAggregator, initialize  # noqa: E402


def main() -> None:
    port, process_id = sys.argv[1], int(sys.argv[2])
    n_procs = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    initialize(f"127.0.0.1:{port}", num_processes=n_procs, process_id=process_id)
    assert jax.process_count() == n_procs, jax.process_count()
    assert jax.device_count() == n_procs * int(_DEVS), jax.device_count()

    config = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    order = config.order
    n_limb = host_limbs.n_limbs_for_order(order)
    ol = host_limbs.order_limbs_for(order)
    model_len, k = 1000, 6  # deliberately NOT divisible by 8 (exercises padding)

    # identical deterministic data on both workers; each stages its slice
    rng = np.random.default_rng(123)
    # valid group elements: bound the top limb so value < order
    top = int(order >> 32)
    wire = rng.integers(0, 1 << 32, size=(k, model_len, n_limb), dtype=np.uint32)
    wire[:, :, n_limb - 1] = rng.integers(0, top, size=(k, model_len), dtype=np.uint32)
    mask = rng.integers(0, 1 << 32, size=(model_len, n_limb), dtype=np.uint32)
    mask[:, n_limb - 1] = rng.integers(0, top, size=model_len, dtype=np.uint32)

    agg = MultiHostAggregator(config, model_len)
    lo, hi = agg.local_slice
    assert hi > lo, (lo, hi)
    agg.add_local_batch(wire[:, lo:hi, :])
    assert agg.nb_models == k

    out_local = agg.unmask_local(mask[lo:hi])

    # host oracle over the full model; compare this worker's slice
    expected = host_limbs.batch_mod_sum(wire, ol)
    expected = host_limbs.mod_sub(expected, mask, ol)
    assert np.array_equal(out_local, expected[lo:hi]), "unmasked slice mismatch"

    # --- wire-ingest leg: each host ships only its RAW byte sub-block ----
    # one extra update carries an invalid element in the LAST host's slice;
    # the validity psum must exclude it on EVERY host identically
    bpn = config.bytes_per_number
    bad = wire[0].copy()
    bad[model_len - 1] = np.iinfo(np.uint32).max  # element >= order
    stack2 = np.concatenate([wire, bad[None]], axis=0)
    raw_full = np.stack(
        [
            np.frombuffer(host_limbs.limbs_to_bytes_le(stack2[i], bpn), dtype=np.uint8)
            for i in range(k + 1)
        ]
    )
    agg2 = MultiHostAggregator(config, model_len)
    ok = agg2.add_local_wire_batch(raw_full[:, lo * bpn : hi * bpn])
    assert ok.tolist() == [True] * k + [False], f"acceptance diverged: {ok.tolist()}"
    assert agg2.nb_models == k, agg2.nb_models
    assert np.array_equal(
        agg2.snapshot_local(), host_limbs.batch_mod_sum(wire, ol)[lo:hi]
    ), "wire-ingest slice mismatch"

    print(f"WORKER {process_id} OK slice=[{lo},{hi})", flush=True)


if __name__ == "__main__":
    main()
