"""Per-tenant SLO engine + operator console (ISSUE 16, docs/DESIGN.md §20).

Covers the burn-rate math over cumulative samples, the warn/page
transition machinery (both-windows gate, transition counter, bounded
ring, flight dump on page), the scrubbed ``/alerts`` payload, the
``[slo]`` settings section (parsing, validation, env override), and the
live REST surface: ``GET /statusz`` renders the console HTML and
``GET /alerts`` serves the engine's JSON — with the console import
provably jax-free (the zero-jax claim of the REST layer).
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from xaynet_tpu.server.settings import SettingsError, SloSettings  # noqa: E402
from xaynet_tpu.telemetry import slo as slo_mod  # noqa: E402
from xaynet_tpu.telemetry.registry import get_registry  # noqa: E402
from xaynet_tpu.telemetry.slo import SloConfig, SloEngine  # noqa: E402


def _engine(**overrides) -> SloEngine:
    cfg = dict(
        enabled=True,
        round_wall_s=1.0,
        round_wall_budget=0.05,
        degraded_budget=0.1,
        shed_budget=0.05,
        fast_window_s=3600.0,
        slow_window_s=3600.0,
        warn_burn=6.0,
        page_burn=14.4,
    )
    cfg.update(overrides)
    return SloEngine(SloConfig(**cfg))


def _gauge(name: str, tenant: str, slo: str):
    return get_registry().sample_value(name, {"tenant": tenant, "slo": slo})


# --- burn math ---------------------------------------------------------------


def test_burn_rate_is_bad_fraction_over_budget():
    eng = _engine()
    t = "slo-burn"
    for rid, wall in enumerate((2.0, 0.5, 2.0, 0.5)):  # 2 bad of 4
        eng.on_round(t, rid, wall, degraded=False)
    # (2/4) / 0.05 = 10.0
    assert _gauge("xaynet_slo_burn_rate", t, "round_wall") == pytest.approx(10.0)
    assert _gauge("xaynet_slo_budget_remaining", t, "round_wall") == pytest.approx(
        1.0 - 10.0, abs=1e-6
    )
    # all rounds healthy on the degraded SLO
    assert _gauge("xaynet_slo_burn_rate", t, "degraded") == 0.0
    assert _gauge("xaynet_slo_budget_remaining", t, "degraded") == 1.0


def test_per_tenant_targets_move_gauges_independently():
    eng = _engine(tenant_round_wall_s={"strict": 0.1})
    # the same 0.5s wall is healthy for the default target, bad for 'strict'
    eng.on_round("slo-lax", 1, 0.5, degraded=False)
    eng.on_round("strict", 1, 0.5, degraded=False)
    assert _gauge("xaynet_slo_burn_rate", "slo-lax", "round_wall") == 0.0
    assert _gauge("xaynet_slo_burn_rate", "strict", "round_wall") == pytest.approx(20.0)


def test_degraded_slo_counts_degraded_rounds():
    eng = _engine()
    t = "slo-degr"
    eng.on_round(t, 1, 0.1, degraded=True)
    eng.on_round(t, 2, 0.1, degraded=False)
    # (1/2) / 0.1 = 5.0
    assert _gauge("xaynet_slo_burn_rate", t, "degraded") == pytest.approx(5.0)


def test_disabled_engine_records_nothing():
    eng = _engine(enabled=False)
    eng.on_round("slo-off", 1, 99.0, degraded=True)
    assert _gauge("xaynet_slo_burn_rate", "slo-off", "round_wall") is None
    assert eng.active_alerts() == []


# --- alert transitions -------------------------------------------------------


def test_warn_then_page_transitions_counter_and_ring(monkeypatch):
    dumps = []
    monkeypatch.setattr(
        slo_mod, "time", slo_mod.time
    )  # keep module ref (clarity only)
    import xaynet_tpu.telemetry.recorder as recorder_mod

    monkeypatch.setattr(
        recorder_mod, "flight_dump", lambda *a, **kw: dumps.append((a, kw)) or "/x"
    )
    t = "slo-trip"
    before = slo_mod.SLO_ALERTS.labels(slo="round_wall", severity="page").value
    eng = _engine()
    for rid in range(3):  # every round slow: burn (1.0)/0.05 = 20 >= 14.4
        eng.on_round(t, rid, 5.0, degraded=False)
    active = eng.active_alerts()
    assert {"tenant": t, "slo": "round_wall", "severity": "page"} in active
    after = slo_mod.SLO_ALERTS.labels(slo="round_wall", severity="page").value
    assert after == before + 1  # ONE transition, not one per round
    # the page dropped a forensic bundle through the flight recorder
    assert len(dumps) == 1
    args, kwargs = dumps[0]
    assert args[0] == "slo-page"
    assert kwargs["tenant"] == t and kwargs["slo"] == "round_wall"
    ring = [e for e in eng.recent_alerts() if e["tenant"] == t]
    assert ring[-1]["severity"] == "page" and ring[-1]["previous"] == "ok"
    # recovery: enough fast rounds drain the bad fraction below warn
    for rid in range(3, 60):
        eng.on_round(t, rid, 0.1, degraded=False)
    assert eng.active_alerts() == []
    ring = [e for e in eng.recent_alerts() if e["tenant"] == t]
    # the burn drains gradually, so recovery steps page -> warn -> ok
    assert [e["severity"] for e in ring] == ["page", "warn", "ok"]
    # clearing is NOT a new alert transition
    assert slo_mod.SLO_ALERTS.labels(slo="round_wall", severity="page").value == after


def test_both_windows_must_burn(monkeypatch):
    """A fast spike with a clean slow window must not alert: the effective
    burn is min(fast, slow)."""
    eng = _engine(fast_window_s=10.0, slow_window_s=3600.0)
    t = "slo-spike"
    now = [1000.0]
    monkeypatch.setattr(slo_mod.time, "monotonic", lambda: now[0])
    # a long healthy history ages into the slow window only
    for rid in range(50):
        now[0] += 30.0
        eng.on_round(t, rid, 0.1, degraded=False)
    # then a slow-round spike, alone inside the fast window
    now[0] += 25.0
    eng.on_round(t, 50, 5.0, degraded=False)
    fast = _gauge("xaynet_slo_burn_rate", t, "round_wall")
    assert fast == pytest.approx(20.0)  # 1/1 bad in the fast window
    assert eng.active_alerts() == []  # slow window kept it from firing


def test_alerts_payload_shape_and_scrub():
    eng = _engine(tenant_round_wall_s={"edge": 2.0})
    # a dynamically-secret key sneaking into the ring must not survive
    # export (defense-in-depth §18; scrub_attrs also runs at append time)
    eng._ring.append({"tenant": "x", "api_token": "hunter2-very-secret"})
    payload = eng.alerts_payload()
    assert set(payload) == {"enabled", "targets", "active", "recent"}
    assert payload["targets"]["tenants"] == {"edge": 2.0}
    blob = json.dumps(payload)
    assert "hunter2-very-secret" not in blob
    assert "<redacted" in blob


# --- [slo] settings section --------------------------------------------------


def test_slo_settings_tenant_targets_parse():
    s = SloSettings(tenant_round_wall_s="alpha=3.0, beta=9")
    assert s.tenant_targets() == {"alpha": 3.0, "beta": 9.0}
    SloSettings().validate()  # defaults are valid


@pytest.mark.parametrize(
    "kwargs",
    [
        {"round_wall_s": 0.0},
        {"tenant_round_wall_s": "alpha=x"},
        {"tenant_round_wall_s": "=3.0"},
        {"round_wall_budget": 0.0},
        {"degraded_budget": 1.5},
        {"fast_window_s": 600.0, "slow_window_s": 300.0},
        {"warn_burn": 10.0, "page_burn": 5.0},
    ],
)
def test_slo_settings_validation_rejects(kwargs):
    with pytest.raises(SettingsError):
        SloSettings(**kwargs).validate()


def test_slo_settings_env_override(monkeypatch):
    from xaynet_tpu.server.settings import Settings

    monkeypatch.setenv("XAYNET__SLO__ROUND_WALL_S", "12.5")
    monkeypatch.setenv("XAYNET__SLO__TENANT_ROUND_WALL_S", "a=3.0,b=9")
    s = Settings.load(None)
    assert s.slo.round_wall_s == 12.5
    assert s.slo.tenant_targets() == {"a": 3.0, "b": 9.0}


def test_configure_from_settings_section():
    eng_before = slo_mod.get_engine().config
    try:
        slo_mod.configure(SloSettings(round_wall_s=42.0, tenant_round_wall_s="t=7"))
        cfg = slo_mod.get_engine().config
        assert cfg.round_wall_s == 42.0
        assert cfg.target_for("t") == 7.0
        assert cfg.target_for("other") == 42.0
    finally:
        slo_mod.get_engine().configure(eng_before)


# --- REST surface ------------------------------------------------------------


def test_console_module_needs_no_jax():
    """The /statusz path renders from registry/timeline/SLO state only —
    importing it must not drag jax into the process."""
    code = (
        "import sys; import xaynet_tpu.server.console, xaynet_tpu.server.rest; "
        "sys.exit(1 if 'jax' in sys.modules else 0)"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=str(REPO), capture_output=True
    )
    assert proc.returncode == 0, proc.stderr.decode()


async def _http_get(host: str, port: int, path: str):
    # raw-socket GET (test_telemetry_endpoint idiom, inlined so this file
    # needs no crypto-gated imports)
    import asyncio

    reader, writer = await asyncio.open_connection(host, port)
    writer.write(
        f"GET {path} HTTP/1.1\r\nHost: t\r\nConnection: close\r\n\r\n".encode()
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except Exception:
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    head_lines = head.decode().split("\r\n")
    status = int(head_lines[0].split()[1])
    headers = {}
    for line in head_lines[1:]:
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    return status, headers, body


def test_statusz_and_alerts_endpoints():
    import asyncio

    from xaynet_tpu.server.rest import RestServer
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.settings import Settings
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store
    from xaynet_tpu.telemetry import BridgedMetrics

    async def _run() -> None:
        settings = Settings.load(None)
        settings.model.length = 7
        store = Store(
            InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor()
        )
        metrics = BridgedMetrics()
        machine, request_tx, events = await StateMachineInitializer(
            settings, store, metrics
        ).init()
        rest = RestServer(
            Fetcher(events), PetMessageHandler(events, request_tx),
            registry=metrics.registry,
        )
        host, port = await rest.start("127.0.0.1", 0)
        machine_task = asyncio.create_task(machine.run())
        try:
            status, headers, body = await _http_get(host, port, "/statusz")
            assert status == 200
            assert headers["content-type"].startswith("text/html")
            page = body.decode()
            assert page.startswith("<!doctype html>")
            assert "xaynet-tpu coordinator" in page
            assert "default" in page  # the bare-route tenant row

            status, headers, body = await _http_get(host, port, "/alerts")
            assert status == 200
            assert headers["content-type"].startswith("application/json")
            payload = json.loads(body)
            assert set(payload) == {"enabled", "targets", "active", "recent"}
        finally:
            machine_task.cancel()
            await rest.stop()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass
            metrics.close()

    asyncio.run(asyncio.wait_for(_run(), timeout=60))
