"""The differential oracle: sim round vs the in-process production server.

Acceptance property (ISSUE 8 / DESIGN §13): for seeded
(mask config x model size x participant count) combinations, the sim
round's unmasked global model is BYTE-identical to the production round
with the same injected mask seeds — on a single device and on the
8-virtual-device CPU mesh. The production leg is the real coordinator
state machine + SDK participant FSMs; only the transport is in-process.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from xaynet_tpu.core.mask.config import GroupType
from xaynet_tpu.parallel.mesh import make_mesh
from xaynet_tpu.sim import OracleCase, OracleMismatch, run_oracle_case, run_production_round
from xaynet_tpu.sim.oracle import run_sim_round

# three seeded combinations, one per finite-group family, distinct model
# sizes and populations (the nightly sweep in tools/sim_check.py walks a
# larger menu)
CASES = [
    OracleCase(group_type=GroupType.INTEGER, model_length=13, n_update=3, seed=101, block_size=2),
    OracleCase(group_type=GroupType.PRIME, model_length=37, n_update=4, seed=202, block_size=4),
    OracleCase(group_type=GroupType.POWER2, model_length=64, n_update=5, seed=303, block_size=3),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.describe())
def test_sim_matches_production_round_single_device_and_mesh(case):
    production = run_production_round(case)
    report = run_oracle_case(case, production_model=production)
    assert report.identical and report.max_abs_diff == 0.0
    assert report.production_sha == report.sim_sha

    if len(jax.devices()) > 1:
        mesh_report = run_oracle_case(case, mesh=make_mesh(), production_model=production)
        assert mesh_report.identical
        assert mesh_report.legs["mesh"] == len(jax.devices())


PROMOTED_SUM2_CASES = [
    # the promoted production sum2 path (ISSUE 11): sum participants run
    # masking_jax.sum_masks with device_sum2 forced + strict and a PINNED
    # route per leg. "batch" streams the mask planes through the shard
    # pipeline on the DEFAULT mesh — all 8 virtual devices under the CI
    # flags (mesh=8; degenerates to mesh=1 on a single device) — while the
    # fused interpret route is single-device by construction (mesh=1), so
    # the two legs cover both mesh shapes of the promoted pipeline.
    OracleCase(
        group_type=GroupType.INTEGER,
        model_length=13,
        n_update=3,
        seed=101,
        block_size=2,
        device_sum2=True,
        mask_kernel="batch",
    ),
    OracleCase(
        group_type=GroupType.PRIME,
        model_length=37,
        n_update=4,
        seed=202,
        block_size=4,
        device_sum2=True,
        mask_kernel="fused-pallas-interpret",
    ),
]


@pytest.mark.parametrize(
    "case", PROMOTED_SUM2_CASES, ids=lambda c: f"{c.mask_kernel}-{c.group_type.name}"
)
def test_oracle_covers_promoted_production_sum2(case):
    """The production leg's sum2 runs the PROMOTED pipeline (strict — a
    broken kernel trips the oracle instead of hiding in the host
    fallback) and stays float64-byte-identical to the sim round."""
    production = run_production_round(case)
    report = run_oracle_case(case, production_model=production)
    assert report.identical and report.max_abs_diff == 0.0
    if len(jax.devices()) > 1:
        mesh_report = run_oracle_case(
            case, mesh=make_mesh(), production_model=production
        )
        assert mesh_report.identical


def test_oracle_detects_divergence():
    """A corrupted production model must trip OracleMismatch — the oracle
    is only worth its name if it actually fails on a byte flip."""
    case = CASES[0]
    sim_model = run_sim_round(case).global_model
    corrupted = sim_model.copy()
    corrupted[0] = np.nextafter(corrupted[0], np.inf)  # single-ULP flip
    with pytest.raises(OracleMismatch, match="diverged"):
        run_oracle_case(case, production_model=corrupted)


def test_mask_seed_injection_surface():
    """PetSettings.mask_seed: validated, serialized, and optional."""
    from xaynet_tpu.core.crypto.sign import SigningKeyPair
    from xaynet_tpu.sdk.state_machine import PetSettings

    keys = SigningKeyPair.derive_from_seed(b"\x01" * 32)
    with pytest.raises(ValueError, match="32 bytes"):
        PetSettings(keys=keys, mask_seed=b"short")
    s = PetSettings(keys=keys, mask_seed=b"\x07" * 32)
    assert s.mask_seed == b"\x07" * 32
    assert PetSettings(keys=keys).mask_seed is None


def test_mask_seed_survives_save_restore():
    from xaynet_tpu.core.crypto.sign import SigningKeyPair
    from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine
    from xaynet_tpu.sdk.traits import ModelStore, XaynetClient

    class _NullStore(ModelStore):
        async def load_model(self):
            return None

    class _NullClient(XaynetClient):
        async def get_round_params(self):
            raise NotImplementedError

        async def get_sums(self):
            raise NotImplementedError

        async def get_seeds(self, pk):
            raise NotImplementedError

        async def get_model(self):
            raise NotImplementedError

        async def send_message(self, data):
            raise NotImplementedError

    keys = SigningKeyPair.derive_from_seed(b"\x02" * 32)
    sm = StateMachine(
        PetSettings(keys=keys, mask_seed=b"\x09" * 32), _NullClient(), _NullStore()
    )
    restored = StateMachine.restore(sm.save(), _NullClient(), _NullStore())
    assert restored.mask_seed == b"\x09" * 32
