"""Native C++ kernels vs the pure-python/numpy implementations."""

import os
import random

import numpy as np
import pytest

from xaynet_tpu.utils import native

pytestmark = pytest.mark.skipif(native.load() is None, reason="native library unavailable")


def test_native_chacha_blocks_match():
    from xaynet_tpu.core.crypto.chacha import keystream_blocks

    lib = native.load()
    key = bytes(range(32))
    out = np.empty(8 * 64, dtype=np.uint8)
    lib.xn_chacha20_blocks(native.as_u8p(key), 3, 8, native.np_u8p(out))
    assert bytes(out) == bytes(keystream_blocks(key, 3, 8))


@pytest.mark.parametrize(
    "order",
    [20_000_000_000_001, 2**45, 2**88, 2**96, 255, (2**128 - 1) ** 2],
)
def test_native_sampler_matches_python(order):
    """Native and numpy samplers must consume the identical keystream."""
    from xaynet_tpu.core.crypto.chacha import ChaChaStream
    from xaynet_tpu.core.crypto.prng import StreamSampler, generate_integer
    from xaynet_tpu.ops import limbs as limb_ops

    seed = b"\x13" * 32
    oracle = ChaChaStream(seed)
    expected = [generate_integer(oracle, order) for _ in range(100)]

    sampler = StreamSampler(seed)  # native path (library is loaded)
    got = limb_ops.limbs_to_ints(sampler.draw_limbs(100, order))
    assert got == expected


def test_native_python_interleave():
    """Mixed native/python draws stay on the same keystream offset."""
    from xaynet_tpu.core.crypto.chacha import ChaChaStream
    from xaynet_tpu.core.crypto.prng import StreamSampler, generate_integer
    from xaynet_tpu.ops import limbs as limb_ops

    order_a, order_b = 20_000_000_000_021, 2**45
    seed = b"\x31" * 32
    oracle = ChaChaStream(seed)
    exp_a = [generate_integer(oracle, order_a) for _ in range(7)]
    exp_b = [generate_integer(oracle, order_b) for _ in range(7)]
    exp_c = [generate_integer(oracle, order_a) for _ in range(7)]

    sampler = StreamSampler(seed)
    a = limb_ops.limbs_to_ints(sampler.draw_limbs(7, order_a))
    # force the numpy path for the middle draw
    os.environ["XAYNET_TPU_NO_NATIVE"] = "1"
    try:
        native._tried = False
        native._lib = None
        b = limb_ops.limbs_to_ints(sampler.draw_limbs(7, order_b))
    finally:
        del os.environ["XAYNET_TPU_NO_NATIVE"]
        native._tried = False
        native._lib = None
    c = limb_ops.limbs_to_ints(sampler.draw_limbs(7, order_a))
    assert (a, b, c) == (exp_a, exp_b, exp_c)


@pytest.mark.parametrize("order", [20_000_000_000_001, 2**96, 2**64 - 59])
def test_native_mod_ops_match(order):
    from xaynet_tpu.ops import limbs as limb_ops

    rng = random.Random(4)
    n_limb = limb_ops.n_limbs_for_order(order)
    ol = limb_ops.order_limbs_for(order)
    a_i = [rng.randrange(order) for _ in range(200)]
    b_i = [rng.randrange(order) for _ in range(200)]
    a = limb_ops.ints_to_limbs(a_i, n_limb)
    b = limb_ops.ints_to_limbs(b_i, n_limb)

    got_add = limb_ops.limbs_to_ints(limb_ops.mod_add(a, b, ol))
    assert got_add == [(x + y) % order for x, y in zip(a_i, b_i)]
    got_sub = limb_ops.limbs_to_ints(limb_ops.mod_sub(a, b, ol))
    assert got_sub == [(x - y) % order for x, y in zip(a_i, b_i)]
