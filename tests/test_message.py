"""Wire-format round-trip tests (reference test strategy §4.2)."""

import pytest

from xaynet_tpu.core.crypto import EncryptKeyPair, SigningKeyPair
from xaynet_tpu.core.crypto.prng import uniform_ints
from xaynet_tpu.core.mask import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    MaskObject,
    MaskSeed,
    ModelType,
)
from xaynet_tpu.core.mask.serialization import (
    parse_mask_object,
    serialize_mask_object,
    serialized_object_length,
)
from xaynet_tpu.core.message import (
    HEADER_LENGTH,
    Chunk,
    DecodeError,
    Message,
    Sum,
    Sum2,
    Tag,
    Update,
    parse_local_seed_dict,
    serialize_local_seed_dict,
)
from xaynet_tpu.core.message.encoder import MessageBuilder, MessageEncoder

CFG = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3)


def _mask_object(n=5, seed=7):
    ints = uniform_ints(bytes([seed]) * 32, n + 1, CFG.order)
    return MaskObject.new(CFG.pair(), ints[1:], ints[0])


def _keys():
    return SigningKeyPair.derive_from_seed(b"\x03" * 32)


def test_mask_object_roundtrip():
    obj = _mask_object()
    wire = serialize_mask_object(obj)
    assert len(wire) == serialized_object_length(obj.config, len(obj))
    # config(4) + count(4) + 5 elements * 6 bytes + config(4) + 6 bytes
    assert len(wire) == 4 + 4 + 5 * 6 + 4 + 6
    back, consumed = parse_mask_object(wire)
    assert consumed == len(wire)
    assert back == obj


def test_mask_object_rejects_invalid_elements():
    obj = _mask_object()
    wire = bytearray(serialize_mask_object(obj))
    # corrupt first element to be >= order (set all element bytes to 0xff)
    for i in range(8, 14):
        wire[i] = 0xFF
    with pytest.raises(DecodeError):
        parse_mask_object(bytes(wire))


def test_seed_dict_roundtrip():
    ephm = EncryptKeyPair.generate()
    seed = MaskSeed.generate()
    d = {bytes([i]) * 32: seed.encrypt(ephm.public) for i in range(3)}
    wire = serialize_local_seed_dict(d)
    assert len(wire) == 4 + 3 * 112
    back, consumed = parse_local_seed_dict(wire)
    assert consumed == len(wire)
    assert back.keys() == d.keys()
    assert all(back[k] == d[k] for k in d)


@pytest.mark.parametrize("kind", ["sum", "update", "sum2"])
def test_message_roundtrip(kind):
    keys = _keys()
    coord_pk = b"\x09" * 32
    if kind == "sum":
        payload = Sum(sum_signature=b"\x01" * 64, ephm_pk=b"\x02" * 32)
        tag = Tag.SUM
    elif kind == "update":
        ephm = EncryptKeyPair.generate()
        payload = Update(
            sum_signature=b"\x01" * 64,
            update_signature=b"\x05" * 64,
            masked_model=_mask_object(),
            local_seed_dict={bytes([9]) * 32: MaskSeed.generate().encrypt(ephm.public)},
        )
        tag = Tag.UPDATE
    else:
        payload = Sum2(sum_signature=b"\x01" * 64, model_mask=_mask_object())
        tag = Tag.SUM2

    msg = Message(participant_pk=keys.public, coordinator_pk=coord_pk, payload=payload)
    assert msg.tag == tag
    wire = msg.to_bytes(keys.secret)
    assert len(wire) == msg.serialized_length()

    back = Message.from_bytes(wire)
    assert back.tag == tag
    assert back.participant_pk == keys.public
    assert back.coordinator_pk == coord_pk
    assert back.payload.to_bytes() == payload.to_bytes()


def test_message_rejects_bad_signature():
    keys = _keys()
    msg = Message(
        participant_pk=keys.public,
        coordinator_pk=b"\x09" * 32,
        payload=Sum(sum_signature=b"\x01" * 64, ephm_pk=b"\x02" * 32),
    )
    wire = bytearray(msg.to_bytes(keys.secret))
    wire[HEADER_LENGTH] ^= 0xFF  # flip payload byte
    with pytest.raises(DecodeError):
        Message.from_bytes(bytes(wire))


def test_chunk_roundtrip():
    c = Chunk(id=3, message_id=700, last=True, data=b"hello world", tag=Tag.UPDATE)
    wire = c.to_bytes()
    back = Chunk.from_bytes(wire, tag=Tag.UPDATE)
    assert (back.id, back.message_id, back.last, back.data) == (3, 700, True, b"hello world")


def test_multipart_encode_reassemble():
    """Large update -> chunked signed messages -> reassembly -> same payload."""
    keys = _keys()
    ephm = EncryptKeyPair.generate()
    payload = Update(
        sum_signature=b"\x01" * 64,
        update_signature=b"\x05" * 64,
        masked_model=_mask_object(n=500),
        local_seed_dict={bytes([i]) * 32: MaskSeed.generate().encrypt(ephm.public) for i in range(10)},
    )
    msg = Message(participant_pk=keys.public, coordinator_pk=b"\x09" * 32, payload=payload)
    parts = list(MessageEncoder(msg, keys.secret, max_message_size=512))
    assert len(parts) > 3
    assert all(len(p) <= 512 for p in parts)

    builder = MessageBuilder()
    done = False
    # deliver out of order
    order = list(range(len(parts)))
    order.reverse()
    for i in order:
        m = Message.from_bytes(parts[i])
        assert m.is_multipart and m.tag == Tag.UPDATE
        done = builder.add(m.payload)
    assert done
    reassembled = Update.from_bytes(builder.payload_bytes())
    assert reassembled.to_bytes() == payload.to_bytes()


def test_small_message_not_chunked():
    keys = _keys()
    msg = Message(
        participant_pk=keys.public,
        coordinator_pk=b"\x09" * 32,
        payload=Sum(sum_signature=b"\x01" * 64, ephm_pk=b"\x02" * 32),
    )
    parts = list(MessageEncoder(msg, keys.secret, max_message_size=4096))
    assert len(parts) == 1
    assert not Message.from_bytes(parts[0]).is_multipart
