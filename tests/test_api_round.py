"""Rounds driven through the high-level API (spawn_participant / ABC).

Mirrors the reference's python-bindings usage
(bindings/python/examples/hello_world.py): a coordinator over REST, N
background participant threads implementing ``ParticipantABC``, two full
rounds including global-model delivery back into the trainers.
"""

import asyncio
import threading
import time
from fractions import Fraction

_COORDINATORS: list = []


import pytest as _pytest


@_pytest.fixture(autouse=True)
def _stop_coordinators():
    yield
    while _COORDINATORS:
        info = _COORDINATORS.pop()
        loop, task = info.get("loop"), info.get("task")
        if loop is not None and task is not None:
            try:
                loop.call_soon_threadsafe(task.cancel)
            except Exception:
                pass


import numpy as np

from xaynet_tpu.sdk.api import ParticipantABC, spawn_participant
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store

N_SUM, N_UPDATE, MODEL_LEN = 1, 3, 5
SUM_PROB, UPDATE_PROB = 0.4, 0.5


class ConstantTrainer(ParticipantABC):
    def __init__(self, value: float):
        self.value = value
        self.received_models: list[np.ndarray] = []

    def train_round(self, training_input):
        return np.full(MODEL_LEN, self.value, dtype=np.float32)

    def on_new_global_model(self, model):
        self.received_models.append(np.asarray(model))


def _start_coordinator():
    settings = Settings(
        pet=ServerPet(
            sum=PhaseSettings(prob=SUM_PROB, count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 30)),
            update=PhaseSettings(prob=UPDATE_PROB, count=CountSettings(N_UPDATE, N_UPDATE), time=TimeSettings(0, 30)),
            sum2=Sum2Settings(count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 30)),
        )
    )
    settings.model.length = MODEL_LEN
    started = threading.Event()
    info = {}

    def run():
        async def main():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, request_tx, events = await StateMachineInitializer(settings, store).init()
            handler = PetMessageHandler(events, request_tx)
            fetcher = Fetcher(events)
            rest = RestServer(fetcher, handler)
            host, port = await rest.start("127.0.0.1", 0)
            info["url"] = f"http://{host}:{port}"
            info["loop"] = asyncio.get_running_loop()
            task = asyncio.ensure_future(machine.run())
            info["task"] = task
            started.set()
            try:
                await task
            except asyncio.CancelledError:
                pass

        asyncio.run(main())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(10)
    _COORDINATORS.append(info)
    return info["url"]


def test_spawn_participants_two_rounds():
    url = _start_coordinator()
    probe = HttpClient(url)

    def sync(coro):
        return asyncio.run(asyncio.wait_for(coro, 20))

    # wait for round params of round 1
    for _ in range(200):
        try:
            params = sync(probe.get_round_params())
            break
        except Exception:
            time.sleep(0.05)
    seed = params.seed.as_bytes()

    threads = []
    trainers = []
    for i in range(N_SUM):
        keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
        threads.append(spawn_participant(url, ConstantTrainer, args=(0.0,), keys=keys))
    for i in range(N_UPDATE):
        keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(30 + i) * 1000)
        trainer_thread = spawn_participant(
            url, ConstantTrainer, args=(float(i + 1),), scalar=Fraction(1, N_UPDATE), keys=keys
        )
        threads.append(trainer_thread)
        trainers.append(trainer_thread)

    # round 1 completes: global model = mean(1, 2, 3) = 2.0
    deadline = time.time() + 45
    model = None
    while time.time() < deadline:
        model = sync(probe.get_model())
        if model is not None:
            break
        time.sleep(0.1)
    assert model is not None, "round did not complete"
    np.testing.assert_allclose(model, np.full(MODEL_LEN, 2.0), atol=1e-8)

    for t in threads:
        t.stop()


def test_async_participant_round():
    """AsyncParticipant: queue a model any time, receive the global model."""
    from xaynet_tpu.sdk.api import spawn_async_participant
    from xaynet_tpu.sdk.participant import Participant

    url = _start_coordinator()
    probe = HttpClient(url)

    def sync(coro):
        return asyncio.run(asyncio.wait_for(coro, 20))

    for _ in range(200):
        try:
            params = sync(probe.get_round_params())
            break
        except Exception:
            time.sleep(0.05)
    seed = params.seed.as_bytes()

    # role-pinned summer driven manually; async updaters
    sum_keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=0)
    summer = Participant(url, keys=sum_keys)

    handles = []
    for i in range(N_UPDATE):
        keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(70 + i) * 1000)
        h = spawn_async_participant(url, scalar=Fraction(1, N_UPDATE))
        # the async API takes the model whenever the caller has one
        h._inner._sm.keys = keys  # pin role for the simulation
        h._inner._sm.round_params = None  # re-evaluate with pinned keys
        h.set_model(np.full(MODEL_LEN, float(i + 1), dtype=np.float32))
        handles.append(h)

    deadline = time.time() + 45
    model = None
    while time.time() < deadline:
        summer.tick()
        model = sync(probe.get_model())
        if model is not None:
            break
        time.sleep(0.05)
    assert model is not None
    np.testing.assert_allclose(model, np.full(MODEL_LEN, 2.0), atol=1e-8)

    # the async handle surfaces the new global model
    got = handles[0].get_global_model(timeout=20)
    assert got is not None
    np.testing.assert_allclose(got, model)
    for h in handles:
        h.stop()
