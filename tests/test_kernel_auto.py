"""``kernel="auto"`` calibration machinery, proven in CI before hardware.

VERDICT r04 (missing 2 / weak 3): the accelerator timing branch of
``ShardedAggregator._resolve_kernel`` had never executed anywhere — its
first-ever run would have been on a precious tunnel window. These tests
monkeypatch ``jax.default_backend()`` to a non-cpu value and let the Pallas
interpreter stand in for the Mosaic compiler, so the only code that has
never run on hardware is the Mosaic compile itself: winner selection,
compiled-fn reuse, exception->XLA fallback, and cache keying (mesh size and
K, ADVICE r04) are all asserted here.

Reference analogue: the reference never ships an untested hot loop —
rust/xaynet-core/src/mask/masking.rs runs the exact production aggregation
code in its own test module.
"""

import numpy as np
import pytest

import jax

from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    Masker,
    MaskConfig,
    ModelType,
    Scalar,
)
from xaynet_tpu.ops import fold_pallas
from xaynet_tpu.parallel import aggregator as agg_mod
from xaynet_tpu.parallel.aggregator import ShardedAggregator
from xaynet_tpu.parallel.mesh import make_mesh

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)


@pytest.fixture
def clean_caches():
    """Snapshot the process-wide kernel caches; drop anything a test adds.

    A forced-interpret "pallas" callable must never leak into other tests
    (the caches are keyed by mesh/order, which other tests share).
    """
    auto_before = dict(agg_mod._AUTO_KERNEL_CACHE)
    fold_before = dict(agg_mod._FOLD_FN_CACHE)
    agg_mod._AUTO_KERNEL_CACHE.clear()
    for key in [k for k in agg_mod._FOLD_FN_CACHE if k[0] == "pallas"]:
        del agg_mod._FOLD_FN_CACHE[key]
    yield
    agg_mod._AUTO_KERNEL_CACHE.clear()
    agg_mod._AUTO_KERNEL_CACHE.update(auto_before)
    for key in [k for k in agg_mod._FOLD_FN_CACHE if k not in fold_before]:
        del agg_mod._FOLD_FN_CACHE[key]
    agg_mod._FOLD_FN_CACHE.update(fold_before)


def _masked_stacks(n, k, seed=0):
    rng = np.random.default_rng(seed)
    host = Aggregation(CFG.pair(), n)
    stacks = []
    for _ in range(k):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(CFG.pair()).mask(Scalar(1, k), w)
        host.aggregate(masked)
        stacks.append(masked.vect.data)
    return np.stack(stacks), host


def _force_interpret(monkeypatch):
    """Pallas-interpret stands in for the Mosaic compiler on this CPU host."""
    real = fold_pallas.fold_planar_batch_pallas
    calls = []

    def forced(acc, stack, order, interpret=False, tile_size=None):
        calls.append(interpret)
        return real(acc, stack, order, interpret=True, tile_size=tile_size)

    monkeypatch.setattr(fold_pallas, "fold_planar_batch_pallas", forced)
    return calls


def _spy_make_fold_fn(monkeypatch):
    """Record which kernels _make_fold_fn builds: calibration asks for both
    ("xla" then "pallas"), a cached verdict asks only for the winner."""
    made = []
    orig = ShardedAggregator._make_fold_fn

    def spy(self, kernel):
        made.append(kernel)
        return orig(self, kernel)

    monkeypatch.setattr(ShardedAggregator, "_make_fold_fn", spy)
    return made


def test_auto_times_both_kernels_and_keeps_winner(monkeypatch, clean_caches):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    calls = _force_interpret(monkeypatch)
    made = _spy_make_fold_fn(monkeypatch)
    stack, host = _masked_stacks(103, 6)

    agg = ShardedAggregator(CFG, 103, kernel="auto")
    agg.add_batch(stack)
    assert made == ["xla", "pallas"]  # the timing branch really ran
    assert calls  # ...and the pallas candidate went through the interpreter
    assert agg.kernel_used in ("xla", "pallas")
    # the winner's already-compiled callable is kept, not rebuilt: it is the
    # very object the process-wide cache holds for that kernel
    assert agg._fold_fn is ShardedAggregator._make_fold_fn(agg, agg.kernel_used)
    # aggregation through the auto path is still exact
    assert agg.nb_models == 6
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    # verdict memoized under (backend, mesh size, limbs, padded len, order, K)
    key = ("tpu", agg.mesh.devices.size, agg.n_limbs, agg.padded_length, agg.order, 6)
    assert agg_mod._AUTO_KERNEL_CACHE[key] == agg.kernel_used


def test_auto_verdict_cached_and_keyed_by_k_and_mesh(monkeypatch, clean_caches):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    _force_interpret(monkeypatch)
    made = _spy_make_fold_fn(monkeypatch)
    stack6, _ = _masked_stacks(64, 6)

    agg1 = ShardedAggregator(CFG, 64, kernel="auto")
    agg1.add_batch(stack6)
    assert made == ["xla", "pallas"]
    n_keys = len(agg_mod._AUTO_KERNEL_CACHE)

    # same backend/shape/K: the verdict is reused, no re-calibration
    made.clear()
    agg2 = ShardedAggregator(CFG, 64, kernel="auto")
    agg2.add_batch(stack6)
    assert agg2.kernel_used == agg1.kernel_used
    assert made == [agg1.kernel_used]
    assert len(agg_mod._AUTO_KERNEL_CACHE) == n_keys

    # different K (a remainder flush): its own calibration and cache entry
    stack3, _ = _masked_stacks(64, 3, seed=1)
    made.clear()
    agg3 = ShardedAggregator(CFG, 64, kernel="auto")
    agg3.add_batch(stack3)
    assert made == ["xla", "pallas"]
    assert len(agg_mod._AUTO_KERNEL_CACHE) == n_keys + 1

    # different mesh size with the SAME padded length (64 divides both 8 and
    # 1): its own verdict — a timing taken on one mesh must not silently
    # bind another (ADVICE r04)
    made.clear()
    agg4 = ShardedAggregator(CFG, 64, mesh=make_mesh(jax.devices()[:1]), kernel="auto")
    assert agg4.padded_length == agg1.padded_length
    agg4.add_batch(stack6)
    assert made == ["xla", "pallas"]
    assert len(agg_mod._AUTO_KERNEL_CACHE) == n_keys + 2


def test_auto_mosaic_failure_falls_back_to_xla(monkeypatch, clean_caches):
    """A Pallas (Mosaic) compile failure can never sink a round."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def boom(*a, **k):
        raise RuntimeError("Mosaic compile failed (stand-in)")

    monkeypatch.setattr(fold_pallas, "fold_planar_batch_pallas", boom)
    stack, host = _masked_stacks(50, 4)
    agg = ShardedAggregator(CFG, 50, kernel="auto")
    agg.add_batch(stack)  # must not raise
    assert agg.kernel_used == "xla"
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    key = ("tpu", agg.mesh.devices.size, agg.n_limbs, agg.padded_length, agg.order, 4)
    assert agg_mod._AUTO_KERNEL_CACHE[key] == "xla"


def test_auto_on_cpu_races_native_per_shard_on_multi_device_mesh(
    clean_caches, monkeypatch
):
    """Interpret-mode Pallas is an oracle, not a production kernel: on a CPU
    backend auto must not burn time calibrating it. The native host fold,
    however, now serves multi-device meshes too (one concurrent strided
    slice call per shard), so auto on the default 8-device test mesh races
    XLA against the per-shard native fold instead of short-circuiting to
    XLA — and the winner's arithmetic must match the host oracle."""
    made = _spy_make_fold_fn(monkeypatch)
    stack, host = _masked_stacks(40, 3)
    agg = ShardedAggregator(CFG, 40, kernel="auto")
    native_ok = agg._native_u64_usable(3)
    agg.add_batch(stack)
    if native_ok:
        assert made == ["xla", "native-u64"]  # the race really ran, no pallas
        assert agg.kernel_used in ("xla", "native-u64")
    else:
        assert made == ["xla"]
        assert agg.kernel_used == "xla"
    assert np.array_equal(agg.snapshot(), host.object.vect.data)


def test_auto_on_cpu_races_native_u64_on_single_device_mesh(clean_caches, monkeypatch):
    """Single-device CPU mesh: auto calibrates the native host fold against
    XLA (the ~2.5x CPU win BENCH_r05 measured while auto short-circuited
    to XLA and left it on the table). Whichever wins, the arithmetic must
    match the host oracle."""
    made = _spy_make_fold_fn(monkeypatch)
    stack, host = _masked_stacks(48, 4)
    agg = ShardedAggregator(CFG, 48, mesh=make_mesh(jax.devices()[:1]), kernel="auto")
    if not agg._native_u64_usable(4):
        pytest.skip("native library unavailable in this environment")
    agg.add_batch(stack)
    assert made == ["xla", "native-u64"]  # the CPU timing branch really ran
    assert agg.kernel_used in ("xla", "native-u64")
    assert agg.nb_models == 4
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    key = ("cpu", 1, agg.n_limbs, agg.padded_length, agg.order, 4)
    assert agg_mod._AUTO_KERNEL_CACHE[key] == agg.kernel_used


def test_explicit_native_u64_runs_and_matches(clean_caches):
    """kernel="native-u64" as a first-class production choice: folds run on
    the host C++ kernel (no device staging after resolution) and stay
    byte-identical to the host oracle across multiple batches."""
    stack, host = _masked_stacks(30, 6)
    agg = ShardedAggregator(CFG, 30, mesh=make_mesh(jax.devices()[:1]), kernel="native-u64")
    if not agg._native_u64_usable(3):
        pytest.skip("native library unavailable in this environment")
    agg.add_batch(stack[:3])
    agg.add_batch(stack[3:])
    assert agg.kernel_used == "native-u64"
    assert agg.nb_models == 6
    assert np.array_equal(agg.snapshot(), host.object.vect.data)


def test_explicit_native_u64_falls_back_cleanly_without_library(
    clean_caches, monkeypatch
):
    """A missing/unbuildable .so must degrade to XLA, never sink a round."""
    from xaynet_tpu.utils import native

    monkeypatch.setattr(native, "load", lambda: None)
    stack, host = _masked_stacks(30, 3)
    agg = ShardedAggregator(
        CFG, 30, mesh=make_mesh(jax.devices()[:1]), kernel="native-u64"
    )
    agg.add_batch(stack)
    assert agg.kernel_used == "xla"
    assert np.array_equal(agg.snapshot(), host.object.vect.data)


def test_native_u64_oversized_batch_takes_xla_not_numpy_tree(clean_caches, caplog):
    """A native-u64 verdict bound on a small first batch must not send a
    later batch past the u64 running-sum headroom into the silent
    pairwise-numpy fallback: the oversized batch folds through the XLA
    kernel (with a one-time warning) and the arithmetic stays exact.

    INTEGER/B2/M6 is a real such config: a ~2^61 order leaves u64 headroom
    for only K+1 <= 9 terms, so a coalescer-style small first flush (K=3)
    binds native-u64 while the steady-state batch (K=16) exceeds it."""
    import logging

    from xaynet_tpu.parallel import aggregator as agg_module

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B2, ModelType.M6)
    assert (1 << 64) // cfg.order < 17  # the premise: K=16 exceeds headroom
    n, k_small, k_big = 16, 3, 16
    rng = np.random.default_rng(23)
    host = Aggregation(cfg.pair(), n)
    stacks = []
    for _ in range(k_small + k_big):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(cfg.pair()).mask(Scalar(1, k_small + k_big), w)
        host.aggregate(masked)
        stacks.append(masked.vect.data)
    stack = np.stack(stacks)

    agg = ShardedAggregator(cfg, n, mesh=make_mesh(jax.devices()[:1]), kernel="native-u64")
    if not agg._native_u64_usable(k_small):
        pytest.skip("native library unavailable in this environment")
    agg.add_batch(stack[:k_small])
    assert agg.kernel_used == "native-u64"
    with caplog.at_level(logging.WARNING, logger=agg_module.__name__):
        agg.add_batch(stack[k_small:])
    assert any("headroom exceeded" in r.message for r in caplog.records)
    assert agg.nb_models == k_small + k_big
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
