"""RFC conformance of the pure-stdlib crypto fallback (_purecrypto).

These vectors pin the fallback to the exact primitives the ``cryptography``
wheel implements, so an environment without the wheel computes
byte-identical sealed boxes and signatures to one with it: X25519 (RFC 7748
§5.2/§6.1), Ed25519 (RFC 8032 §7.1), ChaCha20-Poly1305 (RFC 8439 §2.8.2),
HKDF-SHA256 (RFC 5869 A.1). The roundtrip tests exercise the *public*
``core.crypto`` API, whichever backend it picked.
"""

import pytest

from xaynet_tpu.core.crypto import _purecrypto as pc
from xaynet_tpu.core.crypto.encrypt import DecryptError, EncryptKeyPair, PublicEncryptKey
from xaynet_tpu.core.crypto.sign import SigningKeyPair, verify_detached


def test_x25519_rfc7748_vectors():
    a = bytes.fromhex("77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a")
    b = bytes.fromhex("5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb")
    pub_a = pc.x25519_public(a)
    pub_b = pc.x25519_public(b)
    assert pub_a == bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    assert pub_b == bytes.fromhex(
        "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
    )
    shared = bytes.fromhex("4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742")
    assert pc.x25519(a, pub_b) == shared
    assert pc.x25519(b, pub_a) == shared
    # §5.2 single-iteration vector
    k = bytes.fromhex("a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4")
    u = bytes.fromhex("e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c")
    assert pc.x25519(k, u) == bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
    )


def test_ed25519_rfc8032_vectors():
    seed = bytes.fromhex("9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60")
    pk = pc.ed25519_public(seed)
    assert pk == bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig = pc.ed25519_sign(seed, b"")
    assert sig == bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert pc.ed25519_verify(pk, sig, b"")
    assert not pc.ed25519_verify(pk, sig, b"x")

    seed3 = bytes.fromhex("c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7")
    msg3 = bytes.fromhex("af82")
    sig3 = pc.ed25519_sign(seed3, msg3)
    assert sig3 == bytes.fromhex(
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"
    )
    assert pc.ed25519_verify(pc.ed25519_public(seed3), sig3, msg3)


def test_ed25519_rejects_malleable_s():
    seed = bytes.fromhex("9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60")
    sig = bytearray(pc.ed25519_sign(seed, b"m"))
    s = int.from_bytes(sig[32:], "little") + pc._L
    sig[32:] = s.to_bytes(32, "little")
    assert not pc.ed25519_verify(pc.ed25519_public(seed), bytes(sig), b"m")


def test_chacha20poly1305_rfc8439_vector():
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    sealed = pc.chacha20poly1305_encrypt(key, nonce, plaintext, aad)
    assert sealed[:16] == bytes.fromhex("d31a8d34648e60db7b86afbc53ef7ec2")
    assert sealed[-16:] == bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
    assert pc.chacha20poly1305_decrypt(key, nonce, sealed, aad) == plaintext
    tampered = sealed[:-1] + bytes([sealed[-1] ^ 1])
    with pytest.raises(pc.AeadTagError):
        pc.chacha20poly1305_decrypt(key, nonce, tampered, aad)


def test_hkdf_sha256_rfc5869_vector():
    okm = pc.hkdf_sha256(
        bytes([0x0B] * 22), bytes.fromhex("f0f1f2f3f4f5f6f7f8f9"), 42, bytes(range(13))
    )
    assert okm == bytes.fromhex(
        "3cb25f25faacd57a90434f64d0362f2a2d2d0a90cf1a5a4c5db02d56ecc4c5bf34007208d5b887185865"
    )


def test_sealed_box_roundtrip_public_api():
    """Whichever backend ``encrypt.py`` picked, the sealed box roundtrips
    and authenticates."""
    kp = EncryptKeyPair.derive_from_seed(b"\x07" * 32)
    msg = b"masked model bytes" * 64
    sealed = PublicEncryptKey(kp.public.as_bytes()).encrypt(msg)
    assert kp.secret.decrypt(sealed) == msg
    with pytest.raises(DecryptError):
        kp.secret.decrypt(sealed[:-1] + bytes([sealed[-1] ^ 0x40]))
    with pytest.raises(DecryptError):
        kp.secret.decrypt(b"\x00" * 20)


def test_signing_roundtrip_public_api():
    keys = SigningKeyPair.derive_from_seed(b"\x09" * 32)
    sig = keys.sign(b"round seed" + b"sum")
    assert verify_detached(keys.public, sig.as_bytes(), b"round seed" + b"sum")
    assert not verify_detached(keys.public, sig.as_bytes(), b"round seed" + b"update")
    assert not verify_detached(b"\x00" * 32, sig.as_bytes(), b"round seed" + b"sum")
