"""Loadgen subsystem unit tests: the in-graph population engine must be
byte-identical to the host masker, the forge must emit uploads the
production message parser accepts and decrypts, and the sharding /
scheduling helpers must be deterministic partitions.

No live coordinator here — the end-to-end REST replay (negotiation,
ingest shedding, round byte-identity against a flood control) runs in
``tools/loadgen_soak.py``.
"""

from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.core.common import RoundParameters, RoundSeed
from xaynet_tpu.core.crypto.encrypt import EncryptKeyPair
from xaynet_tpu.core.mask import Masker, Scalar
from xaynet_tpu.core.mask.seed import EncryptedMaskSeed, MaskSeed
from xaynet_tpu.core.message import Message
from xaynet_tpu.loadgen import (
    ChurnSpec,
    PopulationEngine,
    ReplaySchedule,
    forge_population,
)
from xaynet_tpu.loadgen.runner import KEY_SPACING, shard_sizes, targets_for
from xaynet_tpu.server.settings import MaskSettings

CFG = MaskSettings().to_config().pair()  # the production default


def _round(wire_format: int, coord: EncryptKeyPair, model_length: int = 40):
    return RoundParameters(
        pk=coord.public.as_bytes(),
        sum=0.5,
        update=0.9,
        seed=RoundSeed(b"\x2a" * 32),
        mask_config=CFG,
        model_length=model_length,
        wire_format=wire_format,
    )


def test_engine_blocks_match_host_masker_bytes():
    """The tentpole identity: a jitted engine block derives the same
    masked limb tensors the host ``Masker.mask`` produces seed-for-seed —
    the forged traffic is byte-correct, not statistically similar."""
    n, P = 33, 5
    rng = np.random.default_rng(3)
    seeds = [rng.bytes(32) for _ in range(P)]
    weights = rng.uniform(-1, 1, (P, n)).astype(np.float32)
    scalar = Fraction(1, P)

    eng = PopulationEngine(CFG, n, block_size=4)  # forces a ragged tail
    vects, units = eng.emit(seeds, weights, scalar)

    for i in range(P):
        masker = Masker(CFG, seed=MaskSeed(seeds[i]))
        _, masked = masker.mask(Scalar.from_fraction(scalar), weights[i])
        assert np.array_equal(vects[i], masked.vect.data)
        assert np.array_equal(units[i], masked.unit.data)


@pytest.mark.parametrize("wire_format", [1, 2])
def test_forged_upload_parses_as_production_message(wire_format):
    """Seal -> decrypt -> parse: the production parser must accept a
    forged upload, verify its signatures, and see the negotiated wire
    framing on the Update payload."""
    coord = EncryptKeyPair.generate()
    ephm = EncryptKeyPair.generate()
    params = _round(wire_format, coord)
    sum_dict = {b"\x05" * 32: ephm.public.as_bytes()}

    pop = forge_population(params, sum_dict, 3, model_length=40, block_size=2)
    assert len(pop.messages) == 3
    for blob in pop.messages:
        plain = coord.secret.decrypt(blob, coord.public)
        # lazy parse keeps the element block unwidened, so the payload's
        # wire_planar reflects the framing actually on the wire
        msg = Message.from_bytes(plain, verify=True, lazy_update_vect=True)
        payload = msg.payload
        assert payload.wire_planar is (wire_format >= 2)
        # the seed dict round-trips through the ephemeral box
        entry = payload.local_seed_dict[b"\x05" * 32]
        if not isinstance(entry, EncryptedMaskSeed):
            entry = EncryptedMaskSeed(bytes(entry))
        assert len(entry.decrypt(ephm.secret, ephm.public).as_bytes()) == 32


def test_forge_is_deterministic_and_key_partitioned():
    coord = EncryptKeyPair.generate()
    ephm = EncryptKeyPair.generate()
    params = _round(2, coord)
    sum_dict = {b"\x05" * 32: ephm.public.as_bytes()}
    kw = dict(model_length=24, block_size=8, rng_seed=11)

    a = forge_population(params, sum_dict, 4, key_start=7, key_spacing=3, **kw)
    b = forge_population(params, sum_dict, 4, key_start=7, key_spacing=3, **kw)
    assert a.key_starts == b.key_starts == [7, 10, 13, 16]
    assert a.mask_seeds == b.mask_seeds
    assert np.array_equal(a.weights, b.weights)
    # sealed boxes are randomized (fresh ephemeral sender keys), but the
    # participant identity inside must agree run-to-run
    pka = [
        Message.from_bytes(coord.secret.decrypt(m, coord.public)).participant_pk
        for m in a.messages
    ]
    pkb = [
        Message.from_bytes(coord.secret.decrypt(m, coord.public)).participant_pk
        for m in b.messages
    ]
    assert pka == pkb
    assert len(set(pka)) == 4  # partitioned: no key collisions


def test_shard_sizes_partition():
    assert shard_sizes(10, 3) == [4, 3, 3]
    assert shard_sizes(2, 4) == [1, 1, 0, 0]
    for n, d in ((0, 1), (1, 1), (100_000, 7)):
        sizes = shard_sizes(n, d)
        assert sum(sizes) == n and len(sizes) == d
        assert max(sizes) - min(sizes) <= 1


def test_targets_for_tenant_routes():
    assert targets_for("http://h:1/", "") == ["http://h:1/"]
    assert targets_for("http://h:1", "a, b") == [
        "http://h:1/t/a",
        "http://h:1/t/b",
    ]
    assert KEY_SPACING >= 100  # wide enough for the per-key task search


def test_replay_schedule_churn_is_deterministic():
    spec = ChurnSpec(dropout_rate=0.25, stragglers=3, straggle_delay_s=0.5, seed=9)
    a = ReplaySchedule(40, spec, ramp_s=2.0)
    b = ReplaySchedule(40, spec, ramp_s=2.0)
    assert list(a.events()) == list(b.events())
    assert a.senders == b.senders
    # dropped participants never appear in the event stream
    sent = {i for _, i in a.events()}
    assert len(sent) == a.senders < 40
    # offsets live inside the ramp window (+ the straggle delay tail)
    assert all(0.0 <= t <= 2.0 + 0.5 for t, _ in a.events())
    # a different seed reshuffles the plan
    c = ReplaySchedule(40, ChurnSpec(0.25, 3, 0.5, seed=10), ramp_s=2.0)
    assert list(c.events()) != list(a.events())


def test_replay_schedule_no_churn_sends_everyone():
    sched = ReplaySchedule(17, ChurnSpec(), ramp_s=0.0)
    assert sched.senders == 17
    assert sorted(i for _, i in sched.events()) == list(range(17))
    assert all(t == 0.0 for t, _ in sched.events())
