"""Round liveness under participant churn (docs/DESIGN.md §10).

Pins the PR-5 contracts:

1. **quorum completion** — a request window stalled at/above
   ``count.quorum`` closes DEGRADED after the stall grace instead of
   timing out; below quorum the window still fails, and the
   ``PhaseTimeout`` carries the full accepted/min/quorum diagnostics;
2. **chaos round** — ``flood`` with 30% dropout + a straggler mid-update
   completes the round at quorum with a global model BYTE-identical to a
   fault-free run over the same surviving participant set;
3. **adaptive windows** — the ``RoundController`` shrinks a mis-sized
   ``count.min`` to the offered load within the hysteresis budget, regrows
   it when load returns, and respects floor/ceiling bounds throughout —
   unit-level and against a live coordinator;
4. **purge accounting** — phase-end purges land on the ``purged`` metric
   outcome, not the in-window ``rejected`` bucket.
"""

import asyncio
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.sdk.client import InProcessClient
from xaynet_tpu.sdk.simulation import flood, keys_for_task
from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
from xaynet_tpu.sdk.traits import ModelStore
from xaynet_tpu.server.events import EventPublisher, PhaseName
from xaynet_tpu.server.metrics import Metrics
from xaynet_tpu.server.phases.base import (
    PHASE_OUTCOMES,
    PhaseState,
    PhaseTimeout,
    Shared,
)
from xaynet_tpu.server.requests import RequestError, RequestReceiver, SumRequest
from xaynet_tpu.server.round_controller import RoundController
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    SettingsError,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store


def _mem_store() -> Store:
    return Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())


def _settings(
    n_sum=1,
    n_update=3,
    update_max=None,
    quorum=None,
    model_len=13,
    stall_grace=0.3,
    update_tmax=20.0,
) -> Settings:
    s = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=0.4,
                count=CountSettings(min=n_sum, max=n_sum),
                time=TimeSettings(min=0.0, max=20.0),
            ),
            update=PhaseSettings(
                prob=0.5,
                count=CountSettings(
                    min=n_update, max=update_max or n_update, quorum=quorum
                ),
                time=TimeSettings(min=0.0, max=update_tmax),
            ),
            sum2=Sum2Settings(
                count=CountSettings(min=n_sum, max=n_sum),
                time=TimeSettings(min=0.0, max=20.0),
            ),
        )
    )
    s.model.length = model_len
    s.liveness.stall_grace_s = stall_grace
    return s


# --------------------------------------------------------------------------
# Window-level quorum semantics
# --------------------------------------------------------------------------


class _AcceptAll(PhaseState):
    NAME = PhaseName.SUM

    async def handle_request(self, req):
        if getattr(req, "participant_pk", b"") == b"reject":
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "test")


class _SpyMetrics(Metrics):
    """No-op sink that records purge/reject calls and free-form events."""

    def __init__(self):
        self.purged = []
        self.rejected = []
        self.events = []

    def message_purged(self, round_id, phase):
        self.purged.append(phase)

    def message_rejected(self, round_id, phase):
        self.rejected.append(phase)

    def event(self, round_id, kind, detail=""):
        self.events.append((kind, detail))


def _shared(settings=None, metrics=None):
    class _State:
        round_id = 1

    events = EventPublisher(1, None, None, PhaseName.SUM)
    return Shared(
        state=_State(),
        request_rx=RequestReceiver(),
        events=events,
        store=None,
        settings=settings or Settings.default(),
        metrics=metrics,
    )


def _params(cmin, cmax, tmin, tmax, quorum=None):
    return PhaseSettings(
        prob=0.5,
        count=CountSettings(cmin, cmax, quorum=quorum),
        time=TimeSettings(tmin, tmax),
    )


def test_window_degraded_close_at_quorum_on_stall():
    """2 of 5 arrive, quorum 2: the window closes degraded a stall-grace
    after the last acceptance instead of burning the full time.max."""

    async def run():
        import time as time_mod

        settings = Settings.default()
        settings.liveness.stall_grace_s = 0.25
        shared = _shared(settings)
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()
        tasks = [
            asyncio.create_task(sender.request(SumRequest(bytes([i]) * 4, b"e")))
            for i in range(2)
        ]
        before = PHASE_OUTCOMES.labels(phase="sum", outcome="degraded").value
        t0 = time_mod.monotonic()
        outcome = await phase.process_requests(_params(5, 10, 0.0, 30.0, quorum=2))
        elapsed = time_mod.monotonic() - t0
        assert outcome == "degraded"
        assert elapsed < 5.0  # stalled close, nowhere near time.max = 30
        assert PHASE_OUTCOMES.labels(phase="sum", outcome="degraded").value == before + 1
        await asyncio.gather(*tasks)

    asyncio.run(asyncio.wait_for(run(), 20))


def test_window_full_close_reports_full_outcome():
    async def run():
        settings = Settings.default()
        settings.liveness.stall_grace_s = 5.0
        shared = _shared(settings)
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()
        tasks = [
            asyncio.create_task(sender.request(SumRequest(bytes([i]) * 4, b"e")))
            for i in range(3)
        ]
        before = PHASE_OUTCOMES.labels(phase="sum", outcome="full").value
        outcome = await phase.process_requests(_params(3, 10, 0.0, 20.0, quorum=2))
        assert outcome == "full"
        assert PHASE_OUTCOMES.labels(phase="sum", outcome="full").value == before + 1
        await asyncio.gather(*tasks)

    asyncio.run(asyncio.wait_for(run(), 20))


def test_window_timeout_below_quorum_rich_diagnostics():
    """1 accepted + 1 rejected below quorum 2: PhaseTimeout names the
    accepted/min/quorum/rejected counts and the seconds in phase."""

    async def run():
        shared = _shared()
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()
        ok = asyncio.create_task(sender.request(SumRequest(b"good", b"e")))
        bad = asyncio.create_task(sender.request(SumRequest(b"reject", b"e")))
        before = PHASE_OUTCOMES.labels(phase="sum", outcome="timeout").value
        with pytest.raises(PhaseTimeout) as ei:
            await phase.process_requests(_params(4, 10, 0.0, 0.5, quorum=2))
        err = ei.value
        assert err.accepted == 1 and err.count_min == 4 and err.quorum == 2
        assert err.rejected == 1
        assert err.seconds >= 0.5
        msg = str(err)
        assert "1 accepted / min 4 / quorum 2" in msg and "1 rejected" in msg
        assert PHASE_OUTCOMES.labels(phase="sum", outcome="timeout").value == before + 1
        await ok
        with pytest.raises(RequestError):
            await bad

    asyncio.run(asyncio.wait_for(run(), 20))


def test_stall_close_drains_queued_requests_first():
    """Slow PROCESSING must not masquerade as an arrival stall: a valid
    request that arrived in time but sat queued behind a slow reject is
    still handled when the stall clock runs out — not purged."""

    async def run():
        settings = Settings.default()
        settings.liveness.stall_grace_s = 0.15

        class _SlowReject(_AcceptAll):
            async def handle_request(self, req):
                if req.participant_pk == b"reject":
                    # burns > stall_grace without resetting the stall clock
                    await asyncio.sleep(0.3)
                await super().handle_request(req)

        shared = _shared(settings)
        phase = _SlowReject(shared)
        sender = shared.request_rx.sender()
        good1 = asyncio.create_task(sender.request(SumRequest(b"gd01", b"e")))
        bad = asyncio.create_task(sender.request(SumRequest(b"reject", b"e")))
        good2 = asyncio.create_task(sender.request(SumRequest(b"gd02", b"e")))
        outcome = await phase.process_requests(_params(2, 10, 0.0, 20.0, quorum=1))
        # the queued good2 was drained at stall time and completed the
        # window FULL; a purge would have rejected it and closed degraded
        assert outcome == "full"
        await good1
        await good2
        with pytest.raises(RequestError):
            await bad

    asyncio.run(asyncio.wait_for(run(), 20))


def test_deadline_close_never_cancels_inflight_request():
    """``time.max`` expiring while a request is mid-handle must let the
    handler run to completion before the degraded close is declared: a
    cancellation between an update's seed-dict insert and its fold would
    strand a seeded-but-never-staged update and break the
    nb_models == seed-watermark unmask invariant (DESIGN §10)."""

    async def run():
        settings = Settings.default()
        settings.liveness.stall_grace_s = 10.0  # only the deadline closes
        done = []

        class _SlowAccept(_AcceptAll):
            async def handle_request(self, req):
                if req.participant_pk == b"slow":
                    # a two-step "atomic" handler straddling the deadline
                    await asyncio.sleep(0.7)
                    done.append(req.participant_pk)

        shared = _shared(settings)
        phase = _SlowAccept(shared)
        sender = shared.request_rx.sender()
        fast = asyncio.create_task(sender.request(SumRequest(b"fast", b"e")))
        slow = asyncio.create_task(sender.request(SumRequest(b"slow", b"e")))
        outcome = await phase.process_requests(_params(3, 10, 0.0, 0.3, quorum=1))
        assert outcome == "degraded"
        assert done == [b"slow"], "in-flight request was cancelled at time.max"
        await asyncio.gather(fast, slow)

    asyncio.run(asyncio.wait_for(run(), 20))


def test_deadline_drains_queued_quorum_completing_request():
    """A request that arrived IN time but sat queued behind slow
    processing must still be handled when ``time.max`` expires below
    quorum — it may lift the phase to quorum (degraded close) instead of
    being purged by an immediate PhaseTimeout."""

    async def run():
        settings = Settings.default()
        settings.liveness.stall_grace_s = 10.0
        shared = _shared(settings)

        class _SlowFirst(_AcceptAll):
            async def handle_request(self, req):
                if req.participant_pk == b"slow":
                    await asyncio.sleep(0.5)  # overruns time.max = 0.3
                await super().handle_request(req)

        phase = _SlowFirst(shared)
        sender = shared.request_rx.sender()
        slow = asyncio.create_task(sender.request(SumRequest(b"slow", b"e")))
        queued = asyncio.create_task(sender.request(SumRequest(b"qd01", b"e")))
        outcome = await phase.process_requests(_params(3, 10, 0.0, 0.3, quorum=2))
        # slow accepted (1) + queued drained at the deadline (2) == quorum
        assert outcome == "degraded"
        await asyncio.gather(slow, queued)

    asyncio.run(asyncio.wait_for(run(), 20))


def test_rejections_do_not_reset_stall_clock():
    """A trickle of rejected stragglers must not keep a quorum'd window
    open forever: only ACCEPTED messages reset the stall clock."""

    async def run():
        import time as time_mod

        settings = Settings.default()
        settings.liveness.stall_grace_s = 0.4
        shared = _shared(settings)
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()
        ok = asyncio.create_task(sender.request(SumRequest(b"good", b"e")))

        async def reject_trickle():
            outcomes = []
            for _ in range(6):
                await asyncio.sleep(0.15)  # spaced closer than the grace
                try:
                    await sender.request(SumRequest(b"reject", b"e"))
                    outcomes.append("ok")
                except RequestError:
                    outcomes.append("rejected")
            return outcomes

        trickle = asyncio.create_task(reject_trickle())
        t0 = time_mod.monotonic()
        outcome = await phase.process_requests(_params(5, 10, 0.0, 30.0, quorum=1))
        elapsed = time_mod.monotonic() - t0
        assert outcome == "degraded"
        # closed ~one grace after the single acceptance, despite the trickle
        assert elapsed < 2.0
        await ok
        trickle.cancel()
        try:
            await trickle
        except asyncio.CancelledError:
            pass

    asyncio.run(asyncio.wait_for(run(), 20))


def test_purge_counts_as_purged_not_rejected():
    """Requests left queued at phase end land on message_purged — the
    degraded-close straggler burst must not pollute reject dashboards."""

    async def run():
        spy = _SpyMetrics()
        shared = _shared(metrics=spy)
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()
        ok = asyncio.create_task(sender.request(SumRequest(b"good", b"e")))
        await phase.process_requests(_params(1, 1, 0.0, 10.0))
        late = asyncio.create_task(sender.request(SumRequest(b"late", b"e")))
        await asyncio.sleep(0)  # let the straggler enqueue
        await phase.purge_outdated_requests()
        assert spy.purged == ["sum"]
        assert spy.rejected == []  # in-window rejects only
        await ok
        with pytest.raises(RequestError):
            await late

    asyncio.run(asyncio.wait_for(run(), 20))


def test_quorum_validation():
    with pytest.raises(SettingsError):
        _settings(n_update=5, quorum=6).validate()  # quorum > min
    with pytest.raises(SettingsError):
        _settings(n_update=5, quorum=2).validate()  # below UPDATE floor (3)
    _settings(n_update=5, quorum=3).validate()


# --------------------------------------------------------------------------
# RoundController (unit)
# --------------------------------------------------------------------------


def _adaptive_settings(update_min=10, update_max=20, tmax=30.0) -> Settings:
    s = _settings(n_update=update_min, update_max=update_max, update_tmax=tmax)
    s.liveness.adaptive = True
    s.liveness.shrink_after = 2
    s.liveness.grow_after = 2
    return s


def test_round_controller_shrinks_to_offered_load_and_regrows():
    s = _adaptive_settings()
    ctl = RoundController(s)
    update = s.pet.update

    # offered load is 4 << count.min 10: two failed rounds trigger a shrink
    for _ in range(2):
        ctl.observe_phase("update", 4, "timeout", 30.0)
        ctl.round_failed()
    assert update.count.min == 4  # clamped to the observed arrivals
    assert update.time.max == pytest.approx(45.0)  # relaxed 30 * 1.5

    # load returns (12 arrivals, full rounds): regrow toward the configured
    # ceiling, never past it, time.max decays back to the configured value
    seen = [update.count.min]
    for _ in range(10):
        ctl.observe_phase("update", 12, "full", 2.0)
        ctl.observe_phase("update", 12, "full", 2.0)
        ctl.round_completed()
        ctl.round_completed()
        seen.append(update.count.min)
    assert update.count.min == 10  # back at the configured ceiling
    assert max(seen) == 10  # never overshot it
    assert all(b >= a for a, b in zip(seen, seen[1:]))  # monotone regrowth
    assert update.time.max == pytest.approx(30.0)


def test_round_controller_shrinks_despite_healthy_history():
    """A load DROP after a healthy era must still shrink within
    shrink_after rounds: the stale at-min readings in the history window
    must not mask the starved phase."""
    s = _adaptive_settings()
    ctl = RoundController(s)
    update = s.pet.update
    for _ in range(3):  # healthy era: full rounds right at count.min
        ctl.observe_phase("update", 10, "full", 2.0)
        ctl.round_completed()
    for _ in range(2):  # load drops to 4: exactly shrink_after failures
        ctl.observe_phase("update", 4, "timeout", 30.0)
        ctl.round_failed()
    assert update.count.min == 4  # shrunk immediately, not `window` later
    assert update.time.max == pytest.approx(45.0)


def test_round_controller_regrows_past_censored_observations():
    """Live windows close the moment ``count.min`` is reached (time.min is
    usually 0), so full-round arrival observations are censored AT min; the
    controller must still probe back toward the configured ceiling instead
    of ratcheting a shrunk window down forever."""
    s = _adaptive_settings()
    ctl = RoundController(s)
    update = s.pet.update
    for _ in range(2):
        ctl.observe_phase("update", 4, "timeout", 30.0)
        ctl.round_failed()
    assert update.count.min == 4

    seen = [update.count.min]
    for _ in range(10):
        for _ in range(2):
            # exactly count.min accepted: what a real full window reports
            ctl.observe_phase("update", update.count.min, "full", 2.0)
            ctl.round_completed()
        seen.append(update.count.min)
    assert update.count.min == 10  # back at the configured ceiling
    assert max(seen) == 10
    assert all(b >= a for a, b in zip(seen, seen[1:]))
    assert update.time.max == pytest.approx(30.0)


def test_round_controller_time_decay_floored_by_observed_duration():
    """time.max decays back after full rounds, but never below the window
    durations those rounds actually took — cutting under them would
    re-induce the timeouts the relax was for."""
    s = _adaptive_settings()
    ctl = RoundController(s)
    update = s.pet.update
    for _ in range(2):
        ctl.observe_phase("update", 4, "timeout", 30.0)
        ctl.round_failed()
    assert update.time.max == pytest.approx(45.0)  # relaxed 30 * 1.5
    for _ in range(2):
        # full rounds, but the windows genuinely ran 40s
        ctl.observe_phase("update", 12, "full", 40.0)
        ctl.round_completed()
    assert update.time.max == pytest.approx(40.0)  # floored, not 30


def test_round_controller_ceiling_burning_degraded_excluded_from_latency_floor():
    """A degraded close that only fired because the (relaxed) time.max
    expired measures the CEILING, not demand — it must not floor the
    time.max decay once load recovers."""
    s = _adaptive_settings()
    ctl = RoundController(s)
    update = s.pet.update
    for _ in range(2):
        ctl.observe_phase("update", 4, "timeout", 30.0)
        ctl.round_failed()
    assert update.time.max == pytest.approx(45.0)  # relaxed
    # a degraded round that burned the whole relaxed window at quorum
    ctl.observe_phase("update", 4, "degraded", 45.0)
    ctl.round_completed()
    # load recovers: full rounds closing early regrow and decay time.max
    for _ in range(2):
        ctl.observe_phase("update", 12, "full", 2.0)
        ctl.round_completed()
    assert update.time.max == pytest.approx(30.0)  # decayed, not stuck at 45


def test_resumed_window_reports_offset_arrivals_to_controller():
    """A checkpoint-resumed update phase runs a REDUCED window; the
    restored models were real arrivals and must be included in what the
    adaptive controller observes, or a resumed 100-participant round looks
    like a 5-participant deployment to the shrink clamp."""

    async def run():
        class _CtlSpy:
            def __init__(self):
                self.seen = []

            def observe_phase(self, phase, accepted, outcome, seconds):
                self.seen.append((phase, accepted, outcome))

        ctl = _CtlSpy()
        shared = _shared()
        shared.round_ctl = ctl
        phase = _AcceptAll(shared)
        phase.arrivals_offset = 95  # what UpdatePhase sets on resume
        sender = shared.request_rx.sender()
        tasks = [
            asyncio.create_task(sender.request(SumRequest(bytes([i]) * 4, b"e")))
            for i in range(5)
        ]
        outcome = await phase.process_requests(_params(5, 10, 0.0, 20.0))
        assert outcome == "full"
        assert ctl.seen == [("sum", 100, "full")]
        await asyncio.gather(*tasks)

    asyncio.run(asyncio.wait_for(run(), 20))


def test_round_controller_hysteresis_resists_alternation():
    """full/failed alternation never reaches either streak threshold: the
    windows must not move."""
    s = _adaptive_settings()
    ctl = RoundController(s)
    for _ in range(6):
        ctl.observe_phase("update", 4, "timeout", 30.0)
        ctl.round_failed()
        ctl.observe_phase("update", 12, "full", 2.0)
        ctl.round_completed()
    assert s.pet.update.count.min == 10
    assert s.pet.update.time.max == pytest.approx(30.0)


def test_round_controller_floor_and_untouched_phases():
    """Shrinks bottom out at the protocol floor (or quorum) and never touch
    phases that met their window or never ran."""
    s = _adaptive_settings(update_min=4, update_max=20)
    s.pet.sum.count.min = 1  # sum meets its window every round
    s.liveness.shrink_after = 1
    ctl = RoundController(s)
    for _ in range(6):
        ctl.observe_phase("sum", 1, "full", 0.5)
        ctl.observe_phase("update", 0, "timeout", 30.0)
        ctl.round_failed()
    assert s.pet.update.count.min == 3  # UPDATE_COUNT_MIN floor
    assert s.pet.sum.count.min == 1  # full phase untouched
    assert s.pet.sum2.count.min == 1  # never observed -> untouched

    # with a configured quorum the floor is the quorum, not the protocol min
    s2 = _adaptive_settings(update_min=8, update_max=20)
    s2.pet.update.count.quorum = 5
    s2.liveness.shrink_after = 1
    ctl2 = RoundController(s2)
    for _ in range(6):
        ctl2.observe_phase("update", 0, "timeout", 30.0)
        ctl2.round_failed()
    assert s2.pet.update.count.min == 5


# --------------------------------------------------------------------------
# Adaptive controller against a live coordinator
# --------------------------------------------------------------------------


class _ArrayModelStore(ModelStore):
    def __init__(self, model):
        self.model = model

    async def load_model(self):
        return self.model


def test_adaptive_controller_converges_live():
    """count.min = 5 but only 3 updaters exist: round 1 times out, the
    controller shrinks the window to the offered load, and the next round
    completes — the acceptance scenario for a mis-sized deployment."""

    async def run():
        offered = 3
        settings = _settings(
            n_update=5, update_max=10, model_len=7, update_tmax=1.2
        )
        settings.liveness.adaptive = True
        settings.liveness.shrink_after = 1
        store = _mem_store()
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        machine_task = asyncio.create_task(machine.run())
        try:
            model = None
            for _round in range(4):
                while fetcher.phase().value != "sum":
                    await asyncio.sleep(0.01)
                params = fetcher.round_params()
                seed = params.seed.as_bytes()
                participants = [
                    ParticipantSM(
                        PetSettings(
                            keys=keys_for_task(seed, params.sum, params.update, "sum")
                        ),
                        InProcessClient(fetcher, handler),
                        _ArrayModelStore(None),
                    )
                ]
                rng = np.random.default_rng(_round)
                for i in range(offered):
                    participants.append(
                        ParticipantSM(
                            PetSettings(
                                keys=keys_for_task(
                                    seed, params.sum, params.update, "update",
                                    start=(10 + i) * 1000,
                                ),
                                scalar=Fraction(1, offered),
                            ),
                            InProcessClient(fetcher, handler),
                            _ArrayModelStore(
                                rng.uniform(-1, 1, 7).astype(np.float32)
                            ),
                        )
                    )

                async def drive(sm):
                    for _ in range(600):
                        try:
                            await sm.transition()
                        except Exception:
                            pass
                        if fetcher.model() is not None:
                            return
                        if fetcher.round_params().seed.as_bytes() != seed:
                            return  # round failed; next loop builds anew
                        await asyncio.sleep(0.01)

                await asyncio.gather(*(drive(p) for p in participants))
                if fetcher.model() is not None:
                    model = np.asarray(fetcher.model())
                    break
            assert model is not None, "no round ever completed"
            # the controller converged onto the offered load
            assert settings.pet.update.count.min == offered
            return model
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(asyncio.wait_for(run(), 60))


# --------------------------------------------------------------------------
# Seeded chaos: dropout + stragglers mid-update, quorum completion
# --------------------------------------------------------------------------

N_FLOOD = 5
DROPOUT = 0.3  # 2 of 5 withheld -> 3 survivors
SCALAR = Fraction(1, N_FLOOD)


def _flood_models(model_len: int) -> list:
    rng = np.random.default_rng(99)
    return [rng.uniform(-1, 1, model_len).astype(np.float32) for _ in range(N_FLOOD)]


async def _drive_flood_round(settings, store, models, metrics=None, **flood_kwargs):
    """Sum leg via the SDK FSM, update leg via ``flood``; returns
    (global model or None, flood stats)."""
    init = StateMachineInitializer(settings, store, metrics=metrics)
    machine, request_tx, events = await init.init()
    handler = PetMessageHandler(events, request_tx)
    fetcher = Fetcher(events)
    machine_task = asyncio.create_task(machine.run())
    try:
        while fetcher.phase().value != "sum":
            await asyncio.sleep(0.01)
        params = fetcher.round_params()
        seed = params.seed.as_bytes()
        summer = ParticipantSM(
            PetSettings(keys=keys_for_task(seed, params.sum, params.update, "sum")),
            InProcessClient(fetcher, handler),
            _ArrayModelStore(None),
        )
        # drive the summer through Sum so the sum dictionary broadcasts
        for _ in range(100):
            await summer.transition()
            if fetcher.sum_dict():
                break
            await asyncio.sleep(0.01)
        sum_dict = fetcher.sum_dict()
        assert sum_dict, "sum dictionary never appeared"
        while fetcher.phase().value != "update":
            await asyncio.sleep(0.01)
        stats = await flood(
            handler,
            params,
            sum_dict,
            len(models),
            models=models,
            scalar=SCALAR,
            **flood_kwargs,
        )
        # the summer completes sum2 (or the round fails); either way the
        # machine leaves the current round
        for _ in range(800):
            await summer.transition()
            if fetcher.model() is not None:
                return np.asarray(fetcher.model()), stats
            if fetcher.round_params().seed.as_bytes() != seed:
                return None, stats  # round failed and restarted
            await asyncio.sleep(0.01)
        raise AssertionError("round neither completed nor failed")
    finally:
        machine_task.cancel()
        try:
            await machine_task
        except (asyncio.CancelledError, Exception):
            pass


def test_chaos_dropout_round_completes_degraded_at_quorum_byte_identical():
    model_len = 13
    models = _flood_models(model_len)

    # chaos run: count.min demands all 5, quorum allows the 3 survivors;
    # one survivor straggles (still inside the stall grace)
    chaos_settings = _settings(
        n_update=N_FLOOD, quorum=3, model_len=model_len, stall_grace=0.4
    )
    degraded_before = PHASE_OUTCOMES.labels(phase="update", outcome="degraded").value
    chaos_model, stats = asyncio.run(
        asyncio.wait_for(
            _drive_flood_round(
                chaos_settings,
                _mem_store(),
                models,
                dropout_rate=DROPOUT,
                stragglers=1,
                straggle_delay_s=0.05,
                churn_seed=7,
            ),
            timeout=90,
        )
    )
    assert chaos_model is not None, "chaos round failed instead of degrading"
    assert stats.dropped == 2 and stats.straggled == 1
    assert stats.accepted == 3  # exactly the survivors landed
    assert (
        PHASE_OUTCOMES.labels(phase="update", outcome="degraded").value
        == degraded_before + 1
    )

    # control run: the SAME surviving models (same scalar), no faults, a
    # window sized to them — byte-identical unmasked global model
    survivors = [m for i, m in enumerate(models) if i not in stats.dropped_indices]
    assert len(survivors) == 3
    control_settings = _settings(n_update=3, model_len=model_len)
    control_model, control_stats = asyncio.run(
        asyncio.wait_for(
            _drive_flood_round(control_settings, _mem_store(), survivors),
            timeout=90,
        )
    )
    assert control_model is not None and control_stats.accepted == 3
    assert chaos_model.tobytes() == control_model.tobytes()

    # and the float content is the scalar-weighted mean over the survivors
    # (unmask normalizes by the aggregated scalar sum: 3 x 1/5 here)
    expected = sum(m.astype(np.float64) for m in survivors) / len(survivors)
    np.testing.assert_allclose(chaos_model, expected, atol=1e-6)


def test_chaos_below_quorum_still_fails_with_diagnostics():
    """4 of 5 dropped -> 1 survivor < quorum 3: the round must FAIL (no
    silent quorum bypass), and the failure event carries the enriched
    PhaseTimeout diagnostics."""
    model_len = 13
    models = _flood_models(model_len)
    settings = _settings(
        n_update=N_FLOOD,
        quorum=3,
        model_len=model_len,
        stall_grace=0.2,
        update_tmax=1.5,
    )
    spy = _SpyMetrics()
    timeout_before = PHASE_OUTCOMES.labels(phase="update", outcome="timeout").value
    model, stats = asyncio.run(
        asyncio.wait_for(
            _drive_flood_round(
                settings,
                _mem_store(),
                models,
                metrics=spy,
                dropout_rate=0.8,  # 4 of 5 withheld
                churn_seed=7,
            ),
            timeout=90,
        )
    )
    assert model is None, "below-quorum round must not produce a model"
    assert stats.accepted == 1
    assert (
        PHASE_OUTCOMES.labels(phase="update", outcome="timeout").value
        == timeout_before + 1
    )
    errors = [d for k, d in spy.events if k == "phase_error"]
    assert any(
        "1 accepted / min 5 / quorum 3" in d and "s in phase" in d for d in errors
    ), f"enriched diagnostics missing from failure events: {errors}"

    asyncio.run(asyncio.sleep(0))  # drain any lingering loop callbacks
