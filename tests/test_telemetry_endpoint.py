"""Telemetry endpoint smoke test: run a full PET round over the real REST
API, scrape ``GET /metrics`` and ``GET /healthz`` mid-round and after, and
assert the exposition is well-formed with phase histograms and aggregation
kernel stats; the per-round JSON report must be written and parseable."""

import asyncio
import json
import re
from fractions import Fraction

import numpy as np
import pytest

# the PET message pipeline needs the sealed-box primitives; environments
# without the cryptography package skip the end-to-end smoke (the registry,
# bridge and profiling layers have crypto-free coverage elsewhere)
pytest.importorskip("cryptography")

from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
from xaynet_tpu.sdk.traits import ModelStore
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store
from xaynet_tpu.telemetry import BridgedMetrics, RoundReporter

N_SUM, N_UPDATE, MODEL_LEN = 1, 3, 7
SUM_PROB, UPDATE_PROB = 0.4, 0.5


class ArrayModelStore(ModelStore):
    def __init__(self, model):
        self.model = model

    async def load_model(self):
        return self.model


async def _http_get(host: str, port: int, path: str) -> tuple[int, dict, bytes]:
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\nConnection: close\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except Exception:
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    head_lines = head.decode().split("\r\n")
    status = int(head_lines[0].split()[1])
    headers = {}
    for line in head_lines[1:]:
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    return status, headers, body


def _assert_exposition_well_formed(text: str) -> None:
    assert text.endswith("\n")
    sample_re = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? \S+$')
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert sample_re.match(line), f"malformed sample line: {line!r}"


async def _run(report_path: str) -> None:
    settings = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=SUM_PROB, count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 20)
            ),
            update=PhaseSettings(
                prob=UPDATE_PROB,
                count=CountSettings(N_UPDATE, N_UPDATE),
                time=TimeSettings(0, 20),
            ),
            sum2=Sum2Settings(count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 20)),
        )
    )
    settings.model.length = MODEL_LEN
    store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
    metrics = BridgedMetrics(reporter=RoundReporter(report_path))
    machine, request_tx, events = await StateMachineInitializer(
        settings, store, metrics
    ).init()
    handler = PetMessageHandler(events, request_tx)
    fetcher = Fetcher(events)
    rest = RestServer(fetcher, handler, registry=metrics.registry)
    host, port = await rest.start("127.0.0.1", 0)
    machine_task = asyncio.create_task(machine.run())

    try:
        url = f"http://{host}:{port}"
        probe = HttpClient(url)
        while fetcher.phase().value != "sum":
            await asyncio.sleep(0.01)

        # --- mid-round scrape --------------------------------------------
        status, headers, body = await _http_get(host, port, "/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["status"] == "ok"
        assert health["phase"] == "sum"
        assert health["round_id"] >= 1
        assert health["uptime_seconds"] >= 0

        status, headers, body = await _http_get(host, port, "/metrics")
        assert status == 200
        assert headers["content-type"].startswith("text/plain")
        mid = body.decode()
        _assert_exposition_well_formed(mid)
        assert '# TYPE xaynet_phase_transitions_total counter' in mid
        assert 'xaynet_phase_transitions_total{phase="sum"}' in mid
        assert "# TYPE xaynet_request_queue_depth gauge" in mid

        # --- drive one full round ----------------------------------------
        params = await probe.get_round_params()
        seed = params.seed.as_bytes()
        rng = np.random.default_rng(5)
        participants = []
        for i in range(N_SUM):
            keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
            participants.append(
                ParticipantSM(PetSettings(keys=keys), HttpClient(url), ArrayModelStore(None))
            )
        for i in range(N_UPDATE):
            keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(20 + i) * 1000)
            local = rng.uniform(-1, 1, MODEL_LEN).astype(np.float32)
            participants.append(
                ParticipantSM(
                    PetSettings(keys=keys, scalar=Fraction(1, N_UPDATE)),
                    HttpClient(url),
                    ArrayModelStore(local),
                )
            )

        async def drive(sm):
            for _ in range(500):
                try:
                    await sm.transition()
                except Exception:
                    pass
                model = await probe.get_model()
                if model is not None and sm.phase.value == "awaiting":
                    return
                await asyncio.sleep(0.01)

        await asyncio.gather(*(drive(p) for p in participants))
        assert await probe.get_model() is not None

        # round 2's Idle flushes round 1's report
        deadline = asyncio.get_running_loop().time() + 20
        while events.params.get_latest().round_id < 2:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.01)

        # --- post-round scrape -------------------------------------------
        status, _, body = await _http_get(host, port, "/metrics")
        assert status == 200
        text = body.decode()
        _assert_exposition_well_formed(text)
        # per-phase duration histograms for the full round
        assert "# TYPE xaynet_phase_duration_seconds histogram" in text
        for phase in ("sum", "update", "sum2", "unmask"):
            assert f'xaynet_phase_duration_seconds_bucket{{phase="{phase}",le=' in text
        # message outcome counters
        assert 'xaynet_messages_total{phase="update",outcome="accepted"}' in text
        # aggregation kernel timings with derived throughput
        assert 'xaynet_kernel_seconds_bucket{op="masked_add",le=' in text
        assert 'xaynet_kernel_seconds_bucket{op="unmask",le=' in text
        assert 'xaynet_kernel_elements_per_second{op="masked_add"}' in text
        assert 'xaynet_kernel_elements_per_second{op="unmask"}' in text
        # HTTP surface instruments itself too
        assert 'xaynet_http_requests_total{method="GET",path="/metrics",status="200",tenant=""}' in text
    finally:
        machine_task.cancel()
        await rest.stop()
        try:
            await machine_task
        except (asyncio.CancelledError, Exception):
            pass
        metrics.close()


def test_telemetry_endpoints_and_round_report(tmp_path):
    report_path = str(tmp_path / "round_reports.jsonl")
    asyncio.run(asyncio.wait_for(_run(report_path), timeout=60))

    with open(report_path) as f:
        reports = [json.loads(line) for line in f if line.strip()]
    assert reports, "no round report written"
    first = reports[0]
    assert first["round_id"] == 1
    assert "unmask" in first["phases"]
    for phase in ("sum", "update", "sum2", "unmask"):
        assert first["phase_durations"][phase] >= 0
    assert first["messages"]["update"]["accepted"] == N_UPDATE
    assert first["masks_total"] == 1
    kernels = first["kernels"]
    assert "masked_add" in kernels and "unmask" in kernels
    assert kernels["masked_add"]["calls"] >= 1
    assert kernels["masked_add"]["elements"] >= N_UPDATE * MODEL_LEN
    assert kernels["masked_add"]["elements_per_sec"] >= 0
