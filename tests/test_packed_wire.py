"""Packed wire (v2 byte-planar) transport through the aggregation path.

Promotes the packed-rows pivot smoke into the suite: a round that mixes
wire v1 (interleaved uint32) and wire v2 (byte-planar) members on the
device aggregator must finalize byte-identically to the host eager
control — at mesh=1 and mesh=8 — while v2 members stay PACKED uint8
rows through staging. Malformed and truncated packed bodies must reject
cleanly without poisoning the accumulator, and the round-parameter
negotiation must round-trip the wire format.
"""

import jax
import numpy as np
import pytest

from xaynet_tpu.core.common import RoundParameters, RoundSeed
from xaynet_tpu.core.mask import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    Masker,
    ModelType,
    Scalar,
)
from xaynet_tpu.core.mask.masking import AggregationError
from xaynet_tpu.core.mask.object import MaskObject
from xaynet_tpu.core.mask.serialization import (
    DecodeError,
    parse_mask_vect,
    serialize_mask_vect,
)
from xaynet_tpu.parallel.mesh import make_mesh
from xaynet_tpu.server.aggregation import StagedAggregator

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
N = 57


def _mesh(n_devices: int):
    assert len(jax.devices()) >= n_devices, jax.devices()
    return make_mesh(jax.devices()[:n_devices])


def _mixed_members(k: int, seed: int = 5):
    """k masked members, alternating wire v2 (planar) / v1, each
    round-tripped through the real serializer so staging sees exactly the
    bytes a participant would put on the wire."""
    rng = np.random.default_rng(seed)
    members = []
    for i in range(k):
        w = rng.uniform(-1, 1, N).astype(np.float32)
        _, masked = Masker(CFG.pair()).mask(Scalar(1, k), w)
        planar = i % 2 == 0
        blob = serialize_mask_vect(masked.vect, planar=planar)
        vect, _ = parse_mask_vect(blob, lazy=True)
        assert vect.planar is planar
        members.append((MaskObject(vect, masked.unit), masked))
    return members


@pytest.mark.parametrize("mesh_n", [1, 8])
def test_mixed_wire_round_matches_all_legacy_control(mesh_n):
    members = _mixed_members(6)
    host = StagedAggregator(CFG.pair(), N, device=False, batch_size=8)
    dev = StagedAggregator(
        CFG.pair(), N, device=True, batch_size=8, kernel="xla",
        mesh=_mesh(mesh_n),
    )
    # batch-prevalidate half, per-member validate the rest: both intake
    # code paths must land in the same accumulator state
    dev.prevalidate_wire_batch([obj for obj, _ in members[:3]])
    for obj, masked in members:
        host.validate_aggregation(masked)
        host.aggregate(masked)
        dev.validate_aggregation(obj)
        staged = obj.vect._staged_planar
        assert staged is not None
        if obj.vect.planar:
            # the v2 promise: accepted rows stay byte-planar uint8 planes
            # (bytes_per_number x padded), never widened to uint32 limbs
            assert staged.dtype == np.uint8 and staged.ndim == 2
            assert staged.shape[0] == CFG.bytes_per_number
        else:
            assert staged.dtype == np.uint32
        dev.aggregate(obj)
    dev.drain()
    a, b = host.finalize(), dev.finalize()
    assert a.nb_models == b.nb_models == len(members)
    assert a.object == b.object


@pytest.mark.parametrize("mesh_n", [1, 8])
def test_invalid_planar_member_rejects_without_poisoning(mesh_n):
    rng = np.random.default_rng(11)
    w = rng.uniform(-1, 1, N).astype(np.float32)
    _, masked = Masker(CFG.pair()).mask(Scalar(1, 2), w)
    blob = bytearray(serialize_mask_vect(masked.vect, planar=True))
    # blast every plane of element 0 to 0xFF -> value >= group order
    bpn = CFG.bytes_per_number
    hdr = len(blob) - bpn * N
    for p in range(bpn):
        blob[hdr + p * N] = 0xFF
    vect, _ = parse_mask_vect(bytes(blob), lazy=True)
    bad = MaskObject(vect, masked.unit)

    agg = StagedAggregator(
        CFG.pair(), N, device=True, batch_size=8, kernel="xla",
        mesh=_mesh(mesh_n),
    )
    with pytest.raises(AggregationError, match="InvalidObject"):
        agg.validate_aggregation(bad)

    # the reject must not poison the round: a good member still folds and
    # the aggregate equals the host control
    host = StagedAggregator(CFG.pair(), N, device=False, batch_size=8)
    for obj, good in _mixed_members(2, seed=13):
        host.validate_aggregation(good)
        host.aggregate(good)
        agg.validate_aggregation(obj)
        agg.aggregate(obj)
    agg.drain()
    assert host.finalize().object == agg.finalize().object


def test_truncated_planar_body_rejects_cleanly():
    rng = np.random.default_rng(17)
    w = rng.uniform(-1, 1, N).astype(np.float32)
    _, masked = Masker(CFG.pair()).mask(Scalar(1, 1), w)
    blob = serialize_mask_vect(masked.vect, planar=True)
    # eager and lazy parse must both reject every truncation point
    for cut in (len(blob) - 1, len(blob) // 2, 5):
        for lazy in (False, True):
            with pytest.raises(DecodeError):
                vect, _ = parse_mask_vect(blob[:cut], lazy=lazy)
                # lazy parses defer the element block: force it
                np.asarray(vect.numbers())


def test_planar_staging_strictly_narrower_than_legacy_uint32():
    """The point of v2: both wire framings pack bytes_per_number bytes per
    element, but a LEGACY member is widened to 4*n_limbs uint32 planes
    before host->device staging while a v2 member stages its byte planes
    verbatim — strictly fewer bytes per accepted update whenever the
    group order is not a whole number of limbs, which is true of the
    production default (PRIME/F32/B0/M3: 6 < 8 bytes per element)."""
    from xaynet_tpu.ops.limbs import n_limbs_for_order
    from xaynet_tpu.server.settings import MaskSettings

    cfg = MaskSettings().to_config()
    assert cfg.bytes_per_number < 4 * n_limbs_for_order(cfg.order)
    # and framing v2 never costs more wire bytes than v1 for one member
    rng = np.random.default_rng(19)
    w = rng.uniform(-1, 1, N).astype(np.float32)
    _, masked = Masker(cfg.pair()).mask(Scalar(1, 1), w)
    v2 = serialize_mask_vect(masked.vect, planar=True)
    v1 = serialize_mask_vect(masked.vect, planar=False)
    assert len(v2) <= len(v1)


def test_round_parameters_negotiate_wire_format():
    params = RoundParameters(
        pk=b"\x01" * 32,
        sum=0.5,
        update=0.9,
        seed=RoundSeed(b"\x07" * 32),
        mask_config=CFG.pair(),
        model_length=N,
        wire_format=2,
    )
    assert RoundParameters.from_dict(params.to_dict()).wire_format == 2
    # legacy coordinators omit the field: clients must default to v1
    legacy = params.to_dict()
    legacy.pop("wire_format")
    assert RoundParameters.from_dict(legacy).wire_format == 1
