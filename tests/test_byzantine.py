"""Byzantine-participant and griefing defenses.

Mirrors the reference's defensive surface (reference:
rust/xaynet-server/src/services/messages/task_validator.rs:40-88,
multipart/service.rs:26-117, state_machine/phases/unmask.rs:96-115):
structurally-valid-but-hostile inputs must be rejected into the right
counter, never crash a phase, and never grow coordinator memory without
bound.
"""

import asyncio

import pytest

from xaynet_tpu.core.crypto.encrypt import PublicEncryptKey
from xaynet_tpu.core.crypto.prng import uniform_ints
from xaynet_tpu.core.mask import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    MaskObject,
    ModelType,
)
from xaynet_tpu.core.message import Message, Sum, Tag, Update
from xaynet_tpu.core.message.payloads import Chunk
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.server.requests import RequestError
from xaynet_tpu.server.services import PetMessageHandler
from xaynet_tpu.server.settings import CountSettings, Settings
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store

CFG = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3)


class _CountingMetrics:
    """Recorder stub: counts (measurement, phase) pairs."""

    def __init__(self):
        self.counts: dict[tuple[str, str], int] = {}

    def _bump(self, name, phase):
        self.counts[(name, phase)] = self.counts.get((name, phase), 0) + 1

    def message_accepted(self, round_id, phase):
        self._bump("accepted", phase)

    def message_rejected(self, round_id, phase):
        self._bump("rejected", phase)

    def message_discarded(self, round_id, phase):
        self._bump("discarded", phase)

    def __getattr__(self, name):  # every other measurement is a no-op
        return lambda *a, **k: None


def _settings(tmax=5.0):
    s = Settings.default()
    s.mask.group_type = CFG.group_type
    s.mask.data_type = CFG.data_type
    s.mask.bound_type = CFG.bound_type
    s.mask.model_type = CFG.model_type
    s.model.length = 6
    for phase in (s.pet.sum, s.pet.update, s.pet.sum2):
        phase.time.min = 0.0
        phase.time.max = tmax
    return s


def _store():
    return Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())


async def _until_phase(events, name):
    while events.phase.get_latest().event.value != name:
        await asyncio.sleep(0.01)


def _encrypt_for(params, payload, keys, tag=None, is_multipart=False):
    msg = Message(
        participant_pk=keys.public,
        coordinator_pk=params.pk,
        payload=payload,
        tag=tag,
        is_multipart=is_multipart,
    )
    return PublicEncryptKey(params.pk).encrypt(msg.to_bytes(keys.secret))


def _masked_model(seed: int, n: int = 6) -> MaskObject:
    ints = uniform_ints(bytes([seed]) * 32, n + 1, CFG.order)
    return MaskObject.new(CFG.pair(), ints[1:], ints[0])


async def _drive_to_update(settings, store, metrics, n_summers=2, wire_ingest=False):
    """Start a coordinator, fill the sum phase, land in update phase."""
    machine, tx, events = await StateMachineInitializer(settings, store, metrics).init()
    handler = PetMessageHandler(events, tx, wire_ingest=wire_ingest)
    machine_task = asyncio.create_task(machine.run())
    await _until_phase(events, "sum")
    params = events.params.get_latest().event
    seed = params.seed.as_bytes()
    summers = []
    start = 0
    while len(summers) < n_summers:
        k = keys_for_task(seed, params.sum, params.update, "sum", start=start)
        start += 100000
        if all(k.public != s.public for s in summers):
            summers.append(k)
    for i, k in enumerate(summers):
        payload = Sum(
            sum_signature=k.sign(seed + b"sum").as_bytes(), ephm_pk=bytes([i + 1]) * 32
        )
        await handler.handle_message(_encrypt_for(params, payload, k))
    await _until_phase(events, "update")
    return machine, machine_task, handler, events, params, summers


async def _stop(machine_task):
    machine_task.cancel()
    try:
        await machine_task
    except (asyncio.CancelledError, Exception):
        pass


def _updater(params, start=0):
    seed = params.seed.as_bytes()
    return keys_for_task(seed, params.sum, params.update, "update", start=start)


def _update_payload(params, keys, seed_dict, masked_model=None):
    seed = params.seed.as_bytes()
    return Update(
        sum_signature=keys.sign(seed + b"sum").as_bytes(),
        update_signature=keys.sign(seed + b"update").as_bytes(),
        masked_model=masked_model if masked_model is not None else _masked_model(3),
        local_seed_dict=seed_dict,
    )


def test_seed_dict_targeting_subset_rejected():
    """A seed dict covering only SOME sum participants (an attempt to bias
    which summers can reconstruct the mask) is atomically rejected with
    LENGTH_MISMATCH and lands in the rejected counter."""

    async def run():
        settings = _settings()
        settings.pet.sum.count = CountSettings(2, 2)
        settings.pet.update.count = CountSettings(3, 3)  # protocol floor is 3
        metrics = _CountingMetrics()
        store = _store()
        machine, machine_task, handler, events, params, summers = await _drive_to_update(
            settings, store, metrics
        )
        try:
            updater = _updater(params)
            # subset: only the FIRST summer gets a seed
            subset = {summers[0].public: b"\x07" * 80}
            with pytest.raises(RequestError) as e:
                await handler.handle_message(
                    _encrypt_for(params, _update_payload(params, updater, subset), updater)
                )
            assert e.value.kind is RequestError.Kind.MESSAGE_REJECTED
            assert metrics.counts.get(("rejected", "update")) == 1
            # seed dict of the right SIZE but with an unknown sum pk
            unknown = {summers[0].public: b"\x07" * 80, b"\xee" * 32: b"\x07" * 80}
            with pytest.raises(RequestError) as e:
                await handler.handle_message(
                    _encrypt_for(params, _update_payload(params, updater, unknown), updater)
                )
            assert e.value.kind is RequestError.Kind.MESSAGE_REJECTED
            assert metrics.counts.get(("rejected", "update")) == 2
            # an honest update with the full seed dict is still accepted
            full = {s.public: b"\x07" * 80 for s in summers}
            await handler.handle_message(
                _encrypt_for(params, _update_payload(params, updater, full), updater)
            )
            assert metrics.counts.get(("accepted", "update")) == 1
        finally:
            await _stop(machine_task)

    asyncio.run(asyncio.wait_for(run(), 30))


@pytest.mark.parametrize("wire_ingest", [True, False])
def test_invalid_element_update_rejected(wire_ingest):
    """A masked model with an element >= the group order. Under the
    device-ingest pipeline (aggregation.wire_ingest) the lazy parse
    accepts the bytes, but the DEVICE validity check rejects the message
    at validate_aggregation — BEFORE its seed-dict insert — and the
    attacker's seeds never reach any sum participant. Eager mode drops the
    same message one stage earlier (parse DecodeError -> pipeline drop);
    both end with the update not counted."""
    import numpy as np

    from xaynet_tpu.core.mask.object import MaskVect
    from xaynet_tpu.server.services import ServiceError

    def _poisoned_model():
        obj = _masked_model(3)
        bad = obj.vect.data.copy()
        bad[2, :] = np.uint32(0xFFFFFFFF)  # element >= every M3 order
        return MaskObject(MaskVect(CFG, bad), obj.unit)

    async def run(wire_ingest):
        settings = _settings()
        settings.pet.sum.count = CountSettings(2, 2)
        settings.pet.update.count = CountSettings(3, 3)
        if wire_ingest:
            settings.aggregation.device = True
            settings.aggregation.wire_ingest = True
            settings.aggregation.kernel = "xla"
        metrics = _CountingMetrics()
        store = _store()
        machine, machine_task, handler, events, params, summers = await _drive_to_update(
            settings, store, metrics, wire_ingest=wire_ingest
        )
        try:
            attacker = _updater(params)
            full = {s.public: b"\x07" * 80 for s in summers}
            poisoned = _update_payload(params, attacker, full, masked_model=_poisoned_model())
            if wire_ingest:
                with pytest.raises(RequestError) as e:
                    await handler.handle_message(_encrypt_for(params, poisoned, attacker))
                assert e.value.kind is RequestError.Kind.MESSAGE_REJECTED
                assert metrics.counts.get(("rejected", "update")) == 1
            else:
                # eager parse: the same bytes die at the parse stage
                with pytest.raises(ServiceError):
                    await handler.handle_message(_encrypt_for(params, poisoned, attacker))
            # the attacker's seeds were never inserted
            sd = await store.coordinator.seed_dict()
            assert not any(attacker.public in inner for inner in (sd or {}).values())
            # an honest update through the same pipeline still lands
            honest = _updater(params, start=500_000)
            await handler.handle_message(
                _encrypt_for(params, _update_payload(params, honest, full), honest)
            )
            assert metrics.counts.get(("accepted", "update")) == 1
            sd = await store.coordinator.seed_dict()
            assert all(honest.public in inner for inner in sd.values())
            assert not any(attacker.public in inner for inner in sd.values())
        finally:
            await _stop(machine_task)

    asyncio.run(asyncio.wait_for(run(wire_ingest), 60))


def test_multipart_buffer_exhaustion_evicts_oldest():
    """A flood of never-completing multipart messages cannot grow coordinator
    memory: the buffer table is bounded and evicts oldest-first
    (reference: multipart buffering, bounded here by max_multipart_buffers)."""

    async def run():
        settings = _settings()
        settings.pet.sum.count = CountSettings(64, 64)  # keep sum phase open
        store = _store()
        machine, tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, tx)
        handler.max_multipart_buffers = 8
        machine_task = asyncio.create_task(machine.run())
        try:
            await _until_phase(events, "sum")
            params = events.params.get_latest().event
            seed = params.seed.as_bytes()
            attacker = keys_for_task(seed, params.sum, params.update, "sum")
            for message_id in range(50):
                chunk = Chunk(id=1, message_id=message_id, last=False, data=b"\xab" * 64)
                enc = _encrypt_for(params, chunk, attacker, tag=Tag.SUM, is_multipart=True)
                await handler.handle_message(enc)  # incomplete: returns, no error
            assert len(handler._multipart) <= 8
        finally:
            await _stop(machine_task)

    asyncio.run(asyncio.wait_for(run(), 30))


def test_duplicate_chunk_flood_is_bounded_and_idempotent():
    """Re-sending the same chunk ad infinitum neither grows the buffer nor
    completes the message twice."""

    async def run():
        settings = _settings()
        settings.pet.sum.count = CountSettings(64, 64)
        store = _store()
        machine, tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, tx)
        machine_task = asyncio.create_task(machine.run())
        try:
            await _until_phase(events, "sum")
            params = events.params.get_latest().event
            seed = params.seed.as_bytes()
            attacker = keys_for_task(seed, params.sum, params.update, "sum")
            chunk = Chunk(id=1, message_id=9, last=False, data=b"\xcd" * 32)
            enc = _encrypt_for(params, chunk, attacker, tag=Tag.SUM, is_multipart=True)
            for _ in range(100):
                await handler.handle_message(enc)
            assert len(handler._multipart) == 1
            (builder,) = handler._multipart.values()
            assert len(builder._chunks) == 1  # duplicates overwrite, not append
        finally:
            await _stop(machine_task)

    asyncio.run(asyncio.wait_for(run(), 30))


def test_mask_election_majority_wins_and_tie_fails():
    """The unmask election requires a unique maximum: a Byzantine minority
    mask loses; an exact tie aborts the round instead of guessing
    (reference: unmask.rs:96-115)."""
    from xaynet_tpu.server.phases.base import PhaseError
    from xaynet_tpu.server.phases.unmask import Unmask

    honest, byzantine = _masked_model(1), _masked_model(2)
    # majority: honest mask has 2 votes, byzantine 1
    assert Unmask._freeze_mask_dict([(honest, 2), (byzantine, 1)]) == honest
    # tie: must abort, not pick arbitrarily
    with pytest.raises(PhaseError):
        Unmask._freeze_mask_dict([(honest, 1), (byzantine, 1)])
