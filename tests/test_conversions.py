"""Primitive conversion edges (reference parity: model.rs / scalar.rs)."""

from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.core.mask import DataType, MaskConfigPair, Model, PrimitiveCastError
from xaynet_tpu.core.mask.config import BoundType, GroupType, MaskConfig, ModelType
from xaynet_tpu.core.mask.model import Scalar


def test_from_primitives_rejects_non_finite():
    with pytest.raises(PrimitiveCastError):
        Model.from_primitives([1.0, float("inf")], DataType.F32)
    with pytest.raises(PrimitiveCastError):
        Model.from_primitives([float("nan")], DataType.F64)


def test_from_primitives_bounded_clamps():
    m = Model.from_primitives_bounded(
        [float("inf"), float("-inf"), float("nan"), 1.5], DataType.F32
    )
    fmax = Fraction(float(np.finfo(np.float32).max))
    assert m[0] == fmax
    assert m[1] == -fmax
    assert m[2] == 0
    assert m[3] == Fraction(1.5)


def test_into_primitives_roundtrip_exactness():
    vals = [-1.25, 0.0, 0.1, 123.456]
    m = Model.from_primitives(vals, DataType.F32)
    back = m.into_primitives(DataType.F32)
    assert back == [float(np.float32(v)) for v in vals]

    ints = [-(2**31), 2**31 - 1, 0, 42]
    mi = Model.from_primitives(ints, DataType.I32)
    assert mi.into_primitives(DataType.I32) == ints


def test_scalar_bounded_conversion():
    assert Scalar.from_float_bounded(float("nan")).value == 0
    assert Scalar.from_float_bounded(-3.0).value == 0
    assert Scalar.from_float_bounded(float("inf")).value == Fraction(
        float(np.finfo(np.float64).max)
    )
    with pytest.raises(ValueError):
        Scalar.from_float(float("inf"))
    with pytest.raises(ValueError):
        Scalar.from_float(-1.0)


def test_mask_config_pair_wire_roundtrip():
    pair = MaskConfigPair(
        vect=MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3),
        unit=MaskConfig(GroupType.INTEGER, DataType.F64, BoundType.B6, ModelType.M9),
    )
    assert MaskConfigPair.from_bytes(pair.to_bytes()) == pair


def test_model_array_bridges():
    arr = np.asarray([0.5, -0.25, 0.125], dtype=np.float32)
    m = Model.from_array(arr)
    np.testing.assert_array_equal(m.to_array(DataType.F32), arr)
