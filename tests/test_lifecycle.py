"""Elastic tenant lifecycle (docs/DESIGN.md §23): pool defrag, SLO-weighted
scheduling, the quarantine/drain state machine, and the /admin/tenants REST
surface — all against fakes and an injectable clock, so no test sleeps
through a drain budget or a quarantine reset."""

import asyncio
import json
import threading
import time
import types

import numpy as np
import pytest

from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.settings import TenancySettings
from xaynet_tpu.telemetry.registry import get_registry
from xaynet_tpu.tenancy.lifecycle import (
    DRAINED,
    QUARANTINED,
    SERVING,
    LifecycleError,
    TenantLifecycle,
    get_manager,
    install_manager,
    note_round_failed,
)
from xaynet_tpu.tenancy.pool import PagePool
from xaynet_tpu.tenancy.registry import TenantContext, TenantRegistry
from xaynet_tpu.tenancy.scheduler import TenantScheduler, get_scheduler


def _sample(name, labels=None):
    return get_registry().sample_value(name, labels or {}) or 0.0


def _wait_for(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.005)
    return False


# --------------------------------------------------------------------------
# PagePool: fragmentation gauge + between-round compaction
# --------------------------------------------------------------------------


def test_pool_fragmentation_tracks_free_run_shred():
    pool = PagePool(page_bytes=4096, slab_pages=8)
    assert pool.fragmentation() == 0.0  # nothing leased: one 8-page run
    a = pool.lease_host("fr", (4096,), np.uint8)
    b = pool.lease_host("fr", (2 * 4096,), np.uint8)
    c = pool.lease_host("fr", (4096,), np.uint8)
    pool.release(b)  # hole of 2 between a and c; tail run of 4 behind c
    frag = pool.fragmentation()
    assert abs(frag - (1.0 - 4 / 6)) < 1e-9
    pool.release(a)
    pool.release(c)
    assert pool.fragmentation() == 0.0  # all free runs coalesce back


def test_pool_compact_slides_migratable_leases_and_coalesces_free_space():
    pool = PagePool(page_bytes=4096, slab_pages=8)
    a = pool.lease_host("cp", (4096,), np.uint8)  # page 0 (barrier, packed)
    b = pool.lease_host("cp", (2 * 4096,), np.uint8)  # pages 1-2
    c = pool.lease_host("cp", (4096,), np.uint8)  # page 3
    c.array[:] = 7
    pool.release(b)  # the hole c will slide into
    swapped = []
    pool.set_migrator(c, swapped.append)
    moved = pool.compact()
    assert moved == 1
    assert c.offset == 1  # slid down against the barrier at page 0
    # the holder's reference swap happened under the pool lock, bytes intact
    assert len(swapped) == 1 and swapped[0] is c.array
    assert (c.array == 7).all()
    assert pool.fragmentation() == 0.0
    # the free list is the complement of the packed runs: one lease can now
    # take every remaining page as a single contiguous run
    big = pool.lease_host("cp", (6 * 4096,), np.uint8)
    assert pool.stats()["slabs"] == 1
    for lease in (a, c, big):
        pool.release(lease)
    assert pool.balanced("cp")


def test_pool_compact_never_crosses_immovable_barriers():
    pool = PagePool(page_bytes=4096, slab_pages=8)
    a = pool.lease_host("bar", (4096,), np.uint8)  # page 0
    b = pool.lease_host("bar", (4096,), np.uint8)  # page 1: NO migrator
    c = pool.lease_host("bar", (4096,), np.uint8)  # page 2
    pool.release(a)  # free page 0, below the barrier
    pool.set_migrator(c, lambda view: None)
    assert pool.compact() == 0  # b blocks the slide; c is already packed
    assert b.offset == 1 and c.offset == 2
    pool.release(b)
    pool.release(c)


def test_pool_compact_trims_trailing_free_slabs():
    pool = PagePool(page_bytes=4096, slab_pages=2)
    a = pool.lease_host("tr", (4096,), np.uint8)  # slab 0
    big = pool.lease_host("tr", (3 * 4096,), np.uint8)  # dedicated slab 1
    assert pool.stats()["slabs"] == 2
    pool.release(big)
    pool.compact()
    assert pool.stats()["slabs"] == 1  # the fully-free trailing slab dropped
    pool.release(a)
    assert pool.balanced("tr")


def test_pool_set_migrator_is_noop_on_released_leases():
    pool = PagePool(page_bytes=4096, slab_pages=4)
    a = pool.lease_host("rel", (4096,), np.uint8)
    pool.release(a)
    pool.set_migrator(a, lambda view: None)
    assert a.migrator is None  # a released lease never becomes migratable


def test_pool_reclaim_counts_only_the_releases_it_won():
    # regression: a GC finalizer releasing a straggler between reclaim's
    # outstanding() snapshot and its force-release must not be counted by
    # reclaim too — xaynet_pool_reclaimed_total moves only for won releases
    pool = PagePool(page_bytes=4096, slab_pages=4)
    a = pool.lease_host("race", (4096,), np.uint8)
    pool.lease_host("race", (4096,), np.uint8)
    before = _sample("xaynet_pool_reclaimed_total", {"tenant": "race"})
    snapshot = pool.outstanding

    def racing_outstanding(tenant=None):
        leases = snapshot(tenant)
        pool.release(a)  # the finalizer wins lease a after the snapshot
        return leases

    pool.outstanding = racing_outstanding
    try:
        assert pool.reclaim("race") == 1  # only the lease this call released
    finally:
        del pool.__dict__["outstanding"]
    assert _sample("xaynet_pool_reclaimed_total", {"tenant": "race"}) == before + 1
    assert pool.balanced("race")
    assert pool.reclaim("race") == 0  # idempotent once everything returned


# --------------------------------------------------------------------------
# TenantScheduler: weights, tiers, demotion
# --------------------------------------------------------------------------


def _grant_order(sched, first, second):
    """Start two waiters (``first`` queues before ``second``) against a
    fully-held scheduler, free one slot, and report who got granted."""
    order = []

    def waiter(tenant, owner):
        sched.acquire(tenant, owner)
        order.append(tenant)

    owners = {t: sched.new_owner() for t in (first, second)}
    ta = threading.Thread(target=waiter, args=(first, owners[first]), daemon=True)
    ta.start()
    assert _wait_for(lambda: len(sched._waiting) == 1)
    tb = threading.Thread(target=waiter, args=(second, owners[second]), daemon=True)
    tb.start()
    assert _wait_for(lambda: len(sched._waiting) == 2)
    return order, owners


def test_scheduler_weighted_deficit_round_robin():
    sched = TenantScheduler(max_inflight=1)
    holder = sched.new_owner()
    # history: a served once, b served twice — unweighted, a is owed next
    sched.acquire("a", holder)
    sched.release(holder)
    for _ in range(2):
        sched.acquire("b", holder)
        sched.release(holder)
    sched.set_weight("b", 4.0)  # weighted deficits: a = 1/1, b = 2/4
    sched.acquire("hold", holder)
    order, owners = _grant_order(sched, "a", "b")
    sched.release(holder)
    # b's weighted deficit is smaller, so b beats both FIFO and raw counts
    assert _wait_for(lambda: order == ["b"])
    sched.release(owners["b"])
    assert _wait_for(lambda: order == ["b", "a"])
    for owner in owners.values():
        sched.release_owner(owner)
    sched.release_owner(holder)


def test_scheduler_tier_dominates_deficit():
    sched = TenantScheduler(max_inflight=1)
    holder = sched.new_owner()
    sched.acquire("hold", holder)
    sched.set_tier("a", 1)  # lower tier number wins; b stays at default 0
    order, owners = _grant_order(sched, "a", "b")
    sched.release(holder)
    assert _wait_for(lambda: order == ["b"])
    sched.release(owners["b"])
    assert _wait_for(lambda: order == ["b", "a"])
    for owner in owners.values():
        sched.release_owner(owner)
    sched.release_owner(holder)


def test_scheduler_demotion_yields_slots_and_counts_transitions():
    sched = TenantScheduler(max_inflight=1)
    before = _sample("xaynet_tenant_sched_demotions_total", {"tenant": "a"})
    sched.set_demoted("a", True)
    sched.set_demoted("a", True)  # idempotent: no second transition
    assert _sample("xaynet_tenant_sched_demotions_total", {"tenant": "a"}) == before + 1
    assert sched.demoted() == {"a"}
    holder = sched.new_owner()
    sched.acquire("hold", holder)
    order, owners = _grant_order(sched, "a", "b")
    sched.release(holder)
    # the demoted tenant only wins a slot once no healthy tenant waits
    assert _wait_for(lambda: order == ["b"])
    sched.release(owners["b"])
    assert _wait_for(lambda: order == ["b", "a"])
    sched.set_demoted("a", False)
    assert sched.demoted() == set()
    sched.forget_tenant("a")
    assert "a" not in sched.split()
    for owner in owners.values():
        sched.release_owner(owner)
    sched.release_owner(holder)


# --------------------------------------------------------------------------
# TenantLifecycle: quarantine, drain, onboard — fake clock throughout
# --------------------------------------------------------------------------


class _Clock:
    def __init__(self, start=1000.0):
        self.now = float(start)

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def _lifecycle(builder=None, budget=None, **overrides):
    settings = dict(
        enabled=True,
        admin_token="test-admin-token",
        drain_timeout_s=5.0,
        quarantine_failures=2,
        quarantine_reset_s=30.0,
    )
    settings.update(overrides)
    clock = _Clock()
    registry = TenantRegistry()
    routes = {}
    lc = TenantLifecycle(
        TenancySettings(**settings),
        registry,
        routes,
        budget=budget,
        builder=builder,
        clock=clock,
    )
    return lc, clock, registry, routes


def test_quarantine_trips_sheds_and_probe_readmits():
    lc, clock, _, _ = _lifecycle()
    lc.mark_serving("q1")
    before = _sample("xaynet_tenant_quarantines_total", {"tenant": "q1"})
    assert _sample("xaynet_tenant_state", {"tenant": "q1"}) == 2.0
    lc.note_round_failed("q1")
    assert lc.state("q1") == SERVING  # one failure is below the threshold
    assert lc.admit("q1") == (True, None)
    lc.note_round_failed("q1")  # threshold reached: breaker opens
    assert lc.state("q1") == QUARANTINED
    assert _sample("xaynet_tenant_state", {"tenant": "q1"}) == 3.0
    assert _sample("xaynet_tenant_quarantines_total", {"tenant": "q1"}) == before + 1
    assert "q1" in get_scheduler().demoted()
    admitted, retry_after = lc.admit("q1")
    assert not admitted and retry_after == 30.0
    # outcomes while the breaker is OPEN are self-inflicted (we shed the
    # traffic): neither failures nor degraded closes move the quarantine
    lc.note_round_failed("q1")
    lc.note_round_completed("q1")
    assert lc.state("q1") == QUARANTINED
    assert lc.admit("q1")[0] is False
    # after the reset window the next admit IS the half-open probe
    clock.advance(31.0)
    assert lc.admit("q1") == (True, None)
    lc.note_round_failed("q1")  # failed probe: re-opened, no double count
    assert lc.admit("q1")[0] is False
    assert _sample("xaynet_tenant_quarantines_total", {"tenant": "q1"}) == before + 1
    clock.advance(31.0)
    lc.note_round_completed("q1")  # completed probe lifts the quarantine
    assert lc.state("q1") == SERVING
    assert lc.admit("q1") == (True, None)
    assert "q1" not in get_scheduler().demoted()
    get_scheduler().forget_tenant("q1")


def test_slo_transitions_drive_scheduler_demotion():
    lc, _, _, _ = _lifecycle()
    lc.mark_serving("s1")
    lc.slo_transition("s1", "round_wall", "page")
    assert "s1" in get_scheduler().demoted()
    lc.slo_transition("s1", "ingest", "page")
    lc.slo_transition("s1", "round_wall", "warn")  # one SLO still pages
    assert "s1" in get_scheduler().demoted()
    lc.slo_transition("s1", "ingest", "ok")  # both recovered
    assert "s1" not in get_scheduler().demoted()
    lc.slo_transition("ghost", "round_wall", "page")  # unknown tenant: no-op
    assert "ghost" not in get_scheduler().demoted()
    engine = types.SimpleNamespace(hook=None)
    engine.set_transition_hook = lambda hook: setattr(engine, "hook", hook)
    lc.install_slo_hook(engine)
    assert engine.hook == lc.slo_transition
    get_scheduler().forget_tenant("s1")


def test_mark_serving_applies_configured_weights_and_tiers():
    lc, _, _, _ = _lifecycle(weights="w1=2.5", tiers="w1=1")
    lc.mark_serving("w1")
    sched = get_scheduler()
    assert sched._weights["w1"] == 2.5
    assert sched._tiers["w1"] == 1
    sched.forget_tenant("w1")


def test_reconfigure_requires_a_live_tenant():
    lc, _, _, _ = _lifecycle()
    with pytest.raises(LifecycleError):
        lc.reconfigure("nobody", weight=2.0)
    lc.mark_serving("r1")
    assert lc.reconfigure("r1", weight=2.0, tier=1) == {
        "tenant": "r1",
        "weight": 2.0,
        "tier": 1,
    }
    with pytest.raises(ValueError):
        lc.reconfigure("r1", weight=0.0)  # scheduler rejects it
    get_scheduler().forget_tenant("r1")


def test_offboard_graceful_on_round_boundary():
    async def run():
        lc, _, registry, routes = _lifecycle()

        async def forever():
            await asyncio.sleep(3600)

        ctx = TenantContext(tenant="d1", settings=None)
        registry.add(ctx)
        ctx.task = asyncio.create_task(forever())
        routes["d1"] = object()
        lc.mark_serving("d1")
        before = _sample("xaynet_tenant_drains_total", {"outcome": "graceful"})
        verdicts = []

        async def close_round():
            await asyncio.sleep(0.12)
            verdicts.append(lc.admit("d1"))  # draining: mutating traffic shed
            lc.note_round_completed("d1")  # the in-flight round's boundary

        closer = asyncio.create_task(close_round())
        result = await lc.offboard("d1")
        await closer
        assert verdicts == [(False, None)]
        assert result == {"tenant": "d1", "state": DRAINED, "outcome": "graceful"}
        assert lc.state("d1") == DRAINED
        assert _sample("xaynet_tenant_drains_total", {"outcome": "graceful"}) == before + 1
        assert "d1" not in routes and registry.get("d1") is None
        assert ctx.task.cancelled()
        with pytest.raises(LifecycleError):
            await lc.offboard("d1")  # already drained: not drainable

    asyncio.run(run())


def test_offboard_timeout_hard_kills_and_tears_down():
    async def run():
        class _Budget:
            def __init__(self):
                self.discharged = []

            def held(self, tenant):
                return 3

            def discharge(self, tenant, amount):
                self.discharged.append((tenant, amount))

        budget = _Budget()
        lc, clock, registry, routes = _lifecycle(budget=budget, drain_timeout_s=1.0)
        closed = []

        async def forever():
            await asyncio.sleep(3600)

        async def pipeline_stop():
            closed.append("pipeline")

        ctx = TenantContext(
            tenant="d2",
            settings=None,
            request_tx=types.SimpleNamespace(close=lambda: closed.append("tx")),
            pipeline=types.SimpleNamespace(stop=pipeline_stop),
            metrics=types.SimpleNamespace(close=lambda: closed.append("metrics")),
        )
        registry.add(ctx)
        ctx.task = asyncio.create_task(forever())
        routes["d2"] = object()
        lc.mark_serving("d2")
        before = _sample("xaynet_tenant_drains_total", {"outcome": "timeout"})

        async def burn_the_budget():
            await asyncio.sleep(0.12)
            clock.advance(10.0)  # no boundary ever arrives; budget expires

        burner = asyncio.create_task(burn_the_budget())
        result = await lc.offboard("d2")
        await burner
        assert result["outcome"] == "timeout"
        assert lc.state("d2") == DRAINED
        assert _sample("xaynet_tenant_drains_total", {"outcome": "timeout"}) == before + 1
        # hard teardown ran in full: task, channel, pipeline, metrics, budget
        assert ctx.task.cancelled()
        assert set(closed) == {"tx", "pipeline", "metrics"}
        assert budget.discharged == [("d2", 3)]
        assert "d2" not in routes and registry.get("d2") is None

    asyncio.run(run())


def test_onboard_builds_admits_and_rolls_back_on_failure():
    async def run():
        cell = {}
        admit_during_build = []

        async def builder(tenant):
            if tenant == "boom":
                raise RuntimeError("builder exploded")
            # while the build runs the tenant is onboarding: traffic sheds
            admit_during_build.append(cell["lc"].admit(tenant))

            async def machine_run():
                return None

            ctx = TenantContext(
                tenant=tenant,
                settings=None,
                machine=types.SimpleNamespace(run=machine_run),
            )
            cell["registry"].add(ctx)
            return ctx, ("routes", tenant)

        lc, _, registry, routes = _lifecycle(builder=builder)
        cell["lc"], cell["registry"] = lc, registry
        result = await lc.onboard("n1")
        assert admit_during_build == [(False, None)]
        assert result["tenant"] == "n1" and result["state"] == SERVING
        assert result["onboard_s"] >= 0.0
        assert routes["n1"] == ("routes", "n1")
        assert lc.state("n1") == SERVING
        with pytest.raises(LifecycleError):
            await lc.onboard("n1")  # already live
        with pytest.raises(ValueError):
            await lc.onboard("NOT A VALID ID")
        # builder failure rolls the state back so a retry can run
        with pytest.raises(RuntimeError):
            await lc.onboard("boom")
        assert lc.state("boom") == DRAINED
        assert "boom" not in lc.states()
        # the rolled-back id onboards cleanly on the next attempt
        result = await lc.onboard("boom2")
        assert result["state"] == SERVING
        for tenant in ("n1", "boom2"):
            await asyncio.sleep(0)  # let the (instantly-returning) machines finish
            await lc.offboard(tenant)
        get_scheduler().forget_tenant("n1")
        get_scheduler().forget_tenant("boom2")

        lc_nobuilder, _, _, _ = _lifecycle(builder=None)
        with pytest.raises(LifecycleError):
            await lc_nobuilder.onboard("n2")

    asyncio.run(run())


def test_module_forwarders_are_noops_without_a_manager():
    previous = get_manager()
    install_manager(None)
    note_round_failed("nobody")  # must not raise
    lc, _, _, _ = _lifecycle()
    lc.mark_serving("fw")
    install_manager(lc)
    try:
        assert get_manager() is lc
        note_round_failed("fw")
        assert lc.breaker("fw")._failures == 1
    finally:
        install_manager(previous)
        get_scheduler().forget_tenant("fw")


# --------------------------------------------------------------------------
# /admin/tenants REST surface
# --------------------------------------------------------------------------


def _admin(server, method, path, body=b"", token="test-admin-token"):
    headers = {} if token is None else {"x-admin-token": token}
    return asyncio.run(server._admin_route(method, path, body, headers))


def test_admin_route_disabled_without_lifecycle_or_token():
    lc, _, _, _ = _lifecycle()
    # no lifecycle, or no token: 404, indistinguishable from unknown routes
    no_lc = RestServer(fetcher=None, handler=None, admin_token="x")
    assert _admin(no_lc, "GET", "/admin/tenants")[0] == 404
    no_token = RestServer(fetcher=None, handler=None, lifecycle=lc, admin_token="")
    assert _admin(no_token, "GET", "/admin/tenants")[0] == 404


def test_admin_route_auth_and_status_mapping():
    async def run():
        async def builder(tenant):
            async def machine_run():
                return None

            ctx = TenantContext(
                tenant=tenant,
                settings=None,
                machine=types.SimpleNamespace(run=machine_run),
            )
            return ctx, ("routes", tenant)

        lc, _, _, routes = _lifecycle(builder=builder)
        server = RestServer(
            fetcher=None, handler=None, lifecycle=lc, admin_token="test-admin-token"
        )
        auth = {"x-admin-token": "test-admin-token"}
        # authentication: missing and wrong tokens are both 401
        assert (await server._admin_route("GET", "/admin/tenants", b"", {}))[0] == 401
        wrong = {"x-admin-token": "nope"}
        assert (await server._admin_route("GET", "/admin/tenants", b"", wrong))[0] == 401
        # onboard + states + reconfigure + drain, through the admin surface
        status, payload, ctype, _ = await server._admin_route(
            "POST", "/admin/tenants", json.dumps({"tenant": "rt1"}).encode(), auth
        )
        assert status == 200 and json.loads(payload)["state"] == SERVING
        assert "rt1" in routes
        status, payload, _, _ = await server._admin_route(
            "GET", "/admin/tenants", b"", auth
        )
        assert json.loads(payload)["tenants"]["rt1"] == SERVING
        status, payload, _, _ = await server._admin_route(
            "POST", "/admin/tenants/rt1", json.dumps({"weight": 2.0}).encode(), auth
        )
        assert status == 200 and json.loads(payload)["weight"] == 2.0
        # bad inputs: 400 for malformed ids and bodies, 409 for bad states
        assert (
            await server._admin_route(
                "POST", "/admin/tenants", json.dumps({"tenant": "BAD ID"}).encode(), auth
            )
        )[0] == 400
        assert (
            await server._admin_route("POST", "/admin/tenants", b"{not json", auth)
        )[0] == 400
        assert (
            await server._admin_route(
                "POST", "/admin/tenants", json.dumps({"tenant": "rt1"}).encode(), auth
            )
        )[0] == 409
        assert (
            await server._admin_route("POST", "/admin/tenants/ghost", b"{}", auth)
        )[0] == 409
        assert (await server._admin_route("DELETE", "/admin/tenants", b"", auth))[0] == 404
        status, payload, _, _ = await server._admin_route(
            "DELETE", "/admin/tenants/rt1", b"", auth
        )
        # the fake builder never registered a machine context, so the drain
        # is immediately graceful
        assert status == 200 and json.loads(payload)["outcome"] == "graceful"
        assert "rt1" not in routes
        get_scheduler().forget_tenant("rt1")

    asyncio.run(run())


def test_route_sheds_unadmitted_tenants_with_429():
    async def run():
        class _FakeLifecycle:
            def __init__(self):
                self.admit_calls = []

            def admit(self, tenant):
                self.admit_calls.append(tenant)
                return False, 7.5

        lifecycle = _FakeLifecycle()
        server = RestServer(
            fetcher=None,
            handler=None,
            lifecycle=lifecycle,
            admin_token="x",
            default_tenant="dq",
        )
        status, _, _, extra = await server._route("POST", "/message", b"", {})
        assert status == 429
        assert extra == {"Retry-After": "8"}  # ceil(7.5), at least 1
        assert lifecycle.admit_calls == ["dq"]  # bare routes = default tenant
        # read-only polls are never shed: a draining tenant's in-flight
        # round still needs its participants to fetch params
        lifecycle.admit_calls.clear()
        status, _, _, _ = await server._route("GET", "/params", b"", {})
        assert status != 429
        assert lifecycle.admit_calls == []

    asyncio.run(run())
