"""Federated learning converges: multi-round MLP training over the protocol."""

import asyncio
import threading
import time
from fractions import Fraction

_COORDINATORS: list = []


import pytest as _pytest


@_pytest.fixture(autouse=True)
def _stop_coordinators():
    yield
    while _COORDINATORS:
        info = _COORDINATORS.pop()
        loop, task = info.get("loop"), info.get("task")
        if loop is not None and task is not None:
            try:
                loop.call_soon_threadsafe(task.cancel)
            except Exception:
                pass


import jax
import numpy as np

from xaynet_tpu.models import mlp
from xaynet_tpu.models.federated import FederatedTrainer, model_length
from xaynet_tpu.sdk.api import spawn_participant
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store

INPUT_DIM = 5
N_SUM, N_UPDATE = 1, 3
FEATURES = (8,)


def _start(model_len):
    # count slack + a short time.min keep the round robust against stopped
    # participants from the previous round stealing slots (their roles
    # re-draw on the new seed); the phase stays open long enough for the
    # pinned participants to register even if a leftover got in first
    # generous time.max: under full-suite load (or a TPU-probe subprocess
    # stealing the single CI core) participant jit/training can stall for
    # minutes; a phase timing out mid-test makes the round count flaky —
    # the adaptive loop below exits early on improvement, so the long
    # window only ever costs time on overloaded runs
    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.3, count=CountSettings(N_SUM, N_SUM + 3), time=TimeSettings(1.0, 300)),
            update=PhaseSettings(prob=0.6, count=CountSettings(N_UPDATE, N_UPDATE + 3), time=TimeSettings(1.0, 300)),
            sum2=Sum2Settings(count=CountSettings(N_SUM, N_SUM + 3), time=TimeSettings(1.0, 300)),
        )
    )
    settings.model.length = model_len
    info, started = {}, threading.Event()

    def run():
        async def main():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0)
            info["url"] = f"http://{host}:{port}"
            info["loop"] = asyncio.get_running_loop()
            task = asyncio.ensure_future(machine.run())
            info["task"] = task
            started.set()
            try:
                await task
            except asyncio.CancelledError:
                pass

        asyncio.run(main())

    threading.Thread(target=run, daemon=True).start()
    assert started.wait(10)
    _COORDINATORS.append(info)
    return info["url"]


@_pytest.mark.slow  # multi-round REST training; minutes without the native crypto wheel
def test_federated_mlp_learns():
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=INPUT_DIM).astype(np.float32)

    def make_data(seed):
        r = np.random.default_rng(seed)
        x = r.normal(size=(96, INPUT_DIM)).astype(np.float32)
        y = (x @ w_true).astype(np.float32)
        return x, y

    template = mlp.init_params(jax.random.PRNGKey(0), INPUT_DIM, FEATURES)
    model_len = model_length(template)
    url = _start(model_len)
    probe = HttpClient(url)

    def sync(coro):
        return asyncio.run(coro)

    params = sync(probe.get_round_params())
    seed = params.seed.as_bytes()

    # shared across trainers so the train step compiles once
    shared_step = mlp.make_train_step(FEATURES, learning_rate=5e-3)

    def make_kwargs(i):
        return dict(
            init_params_fn=lambda: mlp.init_params(jax.random.PRNGKey(7), INPUT_DIM, FEATURES),
            make_step=lambda: shared_step,
            data=make_data(100 + i),
            epochs=2,
            batch_size=32,
        )

    # Task eligibility is re-drawn every round (fresh seed), so a simulation
    # pins fresh role-matched participants per round — joining mid-federation
    # is exactly what the protocol supports.
    xs, ys = make_data(999)
    losses = []
    last_model = None
    # adaptive window: stop as soon as improvement is observed; extra
    # rounds only run when a round regressed or failed (e.g. under heavy
    # CI load a phase can time out and restart, costing one slot)
    max_rounds = 5
    for round_no in range(max_rounds):
        deadline = time.time() + 330  # per round, not shared across rounds
        threads, trainers = [], []
        for i in range(N_SUM):
            keys = keys_for_task(seed, 0.3, 0.6, "sum", start=i * 1000)
            threads.append(
                spawn_participant(url, FederatedTrainer, kwargs=make_kwargs(90), keys=keys)
            )
        for i in range(N_UPDATE):
            keys = keys_for_task(seed, 0.3, 0.6, "update", start=(60 + i) * 1000)
            t = spawn_participant(
                url, FederatedTrainer, kwargs=make_kwargs(i), scalar=Fraction(1, N_UPDATE), keys=keys
            )
            threads.append(t)
            trainers.append(t)

        # wait for this round's model
        while time.time() < deadline:
            model = sync(probe.get_model())
            if model is not None and (last_model is None or not np.array_equal(model, last_model)):
                last_model = model
                p = mlp.unflatten_params(template, np.asarray(model, np.float32))
                pred = mlp.MLP(FEATURES).apply(p, xs).squeeze(-1)
                losses.append(float(np.mean((np.asarray(pred) - ys) ** 2)))
                break
            time.sleep(0.1)
        for t in threads:
            t.stop()
        for t in threads:  # fully stopped: no leftover ticking into the
            t.join(timeout=5)  # next round's slots with a stale model
        # the next round's seed (Idle may not have republished params yet
        # at the moment the model broadcast is observed — wait for it)
        while True:
            fresh = sync(probe.get_round_params()).seed.as_bytes()
            if fresh != seed:
                seed = fresh
                break
            time.sleep(0.05)
        if len(losses) >= 2 and min(losses[1:]) < losses[0]:
            break  # improvement observed; no need to burn more rounds

    assert len(losses) >= 2, f"only {len(losses)} rounds completed"
    # a single round can regress when a leftover participant's stale model
    # wins an update slot; training must improve over the window
    assert min(losses[1:]) < losses[0], losses


def test_local_federation_harness():
    """The one-call simulation harness runs rounds and averages exactly."""
    import numpy as np

    from xaynet_tpu.sdk.api import ParticipantABC
    from xaynet_tpu.sdk.federation import LocalFederation

    MLEN = 9

    class Const(ParticipantABC):
        def __init__(self, v):
            self.v = v

        def train_round(self, training_input):
            return np.full(MLEN, self.v, dtype=np.float32)

    fed = LocalFederation(model_length=MLEN, n_sum=1, n_update=3)
    # weights must respect the mask config's bound (default B0: |w| <= 1)
    trainers = [Const(0.0), Const(0.3), Const(0.6), Const(0.9)]
    try:
        results = list(fed.rounds(trainers, n_rounds=2, round_timeout=60))
    finally:
        fed.stop()
    assert len(results) == 2
    np.testing.assert_allclose(results[0].global_model, np.full(MLEN, 0.6), atol=1e-8)
    assert results[0].round_id == 1 and results[1].round_id == 2


def test_local_federation_integer_models():
    """Int64 models federate through an i64 mask config end-to-end (the
    quantized-delta path of examples/lora_federated.py): the SDK must keep
    the integer dtype through set_model and the exact encode must accept
    numpy scalars."""
    import numpy as np

    from xaynet_tpu.core.mask.config import BoundType, DataType, GroupType
    from xaynet_tpu.sdk.api import ParticipantABC
    from xaynet_tpu.sdk.federation import LocalFederation
    from xaynet_tpu.server.settings import (
        CountSettings,
        PetSettings,
        PhaseSettings,
        Settings,
        Sum2Settings,
        TimeSettings,
    )

    MLEN = 7

    class ConstInt(ParticipantABC):
        def __init__(self, v):
            self.v = v

        def train_round(self, training_input):
            return np.full(MLEN, self.v, dtype=np.int64)

        def serialize_training_result(self, result):
            return np.asarray(result, dtype=np.int64)

    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.3, count=CountSettings(1, 1), time=TimeSettings(0, 60)),
            update=PhaseSettings(prob=0.6, count=CountSettings(3, 3), time=TimeSettings(0, 60)),
            sum2=Sum2Settings(count=CountSettings(1, 1), time=TimeSettings(0, 60)),
        )
    )
    settings.mask.group_type = GroupType.INTEGER
    settings.mask.data_type = DataType.I64
    settings.mask.bound_type = BoundType.B6
    fed = LocalFederation(model_length=MLEN, n_sum=1, n_update=3, settings=settings)
    trainers = [ConstInt(0), ConstInt(-90_000), ConstInt(30_000), ConstInt(120_000)]
    try:
        results = list(fed.rounds(trainers, n_rounds=1, round_timeout=60))
    finally:
        fed.stop()
    np.testing.assert_allclose(
        results[0].global_model, np.full(MLEN, 20_000.0), atol=1e-5
    )


def test_ten_round_soak():
    """Ten consecutive rounds: no drift in round ids, seeds, or averages."""
    import numpy as np

    from xaynet_tpu.sdk.api import ParticipantABC
    from xaynet_tpu.sdk.federation import LocalFederation

    MLEN = 5

    class Const(ParticipantABC):
        def __init__(self, v):
            self.v = v

        def train_round(self, training_input):
            return np.full(MLEN, self.v, dtype=np.float32)

    fed = LocalFederation(model_length=MLEN, n_sum=1, n_update=3)
    trainers = [Const(0.0), Const(-0.9), Const(0.3), Const(0.9)]
    try:
        results = list(fed.rounds(trainers, n_rounds=10, round_timeout=60))
    finally:
        fed.stop()
    assert [r.round_id for r in results] == list(range(1, 11))
    for r in results:
        np.testing.assert_allclose(r.global_model, np.full(MLEN, 0.1), atol=1e-8)


def test_moderate_scale_round():
    """33 participants in one round (3 sum + 30 update) with exact averaging."""
    import numpy as np

    from xaynet_tpu.sdk.api import ParticipantABC
    from xaynet_tpu.sdk.federation import LocalFederation

    MLEN = 32
    N_SUM, N_UPD = 3, 30

    class Const(ParticipantABC):
        def __init__(self, v):
            self.v = v

        def train_round(self, training_input):
            return np.full(MLEN, self.v, dtype=np.float32)

    values = [round(-0.9 + 0.06 * i, 6) for i in range(N_UPD)]
    trainers = [Const(0.0)] * N_SUM + [Const(v) for v in values]
    fed = LocalFederation(model_length=MLEN, n_sum=N_SUM, n_update=N_UPD)
    try:
        (result,) = list(fed.rounds(trainers, n_rounds=1, round_timeout=120))
    finally:
        fed.stop()
    np.testing.assert_allclose(
        result.global_model, np.full(MLEN, float(np.mean(values))), atol=1e-7
    )
