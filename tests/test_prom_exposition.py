"""Prometheus text-exposition conformance (ISSUE 16 satellite).

A strict parser over the registry's full ``render()`` — the same text
``GET /metrics`` serves — enforcing the exposition-format v0.0.4
grammar: every family announces ``# TYPE`` before its samples, sample
names stay inside the family's legal suffix set, label values round-trip
through the ``\\\\``/``\\n``/``\\"`` escapes, values parse as floats
(``+Inf``/``-Inf``/``NaN`` included), histograms expose ascending ``le``
bounds with monotone cumulative bucket counts, a ``+Inf`` bucket equal
to ``_count``, and the body ends in a newline. Run against the LIVE
process registry, so every metric any imported subsystem registered —
round-wall histogram and SLO gauges included — must conform, not just a
synthetic fixture.
"""

from __future__ import annotations

import math
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from xaynet_tpu.telemetry.registry import get_registry  # noqa: E402

# exercise the escaping path: label values carrying every escaped char
AWKWARD = get_registry().counter(
    "test_prom_awkward_total",
    "test-only counter with label values that need escaping",
    ("path",),
)
AWKWARD.labels(path='C:\\dir\n"quoted"').inc()

EDGE_GAUGE = get_registry().gauge(
    "test_prom_edge_values", "test-only gauge for non-finite rendering", ("kind",)
)
EDGE_GAUGE.labels(kind="inf").set(math.inf)
EDGE_GAUGE.labels(kind="neg").set(-math.inf)

HISTO = get_registry().histogram(
    "test_prom_conformance_seconds", "test-only histogram", ("leg",)
)
for v in (0.001, 0.02, 0.3, 4.0, 1e6):
    HISTO.labels(leg="a").observe(v)

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(rf"^({_NAME})(?:\{{(.*)\}})? (\S+)$")
# label pairs with escape-aware values: backslash, quote, n after backslash
_LABEL_RE = re.compile(rf'({_NAME})="((?:[^"\\]|\\["\\n])*)"(?:,|$)')


def _parse_value(token: str) -> float:
    if token == "+Inf":
        return math.inf
    if token == "-Inf":
        return -math.inf
    if token == "NaN":
        return math.nan
    return float(token)  # raises on malformed


def _parse_labels(raw: str) -> dict[str, str]:
    labels: dict[str, str] = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        assert m, f"malformed label segment: {raw[pos:]!r} in {raw!r}"
        value = m.group(2)
        labels[m.group(1)] = (
            value.replace("\\\\", "\0")
            .replace('\\"', '"')
            .replace("\\n", "\n")
            .replace("\0", "\\")
        )
        pos = m.end()
    return labels


_HISTO_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(sample_name: str, types: dict[str, str]) -> str:
    if sample_name in types:
        return sample_name
    for suffix in _HISTO_SUFFIXES:
        base = sample_name.removesuffix(suffix)
        if base != sample_name and types.get(base) == "histogram":
            return base
    raise AssertionError(f"sample {sample_name!r} has no preceding # TYPE")


def test_full_registry_render_conforms():
    text = get_registry().render()
    assert text.endswith("\n")
    types: dict[str, str] = {}
    helps: set[str] = set()
    # per (family, labelset-minus-le): ascending le bounds + running counts
    buckets: dict[tuple[str, tuple], list[tuple[float, float]]] = {}
    samples: dict[str, float] = {}
    for line in text.splitlines():
        assert line == line.strip(), f"stray whitespace: {line!r}"
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helps, f"duplicate HELP for {name}"
            helps.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment line: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        sample_name, raw_labels, value_token = m.groups()
        labels = _parse_labels(raw_labels or "")
        value = _parse_value(value_token)
        family = _family_of(sample_name, types)
        if sample_name.endswith("_bucket") and types[family] == "histogram":
            le = labels.pop("le")
            bound = _parse_value(le)
            key = (family, tuple(sorted(labels.items())))
            series = buckets.setdefault(key, [])
            if series:
                assert bound > series[-1][0], f"le not ascending in {family}"
                assert value >= series[-1][1], f"bucket counts not monotone in {family}"
            series.append((bound, value))
        else:
            samples[f"{sample_name}{{{raw_labels or ''}}}"] = value

    # histogram cross-checks: +Inf bucket == _count for every labelset
    for (family, labelset), series in buckets.items():
        assert series[-1][0] == math.inf, f"{family} missing +Inf bucket"
        raw = ",".join(f'{k}="{v}"' for k, v in labelset)
        count = samples.get(f"{family}_count{{{raw}}}")
        assert count is not None, f"{family} missing _count for {raw!r}"
        assert series[-1][1] == count, f"{family} +Inf bucket != _count"
        assert f"{family}_sum{{{raw}}}" in samples, f"{family} missing _sum"

    # the awkward label value survived the escape round-trip
    assert 'path="C:\\\\dir\\n\\"quoted\\""' in text
    # the §20 families render through the same grammar
    assert types.get("xaynet_round_wall_seconds") == "histogram"
    assert types.get("xaynet_slo_burn_rate") == "gauge"
    assert types.get("xaynet_slo_alerts_total") == "counter"


def test_every_family_has_help_and_type():
    text = get_registry().render()
    announced = {
        line.split()[2] for line in text.splitlines() if line.startswith("# TYPE ")
    }
    helped = {
        line.split()[2] for line in text.splitlines() if line.startswith("# HELP ")
    }
    assert announced == helped
