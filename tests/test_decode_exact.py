"""Vectorized exact-path unmask decode vs the Fraction oracle.

``decode_vect_any`` replaces the per-element Python ``Fraction`` loop for
every config family outside the bounded-f32 fast path (i32/i64/f64/Bmax).
The reference computes these decodes in exact big-rational arithmetic
(reference: rust/xaynet-core/src/mask/masking.rs:190-231); here the
cancellation step is exact multi-limb integer arithmetic and the final
rounding is double-double, verified against the Fraction oracle on every
family, with both the native C++ kernel and the numpy fallback.
"""

import math
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.core.mask.config import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.core.mask.encode import decode_vect_any, decode_vect_exact
from xaynet_tpu.ops import limbs as limb_ops

CASES = [
    MaskConfig(GroupType.INTEGER, DataType.I32, BoundType.B0, ModelType.M3),
    MaskConfig(GroupType.INTEGER, DataType.I64, BoundType.B0, ModelType.M3),
    MaskConfig(GroupType.PRIME, DataType.F64, BoundType.B6, ModelType.M6),
    MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.BMAX, ModelType.M3),
    MaskConfig(GroupType.POWER2, DataType.F64, BoundType.BMAX, ModelType.M9),
    MaskConfig(GroupType.PRIME, DataType.I32, BoundType.B2, ModelType.M12),
]


def _check(cfg: MaskConfig, force_numpy: bool, monkeypatch):
    if force_numpy:
        monkeypatch.setenv("XAYNET_TPU_NO_NATIVE", "1")
        import xaynet_tpu.utils.native as nat

        monkeypatch.setattr(nat, "_tried", False)
        monkeypatch.setattr(nat, "_lib", None)

    rng = np.random.default_rng(7)
    order = cfg.order
    L = limb_ops.n_limbs_for_order(order)
    nb, ssum = 3, Fraction(3, 7)
    c = nb * int(cfg.add_shift) * cfg.exp_shift
    # realistic unmasked values: near C (small decoded weights), plus extremes
    vals = [min(order - 1, max(0, c + int(d))) for d in rng.integers(-(10**12), 10**12, 64)]
    vals += [0, order - 1, min(order - 1, c)]
    limbs = limb_ops.ints_to_limbs(vals, L)

    want = decode_vect_exact(vals, cfg, nb, ssum)
    got = decode_vect_any(limbs, cfg, nb, ssum)

    for g, w in zip(got, want):
        g = float(g)
        if math.isinf(g):
            # decoded magnitude exceeds float64 range (Bmax extremes): the
            # oracle must agree it's out of range
            assert abs(w) > Fraction(2) ** 1024
            continue
        err = abs(Fraction(g) - w)
        # ~2^-95 relative from the top-96-bit rounding, plus the float64
        # output rounding itself (2^-53 relative, or denormal absolute ulp)
        tol = max(abs(w) * Fraction(1, 2**50), Fraction(1, 2**1070))
        assert err <= tol, (cfg, float(w), g, float(err))


@pytest.mark.parametrize("cfg", CASES, ids=lambda c: f"{c.group_type.name}-{c.data_type.name}-{c.bound_type.name}")
def test_decode_native(cfg, monkeypatch):
    _check(cfg, force_numpy=False, monkeypatch=monkeypatch)


@pytest.mark.parametrize("cfg", CASES, ids=lambda c: f"{c.group_type.name}-{c.data_type.name}-{c.bound_type.name}")
def test_decode_numpy_fallback(cfg, monkeypatch):
    _check(cfg, force_numpy=True, monkeypatch=monkeypatch)


def test_unmask_array_uses_vectorized_exact_path():
    """Full unmask on an i64 config (no fast path) stays within tolerance."""
    from xaynet_tpu.core.mask import Aggregation, Masker, MaskSeed, Scalar
    from xaynet_tpu.core.mask.model import Model

    # B2 bounds clamp weights to [-100, 100]; keep test values inside
    cfg = MaskConfig(GroupType.INTEGER, DataType.I64, BoundType.B2, ModelType.M3)
    pair = cfg.pair()
    values = [-3, 0, 1, 2, 5, -1]
    model = Model([Fraction(v) for v in values])
    masker = Masker(pair, MaskSeed(b"\x17" * 32))
    seed, masked = masker.mask(Scalar.unit(), model)
    agg = Aggregation.from_object(masked)
    mask = seed.derive_mask(len(values), pair)
    out = agg.unmask_array(mask)
    assert np.allclose(out, values, atol=2.0 / cfg.exp_shift)
