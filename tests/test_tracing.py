"""Distributed round tracing + flight recorder (docs/DESIGN.md §16).

Covers the span layer's contracts (closed name registry, context
propagation, bounded buffers, header round-trip, Chrome-trace export
validity via the SAME validator CI runs), the flight recorder (trigger
dump with ring + metric deltas, rate limiting), the SDK retry-as-child-
spans shape, and the acceptance-criterion forensics: an injected shard
fold poison produces a flight dump whose ring contains the poisoning
batch's per-shard spans.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

pytest.importorskip("jax")

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools import trace_report  # noqa: E402
from xaynet_tpu.telemetry import recorder as recorder_mod, tracing  # noqa: E402
from xaynet_tpu.telemetry.registry import get_registry  # noqa: E402

# test-only span names, declared once at module import (the registry is
# process-wide, so tests reuse these instead of re-declaring per test)
S_A = tracing.declare_span("test.alpha")
S_B = tracing.declare_span("test.beta")
S_RETRO = tracing.declare_span("test.retro")


@pytest.fixture
def tracer():
    """A fresh, isolated tracer (the process singleton stays untouched)."""
    return tracing.Tracer(mode="on", ring_size=64, round_cap=128, trace_dir="")


# --- registry discipline ----------------------------------------------------


def test_declare_span_duplicate_raises():
    with pytest.raises(tracing.SpanNameError, match="already declared"):
        # S_A belongs to THIS module; fake a different declaring module
        exec(
            "from xaynet_tpu.telemetry import tracing\n"
            "tracing.declare_span('test.alpha')",
            {"__name__": "other.module"},
        )


def test_span_requires_declared_name(tracer):
    with pytest.raises(tracing.SpanNameError, match="never declared"):
        tracer.span("test.never_declared_name")
    with pytest.raises(tracing.SpanNameError, match="never declared"):
        tracer.record_span("test.never_declared_name", time.monotonic(), 0.0)


# --- context propagation ----------------------------------------------------


def test_span_nesting_and_ambient_context(tracer):
    tracer.begin_round(7, tracing.round_trace_id(b"s" * 32))
    root_ctx = tracer.round_ctx()
    with tracer.span(S_A) as outer:
        assert tracing.current_ctx().span_id == outer.ctx.span_id
        with tracer.span(S_B) as inner:
            assert inner.ctx.trace_id == root_ctx.trace_id
        # ambient context restored after the inner span exits
        assert tracing.current_ctx().span_id == outer.ctx.span_id
    assert tracing.current_ctx() is None
    spans = {s.name: s for s in tracer.end_round()}
    assert spans["test.beta"].parent_id == spans["test.alpha"].span_id
    assert spans["test.alpha"].parent_id == spans["round"].span_id
    assert (
        spans["test.alpha"].trace_id
        == spans["round"].trace_id
        == tracing.round_trace_id(b"s" * 32)
    )


def test_span_exit_records_error_on_exception(tracer):
    with pytest.raises(ValueError):
        with tracer.span(S_A):
            raise ValueError("boom")
    (span,) = [s for s in tracer.ring_spans() if s.name == "test.alpha"]
    assert "ValueError: boom" in span.error


def test_link_adopts_trace_without_parent(tracer):
    remote = tracing.TraceContext("ab" * 8, "cd" * 8)
    with tracer.span(S_A, link=remote):
        pass
    (span,) = [s for s in tracer.ring_spans() if s.name == "test.alpha"]
    assert span.trace_id == remote.trace_id
    assert span.parent_id is None
    assert span.attrs["link"] == remote.span_id


def test_trace_only_context_has_no_parent(tracer):
    with tracer.span(S_A, ctx=tracing.TraceContext("12" * 8)):
        pass
    (span,) = [s for s in tracer.ring_spans() if s.name == "test.alpha"]
    assert span.trace_id == "12" * 8 and span.parent_id is None


def test_record_span_retroactive(tracer):
    t0 = time.monotonic() - 0.5
    tracer.record_span(S_RETRO, start=t0, duration=0.5, shard=3)
    (span,) = [s for s in tracer.ring_spans() if s.name == "test.retro"]
    assert span.duration == pytest.approx(0.5)
    assert span.attrs["shard"] == 3


# --- header / wire ----------------------------------------------------------


def test_header_roundtrip_and_garbage_rejected():
    ctx = tracing.TraceContext(tracing.new_id(), tracing.new_id())
    parsed = tracing.parse_header(tracing.format_header(ctx))
    assert (parsed.trace_id, parsed.span_id) == (ctx.trace_id, ctx.span_id)
    for bad in ("", "zz", "deadbeef-cafe", "x" * 33, "g" * 16 + "-" + "a" * 16, None):
        assert tracing.parse_header(bad) is None


def test_round_trace_id_deterministic():
    seed = b"q" * 32
    assert tracing.round_trace_id(seed) == tracing.round_trace_id(seed)
    assert tracing.round_trace_id(seed) != tracing.round_trace_id(b"r" * 32)
    assert len(tracing.round_trace_id(seed)) == 16


# --- buffers / modes --------------------------------------------------------


def test_ring_and_round_buffer_bounds():
    tracer = tracing.Tracer(mode="on", ring_size=8, round_cap=4)
    tracer.begin_round(1, tracing.new_id())
    for _ in range(20):
        with tracer.span(S_A):
            pass
    assert len(tracer.ring_spans()) == 8  # ring keeps the most recent
    spans = tracer.end_round()
    # cap + the round root (the root always lands)
    assert len(spans) == 4 + 1


def test_off_mode_is_noop(tracer):
    tracer.configure(mode="off")
    with tracer.span(S_A) as span:
        assert span.ctx is None  # the null span
        span.set(anything=1)
    tracer.record_span(S_A, time.monotonic(), 0.1)
    assert tracer.ring_spans() == []


def test_failure_mode_keeps_ring_skips_export(tmp_path):
    tracer = tracing.Tracer(mode="failure", trace_dir=str(tmp_path))
    tracer.begin_round(3, tracing.new_id())
    with tracer.span(S_A):
        pass
    tracer.end_round()
    assert [s.name for s in tracer.ring_spans()].count("test.alpha") == 1
    assert list(tmp_path.glob("*.trace.json")) == []


# --- chrome export + validator ---------------------------------------------


def _one_round(tracer):
    import importlib

    importlib.import_module("xaynet_tpu.server.phases.base")  # declares phase.* spans

    tracer.begin_round(5, tracing.round_trace_id(b"z" * 32))
    for phase in ("sum", "update", "sum2", "unmask"):
        with tracer.span(f"phase.{phase}"):
            with tracer.span(S_B, phase=phase):
                pass
    return tracer.end_round()


def test_chrome_export_passes_ci_validator(tmp_path, tracer):
    tracer.configure(trace_dir=str(tmp_path))
    _one_round(tracer)
    # filename carries the pid so co-located processes exporting the same
    # round id (coordinator + edges sharing an env-inherited dir) never
    # clobber each other
    (path,) = list(tmp_path.glob("round_5.*.trace.json"))
    events = trace_report.load_events(str(path))
    assert trace_report.validate(events) == []
    # subsystem process metadata present for the viewer
    doc = json.loads(path.read_text())
    meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    assert {e["args"]["name"] for e in meta} >= {"phase", "round", "test"}


def test_validator_flags_orphans_and_coverage(tracer):
    spans = _one_round(tracer)
    events = tracing.to_chrome_trace(spans)["traceEvents"]
    events = [e for e in events if e.get("ph") == "X"]
    # break a parent link
    victim = next(e for e in events if e["name"] == "test.beta")
    victim["args"]["parent"] = "f" * 16
    problems = trace_report.validate(events)
    assert any("orphan parent" in p for p in problems)
    # drop a required phase
    events = [e for e in events if e["name"] != "phase.sum2"]
    problems = trace_report.validate(events)
    assert any("no phase.sum2" in p for p in problems)


def test_report_cross_check_tolerates_and_flags(tracer):
    spans = _one_round(tracer)
    events = [e for e in tracing.to_chrome_trace(spans)["traceEvents"] if e["ph"] == "X"]
    walls = trace_report.phase_walls(events)
    ok_report = {"phase_durations": {k: v for k, v in walls.items()}}
    assert trace_report.cross_check(events, ok_report) == []
    bad_report = {"phase_durations": {"update": walls.get("update", 0.0) + 30.0}}
    assert trace_report.cross_check(events, bad_report)


# --- flight recorder --------------------------------------------------------


def test_flight_dump_contains_ring_and_metric_deltas(tmp_path, monkeypatch):
    monkeypatch.setattr(recorder_mod, "_recorder", None)
    monkeypatch.setenv("XAYNET_FLIGHT_DIR", str(tmp_path))
    rec = recorder_mod.get_recorder()
    tracer = tracing.get_tracer()
    tracer.begin_round(11, tracing.new_id())
    counter = get_registry().counter("xaynet_test_flight_moves_total", "test")
    counter.inc(3)
    with tracer.span(S_A, batch=42):
        pass
    path = rec.dump("pipeline-poison", "batch 42 lost", batch=42)
    assert path is not None and Path(path).exists()
    bundle = json.loads(Path(path).read_text())
    assert bundle["trigger"] == "pipeline-poison"
    assert bundle["round_id"] == 11
    assert any(
        s["name"] == "test.alpha" and s.get("attrs", {}).get("batch") == 42
        for s in bundle["ring"]
    )
    delta = bundle["metrics_delta"]["xaynet_test_flight_moves_total"]
    assert delta["now"] - delta["before"] == 3
    # rate limit: an immediate second dump for the same trigger is dropped
    assert rec.dump("pipeline-poison", "again") is None
    tracer.end_round()


def test_flight_dump_never_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(recorder_mod, "_recorder", None)
    monkeypatch.setenv("XAYNET_FLIGHT_DIR", "/proc/definitely/not/writable")
    assert recorder_mod.flight_dump("degraded-close", "nope") is None


# --- SDK: retries become child spans ---------------------------------------


def test_sdk_retries_are_child_spans(monkeypatch):
    import asyncio

    from xaynet_tpu.resilience.policy import RetryPolicy
    from xaynet_tpu.sdk.client import ClientTransientError, ResilientClient

    class Flaky:
        def __init__(self):
            self.calls = 0

        async def send_message(self, blob):
            self.calls += 1
            if self.calls < 3:
                raise ClientTransientError("flap")

    tracer = tracing.Tracer(mode="on", ring_size=64)
    monkeypatch.setattr(tracing, "_tracer", tracer)
    client = ResilientClient(
        Flaky(), policy=RetryPolicy(max_attempts=5, base_delay_s=0.001, max_delay_s=0.002)
    )
    client.set_round_trace(b"w" * 32)
    asyncio.run(client.send_message(b"payload"))
    spans = tracer.ring_spans()
    send = [s for s in spans if s.name == "sdk.send"]
    attempts = [s for s in spans if s.name == "sdk.attempt"]
    assert len(send) == 1 and send[0].attrs["attempts"] == 3
    assert len(attempts) == 3
    trace_id = tracing.round_trace_id(b"w" * 32)
    assert send[0].trace_id == trace_id
    assert all(a.parent_id == send[0].span_id and a.trace_id == trace_id for a in attempts)
    # the two failed attempts carry their errors; the third is clean
    assert [bool(a.error) for a in sorted(attempts, key=lambda a: a.start)] == [
        True,
        True,
        False,
    ]


# --- acceptance: injected fold poison -> flight dump with shard spans -------


def test_streaming_poison_flight_dump_has_poisoning_batch_shard_spans(
    tmp_path, monkeypatch
):
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    from xaynet_tpu.core.mask import (
        BoundType, DataType, GroupType, Masker, MaskConfig, ModelType, Scalar,
    )
    from xaynet_tpu.parallel.aggregator import ShardedAggregator
    from xaynet_tpu.parallel.mesh import make_mesh
    from xaynet_tpu.parallel.shards import ShardPlan
    from xaynet_tpu.parallel.streaming import StreamingAggregator, StreamingError

    monkeypatch.setattr(recorder_mod, "_recorder", None)
    monkeypatch.setenv("XAYNET_FLIGHT_DIR", str(tmp_path))
    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    n, bs = 48, 3
    rng = np.random.default_rng(17)
    stacks = []
    for _ in range(6):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(cfg.pair()).mask(Scalar(1, 6), w)
        stacks.append(masked.vect.data)

    agg = ShardedAggregator(cfg, n, mesh=make_mesh(jax.devices()[:8]), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    real_fold = ShardPlan.fold_shard
    real_fold_packed = ShardPlan.fold_shard_packed

    def always_broken(self, d, batch):
        if d == 5:
            raise RuntimeError("shard 5 is on fire")
        return real_fold(self, d, batch)

    def always_broken_packed(self, d, batch):
        if d == 5:
            raise RuntimeError("shard 5 is on fire")
        return real_fold_packed(self, d, batch)

    try:
        ShardPlan.fold_shard = always_broken
        ShardPlan.fold_shard_packed = always_broken_packed
        stream.submit_batch(np.stack(stacks[0:3]))
        with pytest.raises(StreamingError, match="poisoned"):
            stream.drain()
    finally:
        ShardPlan.fold_shard = real_fold
        ShardPlan.fold_shard_packed = real_fold_packed
        stream.close()

    dumps = sorted(tmp_path.glob("flight_*_pipeline-poison.json"))
    assert dumps, "poisoning must write a flight-recorder bundle"
    bundle = json.loads(dumps[-1].read_text())
    assert "batch 1" in bundle["detail"]
    shard_folds = [
        s
        for s in bundle["ring"]
        if s["name"] == "stream.fold"
        and s.get("attrs", {}).get("batch") == 1
        and "shard" in s.get("attrs", {})
    ]
    # the poisoning batch's per-shard fold spans are IN the ring, the
    # failing shard's span carrying the root cause
    assert {s["attrs"]["shard"] for s in shard_folds} == set(range(8))
    assert any(
        s["attrs"]["shard"] == 5 and s["attrs"].get("outcome") == "failed"
        for s in shard_folds
    )


# --- satellite: mask-kernel calibration verdicts in the round report --------


def test_mask_calibration_verdicts_land_in_round_report(tmp_path):
    from xaynet_tpu.telemetry.report import (
        RoundReporter,
        drain_mask_calibrations,
        record_mask_calibration,
    )

    drain_mask_calibrations()  # isolate from whatever ran before
    rep = RoundReporter(str(tmp_path / "r.jsonl"))
    rep.begin_round(2)
    record_mask_calibration(
        {
            "winner": "host-threaded",
            "backend": "cpu",
            "length": 64,
            "bucket": 4,
            "mesh": None,
            "probe_length": 64,
            "probe_walls": {"host-threaded": 0.01, "batch": 0.05},
        }
    )
    rep.begin_round(3)  # flushes round 2's report
    line = json.loads((tmp_path / "r.jsonl").read_text().splitlines()[0])
    assert line["round_id"] == 2
    (entry,) = line["mask_calibration"]
    assert entry["winner"] == "host-threaded"
    assert entry["probe_walls"]["batch"] == 0.05
    rep.flush()
    # drained: the verdict is attributed to ONE report, not repeated
    lines = (tmp_path / "r.jsonl").read_text().splitlines()
    assert "mask_calibration" not in json.loads(lines[-1])


def test_calibrate_mask_kernel_records_auditable_verdict():
    """The real auto-calibration race records its verdict (winner +
    per-candidate probe walls) for the round report — a headline shift
    caused by a verdict flip is auditable without a re-run."""
    from xaynet_tpu.core.mask.config import (
        BoundType, DataType, GroupType, MaskConfig, ModelType,
    )
    from xaynet_tpu.ops import masking_jax
    from xaynet_tpu.telemetry.report import drain_mask_calibrations

    drain_mask_calibrations()
    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3).pair()
    seeds = [bytes([i]) * 32 for i in range(3)]
    length = 97  # unusual length: a fresh (backend, shape) cache key
    winner = masking_jax.calibrate_mask_kernel(seeds, length, cfg, seed_batch=3)
    entries = [e for e in drain_mask_calibrations() if e["length"] == length]
    assert entries, "a fresh calibration must record its verdict"
    entry = entries[-1]
    assert entry["winner"] == winner
    assert entry["backend"] == masking_jax.jax.default_backend()
    assert winner in entry["probe_walls"] or entry["winner"] == "host-chunked"
    # memoized second resolution records nothing new
    assert masking_jax.calibrate_mask_kernel(seeds, length, cfg, seed_batch=3) == winner
    assert [e for e in drain_mask_calibrations() if e["length"] == length] == []
