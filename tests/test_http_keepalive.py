"""SDK transport keep-alive: one connection per host, not per request.

The REST stack used to re-handshake per request (ROADMAP item 5's
transport tax); ``HttpClient`` now pools its connection and reuses it
across requests, with ``keep_alive=False`` restoring the historical
one-shot behavior and a one-retry fallback when a pooled connection turns
out to be stale (the server idled it out between requests).
"""

from __future__ import annotations

import asyncio

import pytest

from xaynet_tpu.sdk.client import ClientTransientError, HttpClient


class _MiniServer:
    """Counts TCP connections; answers every request 200 with a tiny body."""

    def __init__(self, close_after_each: bool = False, advertise_close: bool = False):
        self.connections = 0
        self.requests = 0
        self.close_after_each = close_after_each
        self.advertise_close = advertise_close

    async def __aenter__(self):
        self._server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def __aexit__(self, *exc):
        self._server.close()
        await self._server.wait_closed()

    async def _handle(self, reader, writer):
        self.connections += 1
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                length = 0
                while True:
                    header = await reader.readline()
                    if header in (b"\r\n", b""):
                        break
                    name, _, value = header.decode().partition(":")
                    if name.strip().lower() == "content-length":
                        length = int(value.strip())
                if length:
                    await reader.readexactly(length)
                self.requests += 1
                connection = "close" if self.advertise_close else "keep-alive"
                writer.write(
                    b"HTTP/1.1 200 OK\r\nContent-Type: text/plain\r\n"
                    b"Content-Length: 2\r\n"
                    + f"Connection: {connection}\r\n\r\n".encode()
                    + b"ok"
                )
                await writer.drain()
                if self.close_after_each or self.advertise_close:
                    return
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass


def test_keep_alive_reuses_one_connection():
    async def run():
        async with _MiniServer() as srv:
            client = HttpClient(f"http://127.0.0.1:{srv.port}")
            try:
                for _ in range(5):
                    status, _, body = await client._request("GET", "/params")
                    assert status == 200 and body == b"ok"
            finally:
                client.close()
            assert srv.requests == 5
            assert srv.connections == 1, "keep-alive must reuse the connection"
            assert client.connections_opened == 1

    asyncio.run(run())


def test_keep_alive_opt_out_reconnects_per_request():
    async def run():
        async with _MiniServer() as srv:
            client = HttpClient(f"http://127.0.0.1:{srv.port}", keep_alive=False)
            for _ in range(3):
                status, _, _ = await client._request("GET", "/params")
                assert status == 200
            assert srv.requests == 3
            assert srv.connections == 3, "opt-out must re-handshake per request"

    asyncio.run(run())


def test_server_advertised_close_is_respected():
    """A response carrying ``Connection: close`` must not be pooled."""

    async def run():
        async with _MiniServer(advertise_close=True) as srv:
            client = HttpClient(f"http://127.0.0.1:{srv.port}")
            try:
                for _ in range(3):
                    status, _, _ = await client._request("GET", "/params")
                    assert status == 200
            finally:
                client.close()
            assert srv.connections == 3

    asyncio.run(run())


def test_stale_pooled_connection_retried_once():
    """The server silently drops the connection after each response (an
    idle timeout): the next request on the pooled stream fails mid-flight
    and must transparently retry on a fresh connection."""

    async def run():
        async with _MiniServer(close_after_each=True) as srv:
            client = HttpClient(f"http://127.0.0.1:{srv.port}")
            try:
                for _ in range(4):
                    status, _, body = await client._request("GET", "/params")
                    assert status == 200 and body == b"ok"
            finally:
                client.close()
            assert srv.requests == 4

    asyncio.run(run())


class _PartialResponseServer:
    """Answers the first request normally (keep-alive), then kills the
    connection mid-status-line on the second — after response bytes began."""

    def __init__(self, partial: bytes = b"HTT"):
        self.requests = 0
        self.connections = 0
        self.partial = partial

    async def __aenter__(self):
        self._server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def __aexit__(self, *exc):
        self._server.close()
        await self._server.wait_closed()

    async def _handle(self, reader, writer):
        self.connections += 1
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                while True:
                    header = await reader.readline()
                    if header in (b"\r\n", b""):
                        break
                self.requests += 1
                if self.requests == 1:
                    writer.write(
                        b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n"
                        b"Connection: keep-alive\r\n\r\nok"
                    )
                    await writer.drain()
                else:
                    writer.write(self.partial)  # torn response, then die
                    await writer.drain()
                    return
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass


def test_no_silent_resend_after_response_bytes_began():
    """A reused connection that dies AFTER yielding response bytes means
    the server processed the request — a silent re-send could duplicate a
    non-idempotent POST, so the error must surface instead of retrying."""

    async def run():
        async with _PartialResponseServer() as srv:
            client = HttpClient(f"http://127.0.0.1:{srv.port}")
            try:
                status, _, _ = await client._request("GET", "/params")
                assert status == 200
                with pytest.raises(ClientTransientError):
                    await client._request("POST", "/message", b"payload")
            finally:
                client.close()
            # exactly the two requests the caller made: no hidden third
            assert srv.requests == 2
            assert srv.connections == 1

    asyncio.run(run())


def test_no_silent_resend_on_timeout():
    """A timeout on a reused connection is NOT the stale-keep-alive race —
    the peer may be processing — so the client must not re-send."""

    class _StallServer(_PartialResponseServer):
        async def _handle(self, reader, writer):
            self.connections += 1
            try:
                while True:
                    line = await reader.readline()
                    if not line:
                        return
                    while True:
                        header = await reader.readline()
                        if header in (b"\r\n", b""):
                            break
                    self.requests += 1
                    if self.requests == 1:
                        writer.write(
                            b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n"
                            b"Connection: keep-alive\r\n\r\nok"
                        )
                        await writer.drain()
                    else:
                        await asyncio.sleep(30)  # stall past the timeout
                        return
            finally:
                writer.close()

    async def run():
        async with _StallServer() as srv:
            client = HttpClient(f"http://127.0.0.1:{srv.port}", timeout=0.3)
            try:
                status, _, _ = await client._request("GET", "/params")
                assert status == 200
                with pytest.raises(ClientTransientError):
                    await client._request("POST", "/message", b"payload")
            finally:
                client.close()
            assert srv.requests == 2, "timeout must not trigger a re-send"

    asyncio.run(run())


def test_connect_failure_is_transient():
    async def run():
        client = HttpClient("http://127.0.0.1:1")  # nothing listens there
        with pytest.raises(ClientTransientError):
            await client._request("GET", "/params")

    asyncio.run(run())
