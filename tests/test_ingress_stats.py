"""Ingress observability + binary seed fan-out codec unit tests.

The /healthz + /statusz ``ingress`` section (accepted/shed rates, shard
occupancy, accepted wire-format mix) is fed by ``IngestPipeline`` hooks
and ``RateWindow`` buckets; the batched seed fan-out rides the
``pack_seed_entries``/``unpack_seed_entries`` frame. Both must be exact:
operators alert on these numbers and the seed frame carries key material.
"""

from types import SimpleNamespace

from xaynet_tpu.core.crypto.encrypt import EncryptKeyPair
from xaynet_tpu.core.mask.seed import (
    SEED_ENTRY_LENGTH,
    MaskSeed,
    pack_seed_entries,
    unpack_seed_entries,
)
from xaynet_tpu.ingest.pipeline import IngestPipeline, RateWindow
from xaynet_tpu.server.settings import IngestSettings
from xaynet_tpu.server.events import PhaseName

import pytest


# --- RateWindow ---------------------------------------------------------------


def test_rate_window_averages_over_window():
    w = RateWindow(window_s=10)
    for t in range(5):
        w.add(20, now=t)
    assert w.rate(now=4) == pytest.approx(10.0)  # 100 events / 10 s window


def test_rate_window_decays_to_zero():
    w = RateWindow(window_s=5)
    w.add(50, now=100)
    assert w.rate(now=100) == pytest.approx(10.0)
    assert w.rate(now=104) == pytest.approx(10.0)
    assert w.rate(now=106) == 0.0  # bucket aged out of the window


def test_rate_window_same_second_coalesces():
    w = RateWindow(window_s=10)
    for _ in range(7):
        w.add(now=42)
    assert len(w._buckets) == 1
    assert w.rate(now=42) == pytest.approx(0.7)


def test_rate_window_validates_window():
    with pytest.raises(ValueError):
        RateWindow(window_s=0)


# --- ingress_stats / health wiring -------------------------------------------


def _pipeline() -> IngestPipeline:
    latest = SimpleNamespace(event=PhaseName.UPDATE)
    events = SimpleNamespace(phase=SimpleNamespace(get_latest=lambda: latest))
    return IngestPipeline(
        handler=None,
        request_tx=None,
        events=events,
        settings=IngestSettings(enabled=True, shards=2, queue_bound=4),
    )


def test_ingress_stats_counts_wire_mix():
    pipe = _pipeline()
    update_packed = SimpleNamespace(payload=SimpleNamespace(wire_planar=True))
    update_legacy = SimpleNamespace(payload=SimpleNamespace(wire_planar=False))
    sum_msg = SimpleNamespace(payload=SimpleNamespace())  # no wire_planar attr
    for _ in range(3):
        pipe._count_accepted(update_packed)
    pipe._count_accepted(update_legacy)
    pipe._count_accepted(sum_msg)

    stats = pipe.ingress_stats()
    assert stats["accepted_total"] == 5
    assert stats["wire"] == {"packed": 3, "legacy": 1}
    assert stats["accepted_per_s"] > 0
    assert stats["shed_total"] == 0
    assert len(stats["shard_occupancy"]) == 2


def test_health_carries_ingress_section():
    pipe = _pipeline()
    pipe._count_accepted(SimpleNamespace(payload=SimpleNamespace(wire_planar=True)))
    health = pipe.health()
    assert health["ingress"]["accepted_total"] == 1
    assert health["ingress"]["wire"]["packed"] == 1
    # saturation snapshot keys the SLO console reads stay present
    for key in ("saturated", "occupancy", "capacity", "shards"):
        assert key in health


# --- binary seed fan-out frame ------------------------------------------------


def _seed_dict(n: int):
    out = {}
    for i in range(n):
        pk = bytes([i]) * 32
        out[pk] = MaskSeed.generate().encrypt(EncryptKeyPair.generate().public)
    return out


def test_seed_entries_round_trip_and_determinism():
    d = _seed_dict(5)
    body = pack_seed_entries(d)
    assert len(body) == 4 + 5 * SEED_ENTRY_LENGTH
    # deterministic: insertion order must not leak into the frame
    shuffled = dict(reversed(list(d.items())))
    assert pack_seed_entries(shuffled) == body
    back = unpack_seed_entries(body)
    assert back.keys() == d.keys()
    for pk in d:
        assert back[pk].as_bytes() == d[pk].as_bytes()


def test_seed_entries_zero_copy_view_accepted():
    body = pack_seed_entries(_seed_dict(2))
    assert unpack_seed_entries(memoryview(body)).keys() == unpack_seed_entries(body).keys()


def test_seed_entries_reject_malformed_frames():
    body = pack_seed_entries(_seed_dict(3))
    with pytest.raises(ValueError):
        unpack_seed_entries(body[:-1])  # truncated entry
    with pytest.raises(ValueError):
        unpack_seed_entries(body + b"\x00")  # trailing garbage
    with pytest.raises(ValueError):
        unpack_seed_entries(b"\x00\x00")  # shorter than the count frame
    # count lies about the body length
    lied = (99).to_bytes(4, "big") + body[4:]
    with pytest.raises(ValueError):
        unpack_seed_entries(lied)
    with pytest.raises(ValueError):
        pack_seed_entries({b"\x01" * 31: next(iter(_seed_dict(1).values()))})
