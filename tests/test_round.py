"""End-to-end PET round: coordinator + N in-process participants.

The reference proves the whole protocol is testable in-process by injecting
messages straight into the request channel (SURVEY §4.3). Here we go one
layer further out: participants run the real SDK state machine, messages go
through the full service pipeline (sealed box, signature, task validation),
and the coordinator runs the real phase state machine — only the network is
replaced by direct calls.
"""

import asyncio
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.sdk.client import InProcessClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
from xaynet_tpu.sdk.traits import ModelStore
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store

N_SUM = 2
N_UPDATE = 3
MODEL_LEN = 13
SUM_PROB = 0.4
UPDATE_PROB = 0.5


class ArrayModelStore(ModelStore):
    def __init__(self, model):
        self.model = model

    async def load_model(self):
        return self.model


def _settings() -> Settings:
    s = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=SUM_PROB,
                count=CountSettings(min=N_SUM, max=N_SUM),
                time=TimeSettings(min=0.0, max=20.0),
            ),
            update=PhaseSettings(
                prob=UPDATE_PROB,
                count=CountSettings(min=N_UPDATE, max=N_UPDATE),
                time=TimeSettings(min=0.0, max=20.0),
            ),
            sum2=Sum2Settings(
                count=CountSettings(min=N_SUM, max=N_SUM),
                time=TimeSettings(min=0.0, max=20.0),
            ),
        )
    )
    s.model.length = MODEL_LEN
    return s


async def _run_round(
    settings: Settings,
    n_rounds: int = 1,
    sum_pet_kwargs: dict | None = None,
    raise_in_drive: bool = False,
):
    store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
    init = StateMachineInitializer(settings, store)
    machine, request_tx, events = await init.init()
    handler = PetMessageHandler(events, request_tx)
    fetcher = Fetcher(events)

    machine_task = asyncio.create_task(machine.run())

    models = {}
    try:
        for round_no in range(n_rounds):
            # wait for the sum phase of the current round so the published
            # round seed is final
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            params = fetcher.round_params()
            seed = params.seed.as_bytes()

            model_len = settings.model.length
            rng = np.random.default_rng(42 + round_no)
            participants = []
            expected = np.zeros(model_len)
            for i in range(N_SUM):
                keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
                sm = ParticipantSM(
                    PetSettings(keys=keys, **(sum_pet_kwargs or {})),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(None),
                )
                participants.append(sm)
            for i in range(N_UPDATE):
                keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(10 + i) * 1000)
                local = rng.uniform(-1, 1, model_len).astype(np.float32)
                expected += local.astype(np.float64) / N_UPDATE
                sm = ParticipantSM(
                    PetSettings(keys=keys, scalar=Fraction(1, N_UPDATE)),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(local),
                )
                participants.append(sm)

            async def drive(sm):
                for _ in range(500):
                    try:
                        await sm.transition()
                    except Exception:
                        if raise_in_drive:
                            raise
                    if fetcher.model() is not None and sm.phase.value == "awaiting":
                        return
                    await asyncio.sleep(0.01)

            await asyncio.gather(*(drive(p) for p in participants))

            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            models[round_no] = (np.asarray(fetcher.model()), expected)

            # let the machine move into the next round's sum phase
            if round_no + 1 < n_rounds:
                while fetcher.round_params().seed.as_bytes() == seed:
                    await asyncio.sleep(0.01)
    finally:
        machine_task.cancel()
        try:
            await machine_task
        except (asyncio.CancelledError, Exception):
            pass
    return models


def test_full_pet_round():
    models = asyncio.run(asyncio.wait_for(_run_round(_settings()), timeout=60))
    got, expected = models[0]
    assert got.shape == (MODEL_LEN,)
    np.testing.assert_allclose(got, expected, atol=1e-9)


@pytest.mark.parametrize("kernel", ["auto", "pallas-interpret"])
def test_round_with_chunked_updates_and_device_aggregation(kernel, monkeypatch):
    """Multipart update messages + TPU-mesh aggregation, end to end.

    ``auto`` resolves to the XLA fold on the CPU backend; the
    ``pallas-interpret`` leg drives the whole round through the Pallas
    grid/BlockSpec path (via shard_map on the 8-device mesh) so the fused
    kernel is continuously exercised, with a spy proving it folded.
    """
    import xaynet_tpu.ops.fold_pallas as fold_pallas
    import xaynet_tpu.parallel.aggregator as agg_mod

    pallas_calls = []
    if kernel == "pallas-interpret":
        # the process-wide fold-fn cache only re-reads the (spied) module
        # attribute on a retrace; start from a clean cache so the spy is
        # guaranteed to observe the fold
        agg_mod._FOLD_FN_CACHE.clear()
        real = fold_pallas.fold_planar_batch_pallas

        def spy(acc, stack, order, interpret=False, tile_size=None):
            pallas_calls.append(interpret)
            return real(acc, stack, order, interpret=interpret, tile_size=tile_size)

        monkeypatch.setattr(fold_pallas, "fold_planar_batch_pallas", spy)

    async def run():
        settings = _settings()
        settings.model.length = 600  # update payload >> max_message_size
        settings.aggregation.device = True
        settings.aggregation.batch_size = 2
        settings.aggregation.kernel = kernel
        store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        machine_task = asyncio.create_task(machine.run())
        try:
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            params = fetcher.round_params()
            seed = params.seed.as_bytes()
            rng = np.random.default_rng(3)
            expected = np.zeros(600)
            participants = []
            for i in range(N_SUM):
                keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
                sm = ParticipantSM(
                    PetSettings(keys=keys, max_message_size=1024),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(None),
                )
                participants.append(sm)
            for i in range(N_UPDATE):
                keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(10 + i) * 1000)
                local = rng.uniform(-1, 1, 600).astype(np.float32)
                expected += local.astype(np.float64) / N_UPDATE
                sm = ParticipantSM(
                    PetSettings(
                        keys=keys, scalar=Fraction(1, N_UPDATE), max_message_size=1024
                    ),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(local),
                )
                participants.append(sm)

            async def drive(sm):
                for _ in range(500):
                    try:
                        await sm.transition()
                    except Exception:
                        pass
                    if fetcher.model() is not None and sm.phase.value == "awaiting":
                        return
                    await asyncio.sleep(0.01)

            await asyncio.gather(*(drive(p) for p in participants))
            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            return np.asarray(fetcher.model()), expected
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    got, expected = asyncio.run(asyncio.wait_for(run(), timeout=180))
    np.testing.assert_allclose(got, expected, atol=1e-9)
    if kernel == "pallas-interpret":
        assert pallas_calls and all(pallas_calls), "round did not fold through the Pallas kernel"


def test_round_with_wire_ingest(monkeypatch):
    """Full round with ``aggregation.wire_ingest = true``: Update masked
    models parse LAZILY (raw element block kept through the multipart
    stream parse), element unpack + validity run on the device BEFORE the
    seed-dict insert, and the fold consumes device-resident planars — the
    coordinator never executes the host element parse. A spy proves every
    accepted update went through the device validation; the global model
    is still the exact mean."""
    from xaynet_tpu.parallel.aggregator import ShardedAggregator

    validated = []
    real_validate = ShardedAggregator.validate_wire_update

    def spy(self, raw):
        out = real_validate(self, raw)
        validated.append(out is not None)
        return out

    monkeypatch.setattr(ShardedAggregator, "validate_wire_update", spy)

    async def run():
        settings = _settings()
        settings.model.length = 600  # update payload >> max_message_size
        settings.aggregation.device = True
        settings.aggregation.batch_size = 2
        settings.aggregation.kernel = "xla"
        settings.aggregation.wire_ingest = True
        settings.validate()
        store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, request_tx, wire_ingest=True)
        fetcher = Fetcher(events)
        machine_task = asyncio.create_task(machine.run())
        try:
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            params = fetcher.round_params()
            seed = params.seed.as_bytes()
            rng = np.random.default_rng(11)
            expected = np.zeros(600)
            participants = []
            for i in range(N_SUM):
                keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
                participants.append(
                    ParticipantSM(
                        PetSettings(keys=keys, max_message_size=1024),
                        InProcessClient(fetcher, handler),
                        ArrayModelStore(None),
                    )
                )
            for i in range(N_UPDATE):
                keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(10 + i) * 1000)
                local = rng.uniform(-1, 1, 600).astype(np.float32)
                expected += local.astype(np.float64) / N_UPDATE
                participants.append(
                    ParticipantSM(
                        PetSettings(keys=keys, scalar=Fraction(1, N_UPDATE), max_message_size=1024),
                        InProcessClient(fetcher, handler),
                        ArrayModelStore(local),
                    )
                )

            async def drive(sm):
                for _ in range(500):
                    try:
                        await sm.transition()
                    except Exception:
                        pass
                    if fetcher.model() is not None and sm.phase.value == "awaiting":
                        return
                    await asyncio.sleep(0.01)

            await asyncio.gather(*(drive(p) for p in participants))
            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            return np.asarray(fetcher.model()), expected
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    got, expected = asyncio.run(asyncio.wait_for(run(), timeout=180))
    np.testing.assert_allclose(got, expected, atol=1e-9)
    assert len(validated) >= N_UPDATE and all(validated), (
        f"device wire validation did not run for every update: {validated}"
    )


def test_sum_participant_save_restore_mid_round():
    """A sum participant suspended after Sum resumes and completes Sum2
    (the ephemeral decryption key must survive serialization)."""

    async def run():
        settings = _settings()
        store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        machine_task = asyncio.create_task(machine.run())
        try:
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            params = fetcher.round_params()
            seed = params.seed.as_bytes()
            rng = np.random.default_rng(7)

            # one extra summer that will be suspended/resumed
            keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=50_000)
            suspended = ParticipantSM(
                PetSettings(keys=keys), InProcessClient(fetcher, handler), ArrayModelStore(None)
            )
            # drive it through NewRound + Sum (it sends its ephemeral key)
            for _ in range(10):
                await suspended.transition()
                if suspended.phase.value == "sum2":
                    break
            assert suspended.phase.value == "sum2"
            blob = suspended.save()

            participants = []
            for i in range(1, N_SUM):
                k2 = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
                participants.append(
                    ParticipantSM(PetSettings(keys=k2), InProcessClient(fetcher, handler), ArrayModelStore(None))
                )
            expected = np.zeros(MODEL_LEN)
            for i in range(N_UPDATE):
                k2 = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(10 + i) * 1000)
                local = rng.uniform(-1, 1, MODEL_LEN).astype(np.float32)
                expected += local.astype(np.float64) / N_UPDATE
                participants.append(
                    ParticipantSM(
                        PetSettings(keys=k2, scalar=Fraction(1, N_UPDATE)),
                        InProcessClient(fetcher, handler),
                        ArrayModelStore(local),
                    )
                )

            # resume the suspended summer in a "new process"
            resumed = ParticipantSM.restore(
                blob, InProcessClient(fetcher, handler), ArrayModelStore(None)
            )
            assert resumed.phase.value == "sum2"
            participants.append(resumed)

            async def drive(sm):
                for _ in range(500):
                    try:
                        await sm.transition()
                    except Exception:
                        pass
                    if fetcher.model() is not None:
                        return
                    await asyncio.sleep(0.01)

            await asyncio.gather(*(drive(p) for p in participants))
            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            np.testing.assert_allclose(np.asarray(fetcher.model()), expected, atol=1e-9)
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(asyncio.wait_for(run(), timeout=60))


@pytest.mark.slow  # ~2 min per config on the 8-device virtual CPU mesh
@pytest.mark.parametrize(
    "group_type,data_type,model_type",
    [
        ("prime", "f32", "m3"),
        ("integer", "f32", "m6"),
        ("power2", "f32", "m3"),
    ],
)
def test_round_with_device_sum2_strict(monkeypatch, group_type, data_type, model_type):
    """Full federated round with Sum2 on the JAX device path, strict,
    swept over three finite-group config families (VERDICT r03 item 8).

    The model length equals the real ``DEVICE_SUM2_THRESHOLD`` (no
    threshold fudging), ``device_sum2_strict`` turns the silent
    warn-and-fallback into a hard failure, and a spy proves the device
    kernel actually ran for every sum participant (VERDICT r02 item 6).
    """
    from xaynet_tpu.core.mask.config import DataType, GroupType, ModelType
    from xaynet_tpu.ops import masking_jax

    length = ParticipantSM.DEVICE_SUM2_THRESHOLD  # 262,144
    calls = []
    real = masking_jax.sum_masks

    def spy(seeds, n, config):
        calls.append((len(seeds), n))
        return real(seeds, n, config)

    s = _settings()
    s.model.length = length
    s.mask.group_type = GroupType[group_type.upper()]
    s.mask.data_type = DataType[data_type.upper()]
    s.mask.model_type = ModelType[model_type.upper()]
    # headroom for the first-run jit compile of the derivation kernel
    s.pet.update.time = TimeSettings(min=0.0, max=90.0)
    s.pet.sum2.time = TimeSettings(min=0.0, max=90.0)

    # warm the jit cache at the exact shapes the round will use (before the
    # spy is installed), so the in-round sum2 leg measures the protocol,
    # not XLA compilation
    cfg = s.mask.to_config()
    masking_jax.sum_masks([b"\x11" * 32], length, cfg.pair())

    monkeypatch.setattr(masking_jax, "sum_masks", spy)

    models = asyncio.run(
        asyncio.wait_for(
            _run_round(
                s,
                sum_pet_kwargs={
                    "device_sum2": True,
                    "device_sum2_strict": True,
                    "max_message_size": None,  # single-message sends
                },
                raise_in_drive=True,
            ),
            timeout=240,
        )
    )
    got, expected = models[0]
    assert got.shape == (length,)
    np.testing.assert_allclose(got, expected, atol=1e-6)
    # both sum participants took the device path over all update seeds
    assert len(calls) == N_SUM
    assert all(c == (N_UPDATE, length) for c in calls)
