"""Shard-parallel streaming fold (parallel.shards + streaming shard mode).

The property everything rests on: the shard-parallel pipeline — one fold
worker per mesh device, per-shard staging rings, donated per-shard
accumulators, drain() as the cross-shard barrier — is **byte-identical to
the sequential single-device path** across kernels (xla, native-u64, auto)
× mesh sizes (1, 2, 8) × planar/wire submit paths, including
dispatch-ahead out-of-order schedules, and its per-shard degradation
ladder (fold failure → per-shard sync retry → pipeline-wide sync mode →
sticky poison) keeps the shards consistent: a batch commits only when
every shard folded it.
"""

import time

import numpy as np
import pytest

import jax

from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    Masker,
    MaskConfig,
    ModelType,
    Scalar,
)
from xaynet_tpu.core.mask.serialization import serialize_mask_vect, vect_element_block
from xaynet_tpu.ops import limbs as host_limbs
from xaynet_tpu.parallel.aggregator import ShardedAggregator
from xaynet_tpu.parallel.mesh import make_mesh, shard_slices
from xaynet_tpu.parallel.shards import ShardPlan, shard_thread_budget
from xaynet_tpu.parallel.streaming import (
    SHARD_INFLIGHT,
    SHARD_STAGING_DEPTH,
    StreamingAggregator,
    StreamingError,
)

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)

KERNELS = ("xla", "native-u64", "auto")
MESH_SIZES = (1, 2, 8)


def _mesh(n):
    return make_mesh(jax.devices()[:n])


def _updates(n, total, seed=0):
    rng = np.random.default_rng(seed)
    host = Aggregation(CFG.pair(), n)
    stacks, raws = [], []
    for _ in range(total):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(CFG.pair()).mask(Scalar(1, total), w)
        host.aggregate(masked)
        stacks.append(masked.vect.data)
        raws.append(
            np.frombuffer(
                vect_element_block(serialize_mask_vect(masked.vect)), dtype=np.uint8
            )
        )
    return stacks, raws, host


def _sequential_oracle(n, stacks, bs):
    seq = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    for i in range(0, len(stacks), bs):
        seq.add_batch(np.stack(stacks[i : i + bs]))
    return seq


# --- the core property: kernels x mesh sizes x planar/wire ---------------


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("mesh_size", MESH_SIZES)
def test_sharded_planar_byte_identical_to_sequential(kernel, mesh_size):
    n, total, bs = 103, 13, 4  # n not divisible by 8: padding columns in play
    stacks, _, host = _updates(n, total)
    seq = _sequential_oracle(n, stacks, bs)

    agg = ShardedAggregator(CFG, n, mesh=_mesh(mesh_size), kernel=kernel)
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    assert stream._sharded == (mesh_size > 1)
    for i in range(0, total, bs):
        stream.submit_batch(np.stack(stacks[i : i + bs]))
    stream.drain()

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == seq.nb_models == total
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    stream.close()


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("mesh_size", MESH_SIZES)
def test_sharded_wire_byte_identical_with_deferred_acceptance(kernel, mesh_size):
    """Wire path: the per-shard fold must preserve the psum-consistent
    validity semantics (an update invalid anywhere is excluded everywhere),
    the acceptance vectors stay deferred until drain, and the aggregate +
    nb_models equal the sequential path."""
    n, total, bs = 57, 11, 4
    _, raws, _ = _updates(n, total, seed=3)
    bad = raws[5].copy()
    bad[: CFG.bytes_per_number] = 0xFF  # element >= order -> member rejected
    wires = raws[:5] + [bad] + raws[6:]

    seq = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    seq_oks = [
        seq.add_wire_batch(np.stack(wires[i : i + bs])) for i in range(0, total, bs)
    ]

    agg = ShardedAggregator(CFG, n, mesh=_mesh(mesh_size), kernel=kernel)
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    tickets = [
        stream.submit_wire_batch(np.stack(wires[i : i + bs]))
        for i in range(0, total, bs)
    ]
    if mesh_size > 1:
        # acceptance is deferred: no ticket resolves before the barrier
        assert all(t.accepted is None for t in tickets)
    stream.drain()

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == seq.nb_models == total - 1
    got = np.concatenate([t.accepted for t in tickets])
    assert np.array_equal(got, np.concatenate(seq_oks))
    assert not got[5]
    stream.close()


@pytest.mark.parametrize("kernel", ("xla", "native-u64"))
def test_sharded_mixed_paths_across_drain_cycles(kernel):
    """Planar and wire batches interleaved over several drain cycles: the
    plan decomposes/reassembles per cycle and the result stays pinned to
    the sequential single-device fold."""
    n, total, bs = 103, 12, 3
    stacks, raws, _ = _updates(n, total, seed=11)

    seq = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    seq.add_wire_batch(np.stack(raws[0:3]))
    seq.add_batch(np.stack(stacks[3:6]))
    seq.add_wire_batch(np.stack(raws[6:9]))
    seq.add_batch(np.stack(stacks[9:12]))

    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel=kernel)
    stream = StreamingAggregator(agg, staging_buffers=2, dispatch_ahead=2, max_batch=bs)
    stream.submit_wire_batch(np.stack(raws[0:3]))
    stream.submit_batch(np.stack(stacks[3:6]))
    stream.drain()  # cycle 1: reassemble
    stream.submit_wire_batch(np.stack(raws[6:9]))  # cycle 2: re-decompose
    stream.submit_batch(np.stack(stacks[9:12]))
    stream.drain()

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == seq.nb_models == total
    stream.close()


def test_sharded_dispatch_ahead_out_of_order_stress():
    """Producer runs several batches ahead of shard folds that complete
    late with per-shard jitter (shard progress skew): every batch must
    commit exactly once, the per-shard gauges must return to zero, and the
    aggregate must stay byte-identical."""
    n, total, bs = 64, 36, 3
    stacks, _, host = _updates(n, total, seed=7)
    seq = _sequential_oracle(n, stacks, bs)

    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=4, dispatch_ahead=3, max_batch=bs)

    rng = np.random.default_rng(0)
    jitters = {d: rng.uniform(0.0, 0.004, size=64) for d in range(8)}
    counts = {d: 0 for d in range(8)}
    real_fold = ShardPlan.fold_shard

    def slow_fold(self, d, batch):
        i = counts[d]
        counts[d] += 1
        time.sleep(float(jitters[d][i % 64]))
        return real_fold(self, d, batch)

    try:
        ShardPlan.fold_shard = slow_fold
        for i in range(0, total, bs):
            stream.submit_batch(np.stack(stacks[i : i + bs]))
        stream.drain()
    finally:
        ShardPlan.fold_shard = real_fold

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    assert agg.nb_models == total
    for d in range(8):
        assert SHARD_INFLIGHT.labels(shard=str(d)).value == 0
        assert SHARD_STAGING_DEPTH.labels(shard=str(d)).value == 0
    stream.close()


# --- degradation ladder ----------------------------------------------------


def test_shard_failure_degrades_then_completes_byte_identical():
    """One shard's fold fails once (accumulator untouched): that shard
    retries synchronously, the pipeline flips to the sync path, and the
    round completes with the exact sequential aggregate."""
    n, total, bs = 48, 12, 3
    stacks, _, _ = _updates(n, total, seed=5)
    seq = _sequential_oracle(n, stacks, bs)

    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    real_fold = ShardPlan.fold_shard
    real_fold_packed = ShardPlan.fold_shard_packed
    state = {"failed": False}

    def flaky(self, d, batch):
        if d == 3 and not state["failed"]:
            state["failed"] = True
            raise RuntimeError("transient shard fault")
        return real_fold(self, d, batch)

    def flaky_packed(self, d, batch):
        if d == 3 and not state["failed"]:
            state["failed"] = True
            raise RuntimeError("transient shard fault")
        return real_fold_packed(self, d, batch)

    try:
        ShardPlan.fold_shard = flaky
        ShardPlan.fold_shard_packed = flaky_packed
        for i in range(0, total, bs):
            stream.submit_batch(np.stack(stacks[i : i + bs]))
        stream.drain()
    finally:
        ShardPlan.fold_shard = real_fold
        ShardPlan.fold_shard_packed = real_fold_packed

    assert stream.degraded
    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == total
    stream.close()


def test_shard_failure_twice_poisons_with_batch_diagnostics():
    """The same shard failing on the retry too loses the batch: the
    pipeline poisons permanently, every later submit AND drain keeps
    raising with the poisoning batch index and root cause."""
    n, bs = 48, 3
    stacks, _, _ = _updates(n, 9, seed=6)

    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    real_fold = ShardPlan.fold_shard
    real_fold_packed = ShardPlan.fold_shard_packed

    def always_broken(self, d, batch):
        if d == 5:
            raise RuntimeError("shard 5 is on fire")
        return real_fold(self, d, batch)

    def always_broken_packed(self, d, batch):
        if d == 5:
            raise RuntimeError("shard 5 is on fire")
        return real_fold_packed(self, d, batch)

    try:
        ShardPlan.fold_shard = always_broken
        ShardPlan.fold_shard_packed = always_broken_packed
        stream.submit_batch(np.stack(stacks[0:3]))
        with pytest.raises(StreamingError, match="batch 1.*shard 5 is on fire"):
            stream.drain()
    finally:
        ShardPlan.fold_shard = real_fold
        ShardPlan.fold_shard_packed = real_fold_packed
    # sticky: healthy folds cannot resurrect a poisoned pipeline
    with pytest.raises(StreamingError, match="poisoned"):
        stream.submit_batch(np.stack(stacks[3:6]))
    with pytest.raises(StreamingError, match="batch 1"):
        stream.drain()
    assert stream.in_flight_models == 0
    stream.close()


# --- sequential multi-device native fold ----------------------------------


def test_sequential_multidevice_native_fold_and_unmask():
    """add_batch with kernel=native-u64 on an 8-device mesh: the per-shard
    strided host fold must equal the mesh XLA fold, and unmask_limbs must
    handle the host-resident accumulator."""
    n, total, bs = 103, 8, 4
    stacks, _, _ = _updates(n, total, seed=9)
    ref = _sequential_oracle(n, stacks, bs)

    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel="native-u64")
    for i in range(0, total, bs):
        agg.add_batch(np.stack(stacks[i : i + bs]))
    assert agg.kernel_used == "native-u64"
    assert np.array_equal(agg.snapshot(), ref.snapshot())

    mask = _updates(n, 1, seed=13)[0][0]
    assert np.array_equal(agg.unmask_limbs(mask), ref.unmask_limbs(mask))


# --- ShardPlan / slice-fold units ------------------------------------------


def test_fold_planar_slice_host_matches_full_fold():
    order = CFG.order
    ol = host_limbs.order_limbs_for(order)
    rng = np.random.default_rng(2)
    k, L, n = 6, 2, 1024
    stack = rng.integers(0, 2**32, size=(k, L, n), dtype=np.uint32)
    stack[:, L - 1, :] &= np.uint32((1 << 13) - 1)
    ref = host_limbs.fold_planar_batch_host(np.zeros((L, n), np.uint32), stack, ol)

    # full-width buffers, strided per-slice folds
    acc = np.zeros((L, n), np.uint32)
    out = np.empty_like(acc)
    for lo, hi in shard_slices(n, 8):
        assert host_limbs.fold_planar_slice_host(acc, stack, out, lo, hi, ol, n_threads=1)
    assert np.array_equal(out, ref)

    # contiguous per-shard buffers (the streaming accumulators)
    pieces = []
    for lo, hi in shard_slices(n, 4):
        a = np.zeros((L, hi - lo), np.uint32)
        o = np.empty_like(a)
        assert host_limbs.fold_planar_slice_host(
            a, stack, o, lo, hi, ol, n_threads=2, acc_cols=hi - lo
        )
        pieces.append(o)
    assert np.array_equal(np.concatenate(pieces, axis=1), ref)


def test_shard_thread_budget_resolution(monkeypatch):
    monkeypatch.delenv("XAYNET_NATIVE_SHARD_THREADS", raising=False)
    assert shard_thread_budget(4, explicit=3) == 3
    monkeypatch.setenv("XAYNET_NATIVE_SHARD_THREADS", "5")
    assert shard_thread_budget(4) == 5
    monkeypatch.setenv("XAYNET_NATIVE_SHARD_THREADS", "junk")
    total = host_limbs.native_fold_threads()
    assert shard_thread_budget(4) == max(1, total // 4)
    monkeypatch.delenv("XAYNET_NATIVE_SHARD_THREADS", raising=False)
    assert shard_thread_budget(10_000) == 1  # never below one thread


def test_shard_plan_requires_resolved_kernel():
    agg = ShardedAggregator(CFG, 64, mesh=_mesh(2), kernel="auto")
    with pytest.raises(ValueError, match="resolved"):
        ShardPlan(agg)


def test_shard_plan_reassemble_roundtrip():
    """decompose -> per-shard folds -> reassemble equals the sequential
    fold, for both backends, starting from a non-zero accumulator."""
    n, total, bs = 96, 4, 4
    stacks, _, _ = _updates(n, total, seed=17)
    base = _updates(n, 2, seed=18)[0]

    for kernel in ("xla", "native-u64"):
        ref = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
        ref.add_batch(np.stack(base))
        ref.add_batch(np.stack(stacks))

        agg = ShardedAggregator(CFG, n, mesh=_mesh(4), kernel=kernel)
        agg.add_batch(np.stack(base))  # resolves the kernel, non-zero acc
        plan = ShardPlan(agg)
        planar = np.zeros((total, agg.n_limbs, agg.padded_length), np.uint32)
        from xaynet_tpu.ops.fold_jax import wire_to_planar

        planar[:, :, :n] = wire_to_planar(np.stack(stacks))
        if plan.native:
            plan.fold_full(planar)
        else:
            for d, (lo, hi) in enumerate(plan.slices):
                piece = jax.device_put(
                    np.ascontiguousarray(planar[:, :, lo:hi]), plan.devices[d]
                )
                plan.fold_shard(d, piece)
            plan.block_until_ready()
        agg.acc = plan.reassemble()
        plan.close()
        assert np.array_equal(agg.snapshot(), ref.snapshot()), kernel


# --- surfaces --------------------------------------------------------------


def test_shard_parallel_settings_surface():
    from xaynet_tpu.server.settings import SettingsError, Settings

    s = Settings.default()
    assert s.aggregation.shard_parallel is True
    assert s.aggregation.shard_threads == 0
    s.aggregation.shard_threads = -1
    with pytest.raises(SettingsError, match="shard_threads"):
        s.validate()


def test_shard_parallel_opt_out_forces_single_worker():
    n, total, bs = 64, 6, 3
    stacks, _, _ = _updates(n, total, seed=21)
    seq = _sequential_oracle(n, stacks, bs)
    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel="xla")
    stream = StreamingAggregator(
        agg, staging_buffers=2, dispatch_ahead=2, max_batch=bs, shard_parallel=False
    )
    assert not stream._sharded
    for i in range(0, total, bs):
        stream.submit_batch(np.stack(stacks[i : i + bs]))
    stream.drain()
    assert np.array_equal(agg.snapshot(), seq.snapshot())
    stream.close()


def test_healthz_pipeline_section_reports_shards():
    """The REST /healthz builder reads the streaming + per-shard gauges
    straight from the telemetry registry (no jax import on that path)."""
    n, bs = 64, 3
    stacks, _, _ = _updates(n, 6, seed=23)
    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=2, dispatch_ahead=2, max_batch=bs)
    stream.submit_batch(np.stack(stacks[0:3]))
    stream.drain()
    stream.close()

    from xaynet_tpu.server.rest import RestServer

    rest = RestServer.__new__(RestServer)  # only _streaming_health is exercised
    from xaynet_tpu.telemetry.registry import get_registry

    rest.registry = get_registry()
    section = rest._streaming_health()
    assert section is not None
    assert section["degraded"] in (False, True)
    assert "shards" in section
    for d in range(8):
        shard = section["shards"][str(d)]
        assert shard["staging_depth"] == 0
        assert shard["inflight_folds"] == 0


@pytest.mark.parametrize("kernel", ("xla", "native-u64"))
def test_sharded_fold_planar_rows_now_device_resident(kernel):
    """The server wire-ingest flush path: device-resident planars cached by
    validate_wire_updates fold synchronously per shard (the stacked chunk
    re-pinned to the batch sharding) and stay byte-identical."""
    n, total = 96, 10
    _, raws, _ = _updates(n, total, seed=29)

    seq = ShardedAggregator(CFG, n, mesh=_mesh(1), kernel="xla")
    seq.add_wire_batch(np.stack(raws))

    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel=kernel)
    stream = StreamingAggregator(agg, staging_buffers=2, dispatch_ahead=2, max_batch=4)
    planars = agg.validate_wire_updates([np.asarray(r) for r in raws])
    assert all(p is not None for p in planars)
    stream.fold_planar_rows_now(planars)
    stream.drain()

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == seq.nb_models == total
    stream.close()


def test_healthz_pipeline_section_degraded_shard():
    """Satellite (ISSUE 12): after the PR-7 single-shard sync-retry path
    fires, /healthz's pipeline section must surface the global degraded
    flag AND the per-shard triple — the first place an operator looks when
    the mesh goes degraded."""
    n, total, bs = 48, 6, 3
    stacks, _, _ = _updates(n, total, seed=31)
    seq = _sequential_oracle(n, stacks, bs)

    agg = ShardedAggregator(CFG, n, mesh=_mesh(8), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    real_fold = ShardPlan.fold_shard
    real_fold_packed = ShardPlan.fold_shard_packed
    state = {"failed": False}

    def flaky(self, d, batch):
        if d == 2 and not state["failed"]:
            state["failed"] = True
            raise RuntimeError("transient shard fault")
        return real_fold(self, d, batch)

    def flaky_packed(self, d, batch):
        if d == 2 and not state["failed"]:
            state["failed"] = True
            raise RuntimeError("transient shard fault")
        return real_fold_packed(self, d, batch)

    try:
        ShardPlan.fold_shard = flaky
        ShardPlan.fold_shard_packed = flaky_packed
        for i in range(0, total, bs):
            stream.submit_batch(np.stack(stacks[i : i + bs]))
        stream.drain()
    finally:
        ShardPlan.fold_shard = real_fold
        ShardPlan.fold_shard_packed = real_fold_packed

    assert stream.degraded  # the sync-retry path fired
    from xaynet_tpu.server.rest import RestServer
    from xaynet_tpu.telemetry.registry import get_registry

    rest = RestServer.__new__(RestServer)  # only _streaming_health is exercised
    rest.registry = get_registry()
    section = rest._streaming_health()
    assert section is not None
    assert section["degraded"] is True
    assert section["inflight_folds"] == 0  # drained
    for d in range(8):
        shard = section["shards"][str(d)]
        assert shard["staging_depth"] == 0
        assert shard["inflight_folds"] == 0
        assert "overlap_ratio" in shard
    # the degraded round still completed byte-identically (PR-7 ladder)
    assert np.array_equal(agg.snapshot(), seq.snapshot())
    stream.close()
    # close resets the flag for the next healthy pipeline's healthz
    assert rest._streaming_health()["degraded"] is False
