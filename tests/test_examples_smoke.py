"""Baseline-config examples run end to end, shrunken (VERDICT r02 item 5).

The cifar_lenet (baseline config #2) and shakespeare_lstm (config #3)
examples are executed as real subprocesses — the same command a user runs —
with tiny shapes and ``--check-loss``, which makes the script itself exit
nonzero unless the federated global model improves on the initial loss.
Reference analogue: bindings/python/examples/keras_house_prices/ is a
living, documented scenario.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(
    args: list[str], timeout: int = 280, extra_env: dict | None = None
) -> subprocess.CompletedProcess:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, *args],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_cifar_lenet_example_smoke():
    r = _run_example(
        [
            "examples/cifar_lenet.py",
            "--rounds", "2",
            "--participants", "6",
            "--image-size", "8",
            "--epochs", "3",
            "--lr", "0.01",
            "--check-loss",
        ]
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-2000:]}"
    assert "eval loss" in r.stdout


@pytest.mark.slow
def test_cifar_lenet_quantized_round_accuracy_gate():
    """The pre-mask quantization accuracy gate (docs/DESIGN.md §17): a
    quantized round (level 5 — 1-limb prime order, 4-byte wire width)
    through the REAL coordinator + SDK must still pass the --check-loss
    gate, the way PR-3 gated byte-identity. Slow-marked (a full 2-round
    federated example, ~1-4 min on shared cores): CI's unfiltered pytest
    run covers it; the fast analytic accuracy bound lives in
    tests/test_packed_codec.py::test_quantized_round_accuracy_bound."""
    r = _run_example(
        [
            "examples/cifar_lenet.py",
            "--rounds", "2",
            "--participants", "6",
            "--image-size", "8",
            "--epochs", "3",
            "--lr", "0.01",
            "--check-loss",
            "--quant", "5",
        ]
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-2000:]}"
    assert "eval loss" in r.stdout


def test_bench_round_device_path_smoke():
    """The rare-TPU-window bench branch (production wire-ingest flow through
    StagedAggregator) stays continuously tested: XAYNET_BENCH_FORCE_DEVICE_PATH
    drives it on the virtual CPU mesh at smoke scale."""
    import json

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    r = _run_example(
        [
            "tools/bench_round.py",
            "--cpu", "--updates", "32", "--model-len", "50000", "--sum2-seeds", "4",
        ],
        extra_env={"XLA_FLAGS": flags, "XAYNET_BENCH_FORCE_DEVICE_PATH": "1"},
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-2000:]}"
    tail = json.loads(r.stdout.strip().splitlines()[-1])
    assert tail["device_path_forced"] is True
    assert tail["updates"] == 32
    assert tail["breakdown_s"]["stage + fold (device)"] >= 0


def test_lora_federated_example_smoke():
    """Baseline config #5 (stretch): int-masked LoRA adapter federation with
    the loss-improvement gate (VERDICT r04 item 8)."""
    r = _run_example(["examples/lora_federated.py", "--rounds", "2", "--check-loss"])
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-2000:]}"
    assert "eval loss" in r.stdout


def test_shakespeare_lstm_example_smoke():
    r = _run_example(
        [
            "examples/shakespeare_lstm.py",
            "--rounds", "1",
            "--participants", "5",
            "--hidden", "16",
            "--seq-len", "20",
            "--epochs", "3",
            "--lr", "0.01",
            "--check-loss",
        ]
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-2000:]}"
    assert "eval loss" in r.stdout


def test_sim_quickstart_example_smoke():
    """The sim quickstart (DESIGN §13) runs a whole-round program twice and
    must report exactly one program invocation per round."""
    r = _run_example(
        [
            "examples/sim_quickstart.py",
            "-p", "64",
            "-l", "50",
            "-b", "16",
            "--rounds", "2",
        ]
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-2000:]}"
    assert "program invocations: 2" in r.stdout
    assert "participants/s" in r.stdout
