"""Full PET round over the real REST API (sockets on localhost)."""

import asyncio
from fractions import Fraction

import numpy as np

from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
from xaynet_tpu.sdk.traits import ModelStore
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store

N_SUM, N_UPDATE, MODEL_LEN = 1, 3, 7
SUM_PROB, UPDATE_PROB = 0.4, 0.5


class ArrayModelStore(ModelStore):
    def __init__(self, model):
        self.model = model

    async def load_model(self):
        return self.model


async def _run() -> tuple[np.ndarray, np.ndarray]:
    settings = Settings(
        pet=ServerPet(
            sum=PhaseSettings(prob=SUM_PROB, count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 20)),
            update=PhaseSettings(prob=UPDATE_PROB, count=CountSettings(N_UPDATE, N_UPDATE), time=TimeSettings(0, 20)),
            sum2=Sum2Settings(count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, 20)),
        )
    )
    settings.model.length = MODEL_LEN
    store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
    machine, request_tx, events = await StateMachineInitializer(settings, store).init()
    handler = PetMessageHandler(events, request_tx)
    fetcher = Fetcher(events)
    rest = RestServer(fetcher, handler)
    host, port = await rest.start("127.0.0.1", 0)
    machine_task = asyncio.create_task(machine.run())

    try:
        url = f"http://{host}:{port}"
        probe = HttpClient(url)
        while fetcher.phase().value != "sum":
            await asyncio.sleep(0.01)
        params = await probe.get_round_params()
        seed = params.seed.as_bytes()

        rng = np.random.default_rng(5)
        expected = np.zeros(MODEL_LEN)
        participants = []
        for i in range(N_SUM):
            keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
            participants.append(
                ParticipantSM(PetSettings(keys=keys), HttpClient(url), ArrayModelStore(None))
            )
        for i in range(N_UPDATE):
            keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(20 + i) * 1000)
            local = rng.uniform(-1, 1, MODEL_LEN).astype(np.float32)
            expected += local.astype(np.float64) / N_UPDATE
            participants.append(
                ParticipantSM(
                    PetSettings(keys=keys, scalar=Fraction(1, N_UPDATE)),
                    HttpClient(url),
                    ArrayModelStore(local),
                )
            )

        async def drive(sm):
            for _ in range(500):
                try:
                    await sm.transition()
                except Exception:
                    pass
                model = await probe.get_model()
                if model is not None and sm.phase.value == "awaiting":
                    return
                await asyncio.sleep(0.01)

        await asyncio.gather(*(drive(p) for p in participants))
        model = await probe.get_model()
        assert model is not None
        return np.asarray(model), expected
    finally:
        machine_task.cancel()
        await rest.stop()
        try:
            await machine_task
        except (asyncio.CancelledError, Exception):
            pass


def test_rest_round():
    got, expected = asyncio.run(asyncio.wait_for(_run(), timeout=60))
    np.testing.assert_allclose(got, expected, atol=1e-9)
